"""Kernel-driven sweep machinery for the Pallas one-sided block-Jacobi path.

This is the production TPU compute path (SVDConfig.pair_solver="pallas"):
each tournament round forms the Gram panel of its block pairs on the MXU,
hands it to the Pallas rotation kernel (`ops.pallas_blocks`), and applies
the accumulated orthogonal transform back to the tall column panels (and V)
on the MXU. The reference's equivalent hot loop ships two columns to the
GPU per rotation with 8 memcpys around each kernel launch
(lib/JacobiMethods.cu:479-510); here one kernel call rotates every pair of
a round and the matrix never leaves the device.

Design points (measured on TPU v5e — see PROFILE.md):

* Round skipping (threshold Jacobi): each round's panel coupling is
  measured on the freshly formed Gram panel; rounds whose UNMASKED
  coupling is below the target tolerance are skipped via `lax.cond`,
  which tapers late-sweep cost to the Gram + stat only. The skip gate
  deliberately ignores the deflation mask: a sub-noise-floor column still
  needs its rotations (they keep U orthogonal) even though it must not
  block loop termination (that is the masked stat's job).
* The convergence statistic is the dgesvj scaled coupling
  ``max |g_ij| / sqrt(g_ii g_jj)`` with numerically-null columns deflated
  (the quantity the reference computes per pair and discards,
  lib/JacobiMethods.cu:462,234).
* Optional bf16 Gram panels for the bulk phase: Gram errors only perturb
  rotation ANGLES (the transforms stay exactly orthogonal) and the stat by
  ~4e-3, harmless while the coupling is above ``BULK_TOL``; the apply
  matmuls always run at full f32 precision so no backward error enters X
  or V.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from . import block_rotate as br
from . import pallas_apply as pa
from . import pallas_blocks as pb
from . import pallas_gram as pg
from ..obs import metrics
from ..obs.scopes import scope
from ..parallel import schedule as sched

HI = jax.lax.Precision.HIGHEST

# Coupling level above which bf16 Gram panels are safe (their ~4e-3 angle /
# stat noise is well below the couplings being resolved).
BULK_TOL = 3e-2


def _split_bf16(x):
    """(hi, lo) bf16 split of an f32 array: x ~= hi + lo to ~eps_bf16^2.

    The split is done by BIT-MASKING the low mantissa half (truncation):
    the naive form ``x - x.astype(bf16).astype(f32)`` is folded to zero by
    XLA (verified on-chip: its x3 product came out bit-identical to the
    single-pass bf16 product), which silently degraded the whole split to
    one pass. hi is exact in bf16 (mantissa already truncated) and x - hi
    is exact in f32, so the only loss is lo's own bf16 rounding (~2^-16
    relative to x)."""
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    hi = jax.lax.bitcast_convert_type(bits & jnp.uint32(0xFFFF0000),
                                      jnp.float32)
    return hi.astype(jnp.bfloat16), (x - hi).astype(jnp.bfloat16)


def _einsum(a, b, spec, bf16=False, x3=False):
    """Contraction at one of three precision regimes: f32 HIGHEST (6-pass
    emulation, ~25 TF/s on v5e), single native bf16 pass (~138 TF/s,
    ~eps_bf16 input rounding), or the bf16x3 split product
    hi@hi + lo@hi + hi@lo (~46 TF/s, ~eps_bf16^2 ~ 1.5e-5 error — the
    mixed-bulk apply regime, accurate enough that the accumulated rotation
    product stays orthogonal to ~1e-4 over a full solve's applies).

    bf16-STORED operands (the byte-halved mixed-bulk storage regimes)
    contract natively: the stack side already paid its eps_bf16 storage
    rounding, so extra passes on IT claw nothing back — but an f32 q
    under ``x3`` is split into hi+lo bf16 halves (two passes, "qx2"):
    casting q to one bf16 pass floors every rotation angle at eps_bf16
    and stalls the bulk at ~5e-3 coupling (measured on-chip)."""
    if a.dtype == jnp.bfloat16 or b.dtype == jnp.bfloat16:
        if x3 and a.dtype == jnp.bfloat16 and b.dtype != jnp.bfloat16:
            bh, bl = _split_bf16(b.astype(jnp.float32))
            f = lambda q: jnp.einsum(spec, a, q,
                                     preferred_element_type=jnp.float32)
            return f(bh) + f(bl)
        return jnp.einsum(spec, a.astype(jnp.bfloat16),
                          b.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    if x3:
        ah, al = _split_bf16(a)
        bh, bl = _split_bf16(b)
        f = lambda p, q: jnp.einsum(spec, p, q,
                                    preferred_element_type=jnp.float32)
        return f(ah, bh) + (f(al, bh) + f(ah, bl))
    if bf16:
        return jnp.einsum(spec, a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    return jnp.einsum(spec, a, b, precision=HI,
                      preferred_element_type=jnp.float32)


def panel_stats(g: jax.Array, dmax2: jax.Array,
                members=None, criterion: str = "rel"
                ) -> Tuple[jax.Array, jax.Array]:
    """(masked, unmasked) max scaled coupling of a Gram panel stack.

    ``masked`` deflates columns whose squared norm is below
    ``dmax2 * (n2*eps)^2`` (directions at the roundoff floor whose mutual
    cosines are noise and can never converge) — it drives the sweep loop.
    ``unmasked`` keeps them — it gates round skipping. Exactly-zero
    (padding) columns contribute 0 to both.

    ``members`` ((panel->matrix index array, num_matrices) pair, the
    batched-solve lane — see `_members`): panel j of the stack belongs to
    matrix ``members[0][j]``; ``dmax2`` is then a per-matrix vector and
    BOTH returned statistics are per-matrix segment maxima — one matrix's
    couplings (or NaNs) never enter a neighbor's statistic.

    ``criterion``: "rel" is the dgesvj scaled coupling above; "abs" is
    the LAPACK-dgesvd-class ``max |g_ij| / dmax2`` — the statistic the
    blocked-rotation bulk phase drives (its eigh-quality subproblem
    solves converge the abs class fast but leave small-column couplings
    at the eigh floor, so the rel statistic could never terminate the
    bulk loop). The abs form needs no deflation mask — a null column's
    couplings are tiny against dmax2 by construction — so masked and
    unmasked coincide.
    """
    f32 = jnp.float32
    g = g.astype(f32)
    n2 = g.shape[-1]
    eps = jnp.finfo(f32).eps
    d2 = jnp.diagonal(g, axis1=-2, axis2=-1)
    if criterion == "abs":
        no_diag = (1.0 - jnp.eye(n2, dtype=f32))[None]
        c = jnp.abs(g) * no_diag
        if members is None:
            stat = jnp.max(c) / jnp.maximum(dmax2.astype(f32),
                                            jnp.finfo(f32).tiny)
            return stat, stat
        seg, nseg = members
        stat = jax.ops.segment_max(jnp.max(c, axis=(1, 2)), seg,
                                   num_segments=nseg)
        stat = stat / jnp.maximum(dmax2.astype(f32), jnp.finfo(f32).tiny)
        return stat, stat
    inv = 1.0 / jnp.maximum(d2, jnp.finfo(f32).tiny)
    r2 = (g * g) * inv[:, :, None] * inv[:, None, :]
    r2 = r2 * (1.0 - jnp.eye(n2, dtype=f32))[None]
    if members is None:
        unmasked = jnp.sqrt(jnp.max(r2))
        null2 = dmax2.astype(f32) * (n2 * eps) ** 2
        live = d2 > null2
        pair = live[:, :, None] & live[:, None, :]
        masked = jnp.sqrt(jnp.max(jnp.where(pair, r2, 0.0)))
        return masked, unmasked
    seg, nseg = members
    unmasked = jnp.sqrt(jax.ops.segment_max(
        jnp.max(r2, axis=(1, 2)), seg, num_segments=nseg))
    null2 = dmax2.astype(f32)[seg] * (n2 * eps) ** 2
    live = d2 > null2[:, None]
    pair = live[:, :, None] & live[:, None, :]
    masked = jnp.sqrt(jax.ops.segment_max(
        jnp.max(jnp.where(pair, r2, 0.0), axis=(1, 2)), seg,
        num_segments=nseg))
    return masked, unmasked


def _members(batch: int, k_per: int, halves: int = 1):
    """(panel->matrix map, batch) of a batched stack: ``halves`` repeats
    of ``batch`` back-to-back segments of ``k_per`` panels each (the self
    round concatenates the top and bot stacks, hence halves=2). Built
    from iota primitives — NOT a host constant — so no `device_put`
    lands inside the sweep loop bodies (JAXPR003)."""
    seg = jnp.repeat(jnp.arange(batch, dtype=jnp.int32), k_per,
                     total_repeat_length=batch * k_per)
    if halves > 1:
        seg = jnp.concatenate([seg] * halves)
    return seg, batch


def _skip_stat(stat):
    """Scalar round-skip gate over a per-matrix stat vector: NaN (a
    poisoned member) must force the rotations ON for its neighbors'
    sake, so NaN maps to +inf, never to a skipped round."""
    return jnp.max(jnp.where(jnp.isnan(stat), jnp.inf, stat))


def _rotations(g, kind, *, interpret, polish, axis_name):
    """Dispatch to the right rotation generator: the compiled Pallas kernel,
    or (on interpreter backends under a mesh axis) the pure-jnp reference
    body, which keeps shard_map variance types consistent where the
    pallas_call machinery cannot. Panels too wide for the kernel's
    scoped-VMEM budget (explicit block_size >= 512) also take the
    reference body — as plain compiled XLA — instead of dying in Mosaic."""
    b2 = g.shape[-1] // 2   # both kernels carry half-width 4-block panels
    factor = pb.CROSS_FACTOR if kind == "cross" else pb.SELF_FACTOR
    oversized = not pb.kernel_fits(b2, factor)
    with scope("rotations"):
        if (axis_name is not None and interpret) or oversized:
            fn = pb.reference_self if kind == "self" else pb.reference_cross
            return fn(g, polish=polish)
        fn = pb.self_rotations if kind == "self" else pb.cross_rotations
        return fn(g, interpret=interpret, polish=polish,
                  vma=(axis_name,) if axis_name is not None else None)


def _mesh_max(x, axis_name):
    return jax.lax.pmax(x, axis_name) if axis_name is not None else x


def self_round(blocks, vblocks, dmax2, rtol, *, interpret, polish, bf16_gram,
               axis_name=None, apply_x3=False, return_rotated=False,
               batch=1):
    """Annihilate every within-block pair once (full tournament kernel).

    ``axis_name``: when run under shard_map, the mesh axis — the round-skip
    predicate is pmax'd so every device takes the same branch. The returned
    stat stays LOCAL (the sweep pmax's its running max once, not once per
    round). ``return_rotated``: also return the skip decision as an int32
    0/1 (telemetry's rotation-round counter; only computed when asked so
    the zero-telemetry trace is unchanged). ``batch`` (static): the stack
    holds ``batch`` matrices' blocks back to back; ``dmax2`` and the
    returned stat are then per-matrix vectors (the block pair-solves are
    per-panel and need no change — only the statistics segment).
    """
    with scope("gram"):
        g = _einsum(blocks, blocks, "kmi,kmj->kij", bf16_gram)
    if batch > 1:
        stat, skip = panel_stats(
            g, dmax2, members=_members(batch, blocks.shape[0] // (2 * batch),
                                       halves=2))
        skip = _skip_stat(skip)
    else:
        stat, skip = panel_stats(g, dmax2)
    skip = _mesh_max(skip, axis_name)

    def do(args):
        blocks, vblocks = args
        q = _rotations(g, "self", interpret=interpret, polish=polish,
                       axis_name=axis_name)
        with scope("apply"):
            blocks = _einsum(blocks, q, "kmi,kij->kmj",
                             x3=apply_x3).astype(blocks.dtype)
            if vblocks is not None:
                vblocks = _einsum(vblocks, q, "kmi,kij->kmj",
                                  x3=apply_x3).astype(vblocks.dtype)
        return blocks, vblocks

    blocks, vblocks = jax.lax.cond(skip > rtol, do, lambda a: a,
                                   (blocks, vblocks))
    if return_rotated:
        return blocks, vblocks, stat, (skip > rtol).astype(jnp.int32)
    return blocks, vblocks, stat


def cross_round(top, bot, vtop, vbot, dmax2, rtol, *, interpret, polish,
                bf16_gram, axis_name=None, fused_exchange=False,
                fused_apply=False, apply_x3=False, return_rotated=False,
                batch=1):
    """Annihilate every cross pair of each (top[i], bot[i]) block pair.
    ``axis_name``: see `self_round`. ``batch``: see `self_round` — the
    fused-exchange form additionally makes the in-kernel exchange
    block-diagonal per matrix (ops/pallas_apply.py index maps).

    ``fused_exchange`` (single-device compiled path): the rotation apply AND
    the inter-round tournament exchange run as ONE Pallas kernel
    (ops/pallas_apply.py) — the returned stacks are already exchanged, and
    the skip branch performs the exchange alone. The caller must then NOT
    apply its own exchange. The unfused form keeps the concat + one matmul
    + slice chain, which IS the traffic-optimal XLA apply (four block
    matmuls measured 26% slower at 8192^2 — the adds cannot fuse into dot
    epilogues); the mesh path keeps it because its exchange is a ppermute
    ICI hop that cannot live inside a kernel, and interpreter backends keep
    it as the reference semantics.
    """
    b = top.shape[-1]
    vma = (axis_name,) if axis_name is not None else None
    with scope("gram"):
        if not interpret and pg.supported(top.shape[1], b):
            # Compiled path: the Pallas reduction kernel forms the Gram
            # panel at ~2x the throughput of the XLA batched einsum on this
            # reduction-heavy small-output shape (PROFILE.md item 10), and
            # never materializes the (k, m, 2b) concat (under ``bf16_gram``
            # it casts per-chunk in VMEM and contracts in one native pass).
            g = pg.gram_pairs(top, bot, vma=vma, bf16=bf16_gram)
        else:
            x = jnp.concatenate([top, bot], axis=-1)
            g = _einsum(x, x, "kmi,kmj->kij", bf16_gram)
    if batch > 1:
        stat, skip = panel_stats(
            g, dmax2, members=_members(batch, top.shape[0] // batch))
        skip = _skip_stat(skip)
    else:
        stat, skip = panel_stats(g, dmax2)
    skip = _mesh_max(skip, axis_name)

    if fused_exchange:
        def do(args):
            top, bot, vtop, vbot = args
            q = _rotations(g, "cross", interpret=interpret, polish=polish,
                           axis_name=axis_name)
            with scope("apply_exchange"):
                top, bot = pa.apply_exchange(top, bot, q, x3=apply_x3,
                                             batch=batch)
                if vtop is not None:
                    vtop, vbot = pa.apply_exchange(vtop, vbot, q,
                                                   x3=apply_x3, batch=batch)
            return top, bot, vtop, vbot

        def skip_branch(args):
            top, bot, vtop, vbot = args
            with scope("exchange"):
                top, bot = sched.rotate_blocks(top, bot, batch)
                if vtop is not None:
                    vtop, vbot = sched.rotate_blocks(vtop, vbot, batch)
            return top, bot, vtop, vbot

        top, bot, vtop, vbot = jax.lax.cond(skip > rtol, do, skip_branch,
                                            (top, bot, vtop, vbot))
        if return_rotated:
            return (top, bot, vtop, vbot, stat,
                    (skip > rtol).astype(jnp.int32))
        return top, bot, vtop, vbot, stat

    # Compiled mesh path: fuse the apply (the adds live in VMEM) but keep
    # the exchange outside — it is the caller's ppermute ICI hop. The
    # stacks here are the device-LOCAL views under shard_map.
    fused_apply = (fused_apply and not interpret
                   and pa.supported(top.shape[1], b)
                   and (vtop is None or pa.supported(vtop.shape[1], b)))

    def do(args):
        top, bot, vtop, vbot = args
        q = _rotations(g, "cross", interpret=interpret, polish=polish,
                       axis_name=axis_name)
        with scope("apply"):
            if fused_apply:
                top, bot = pa.apply_exchange(top, bot, q, exchange=False,
                                             vma=vma, x3=apply_x3)
                if vtop is not None:
                    vtop, vbot = pa.apply_exchange(vtop, vbot, q,
                                                   exchange=False, vma=vma,
                                                   x3=apply_x3)
                return top, bot, vtop, vbot
            xn = _einsum(jnp.concatenate([top, bot], axis=-1), q,
                         "kmi,kij->kmj", x3=apply_x3).astype(top.dtype)
            top, bot = xn[..., :b], xn[..., b:]
            if vtop is not None:
                vn = _einsum(jnp.concatenate([vtop, vbot], axis=-1), q,
                             "kmi,kij->kmj", x3=apply_x3).astype(vtop.dtype)
                vtop, vbot = vn[..., :b], vn[..., b:]
        return top, bot, vtop, vbot

    top, bot, vtop, vbot = jax.lax.cond(skip > rtol, do, lambda a: a,
                                        (top, bot, vtop, vbot))
    if return_rotated:
        return top, bot, vtop, vbot, stat, (skip > rtol).astype(jnp.int32)
    return top, bot, vtop, vbot, stat


def cross_round_fused(top, bot, vtop, vbot, g, dmax2, rtol, *, polish,
                      bf16_gram, apply_x3=False, interpret=False,
                      return_rotated=False, batch=1):
    """Cross round for the single-device COMPILED path, with the Gram
    panel as loop-carried state: ``g`` is the CURRENT pairs' panel
    (produced by the previous round's fused apply+exchange+gram kernel, or
    the bootstrap `pg.gram_pairs` call), and the returned panel belongs to
    the post-exchange pairs — so the whole round is rotation kernel + ONE
    apply kernel per stack, with zero standalone gram reads on the rotate
    path. The skip branch pays a plain exchange + gram kernel (late
    sweeps, where rounds are cheap anyway). ``batch``: see
    `cross_round`."""
    with_v = vtop is not None
    if batch > 1:
        stat, skip = panel_stats(
            g, dmax2, members=_members(batch, top.shape[0] // batch))
        skip = _skip_stat(skip)
    else:
        stat, skip = panel_stats(g, dmax2)

    def do(args):
        top, bot, vtop, vbot, _ = args
        q = _rotations(g, "cross", interpret=interpret, polish=polish,
                       axis_name=None)
        with scope("apply_exchange"):
            top, bot, g2 = pa.apply_exchange(top, bot, q, x3=apply_x3,
                                             with_gram=True,
                                             gram_bf16=bf16_gram,
                                             interpret=interpret,
                                             batch=batch)
            if with_v:
                vtop, vbot = pa.apply_exchange(vtop, vbot, q, x3=apply_x3,
                                               interpret=interpret,
                                               batch=batch)
        return top, bot, vtop, vbot, g2

    def skip_branch(args):
        top, bot, vtop, vbot, _ = args
        with scope("exchange"):
            top, bot = sched.rotate_blocks(top, bot, batch)
            if with_v:
                vtop, vbot = sched.rotate_blocks(vtop, vbot, batch)
        with scope("gram"):
            g2 = pg.gram_pairs(top, bot, bf16=bf16_gram,
                               interpret=interpret)
        return top, bot, vtop, vbot, g2

    top, bot, vtop, vbot, g = jax.lax.cond(
        skip > rtol, do, skip_branch, (top, bot, vtop, vbot, g))
    if return_rotated:
        return top, bot, vtop, vbot, g, stat, (skip > rtol).astype(jnp.int32)
    return top, bot, vtop, vbot, g, stat


def block_round(top, bot, vtop, vbot, dmax2, rtol, *, apply_x3=False,
                interpret=False, batch=1, return_rotated=False):
    """One blocked-rotation tournament round (the MXU-native lane,
    `ops.block_rotate`): form the pairs' full 2b x 2b Gram panels, solve
    each subproblem COMPLETELY on-chip with the rotations accumulated
    into one orthogonal factor J (`block_rotate.accumulate`), and apply J
    to the m x b panels — and the V panels — as ONE rank-2b matmul per
    pair, batched along the pair axis. On compiled TPU backends the apply
    AND the tournament exchange fuse into the existing
    `pallas_apply.apply_exchange` kernel (J has exactly the cross
    kernel's (k, 2b, 2b) factor shape), so the round is gram kernel +
    batched eigh + one fused apply per stack — zero latency-bound
    rotation steps.

    Statistics are the ABS criterion (`panel_stats(criterion="abs")`,
    segmented per member when ``batch > 1``): the eigh-quality subproblem
    solve converges the abs class, and the rel endgame belongs to the
    scalar-accurate kernel polish (`iterate`). The round-skip gate uses
    the same abs statistic against ``rtol``.
    """
    b = top.shape[-1]
    with_v = vtop is not None
    with scope("gram"):
        if not interpret and pg.supported(top.shape[1], b):
            g = pg.gram_pairs(top, bot)
        else:
            x = jnp.concatenate([top, bot], axis=-1)
            g = _einsum(x, x, "kmi,kmj->kij")
    if batch > 1:
        stat, skip = panel_stats(
            g, dmax2, members=_members(batch, top.shape[0] // batch),
            criterion="abs")
        skip = _skip_stat(skip)
    else:
        stat, skip = panel_stats(g, dmax2, criterion="abs")
    fused = (not interpret and pa.supported(top.shape[1], b)
             and (not with_v or pa.supported(vtop.shape[1], b)))

    def do(args):
        top, bot, vtop, vbot = args
        q = br.accumulate(g)
        if fused:
            with scope("apply_exchange"):
                top, bot = pa.apply_exchange(top, bot, q, x3=apply_x3,
                                             batch=batch)
                if with_v:
                    vtop, vbot = pa.apply_exchange(vtop, vbot, q,
                                                   x3=apply_x3, batch=batch)
            return top, bot, vtop, vbot
        with scope("apply"):
            top, bot, nvt, nvb = br.apply_factor(
                top, bot, vtop if with_v else None,
                vbot if with_v else None, q, x3=apply_x3)
            if with_v:
                vtop, vbot = nvt, nvb
        with scope("exchange"):
            top, bot = sched.rotate_blocks(top, bot, batch)
            if with_v:
                vtop, vbot = sched.rotate_blocks(vtop, vbot, batch)
        return top, bot, vtop, vbot

    def skip_branch(args):
        top, bot, vtop, vbot = args
        with scope("exchange"):
            top, bot = sched.rotate_blocks(top, bot, batch)
            if with_v:
                vtop, vbot = sched.rotate_blocks(vtop, vbot, batch)
        return top, bot, vtop, vbot

    top, bot, vtop, vbot = jax.lax.cond(skip > rtol, do, skip_branch,
                                        (top, bot, vtop, vbot))
    if return_rotated:
        return top, bot, vtop, vbot, stat, (skip > rtol).astype(jnp.int32)
    return top, bot, vtop, vbot, stat


def block_round_fused(top, bot, vtop, vbot, g, dmax2, rtol, *,
                      apply_x3=False, interpret=False, batch=1,
                      return_rotated=False):
    """`block_round` with the Gram panel as loop-carried state (the exact
    carry pattern of `cross_round_fused`): ``g`` is the CURRENT pairs'
    full 2b x 2b panel — produced by the previous round's fused
    apply+exchange+gram kernel, or the bootstrap `pg.gram_pairs` call —
    and the returned panel belongs to the post-exchange pairs, so a
    rotate round is batched eigh + ONE fused apply kernel per stack with
    zero standalone gram reads of the m-height panels (the standalone
    read would be a full extra HBM pass per round on the lane whose
    whole point is attacking the 1.7% MFU). The skip branch pays a plain
    exchange + gram kernel (late sweeps, where rounds are cheap)."""
    with_v = vtop is not None
    if batch > 1:
        stat, skip = panel_stats(
            g, dmax2, members=_members(batch, top.shape[0] // batch),
            criterion="abs")
        skip = _skip_stat(skip)
    else:
        stat, skip = panel_stats(g, dmax2, criterion="abs")

    def do(args):
        top, bot, vtop, vbot, _ = args
        q = br.accumulate(g)
        with scope("apply_exchange"):
            top, bot, g2 = pa.apply_exchange(top, bot, q, x3=apply_x3,
                                             with_gram=True,
                                             interpret=interpret,
                                             batch=batch)
            if with_v:
                vtop, vbot = pa.apply_exchange(vtop, vbot, q, x3=apply_x3,
                                               interpret=interpret,
                                               batch=batch)
        return top, bot, vtop, vbot, g2

    def skip_branch(args):
        top, bot, vtop, vbot, _ = args
        with scope("exchange"):
            top, bot = sched.rotate_blocks(top, bot, batch)
            if with_v:
                vtop, vbot = sched.rotate_blocks(vtop, vbot, batch)
        with scope("gram"):
            g2 = pg.gram_pairs(top, bot, interpret=interpret)
        return top, bot, vtop, vbot, g2

    top, bot, vtop, vbot, g = jax.lax.cond(
        skip > rtol, do, skip_branch, (top, bot, vtop, vbot, g))
    if return_rotated:
        return top, bot, vtop, vbot, g, stat, (skip > rtol).astype(jnp.int32)
    return top, bot, vtop, vbot, g, stat


def sweep_block(top, bot, vtop, vbot, dmax2, rtol, *, interpret,
                apply_x3=False, telemetry=False, batch=1):
    """One blocked-rotation sweep: ``2k-1`` tournament rounds of
    `block_round` — NO separate self round, because each round's fully
    solved 2b x 2b subproblem annihilates the within-block pairs too
    (they are re-covered every round; cross-block pairs exactly once when
    their blocks meet). Returns the max ABS coupling observed across the
    sweep's fresh Gram panels (per-matrix ``(batch,)`` vector on the
    batched lane), measured BEFORE each round's rotations — the bulk
    phase's loop statistic. On compiled TPU backends with lane-sized
    panels the rounds run gram-carried (`block_round_fused` — one
    bootstrap panel, then every round is eigh + fused
    apply/exchange/gram); elsewhere each round recomputes its panel
    (`block_round`). Single-device only (the mesh keeps the kernel
    lane)."""
    k, m, b = top.shape
    with_v = vtop is not None
    k_per = k // batch
    n_rounds = sched.num_rounds(2 * k_per)
    if not with_v:
        vtop = vbot = jnp.zeros((k, 0, top.shape[2]), top.dtype)
    # Same gate as `sweep`'s fused path: compiled backend, kernel-usable
    # panels/rows for every stack (gram kernel needed for bootstrap and
    # the skip branch).
    fused = (not interpret and pa.supported(m, b) and pg.supported(m, b)
             and (not with_v or pa.supported(vtop.shape[1], b)))

    if fused:
        with scope("gram"):
            g0 = pg.gram_pairs(top, bot)

        def body(carry, _):
            top, bot, vtop, vbot, g, mx = carry[:6]
            out = block_round_fused(
                top, bot, vtop if with_v else None,
                vbot if with_v else None, g, dmax2, rtol,
                apply_x3=apply_x3, interpret=interpret, batch=batch,
                return_rotated=telemetry)
            top, bot, nvt, nvb, g, stat = out[:6]
            if with_v:
                vtop, vbot = nvt, nvb
            new = (top, bot, vtop, vbot, g, jnp.maximum(mx, stat))
            if telemetry:
                new += (carry[6] + out[6],)
            return new, None

        mx0 = (jnp.zeros((batch,), jnp.float32) if batch > 1
               else jnp.zeros((), jnp.float32))
        init = (top, bot, vtop, vbot, g0, mx0)
        if telemetry:
            init += (jnp.int32(0),)
        carry, _ = jax.lax.scan(body, init, None, length=n_rounds)
        top, bot, vtop, vbot, _, off = carry[:6]
        out = (top, bot, (vtop if with_v else None),
               (vbot if with_v else None), off)
        return out + (carry[6],) if telemetry else out

    def body(carry, _):
        top, bot, vtop, vbot, mx = carry[:5]
        out = block_round(
            top, bot, vtop if with_v else None, vbot if with_v else None,
            dmax2, rtol, apply_x3=apply_x3, interpret=interpret,
            batch=batch, return_rotated=telemetry)
        top, bot, nvt, nvb, stat = out[:5]
        if with_v:
            vtop, vbot = nvt, nvb
        new = (top, bot, vtop, vbot, jnp.maximum(mx, stat))
        if telemetry:
            new += (carry[5] + out[5],)
        return new, None

    mx0 = (jnp.zeros((batch,), jnp.float32) if batch > 1
           else jnp.zeros((), jnp.float32))
    init = (top, bot, vtop, vbot, mx0)
    if telemetry:
        init += (jnp.int32(0),)
    carry, _ = jax.lax.scan(body, init, None, length=n_rounds)
    top, bot, vtop, vbot, off = carry[:5]
    out = (top, bot, (vtop if with_v else None),
           (vbot if with_v else None), off)
    return out + (carry[5],) if telemetry else out


def iterate_block(top, bot, vtop, vbot, *, abs_tol, max_sweeps, interpret,
                  apply_x3=False, stall_detection=True, start_sweeps=0,
                  telemetry=False, stage="block_bulk", nonfinite0=None,
                  chaos_nan_sweep=None):
    """`lax.while_loop` of `sweep_block`s until the ABS coupling drops
    below ``abs_tol`` (the blocked-rotation BULK phase; the caller's
    kernel polish finishes to the rel criterion). Stall constants are the
    abs criterion's (`solver._should_continue`: gate ``4*abs_tol``,
    shrink 0.75) — an input whose abs floor sits above ``abs_tol``
    (extreme grading) exits on stall and hands the rest to the polish
    instead of burning the sweep budget. Health word semantics follow
    `iterate_phase` exactly (nonfinite rides the dmax2/off reductions;
    ``chaos_nan_sweep`` is the fault-injection hook). Returns
    (top, bot, vtop, vbot, off, sweeps, nonfinite)."""
    from ..resilience import chaos as _chaos
    with_v = vtop is not None
    k = top.shape[0]
    if vtop is None:
        vtop = vbot = jnp.zeros((k, 0, top.shape[2]), top.dtype)

    def cond(st):
        _, _, _, _, off, prev_off, sweeps, nonfinite = st
        return should_continue(off, prev_off, sweeps, tol=abs_tol,
                               max_sweeps=max_sweeps,
                               stall_detection=stall_detection,
                               stall_gate=4.0 * abs_tol, stall_shrink=0.75,
                               nonfinite=nonfinite)

    def body(st):
        top, bot, vtop, vbot, prev_off, _, sweeps, nonfinite = st
        if chaos_nan_sweep is not None:
            top = _chaos.poison(top, sweeps, chaos_nan_sweep)
        dmax2 = _global_dmax2(top, bot)
        out = sweep_block(
            top, bot, vtop if with_v else None, vbot if with_v else None,
            dmax2, abs_tol, interpret=interpret, apply_x3=apply_x3,
            telemetry=telemetry)
        top, bot, nvt, nvb, off = out[:5]
        nonfinite = nonfinite | ~jnp.isfinite(dmax2) | ~jnp.isfinite(off)
        if telemetry:
            metrics.emit("sweep",
                         meta={"path": "block", "stage": stage},
                         sweep=sweeps + 1, off_rel=off,
                         rounds_rotated=out[5])
        if not with_v:
            nvt, nvb = st[2], st[3]
        return (top, bot, nvt, nvb, off, prev_off, sweeps + 1, nonfinite)

    inf = jnp.float32(jnp.inf)
    nf0 = (jnp.zeros((), jnp.bool_) if nonfinite0 is None
           else jnp.asarray(nonfinite0, jnp.bool_))
    state = (top, bot, vtop, vbot, inf, inf,
             jnp.asarray(start_sweeps, jnp.int32), nf0)
    top, bot, vtop, vbot, off, _, sweeps, nonfinite = jax.lax.while_loop(
        cond, body, state)
    return (top, bot, (vtop if with_v else None),
            (vbot if with_v else None), off, sweeps, nonfinite)


def iterate_block_batched(top, bot, vtop, vbot, *, batch, abs_tol,
                          max_sweeps, interpret, apply_x3=False,
                          stall_detection=True, chaos_nan_sweep=None):
    """Batched blocked-rotation bulk loop (`solver.svd_batched`'s
    block-rotation lane): `iterate_batched`'s per-member bookkeeping over
    `sweep_block` sweeps against the ABS statistic. A member that reaches
    ``abs_tol`` (or stalls at its abs floor, or goes non-finite) freezes
    its statistics and rides the remaining bulk sweeps near-identity; the
    caller continues every member through the kernel polish
    (`iterate_batched` with the carried counters). Returns
    (top, bot, vtop, vbot, off (batch,), sweeps scalar, msweeps (batch,),
    nonfinite (batch,))."""
    from ..resilience import chaos as _chaos
    with_v = vtop is not None
    kb = top.shape[0]
    if vtop is None:
        vtop = vbot = jnp.zeros((kb, 0, top.shape[2]), top.dtype)

    def go_mask(off, prev_off, sweeps, nonfinite):
        return should_continue(off, prev_off, sweeps, tol=abs_tol,
                               max_sweeps=max_sweeps,
                               stall_detection=stall_detection,
                               stall_gate=4.0 * abs_tol, stall_shrink=0.75,
                               nonfinite=nonfinite)

    def cond(st):
        _, _, _, _, off, prev_off, sweeps, _, nonfinite = st
        return jnp.any(go_mask(off, prev_off, sweeps, nonfinite))

    def body(st):
        top, bot, vtop, vbot, off, prev_off, sweeps, msweeps, nonfinite = st
        go = go_mask(off, prev_off, sweeps, nonfinite)
        if chaos_nan_sweep is not None:
            top = _chaos.poison(top, sweeps, chaos_nan_sweep)
        dmax2 = _global_dmax2(top, bot, batch=batch)
        out = sweep_block(top, bot, vtop if with_v else None,
                          vbot if with_v else None, dmax2, abs_tol,
                          interpret=interpret, apply_x3=apply_x3,
                          batch=batch)
        top, bot, nvt, nvb, off_new = out[:5]
        nf_new = ~jnp.isfinite(dmax2) | ~jnp.isfinite(off_new)
        nonfinite = nonfinite | (go & nf_new)
        prev_off = jnp.where(go, off, prev_off)
        off = jnp.where(go, off_new, off)
        msweeps = msweeps + go.astype(jnp.int32)
        if not with_v:
            nvt, nvb = st[2], st[3]
        return (top, bot, nvt, nvb, off, prev_off, sweeps + 1, msweeps,
                nonfinite)

    inf = jnp.full((batch,), jnp.inf, jnp.float32)
    state = (top, bot, vtop, vbot, inf, inf, jnp.int32(0),
             jnp.zeros((batch,), jnp.int32),
             jnp.zeros((batch,), jnp.bool_))
    (top, bot, vtop, vbot, off, _, sweeps, msweeps,
     nonfinite) = jax.lax.while_loop(cond, body, state)
    return (top, bot, (vtop if with_v else None),
            (vbot if with_v else None), off, sweeps, msweeps, nonfinite)


def sweep(top, bot, vtop, vbot, dmax2, rtol, *, interpret, polish, bf16_gram,
          axis_name=None, n_rounds=None, exchange=None, apply_x3=False,
          telemetry=False, batch=1):
    """One full sweep: self round + cross tournament rounds.

    Every pair of the n columns is annihilated exactly once: n-1 sequential
    rotation steps in total, the tournament-optimal count. Returns the max
    (deflation-masked) coupling observed across the sweep's fresh Gram
    panels — measured BEFORE each round's rotations.

    Single-device default: ``sched.rotate_blocks`` between rounds. Mesh
    callers (under shard_map) pass ``axis_name``, the global ``n_rounds``,
    and the ICI ring ``exchange`` — the stat is pmax'd once at sweep end.

    ``telemetry`` (static): additionally return the number of rounds whose
    round-skip gate fired the rotations (`obs.metrics`' rotation-round
    counter) as a trailing int32 — the counter rides the scan carry, so
    the flag must be OFF on the zero-telemetry path to keep its HLO
    byte-identical.

    ``batch`` (static): the batched-solve lane — the stacks hold ``batch``
    matrices back to back along the pair axis (``k = batch * k_per``), the
    tournament exchange is block-diagonal per matrix, ``dmax2`` and the
    returned coupling are per-matrix ``(batch,)`` vectors, and the round
    count is the PER-MATRIX ``2*k_per - 1`` (the schedule is identical per
    matrix, so one scan drives them all — the whole point: B matrices cost
    one latency chain, not B). Single-device only (no ``axis_name`` /
    custom ``exchange``).
    """
    k, m, b = top.shape
    with_v = vtop is not None
    if batch > 1 and (axis_name is not None or exchange is not None):
        raise ValueError("batched sweeps are single-device only (no mesh "
                         "axis / ring exchange)")
    k_per = k // batch
    # Fused apply+exchange(+gram) kernels: single-device compiled path
    # with lane-sized panels and kernel-usable row chunks for every stack
    # (the gram-carried loop also needs the standalone gram kernel for its
    # bootstrap panel and skip branch).
    fused = (exchange is None and axis_name is None and not interpret
             and pa.supported(m, b) and pg.supported(m, b)
             and (not with_v or pa.supported(vtop.shape[1], b)))
    # Compiled mesh path: fuse the apply only (exchange stays the caller's
    # ppermute ring hop).
    mesh_fused = axis_name is not None and not interpret
    if exchange is None:
        if batch > 1:
            exchange = lambda t, b_: sched.rotate_blocks(t, b_, batch)
        else:
            exchange = sched.rotate_blocks
    if n_rounds is None:
        n_rounds = sched.num_rounds(2 * k_per)
    blocks = jnp.concatenate([top, bot], axis=0)
    vblocks = jnp.concatenate([vtop, vbot], axis=0) if with_v else None
    self_out = self_round(
        blocks, vblocks, dmax2, rtol, interpret=interpret, polish=polish,
        bf16_gram=bf16_gram, axis_name=axis_name, apply_x3=apply_x3,
        return_rotated=telemetry, batch=batch)
    if telemetry:
        blocks, vblocks, rel_self, cnt0 = self_out
    else:
        blocks, vblocks, rel_self = self_out
        cnt0 = None
    top, bot = blocks[:k], blocks[k:]
    if with_v:
        vtop, vbot = vblocks[:k], vblocks[k:]

    if not with_v:
        vtop = vbot = jnp.zeros((k, 0, b), top.dtype)

    if fused:
        # Gram-carried fused loop: one bootstrap panel, then every rotate
        # round is rotation kernel + fused apply/exchange/gram.
        with scope("gram"):
            g0 = pg.gram_pairs(top, bot, bf16=bf16_gram)

        def body(carry, _):
            top, bot, vtop, vbot, g, mx = carry[:6]
            out = cross_round_fused(
                top, bot, vtop if with_v else None,
                vbot if with_v else None, g, dmax2, rtol, polish=polish,
                bf16_gram=bf16_gram, apply_x3=apply_x3,
                return_rotated=telemetry, batch=batch)
            top, bot, nvt, nvb, g, stat = out[:6]
            if with_v:
                vtop, vbot = nvt, nvb
            new = (top, bot, vtop, vbot, g, jnp.maximum(mx, stat))
            if telemetry:
                new += (carry[6] + out[6],)
            return new, None

        init = (top, bot, vtop, vbot, g0, rel_self.astype(jnp.float32))
        if telemetry:
            init += (cnt0,)
        carry, _ = jax.lax.scan(body, init, None, length=n_rounds)
        top, bot, vtop, vbot, _, off = carry[:6]
        out = (top, bot, (vtop if with_v else None),
               (vbot if with_v else None), off)
        return out + (carry[6],) if telemetry else out

    def body(carry, _):
        top, bot, vtop, vbot, mx = carry[:5]
        out = cross_round(
            top, bot, vtop if with_v else None, vbot if with_v else None,
            dmax2, rtol, interpret=interpret,
            polish=polish, bf16_gram=bf16_gram, axis_name=axis_name,
            fused_exchange=False, fused_apply=mesh_fused, apply_x3=apply_x3,
            return_rotated=telemetry, batch=batch)
        top, bot, nvt, nvb, stat = out[:5]
        if with_v:
            vtop, vbot = nvt, nvb
        with scope("exchange"):
            top, bot = exchange(top, bot)
            if with_v:
                vtop, vbot = exchange(vtop, vbot)
        new = (top, bot, vtop, vbot, jnp.maximum(mx, stat))
        if telemetry:
            new += (carry[5] + out[5],)
        return new, None

    init = (top, bot, vtop, vbot, rel_self.astype(jnp.float32))
    if telemetry:
        init += (cnt0,)
    carry, _ = jax.lax.scan(body, init, None, length=n_rounds)
    top, bot, vtop, vbot, off = carry[:5]
    off = _mesh_max(off, axis_name)
    out = (top, bot, (vtop if with_v else None),
           (vbot if with_v else None), off)
    return out + (carry[5],) if telemetry else out


def _global_dmax2(top, bot, batch: int = 1):
    acc = jnp.promote_types(top.dtype, jnp.float32)
    if batch > 1:
        # Per-matrix deflation scales of a batched stack: one matrix's
        # huge columns must not deflate a small-normed neighbor.
        t2 = jnp.sum(top.astype(acc) ** 2, axis=1).reshape(batch, -1)
        b2 = jnp.sum(bot.astype(acc) ** 2, axis=1).reshape(batch, -1)
        return jnp.maximum(jnp.max(t2, axis=1), jnp.max(b2, axis=1))
    return jnp.maximum(jnp.max(jnp.sum(top.astype(acc) ** 2, axis=1)),
                       jnp.max(jnp.sum(bot.astype(acc) ** 2, axis=1)))


def should_continue(off, prev_off, sweeps, *, tol, max_sweeps,
                    stall_detection=True, stall_gate=1e-4,
                    stall_shrink=0.25, nonfinite=None):
    """THE sweep-loop predicate — one definition shared by every iterate
    loop (solver._should_continue, `iterate_phase`, the mesh solver's
    while_loops): continue while the coupling is above ``tol``, the sweep
    counter is under ``max_sweeps``, and the loop has not stalled. Stall:
    once the coupling is below ``stall_gate`` (the phase's endgame) a sweep
    that fails to shrink it past ``stall_shrink * prev_off`` means the
    phase's roundoff floor is reached. The gate/shrink constants are the
    caller's — they are measured per criterion/regime, not derived (a
    mistuned threshold cost 100x sigma error; see solver._should_continue
    for the per-criterion values). ``nonfinite``: the loop's health word —
    stop immediately once non-finite state is detected (sweeping NaNs to
    the budget is pure waste; the caller surfaces SolveStatus.NONFINITE)."""
    go = jnp.logical_and(sweeps < max_sweeps, off > tol)
    if stall_detection:
        stalled = jnp.logical_and(off < stall_gate,
                                  off > stall_shrink * prev_off)
        go = jnp.logical_and(go, jnp.logical_not(stalled))
    if nonfinite is not None:
        go = jnp.logical_and(go, jnp.logical_not(nonfinite))
    return go


# Bulk-phase target for the mixed bf16x3-compute regime (solver
# "mixed_bulk"): couplings below this are at the split regime's drift
# floor (~eps_bf16^2 per apply, random-walked over a solve's ~n applies)
# — converging the bulk further is wasted work, the f32 polish re-measures
# from the reconstituted state anyway.
MIXED_TOL = 1e-3


def iterate_phase(top, bot, vtop, vbot, *, stop_tol, rtol, max_sweeps,
                  interpret, polish, bf16_gram, stall_detection=True,
                  stall_gate=1e-4, stall_shrink=0.25, start_sweeps=0,
                  apply_x3=False, telemetry=False, stage="single",
                  nonfinite0=None, chaos_nan_sweep=None):
    """`lax.while_loop` of `sweep`s until the masked coupling drops below
    ``stop_tol`` (or the TOTAL sweep counter — which starts at
    ``start_sweeps`` — hits ``max_sweeps``, or a stall, or non-finite
    state is detected). Stall: once the coupling is below ``stall_gate``
    (the phase's endgame) and a sweep fails to shrink it by
    1/``stall_shrink``, the phase's floor is reached.
    Returns (top, bot, vtop, vbot, off, sweeps, nonfinite).

    The health word ``nonfinite`` rides the existing per-sweep reductions
    (``isfinite`` of the dmax2 deflation scale — NaN AND Inf in the work
    stacks both poison a max-of-squares — and of the sweep statistic);
    the deflation mask alone would silently DROP NaN columns from the
    masked stat, which is exactly the "poisoned solve reads converged"
    failure this closes. ``nonfinite0`` seeds the flag from an earlier
    phase. ``chaos_nan_sweep`` (static): fault-injection hook — poison
    one work element at that sweep counter (`resilience.chaos`); None
    (production) traces no injection code at all.

    ``telemetry`` (static): emit one `obs.metrics` "sweep" event per loop
    iteration — post-sweep off-norm and the rotation-round counters —
    tagged with ``stage``. Off by default; the disabled trace is the seed
    trace.
    """
    from ..resilience import chaos as _chaos
    with_v = vtop is not None
    k = top.shape[0]
    if vtop is None:
        vtop = vbot = jnp.zeros((k, 0, top.shape[2]), top.dtype)
    n_rounds_total = 1 + sched.num_rounds(2 * k)   # self + cross rounds
    # Label events with the path sweep() will actually take (same
    # predicate as its fused apply+exchange+gram gate) — interpret-mode /
    # oversized-panel solves run the unfused kernel rounds.
    m_rows, b = top.shape[1], top.shape[2]
    path = ("fused" if (not interpret and pa.supported(m_rows, b)
                        and pg.supported(m_rows, b)
                        and (not with_v or pa.supported(vtop.shape[1], b)))
            else "kernel")

    def cond(st):
        _, _, _, _, off, prev_off, sweeps, nonfinite = st
        return should_continue(off, prev_off, sweeps, tol=stop_tol,
                               max_sweeps=max_sweeps,
                               stall_detection=stall_detection,
                               stall_gate=stall_gate,
                               stall_shrink=stall_shrink,
                               nonfinite=nonfinite)

    def body(st):
        top, bot, vtop, vbot, prev_off, _, sweeps, nonfinite = st
        if chaos_nan_sweep is not None:
            top = _chaos.poison(top, sweeps, chaos_nan_sweep)
        dmax2 = _global_dmax2(top, bot)
        out = sweep(
            top, bot, vtop if with_v else None, vbot if with_v else None,
            dmax2, rtol, interpret=interpret, polish=polish,
            bf16_gram=bf16_gram, apply_x3=apply_x3, telemetry=telemetry)
        top, bot, nvt, nvb, off = out[:5]
        nonfinite = nonfinite | ~jnp.isfinite(dmax2) | ~jnp.isfinite(off)
        if telemetry:
            metrics.emit("sweep",
                         meta={"path": path, "stage": stage,
                               "rounds_total": n_rounds_total},
                         sweep=sweeps + 1, off_rel=off,
                         rounds_rotated=out[5])
        if not with_v:
            nvt, nvb = st[2], st[3]
        return (top, bot, nvt, nvb, off, prev_off, sweeps + 1, nonfinite)

    inf = jnp.float32(jnp.inf)
    nf0 = (jnp.zeros((), jnp.bool_) if nonfinite0 is None
           else jnp.asarray(nonfinite0, jnp.bool_))
    state = (top, bot, vtop, vbot, inf, inf,
             jnp.asarray(start_sweeps, jnp.int32), nf0)
    top, bot, vtop, vbot, off, _, sweeps, nonfinite = jax.lax.while_loop(
        cond, body, state)
    return (top, bot, (vtop if with_v else None),
            (vbot if with_v else None), off, sweeps, nonfinite)


def iterate_batched(top, bot, vtop, vbot, *, batch, tol, max_sweeps,
                    interpret, polish, stall_detection=True,
                    start_sweeps=0, msweeps0=None, nonfinite0=None,
                    chaos_nan_sweep=None):
    """Batched sweep loop (the `solver.svd_batched` lane): the stacks hold
    ``batch`` matrices back to back along the pair axis and ONE fused
    while_loop sweeps them all — for the latency-bound rotation kernel
    this is the whole win (B matrices ~ one latency chain, PROFILE.md
    item 1).

    Convergence bookkeeping is per matrix: the carry's off-norm /
    prev-off / nonfinite are ``(batch,)`` vectors plus a per-matrix sweep
    counter, the predicate is `should_continue` elementwise, and the loop
    runs while ANY member wants another sweep. A member that converged /
    stalled / went non-finite keeps riding the stacked sweeps (its
    rotations are near-identity; a poisoned member's NaNs stay inside its
    own block-diagonal segment) but its statistics freeze at its stopping
    sweep, so one slow or NaN-poisoned member never perturbs a neighbor's
    reported convergence. Returns
    (top, bot, vtop, vbot, off (batch,), sweeps (batch,),
    nonfinite (batch,)).

    ``start_sweeps`` / ``msweeps0`` / ``nonfinite0`` seed the stack-level
    counter, per-member sweep counts, and per-member health word from an
    earlier phase (the blocked-rotation lane's `iterate_block_batched`
    bulk), so ``max_sweeps`` stays a TOTAL budget across phases.
    """
    from ..resilience import chaos as _chaos
    with_v = vtop is not None
    kb = top.shape[0]
    if vtop is None:
        vtop = vbot = jnp.zeros((kb, 0, top.shape[2]), top.dtype)

    def go_mask(off, prev_off, sweeps, nonfinite):
        return should_continue(off, prev_off, sweeps, tol=tol,
                               max_sweeps=max_sweeps,
                               stall_detection=stall_detection,
                               nonfinite=nonfinite)

    def cond(st):
        _, _, _, _, off, prev_off, sweeps, _, nonfinite = st
        return jnp.any(go_mask(off, prev_off, sweeps, nonfinite))

    def body(st):
        top, bot, vtop, vbot, off, prev_off, sweeps, msweeps, nonfinite = st
        go = go_mask(off, prev_off, sweeps, nonfinite)
        if chaos_nan_sweep is not None:
            # Poisons element [0, 0, 0] — member 0's first block — so the
            # chaos lane can assert a NONFINITE member with OK neighbors.
            top = _chaos.poison(top, sweeps, chaos_nan_sweep)
        dmax2 = _global_dmax2(top, bot, batch=batch)
        out = sweep(top, bot, vtop if with_v else None,
                    vbot if with_v else None, dmax2, tol,
                    interpret=interpret, polish=polish, bf16_gram=False,
                    batch=batch)
        top, bot, nvt, nvb, off_new = out[:5]
        nf_new = ~jnp.isfinite(dmax2) | ~jnp.isfinite(off_new)
        nonfinite = nonfinite | (go & nf_new)
        prev_off = jnp.where(go, off, prev_off)
        off = jnp.where(go, off_new, off)
        msweeps = msweeps + go.astype(jnp.int32)
        if not with_v:
            nvt, nvb = st[2], st[3]
        return (top, bot, nvt, nvb, off, prev_off, sweeps + 1, msweeps,
                nonfinite)

    inf = jnp.full((batch,), jnp.inf, jnp.float32)
    msw0 = (jnp.zeros((batch,), jnp.int32) if msweeps0 is None
            else jnp.asarray(msweeps0, jnp.int32))
    nf0 = (jnp.zeros((batch,), jnp.bool_) if nonfinite0 is None
           else jnp.asarray(nonfinite0, jnp.bool_))
    state = (top, bot, vtop, vbot, inf, inf,
             jnp.asarray(start_sweeps, jnp.int32), msw0, nf0)
    (top, bot, vtop, vbot, off, _, _, msweeps,
     nonfinite) = jax.lax.while_loop(cond, body, state)
    return (top, bot, (vtop if with_v else None),
            (vbot if with_v else None), off, msweeps, nonfinite)


def iterate(top, bot, vtop, vbot, *, tol, max_sweeps, interpret, polish,
            bulk_bf16, stall_detection=True, start_sweeps=0,
            telemetry=False, stage="single", nonfinite0=None,
            chaos_nan_sweep=None):
    """Sweep until the masked coupling drops below ``tol``.

    Two phases when ``bulk_bf16``: bf16-Gram sweeps down to BULK_TOL, then
    full-precision sweeps to ``tol``. ``max_sweeps`` is a TOTAL budget
    (including ``start_sweeps`` already spent by the caller — the mixed
    bulk phase). Stall constants are solver._should_continue's rel branch.
    Returns (top, bot, vtop, vbot, off, sweeps, nonfinite) — the health
    word chains through both phases (see `iterate_phase`).
    """
    kwargs = dict(max_sweeps=max_sweeps, interpret=interpret, polish=polish,
                  stall_detection=stall_detection, telemetry=telemetry,
                  chaos_nan_sweep=chaos_nan_sweep)
    bulk_off = jnp.float32(jnp.inf)
    bulk_sweeps = jnp.asarray(start_sweeps, jnp.int32)
    nonfinite = nonfinite0
    if bulk_bf16:
        top, bot, vtop, vbot, bulk_off, bulk_sweeps, nonfinite = \
            iterate_phase(
                top, bot, vtop, vbot, stop_tol=jnp.float32(BULK_TOL),
                rtol=BULK_TOL, bf16_gram=True, start_sweeps=bulk_sweeps,
                stage="bulk_bf16", nonfinite0=nonfinite, **kwargs)
    top, bot, vtop, vbot, off, sweeps, nonfinite = iterate_phase(
        top, bot, vtop, vbot, stop_tol=tol, rtol=tol, bf16_gram=False,
        start_sweeps=bulk_sweeps, stage=stage, nonfinite0=nonfinite,
        **kwargs)
    # If the bulk phase consumed the whole budget, report its statistic
    # rather than the untouched inf carry (cf. solver._svd_padded hybrid).
    off = jnp.where(sweeps > bulk_sweeps, off, bulk_off)
    return top, bot, vtop, vbot, off, sweeps, nonfinite
