"""Batched block-pair orthogonalization — the MXU-facing numerical core.

TPU-native replacement for the reference's per-pair hot loop
(reference: lib/JacobiMethods.cu:437-604 "local pair solver"): the reference
computes Gram scalars with a host dot-product loop (lib/JacobiMethods.cu:450-459),
a scalar Schur rotation (lib/JacobiMethods.cu:466-478), and then ships two
columns to the GPU and back per rotation (8 memcpys + 2 launches,
lib/JacobiMethods.cu:479-510). Here one round processes *all* k block pairs at
once, resident on device:

  X   = [A_I | A_J]               (k, m, 2b)   concat of the paired blocks
  G   = X^T X                     (k, 2b, 2b)  batched matmul -> MXU
  Q   = eigvecs(G) desc.          (k, 2b, 2b)  batched eigh
  X'  = X Q,  V' = V Q                         batched matmuls -> MXU

Post-multiplying by the eigenvectors of the Gram matrix makes the 2b columns
of each pair exactly orthogonal (one-sided block Jacobi with an exact
subproblem solve); ordering eigenvalues descending embeds de-Rijk-style norm
sorting, which accelerates convergence. The generalization from the
reference's b = 1 Givens rotation (lib/JacobiMethods.cu:1483-1491) to b >= 128
blocks is what turns this memory-bound scalar update into MXU matmuls
(SURVEY.md section 7, "hard parts": block-Jacobi formulation).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import _compat
from ..obs.scopes import scope


def _precision(name: str) -> jax.lax.Precision:
    return {
        "highest": jax.lax.Precision.HIGHEST,
        "high": jax.lax.Precision.HIGH,
        "default": jax.lax.Precision.DEFAULT,
    }[name]


def pair_gram(x: jax.Array, gram_dtype, precision: str) -> jax.Array:
    """Batched Gram matrices G = X^T X for X of shape (k, m, 2b)."""
    xg = x.astype(gram_dtype)
    return jnp.einsum(
        "kmi,kmj->kij", xg, xg,
        precision=_precision(precision),
        preferred_element_type=gram_dtype,
    )


def off_diag_stats(g: jax.Array, b: int,
                   dmax2: Optional[jax.Array] = None,
                   criterion: str = "rel") -> Tuple[jax.Array, jax.Array]:
    """(stat, off2): convergence statistics from a round's Gram matrices.

    Two criteria (``criterion``):

    * ``"rel"`` — the dgesvj-style scaled coupling ``max_{i<j} |g_ij| /
      sqrt(g_ii g_jj)`` over every column pair inside each 2b-wide Gram
      matrix — the cosine of the angle between columns, so it bounds the
      orthogonality of U columns independently of conditioning. Columns at
      the roundoff floor relative to the largest column are deflated from
      the statistic (their directions are noise and can never converge).
      Drives the high-relative-accuracy ("qr-svd") path.
    * ``"abs"`` — ``max_{i<j} |g_ij| / dmax2``: couplings scaled by the
      GLOBAL max squared column norm (~sigma_max^2). This is the LAPACK
      dgesvd / XLA-svd accuracy class (|sigma - sigma_true| <~ eps *
      sigma_max): cheap to converge because an eigh-quality rotation always
      reaches it — no scalar cleanup sweeps needed. Default for the fast
      ("gram-eigh") path.

    ``off2`` is the plain squared F-norm of the coupling blocks (diagnostic).

    The "rel" statistic is what the reference computes per pair as
    ``convergence_value = |alpha|/sqrt(beta*gamma)`` and then discards
    (lib/JacobiMethods.cu:462,547; dead because maxIterations = 1,
    lib/JacobiMethods.cu:234) — here it actually drives the sweep loop.

    ``dmax2`` must be the GLOBAL max squared column norm. Under sharding a
    device's local batch can momentarily hold only numerically-null
    (padding/deflated) columns; a batch-local max would then declare them
    live relative to each other and their mutual cosines (~O(1) noise)
    would stall the convergence statistic. Callers on a mesh pmax it.
    """
    acc = jnp.float32 if g.dtype in (jnp.bfloat16, jnp.float16) else g.dtype
    g = g.astype(acc)
    off2 = jnp.sum(jnp.square(g[:, :b, b:]))
    d2 = jnp.diagonal(g, axis1=-2, axis2=-1)                # (k, 2b)
    n2 = g.shape[-1]
    eps = jnp.finfo(g.dtype).eps
    if dmax2 is None:
        dmax2 = jnp.max(d2)
    dmax2 = dmax2.astype(acc)
    no_diag = (1.0 - jnp.eye(n2, dtype=acc))[None]
    if criterion == "abs":
        c = jnp.abs(g) / jnp.maximum(dmax2, jnp.finfo(acc).tiny)
        stat = jnp.max(c * no_diag)
        return stat, off2
    d = jnp.sqrt(jnp.maximum(d2, jnp.finfo(acc).tiny))
    c = jnp.abs(g) / (d[:, :, None] * d[:, None, :])
    c = c * no_diag
    null_thresh = dmax2 * (n2 * eps) ** 2
    live = d2 > null_thresh                                  # (k, 2b)
    pair_live = live[:, :, None] & live[:, None, :]
    max_rel = jnp.max(jnp.where(pair_live, c, jnp.zeros_like(c)))
    return max_rel, off2


def _nearest_identity_order(q: jax.Array) -> jax.Array:
    """Permute/sign eigenvector columns so Q is as close to I as possible.

    eigh orders columns by eigenvalue, which gives Q a permutation component
    even when G is nearly diagonal. A rotation with a permutation component
    moves column *contents* between tournament slots, which lets strongly
    coupled columns chase each other around the ring and never meet — the
    sweep stalls (observed: off-norm frozen while per-pair coupling -> 0).
    Reordering each column to the slot of its dominant entry (and fixing the
    sign) makes Q -> I as G -> diagonal: every rotation is then a small-angle
    rotation, the classical convergence condition for cyclic Jacobi — the
    block generalization of the reference's always-small-angle Rutishauser
    t = sgn(tau)/(|tau| + sqrt(1+tau^2)) choice (lib/JacobiMethods.cu:466-478).
    """
    dom = jnp.argmax(jnp.abs(q), axis=-2)                      # (k, 2b)
    perm = jnp.argsort(dom, axis=-1)                           # (k, 2b)
    q = jnp.take_along_axis(q, perm[:, None, :], axis=-1)
    dom_p = jnp.take_along_axis(dom, perm, axis=-1)
    lead = jnp.take_along_axis(q, dom_p[:, None, :], axis=-2)  # (k, 1, 2b)
    signs = jnp.sign(lead)
    return q * jnp.where(signs == 0, jnp.ones_like(signs), signs)


def _rotate_cols(top: jax.Array, bot: jax.Array):
    """Tournament rotation on the *last* axis (column pairs of a panel)."""
    if top.shape[-1] == 1:
        return top, bot
    new_top = jnp.concatenate([top[..., :1], bot[..., :1], top[..., 1:-1]], axis=-1)
    new_bot = jnp.concatenate([bot[..., 1:], top[..., -1:]], axis=-1)
    return new_top, new_bot


def _maybe_pvary(x, axis_name):
    """Mark a replicated loop-carry init as device-varying under shard_map.

    shard_map's variance checking (check_vma) requires scan carries to keep a
    consistent varying-axes type; inits built from constants (identity
    blocks, zero accumulators) start replicated and must be explicitly
    `pvary`'d onto the mesh axis. Outside shard_map (axis_name None) this is
    the identity.
    """
    if axis_name is None:
        return x
    return _compat.pcast(x, (axis_name,), to="varying")


def givens_cleanup_sweep(p: jax.Array, dmax2: jax.Array,
                         axis_name: Optional[str] = None):
    """One scalar one-sided Jacobi sweep over the columns of each panel.

    ``p``: (k, n2, n2) batch of small panels (the rotated R factors). Runs a
    full tournament of n2-1 rounds of scalar Givens rotations, with (c, s)
    from the Rutishauser/Golub-Van-Loan formula the reference uses
    (tau = (gamma-beta)/(2 alpha), t = sgn(tau)/(|tau|+sqrt(1+tau^2));
    lib/JacobiMethods.cu:466-478, lib/Utils.cu:130-165). Returns
    ``(p', q, max_rel)`` where ``q`` is the accumulated orthogonal transform
    (p' = p @ q) and ``max_rel`` the largest scaled coupling seen (deflated
    columns masked via ``dmax2``, the global max squared column norm).

    Why this exists: XLA's TPU svd/eigh converge to an *absolute* tolerance
    (relative to sigma_max), so couplings between small-norm columns are
    left unresolved — the block rotation comes back as exact identity while
    scaled couplings sit at 1e-2, and the sweep loop spins. Scalar rotations
    computed directly from (alpha, beta, gamma) are accurate at *any* scale
    (the reason sgesvj delivers high relative accuracy); one such sweep after
    the block solve restores sgesvj-grade convergence on TPU.
    """
    k, n2, _ = p.shape
    if n2 < 2:
        return p, jnp.broadcast_to(jnp.eye(n2, dtype=p.dtype), p.shape), jnp.zeros((), jnp.float32)
    b2 = n2 // 2
    eps = jnp.finfo(p.dtype).eps
    tiny = jnp.finfo(p.dtype).tiny
    null_thresh = dmax2.astype(p.dtype) * (n2 * eps) ** 2

    eye = jnp.broadcast_to(jnp.eye(n2, dtype=p.dtype), (k, n2, n2))

    def body(carry, _):
        ptop, pbot, qtop, qbot, max_rel = carry
        alpha = jnp.sum(ptop * pbot, axis=1)                  # (k, b2)
        beta = jnp.sum(ptop * ptop, axis=1)
        gamma = jnp.sum(pbot * pbot, axis=1)
        denom = jnp.sqrt(jnp.maximum(beta, tiny)) * jnp.sqrt(jnp.maximum(gamma, tiny))
        rel = jnp.abs(alpha) / jnp.maximum(denom, tiny)
        live = (beta > null_thresh) & (gamma > null_thresh)
        max_rel = jnp.maximum(
            max_rel, jnp.max(jnp.where(live, rel, 0.0)).astype(jnp.float32))
        # Rutishauser small-angle rotation; skip numerically-null couplings.
        safe_a = jnp.where(jnp.abs(alpha) > tiny, alpha, jnp.ones_like(alpha))
        tau = (gamma - beta) / (2.0 * safe_a)
        sgn = jnp.where(tau >= 0, 1.0, -1.0).astype(p.dtype)
        t = sgn / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
        c = 1.0 / jnp.sqrt(1.0 + t * t)
        s = t * c
        rot = jnp.abs(alpha) > tiny
        c = jnp.where(rot, c, jnp.ones_like(c))[:, None, :]
        s = jnp.where(rot, s, jnp.zeros_like(s))[:, None, :]
        ptop, pbot = c * ptop - s * pbot, s * ptop + c * pbot
        qtop, qbot = c * qtop - s * qbot, s * qtop + c * qbot
        ptop, pbot = _rotate_cols(ptop, pbot)
        qtop, qbot = _rotate_cols(qtop, qbot)
        return (ptop, pbot, qtop, qbot, max_rel), None

    init = (p[..., :b2], p[..., b2:],
            _maybe_pvary(eye[..., :b2], axis_name),
            _maybe_pvary(eye[..., b2:], axis_name),
            _maybe_pvary(jnp.zeros((), jnp.float32), axis_name))
    (ptop, pbot, qtop, qbot, max_rel), _ = jax.lax.scan(body, init, None, length=n2 - 1)
    # A full tournament cycle returns the layout to the initial order.
    return (jnp.concatenate([ptop, pbot], axis=-1),
            jnp.concatenate([qtop, qbot], axis=-1), max_rel)


def _newton_schulz_polish(q: jax.Array, precision) -> jax.Array:
    """One Newton-Schulz step q <- q(3I - q^T q)/2: restores orthogonality of
    an almost-orthogonal q to the dtype floor (TPU svd/eigh return rotations
    that are only ~1e-5/1e-6 orthogonal in f32; applying hundreds of them
    would erode U/V)."""
    n2 = q.shape[-1]
    g = jnp.einsum("kij,kil->kjl", q, q, precision=precision,
                   preferred_element_type=q.dtype)
    return jnp.einsum("kij,kjl->kil", q,
                      1.5 * jnp.eye(n2, dtype=q.dtype) - 0.5 * g,
                      precision=precision, preferred_element_type=q.dtype)


def _orthogonalize_pairs_impl(top, bot, vtop, vbot, *, precision, gram_dtype_name,
                              with_v, method, dmax2=None, criterion="rel",
                              axis_name=None):
    b = top.shape[-1]
    gram_dtype = jnp.dtype(gram_dtype_name)
    x = jnp.concatenate([top, bot], axis=-1)  # (k, m, 2b)
    prec = _precision(precision)
    if method == "gram-eigh":
        # Fast path: Gram + eigh — MXU matmuls + one batched eigh, no QR, no
        # scalar cleanup. Squares the condition number, so it delivers
        # absolute (LAPACK-dgesvd-class) accuracy and should run with
        # criterion="abs"; under the "rel" criterion it stalls once couplings
        # of small-norm columns hit the eigh's absolute-accuracy floor.
        g = pair_gram(x, gram_dtype, precision)
        max_rel, off2 = off_diag_stats(g, b, dmax2, criterion)
        _, q = jnp.linalg.eigh(g)
        q = _nearest_identity_order(q).astype(gram_dtype)
        q = _newton_schulz_polish(q, prec)
    elif method == "qr-svd":
        # Stable path: R = qr(X).R is a backward-stable small image of the
        # pair (conditioning enters linearly, not squared); the rotation is
        # the right singular factor of R. This is the block analogue of why
        # scalar sgesvj stays accurate in f32 where Gram-based methods fail.
        r = jnp.linalg.qr(x.astype(gram_dtype), mode="r")  # (k, 2b, 2b)
        g = jnp.einsum("kij,kil->kjl", r, r, precision=prec,
                       preferred_element_type=gram_dtype)
        max_rel, off2 = off_diag_stats(g, b, dmax2, criterion)
        _, _, vt = jnp.linalg.svd(r)
        q = _nearest_identity_order(vt.mT).astype(gram_dtype)
        q = _newton_schulz_polish(q, prec)
        # Scalar cleanup: XLA's svd on TPU resolves couplings only to an
        # absolute (sigma_max-relative) tolerance; one scale-independent
        # Givens sweep on the rotated panel finishes the job (see
        # givens_cleanup_sweep). Without it the TPU sweep loop stalls with
        # block rotations that come back as exact identity.
        r2 = jnp.einsum("kij,kjl->kil", r, q, precision=prec,
                        preferred_element_type=gram_dtype)
        if dmax2 is None:
            dmax2 = jnp.max(jnp.diagonal(g, axis1=-2, axis2=-1))
        _, q2, _ = givens_cleanup_sweep(r2, dmax2.astype(gram_dtype),
                                        axis_name=axis_name)
        q = jnp.einsum("kij,kjl->kil", q, q2, precision=prec,
                       preferred_element_type=gram_dtype)
    else:
        raise ValueError(f"unknown pair solver method: {method!r}")
    prec = _precision(precision)
    xn = jnp.einsum("kmi,kij->kmj", x.astype(gram_dtype), q, precision=prec,
                    preferred_element_type=gram_dtype).astype(top.dtype)
    new_top, new_bot = xn[..., :b], xn[..., b:]
    if with_v:
        v = jnp.concatenate([vtop, vbot], axis=-1)
        vn = jnp.einsum("kmi,kij->kmj", v.astype(gram_dtype), q, precision=prec,
                        preferred_element_type=gram_dtype).astype(vtop.dtype)
        new_vtop, new_vbot = vn[..., :b], vn[..., b:]
    else:
        new_vtop, new_vbot = vtop, vbot
    return new_top, new_bot, new_vtop, new_vbot, max_rel, off2


def orthogonalize_pairs(
    top: jax.Array,
    bot: jax.Array,
    vtop: Optional[jax.Array],
    vbot: Optional[jax.Array],
    *,
    precision: str = "highest",
    gram_dtype=None,
    method: str = "qr-svd",
    dmax2: Optional[jax.Array] = None,
    criterion: str = "rel",
    axis_name: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array, Optional[jax.Array], Optional[jax.Array], jax.Array, jax.Array]:
    """Orthogonalize each (top[i], bot[i]) block pair; update V alongside.

    Args:
      top, bot: (k, m, b) stacks of paired column blocks of A.
      vtop, vbot: (k, n, b) stacks of the matching V blocks, or None when the
        caller does not accumulate V (NoVec paths).
      dmax2: GLOBAL max squared column norm, for the deflation gates. On a
        mesh this must be pmax'd across devices (see off_diag_stats); None
        falls back to the batch-local max (single-device semantics).
      axis_name: mesh axis when called inside shard_map, so internal loop
        carries can be `pvary`'d for the variance checker; None otherwise.

    Returns:
      (top', bot', vtop', vbot', max_rel, off2) — convergence statistics
      measured on this round's Gram matrices *before* rotation (see
      `off_diag_stats`).
    """
    if gram_dtype is None:
        # The shared accumulation-boundary default (tune.tables
        # .default_gram_dtype — also `solver._resolve_options`'s), so the
        # block-solver lane cannot drift from the fused lane's declared
        # MIXED_PRECISION_BOUNDARIES contract.
        from ..tune import tables as _tables
        gram_dtype = _tables.default_gram_dtype(top.dtype)
    with_v = vtop is not None
    if not with_v:
        # Placeholders keep a single jitted signature; zero-size arrays cost
        # nothing and the with_v=False branch never touches them.
        vtop = jnp.zeros((top.shape[0], 0, top.shape[2]), top.dtype)
        vbot = vtop
    # svdj/pair_solve: the XLA block-solver hot region of the PROFILE.md
    # component map (obs/scopes.py) — coverage enforced by GRAFT005.
    with scope("pair_solve"):
        new_top, new_bot, new_vtop, new_vbot, max_rel, off2 = _orthogonalize_pairs_impl(
            top, bot, vtop, vbot,
            precision=precision,
            gram_dtype_name=jnp.dtype(gram_dtype).name,
            with_v=with_v,
            method=method,
            dmax2=dmax2,
            criterion=criterion,
            axis_name=axis_name,
        )
    if not with_v:
        new_vtop = new_vbot = None
    return new_top, new_bot, new_vtop, new_vbot, max_rel, off2
