"""Randomized range-finder + blocked tall-skinny QR (TSQR).

The two primitives behind the truncated/rectangular solver lanes
(`solver.svd_topk` / `solver.svd_tall`):

  * :func:`tsqr` — a blocked, tree-reduction tall-skinny QR: the input's
    rows split into static chunks, each chunk gets its own reduced QR,
    the stacked per-chunk R factors recurse until one dense QR closes
    the tree, and the thin Q is recombined chunk-wise
    (``Q_chunk = Q_i @ Q2_i``). No step ever touches a buffer taller
    than ``chunk`` rows or wider than ``n`` columns, and in particular
    no square m x m factor is ever materialized — the memory-locality
    property that lets the Drmac preconditioner
    (`solver._precondition_qr`) and the mesh solver handle genuinely
    tall m >> n inputs, and that GSPMD can partition chunk-wise on a
    mesh (the chunked-QR collectives ride OUTSIDE the fused sweep loop,
    so the sharded round loop's collective budget is unchanged —
    `config.COLLECTIVE_BUDGET`).
  * :func:`sketch_project` — the Halko-style randomized range finder: a
    SEEDED Gaussian sketch ``Y = A @ Omega`` (deterministic: the seed is
    a static argument, so two solves of the same problem see the same
    sketch and the jit cache key carries it), optional power iterations
    ``Y <- A (A^T Q(Y))`` for spectral-decay-poor inputs (each
    stabilized through :func:`tsqr` — unstabilized powers lose the
    small-singular-value directions to roundoff), then the projected
    matrix ``B = Q^T A`` returned TRANSPOSED as the tall (n, l) input
    the existing Jacobi core consumes. Cost is O(mnl) with
    l = k + oversample — the whole point: the O(n^3) full decomposition
    is never done for a top-k request.

Accuracy contract (documented in README "Workloads"): with
``A = U S V^T``, the top-k singular values of ``B`` match those of ``A``
up to the tail-energy term of Halko et al. — exact for exactly-rank-k
input, relative error ~ (s_{l+1}/s_k)^(2q+1)-class otherwise, so
decaying spectra are accurate at q = 0-1 and flat spectra keep their
VALUES exact (any l-dimensional subspace of a flat spectrum carries the
same sigmas) while their vectors are arbitrary within the tie.

Both functions are pure trace-time constructions (static shapes/loop
counts); `solver` wraps them in the jitted entries the retrace budgets
name (`config.RETRACE_BUDGETS`).

NaN/Inf policy: a non-finite input poisons the sketch (`B` inherits NaN
through the matmuls/QR), and :func:`sketch_project` returns an explicit
``nonfinite`` flag probed on the SMALL projected matrix — the sketch
path's equivalent of the fused loops' in-graph health word, decoded by
the caller into `SolveStatus.NONFINITE`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..obs.scopes import scope

# The tall-aspect threshold shared with the tuning tables
# (tune.tables.TALL_ASPECT_RATIO): chunked TSQR engages inside the
# preconditioner from m >= 8n up (below it one dense reduced QR is
# cheaper than the tree).
TALL_RATIO = 8

# Default rows per TSQR chunk (the "tsqr chunk rows" tuning knob's
# builtin): small enough that a chunk QR stays cache/VMEM-local, large
# enough that the R-stack reduction tree stays shallow.
DEFAULT_CHUNK_ROWS = 2048


def default_chunk(m: int, n: int) -> int:
    """Heuristic chunk rows for an (m, n) TSQR: at least n (a reduced
    chunk QR needs rows >= cols for its R to be n x n), capped at
    :data:`DEFAULT_CHUNK_ROWS`, and never more than m/8 — so any input
    past the tall threshold (m >= 8n) actually runs the chunked tree
    rather than collapsing to the dense base case."""
    return max(int(n), min(DEFAULT_CHUNK_ROWS, -(-int(m) // TALL_RATIO)))


def tsqr(a: jax.Array, *, chunk: Optional[int] = None
         ) -> Tuple[jax.Array, jax.Array]:
    """Blocked tall-skinny QR: ``a = q @ r`` with ``q`` (m, n) thin
    orthonormal and ``r`` (n, n) upper triangular (up to row signs — QR
    is unique only up to a diagonal sign flip, which every caller here
    absorbs). Computed in the accumulation dtype
    ``promote_types(a.dtype, float32)`` (sub-f32 dtypes have no QR
    kernel); callers cast back as needed.

    ``chunk`` is the static rows-per-chunk (None = :func:`default_chunk`).
    Inputs short enough for one dense reduced QR (m <= max(chunk, 2n))
    take it directly — so calling :func:`tsqr` on a square or
    modestly-tall input is byte-equivalent to ``jnp.linalg.qr``.

    Rows are zero-padded up to a chunk multiple; a zero chunk's QR is
    (Q = I-slice, R = 0) and the zero rows of the stacked R make the
    reduction's matching Q2 rows zero for full-column-rank input, so the
    sliced-back thin Q stays orthonormal. (Exactly rank-deficient input
    can leak padding energy into the dropped rows — the same tie class
    the solver's rank-deficiency guard documents.)
    """
    m, n = a.shape
    acc = jnp.promote_types(a.dtype, jnp.float32)
    if chunk is None:
        chunk = default_chunk(m, n)
    # chunk >= 2n guarantees the reduction tree makes progress: each
    # level's stacked R has ceil(m/chunk)*n <= m/2 + n rows, strictly
    # fewer than m whenever the chunked branch is taken.
    chunk = max(int(chunk), 2 * int(n))
    if m <= max(chunk, 2 * n):
        with scope("tsqr"):
            q, r = jnp.linalg.qr(a.astype(acc))
        return q, r
    with scope("tsqr"):
        hi = jax.lax.Precision.HIGHEST
        c = -(-m // chunk)
        pad = c * chunk - m
        w = a.astype(acc)
        if pad:
            w = jnp.pad(w, ((0, pad), (0, 0)))
        blocks = w.reshape(c, chunk, n)
        qs, rs = jax.vmap(jnp.linalg.qr)(blocks)      # (c,chunk,n), (c,n,n)
    # Reduce the stacked R factors (c*n, n) — recursion keeps every
    # level's buffer at most chunk-rows tall; one extra level suffices
    # until c*n itself exceeds the chunk.
    q2, r = tsqr(rs.reshape(c * n, n), chunk=chunk)
    with scope("tsqr"):
        q = jnp.matmul(qs, q2.reshape(c, n, n), precision=hi)
        q = q.reshape(c * chunk, n)[:m]
    return q, r


def sketch_project(a: jax.Array, *, l: int, power_iters: int,
                   chunk: Optional[int] = None, seed: int = 0
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Halko randomized range finder + projection for a tall (m, n)
    input: returns ``(q, bt, nonfinite)`` with ``q`` (m, l) an
    orthonormal basis of the (power-iterated) sketch range, ``bt``
    (n, l) the TRANSPOSED projected matrix ``B^T = A^T Q`` — the tall
    input the existing Jacobi core consumes directly — and ``nonfinite``
    a scalar bool flag (NaN/Inf anywhere in the input reaches ``bt``
    through the matmul chain; probing the small projection costs O(nl)).

    With ``B^T = W S Z^T`` from the core, ``A ~= (Q Z) S W^T``: the
    lift ``U = Q @ Z`` is the caller's job (`solver._lift_q_jit`).

    Static arguments (all part of the caller's jit key): ``l`` the
    sketch width (k + oversample), ``power_iters`` the number of
    TSQR-stabilized power iterations, ``chunk`` the TSQR chunk rows,
    ``seed`` the sketch seed — resolution of all four goes through the
    tuning tables (`tune.tables`, knobs ``oversample`` /
    ``power_iters`` / ``tsqr_chunk``) so the choice is measured, not
    hand-picked.
    """
    m, n = a.shape
    if not 1 <= l <= min(m, n):
        raise ValueError(f"sketch width l={l} must satisfy "
                         f"1 <= l <= min(m, n) = {min(m, n)}")
    acc = jnp.promote_types(a.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    with scope("sketch"):
        aw = a.astype(acc)
        omega = jax.random.normal(jax.random.PRNGKey(seed), (n, l), acc)
        y = jnp.matmul(aw, omega, precision=hi)
    for _ in range(int(power_iters)):
        qy, _ = tsqr(y, chunk=chunk)
        with scope("sketch"):
            z = jnp.matmul(aw.T, qy, precision=hi)     # (n, l)
            y = jnp.matmul(aw, z, precision=hi)
    q, _ = tsqr(y, chunk=chunk)
    with scope("sketch"):
        bt = jnp.matmul(aw.T, q, precision=hi)         # (n, l) = B^T
        nonfinite = ~jnp.all(jnp.isfinite(bt))
        return q.astype(a.dtype), bt.astype(a.dtype), nonfinite
