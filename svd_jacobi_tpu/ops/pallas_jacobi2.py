"""Pallas TPU kernel v2: cross-pair Givens tournament on Gram panels.

The framework's device kernel — TPU-native replacement for the reference's
CUDA `jacobi_rotation` (reference: lib/JacobiMethods.cu:1483-1491, one pair
per launch with 8 host<->device memcpys around it). One call processes ALL
k panels of a round: for each [I | J] column-pair panel's Gram matrix
``G = [X|Y]^T [X|Y]`` it annihilates every cross pair (x_i, y_j) exactly
once — b cyclic steps of b disjoint scalar Givens rotations, pairing
``(x_i, y_{(i+t) mod b})`` at step t — and returns the accumulated
orthogonal transform Q (the caller applies Q to the tall panels and V on
the MXU).

Design notes (measured on TPU v5e, see PROFILE.md):

* The per-step cost of this kernel family is LATENCY-bound — a sequential
  dependency chain of small VPU ops — so the implementation minimizes
  chain depth, not FLOPs:
  - rotation angles come from the Rutishauser formula fed by the coupling
    diagonal alpha (one masked-sum reduction) and CARRIED column norms
    beta/gamma updated in closed form (no diagonal re-extraction);
  - angles are computed twice, in lane shape (1, b) for column transforms
    and sublane shape (b, 1) for row transforms — two short independent
    chains instead of one chain plus a relayout transpose;
  - the cyclic pairing moves ONLY the Y half (columns via a lane roll,
    rows via a sublane roll, `pltpu.roll`), not the whole tournament
    system; after b steps the layout is back in the original order, so Q
    maps original slots to original slots.
* No convergence statistic is computed in-kernel: the caller derives the
  dgesvj-style scaled-coupling stat from the (already materialized) Gram
  panel, which also lets it skip the whole round (`lax.cond`) when the
  panel is already converged — the threshold-Jacobi work taper.
* Within-block (self) pairs are covered by RECURSIVE HALVING with this
  same kernel: a width-w block is two width-w/2 half-blocks -> cross-pair
  the halves (w/2 steps), recurse. Total sequential rotation steps per
  full sweep: (n/b - 1) outer rounds * b steps + sum_{l} b/2^l = n - 1,
  the tournament-optimal count.

The grid runs over chunks of the panel batch so arbitrarily large rounds
stay within VMEM; panels inside a chunk are batched inside the kernel body
(a serial grid over panels would multiply the latency chain by k —
measured 2-3x slower at b <= 64).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


_TINY = 1e-30


def _rutishauser(alpha, beta, gamma):
    """Small-angle Givens (c, s) — the formula the reference inlines at
    lib/JacobiMethods.cu:466-478; identity on numerically-null couplings."""
    f32 = jnp.float32
    safe_a = jnp.where(jnp.abs(alpha) > _TINY, alpha, jnp.ones_like(alpha))
    tau = (gamma - beta) / (2.0 * safe_a)
    sgn = jnp.where(tau >= 0, f32(1.0), f32(-1.0))
    t = sgn / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
    c = jax.lax.rsqrt(1.0 + t * t)
    s = t * c
    rot = jnp.abs(alpha) > _TINY
    c = jnp.where(rot, c, f32(1.0))
    s = jnp.where(rot, s, f32(0.0))
    return c, s


def _roll_m1(x, axis):
    """Circular shift by -1 (element i takes element i+1) along ``axis``.

    Uses pltpu.roll inside the compiled kernel (single lane/sublane rotate);
    falls back to jnp.roll under the interpreter / outside Pallas.
    """
    try:
        from jax.experimental.pallas import tpu as pltpu
        return pltpu.roll(x, -1, axis)
    except Exception:
        return jnp.roll(x, -1, axis=axis)


def _cross_body(g, q, b, n_steps):
    """Pure function: run ``n_steps`` cyclic cross-rotation steps on the
    (kb, 2b, 2b) Gram panels ``g`` accumulating into ``q``. Returns (g, q).

    Runs identically inside the Pallas kernel (compiled) and as the
    reference implementation in tests.
    """
    f32 = jnp.float32
    dmask = (jax.lax.broadcasted_iota(jnp.int32, (b, b), 0)
             == jax.lax.broadcasted_iota(jnp.int32, (b, b), 1)).astype(f32)[None]

    def step(_, carry):
        g, q = carry
        # Angle inputs re-derived from the congruence-updated panel each
        # step: three independent masked-sum reductions (alpha from the
        # aligned coupling diagonal, beta/gamma from the block diagonals).
        # Mosaic cannot carry (kb,1,b) arrays across fori_loop iterations
        # ("Not implemented: Sublane broadcast"), so closed-form carried
        # norms are not an option here; the reductions run in parallel and
        # add little to the step's latency chain.
        alpha_l = jnp.sum(g[:, :b, b:] * dmask, axis=1)[:, None, :]
        beta_l = jnp.sum(g[:, :b, :b] * dmask, axis=1)[:, None, :]
        gamma_l = jnp.sum(g[:, b:, b:] * dmask, axis=1)[:, None, :]
        c_l, s_l = _rutishauser(alpha_l, beta_l, gamma_l)
        # Sublane-shaped copies for the row transform (Mosaic lowers this
        # transpose; lane-broadcasting sublane-shaped reductions it does not).
        c_s = c_l.transpose(0, 2, 1)
        s_s = s_l.transpose(0, 2, 1)

        # Congruence G <- J^T G J (columns then rows), Q <- Q J.
        gx, gy = g[:, :, :b], g[:, :, b:]
        g = jnp.concatenate([c_l * gx - s_l * gy, s_l * gx + c_l * gy], axis=2)
        hx, hy = g[:, :b, :], g[:, b:, :]
        g = jnp.concatenate([c_s * hx - s_s * hy, s_s * hx + c_s * hy], axis=1)
        qx, qy = q[:, :, :b], q[:, :, b:]
        q = jnp.concatenate([c_l * qx - s_l * qy, s_l * qx + c_l * qy], axis=2)

        # Advance the cyclic pairing: only the Y half moves (columns via a
        # lane roll, rows via a sublane roll); same for Q's Y columns and
        # the carried gamma norms.
        g = jnp.concatenate([g[:, :, :b], _roll_m1(g[:, :, b:], 2)], axis=2)
        g = jnp.concatenate([g[:, :b, :], _roll_m1(g[:, b:, :], 1)], axis=1)
        q = jnp.concatenate([q[:, :, :b], _roll_m1(q[:, :, b:], 2)], axis=2)

        return g, q

    g, q = jax.lax.fori_loop(0, n_steps, step, (g, q))
    return g, q


def _cross_kernel(g_ref, q_ref, *, b, n_steps):
    f32 = jnp.float32
    kb, n2, _ = g_ref.shape
    rows = jax.lax.broadcasted_iota(jnp.int32, (n2, n2), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n2, n2), 1)
    q0 = jnp.broadcast_to((rows == cols).astype(f32)[None], (kb, n2, n2))
    _, q = _cross_body(g_ref[...].astype(f32), q0, b, n_steps)
    q_ref[...] = q


@functools.partial(jax.jit, static_argnames=("interpret", "block_k", "passes"))
def _cross_call(g, *, interpret: bool, block_k: int, passes: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, n2, _ = g.shape
    b = n2 // 2
    kernel = functools.partial(_cross_kernel, b=b, n_steps=passes * b)
    if k % block_k:
        raise ValueError(f"panel count {k} not divisible by block_k={block_k}")
    q = pl.pallas_call(
        kernel,
        grid=(k // block_k,),
        in_specs=[pl.BlockSpec((block_k, n2, n2), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((block_k, n2, n2), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, n2, n2), jnp.float32),
        interpret=interpret,
    )(g.astype(jnp.float32))
    return q


def supported(platform: str | None = None) -> bool:
    """True when the compiled Pallas TPU path can run on this backend."""
    if platform is None:
        platform = jax.default_backend()
    return platform in ("tpu", "axon")


def cross_rotations(g: jax.Array, *, interpret: bool | None = None,
                    block_k: int | None = None, passes: int = 1) -> jax.Array:
    """Annihilate every cross pair of each Gram panel once; return Q.

    Args:
      g: (k, 2b, 2b) symmetric Gram panels of [I | J] column-pair panels.
      interpret: run under the Pallas interpreter (CPU testing); default
        compiles on TPU backends and interprets elsewhere.
      block_k: panels per grid step (VMEM chunking). Default: whole batch
        up to 8 panels, then the largest divisor of k with <= 8 panels.

    Returns:
      q: (k, 2b, 2b) float32, the accumulated product of the b rotation
      steps. Columns of the panel are made mutually orthogonal ACROSS the
      two blocks only; within-block pairs are the recursion's job
      (`self_rotations`).
    """
    if g.ndim != 3 or g.shape[-1] != g.shape[-2] or g.shape[-1] % 2:
        raise ValueError(f"expected (k, n2, n2) panels with even n2, got {g.shape}")
    if block_k is None:
        block_k = _pick_block_k(g.shape[0], g.shape[-1])
    if interpret is None:
        interpret = not supported()
    return _cross_call(g, interpret=bool(interpret), block_k=int(block_k),
                       passes=int(passes))


def _pick_block_k(k: int, n2: int) -> int:
    """Panels per grid step: as many as VMEM comfortably holds (the batched
    body amortizes per-step latency over the chunk; a serial grid multiplies
    it), budgeting ~8 MB for g + q + temporaries of ~6x panel size."""
    budget_panels = max(1, (8 << 20) // (n2 * n2 * 4 * 6))
    block_k = k
    while block_k > budget_panels and block_k % 2 == 0:
        block_k //= 2
    return block_k


def reference_cross(g: jax.Array) -> jax.Array:
    """Pure-jnp reference for `cross_rotations` (tests/CPU oracle): same
    body, no Pallas."""
    k, n2, _ = g.shape
    b = n2 // 2
    q0 = jnp.broadcast_to(jnp.eye(n2, dtype=jnp.float32)[None], (k, n2, n2))
    _, q = _cross_body(g.astype(jnp.float32), q0, b, b)
    return q


# ---------------------------------------------------------------------------
# Full tournament (self coverage): every pair INSIDE each panel exactly once.


def _shift_cols(top, bot):
    """Circle-method tournament shift on the last axis (slot 0 fixed)."""
    if top.shape[-1] == 1:
        return top, bot
    new_top = jnp.concatenate([top[..., :1], bot[..., :1], top[..., 1:-1]], axis=-1)
    new_bot = jnp.concatenate([bot[..., 1:], top[..., -1:]], axis=-1)
    return new_top, new_bot


def _shift_rows(top, bot):
    if top.shape[-2] == 1:
        return top, bot
    new_top = jnp.concatenate(
        [top[..., :1, :], bot[..., :1, :], top[..., 1:-1, :]], axis=-2)
    new_bot = jnp.concatenate([bot[..., 1:, :], top[..., -1:, :]], axis=-2)
    return new_top, new_bot


def _self_body(g, q, b2, n_steps):
    """n2-1 circle-method steps covering every pair inside each panel once.

    Same trimmed structure as `_cross_body` (no in-kernel statistics), but
    the pairing advances by moving ALL slots (the circle method with slot 0
    fixed) because every pair of the n2 = 2*b2 columns must meet.
    """
    f32 = jnp.float32
    dmask = (jax.lax.broadcasted_iota(jnp.int32, (b2, b2), 0)
             == jax.lax.broadcasted_iota(jnp.int32, (b2, b2), 1)).astype(f32)[None]

    def step(_, carry):
        g, q = carry
        alpha_l = jnp.sum(g[:, :b2, b2:] * dmask, axis=1)[:, None, :]
        beta_l = jnp.sum(g[:, :b2, :b2] * dmask, axis=1)[:, None, :]
        gamma_l = jnp.sum(g[:, b2:, b2:] * dmask, axis=1)[:, None, :]
        c_l, s_l = _rutishauser(alpha_l, beta_l, gamma_l)
        c_s = c_l.transpose(0, 2, 1)
        s_s = s_l.transpose(0, 2, 1)

        gx, gy = g[:, :, :b2], g[:, :, b2:]
        g = jnp.concatenate([c_l * gx - s_l * gy, s_l * gx + c_l * gy], axis=2)
        hx, hy = g[:, :b2, :], g[:, b2:, :]
        g = jnp.concatenate([c_s * hx - s_s * hy, s_s * hx + c_s * hy], axis=1)
        qx, qy = q[:, :, :b2], q[:, :, b2:]
        q = jnp.concatenate([c_l * qx - s_l * qy, s_l * qx + c_l * qy], axis=2)

        gt, gb = _shift_cols(g[:, :, :b2], g[:, :, b2:])
        g = jnp.concatenate([gt, gb], axis=2)
        gt, gb = _shift_rows(g[:, :b2, :], g[:, b2:, :])
        g = jnp.concatenate([gt, gb], axis=1)
        qt, qb = _shift_cols(q[:, :, :b2], q[:, :, b2:])
        q = jnp.concatenate([qt, qb], axis=2)
        return g, q

    g, q = jax.lax.fori_loop(0, n_steps, step, (g, q))
    return g, q


def _self_kernel(g_ref, q_ref, *, b2, n_steps):
    f32 = jnp.float32
    kb, n2, _ = g_ref.shape
    rows = jax.lax.broadcasted_iota(jnp.int32, (n2, n2), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n2, n2), 1)
    q0 = jnp.broadcast_to((rows == cols).astype(f32)[None], (kb, n2, n2))
    _, q = _self_body(g_ref[...].astype(f32), q0, b2, n_steps)
    q_ref[...] = q


@functools.partial(jax.jit, static_argnames=("interpret", "block_k", "passes"))
def _self_call(g, *, interpret: bool, block_k: int, passes: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, n2, _ = g.shape
    kernel = functools.partial(_self_kernel, b2=n2 // 2,
                               n_steps=passes * max(n2 - 1, 1))
    q = pl.pallas_call(
        kernel,
        grid=(k // block_k,),
        in_specs=[pl.BlockSpec((block_k, n2, n2), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((block_k, n2, n2), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, n2, n2), jnp.float32),
        interpret=interpret,
    )(g.astype(jnp.float32))
    return q


def self_rotations(g: jax.Array, *, interpret: bool | None = None,
                   block_k: int | None = None, passes: int = 1) -> jax.Array:
    """Annihilate EVERY column pair inside each Gram panel exactly once
    (full n2-1-step tournament); returns the accumulated Q like
    `cross_rotations`. Used once per sweep on the per-block Grams."""
    if g.ndim != 3 or g.shape[-1] != g.shape[-2] or g.shape[-1] % 2:
        raise ValueError(f"expected (k, n2, n2) panels with even n2, got {g.shape}")
    if block_k is None:
        block_k = _pick_block_k(g.shape[0], g.shape[-1])
    if interpret is None:
        interpret = not supported()
    return _self_call(g, interpret=bool(interpret), block_k=int(block_k),
                       passes=int(passes))


def reference_self(g: jax.Array) -> jax.Array:
    """Pure-jnp reference for `self_rotations`."""
    k, n2, _ = g.shape
    q0 = jnp.broadcast_to(jnp.eye(n2, dtype=jnp.float32)[None], (k, n2, n2))
    _, q = _self_body(g.astype(jnp.float32), q0, n2 // 2, max(n2 - 1, 1))
    return q
