"""Pallas TPU kernel for the cross-round Gram panels.

A cross round needs g = x^T x for x = [top_i | bot_i] per pair slot —
three (b, b) quadrants gxx = t^T t, gxy = t^T b, gyy = b^T b with a long
reduction over the m rows and a tiny output. XLA schedules this
reduction-heavy batched einsum at ~11.6 TF/s f32-effective on v5e (vs
~25 TF/s for the same-cost apply matmuls — PROFILE.md component table),
leaving most of the MXU idle. This kernel grids over (pair, row-chunk),
keeps the three quadrant accumulators resident in VMEM across the row
chunks of a pair (TPU pallas iterates the trailing grid dimension
innermost, so each pair's accumulation completes before the next pair
starts), and contracts (mc, b) chunks on the MXU at HIGHEST precision.

Reference lineage: the Gram elements are the alpha/beta/gamma dot
products the reference computes per column pair in a HOST loop
(lib/JacobiMethods.cu:450-459) — here one kernel produces every pair's
full Gram panel on-device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .pallas_apply import _pick_chunk
from .pallas_blocks import _out_struct

HI = jax.lax.Precision.HIGHEST

# Per-grid-step footprint for the VMEM chunk budget (_pick_chunk): 2
# (mc, b) input blocks per row, plus 3 (b, b) f32 quadrant accumulators.
_ROW_BLOCKS = 2


def _fixed_bytes(b: int) -> int:
    return 3 * b * b * 4


def _chunk(m: int, b: int) -> int:
    return _pick_chunk(m, b, _ROW_BLOCKS, _fixed_bytes(b))


def _kernel(xt_ref, xb_ref, gxx_ref, gxy_ref, gyy_ref, *, bf16):
    from jax.experimental import pallas as pl

    f32 = jnp.float32
    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _init():
        gxx_ref[...] = jnp.zeros_like(gxx_ref)
        gxy_ref[...] = jnp.zeros_like(gxy_ref)
        gyy_ref[...] = jnp.zeros_like(gyy_ref)

    # bf16 stacks — or f32 stacks under the ``bf16`` compute mode (the
    # mixed-bulk regime: Gram noise only perturbs rotation angles/stats) —
    # contract natively in one bf16-in/f32-acc MXU pass (HIGHEST is an
    # f32-operand notion; Mosaic rejects it on bf16). Otherwise f32 at
    # HIGHEST. Accumulators stay f32 either way.
    if xt_ref.dtype == jnp.bfloat16 or bf16:
        xt = xt_ref[0].astype(jnp.bfloat16)
        xb = xb_ref[0].astype(jnp.bfloat16)
        prec = None
    else:
        xt, xb = xt_ref[0].astype(f32), xb_ref[0].astype(f32)
        prec = HI
    dot = lambda a, b: jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), precision=prec,
        preferred_element_type=f32)[None]
    gxx_ref[...] += dot(xt, xt)
    gxy_ref[...] += dot(xt, xb)
    gyy_ref[...] += dot(xb, xb)


def supported(m: int, b: int) -> bool:
    """Lane-sized panels and a usable row chunk (the gram step's smaller
    footprint gives it a wider support window than the apply kernel)."""
    return b % 128 == 0 and _chunk(m, b) >= 128


@functools.partial(jax.jit, static_argnames=("interpret", "vma", "bf16"))
def gram_pairs(top, bot, *, interpret: bool = False, vma=None,
               bf16: bool = False):
    """(k, 2b, 2b) symmetric Gram panels of the stacked pairs.

    Equal (to f32 reduction-order rounding; single-bf16-pass rounding under
    ``bf16``) to ``einsum('kmi,kmj->kij', x, x)`` with
    ``x = concat([top, bot], -1)`` — without materializing x. ``vma``: see
    pallas_apply.apply_exchange.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, m, b = top.shape
    mc = _chunk(m, b)
    x_spec = pl.BlockSpec((1, mc, b), lambda i, mi: (i, mi, 0),
                          memory_space=pltpu.VMEM)
    g_spec = pl.BlockSpec((1, b, b), lambda i, mi: (i, 0, 0),
                          memory_space=pltpu.VMEM)
    out = _out_struct((k, b, b), jnp.float32, vma)
    gxx, gxy, gyy = pl.pallas_call(
        functools.partial(_kernel, bf16=bf16),
        grid=(k, m // mc),
        in_specs=[x_spec, x_spec],
        out_specs=[g_spec] * 3,
        out_shape=[out] * 3,
        interpret=interpret,
    )(top, bot)
    top_row = jnp.concatenate([gxx, gxy], axis=-1)
    bot_row = jnp.concatenate([gxy.transpose(0, 2, 1), gyy], axis=-1)
    return jnp.concatenate([top_row, bot_row], axis=-2)
