"""Numerical kernels: batched block orthogonalization, Schur rotations."""

from . import blockwise  # noqa: F401
