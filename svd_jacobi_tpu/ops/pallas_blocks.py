"""Pallas TPU kernel v3: cross-pair tournament in 4-block-array layout.

Math: cyclic mod-b pairing of the two column blocks of a panel, Rutishauser
rotations, congruence on the Gram panel, accumulated Q (the pure-jnp form is
`reference_cross` below) — but the panel is carried as FOUR separate
(kb, b, b) arrays

    G = [[gxx, c ], [ct, gyy]]        q = [qx | qy]  (2b rows, b cols each)

so every per-step operation is a FULL-ARRAY elementwise op or a full-array
`pltpu.roll` — no sub-tile lane slicing and no concatenates inside the hot
loop, which Mosaic lowers to masked merges (measured: the slice/concat form
costs 3.8 us/step at b=128; this form is the replacement).

Reference lineage: the per-pair rotation math is the TPU replacement for
the reference CUDA kernel `jacobi_rotation` (lib/JacobiMethods.cu:1483-1491)
generalized to all b pairs of a block pair per step (SURVEY.md section 7).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .. import _compat

_TINY = 1e-30


def _rutishauser(alpha, beta, gamma):
    f32 = jnp.float32
    safe_a = jnp.where(jnp.abs(alpha) > _TINY, alpha, jnp.ones_like(alpha))
    tau = (gamma - beta) / (2.0 * safe_a)
    sgn = jnp.where(tau >= 0, f32(1.0), f32(-1.0))
    t = sgn / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
    c = jax.lax.rsqrt(1.0 + t * t)
    s = t * c
    rot = jnp.abs(alpha) > _TINY
    c = jnp.where(rot, c, f32(1.0))
    s = jnp.where(rot, s, f32(0.0))
    return c, s


def _roll(x, shift, axis):
    """Circular shift; pltpu.roll in compiled kernels, jnp.roll elsewhere."""
    try:
        from jax.experimental.pallas import tpu as pltpu
        return pltpu.roll(x, shift, axis)
    except Exception:
        return jnp.roll(x, shift, axis=axis)


def _read(ref, strip_vma):
    """Read a kernel ref, optionally stripping the mesh-variance tag.

    COMPILED kernels are traced with variance checking OFF, so computed
    values carry no {V} tag — but a bare ref read DOES keep the caller's
    tag (and a same-dtype astype is a no-op that preserves it), making
    fori_loop carries type-inconsistent; one multiply re-derives the value
    through an op so its aval matches everything else in the kernel.
    INTERPRETED kernels evaluate under full variance semantics where that
    same multiply is a varying/invarying mismatch — so there we must NOT
    strip."""
    x = ref[...].astype(jnp.float32)
    return x * jnp.float32(1.0) if strip_vma else x


def _maybe_pvary(xs, vma):
    """INTERPRETED kernels evaluate under full variance semantics: computed
    values (identity inits, rolls of them) start unvarying and must be
    pvary'd onto the mesh axes to keep fori_loop carries type-consistent.
    (Compiled kernels instead strip the tag at the ref reads — `_read` —
    because pvary has no Mosaic lowering.)"""
    if not vma:
        return xs

    def cast(x):
        have = _compat.vma(x)
        need = tuple(a for a in vma if a not in have)
        return _compat.pcast(x, need, to="varying") if need else x

    return tuple(cast(x) for x in xs)


def _cross_blocks_body(gxx, c, ct, gyy, qx, qy, n_steps, vma=None):
    """Run ``n_steps`` cyclic cross-rotation steps on the 4-block panels.

    All six arrays are (kb, *, *); the aligned pairing couples column i of
    X with aligned column i of Y, and the Y system (c's columns, ct's rows,
    gyy's rows+cols, qy's columns, i.e. everything Y-indexed) rolls by -1
    after each step.
    """
    f32 = jnp.float32
    b = gxx.shape[-1]
    dmask = (jax.lax.broadcasted_iota(jnp.int32, (b, b), 0)
             == jax.lax.broadcasted_iota(jnp.int32, (b, b), 1)).astype(f32)[None]

    def step(_, carry):
        gxx, c, ct, gyy, qx, qy = carry
        alpha = jnp.sum(c * dmask, axis=1)[:, None, :]     # (kb, 1, b)
        beta = jnp.sum(gxx * dmask, axis=1)[:, None, :]
        gamma = jnp.sum(gyy * dmask, axis=1)[:, None, :]
        co_l, si_l = _rutishauser(alpha, beta, gamma)
        # Sublane-shaped copies for the row mix. A transpose relayout beats
        # re-deriving the angles from lane-axis reductions (measured 25%
        # slower per step — lane reductions are long chains).
        co_s = co_l.transpose(0, 2, 1)
        si_s = si_l.transpose(0, 2, 1)

        # Column mix (blocks pair with their horizontal neighbor) ...
        gxx, c = co_l * gxx - si_l * c, si_l * gxx + co_l * c
        ct, gyy = co_l * ct - si_l * gyy, si_l * ct + co_l * gyy
        # ... then row mix (vertical neighbor) with the transposed angles.
        gxx, ct = co_s * gxx - si_s * ct, si_s * gxx + co_s * ct
        c, gyy = co_s * c - si_s * gyy, si_s * c + co_s * gyy
        # Q columns (rows never move).
        qx, qy = co_l * qx - si_l * qy, si_l * qx + co_l * qy

        # Advance the pairing: everything Y-indexed rolls by -1.
        c = _roll(c, -1, 2)
        ct = _roll(ct, -1, 1)
        gyy = _roll(_roll(gyy, -1, 1), -1, 2)
        qy = _roll(qy, -1, 2)
        return _maybe_pvary((gxx, c, ct, gyy, qx, qy), vma)

    init = _maybe_pvary((gxx, c, ct, gyy, qx, qy), vma)
    # Unroll steps per loop iteration: shortens the per-iteration
    # bookkeeping and gives Mosaic a longer straight-line region to schedule
    # (the chain itself is sequential; the win is reduced loop overhead).
    # Largest unroll in {4, 2} that divides the step count; measured at
    # (8, 256, 256) panels the 4-way unroll is 8% faster per call than the
    # 2-way (407.6 vs 444.0 us, differential intra-jit timing on v5e).
    for unroll in (4, 2):
        if n_steps % unroll == 0:
            def block(i, cc, u=unroll):
                for _ in range(u):
                    cc = step(i, cc)
                return cc
            return jax.lax.fori_loop(0, n_steps // unroll, block, init)
    return jax.lax.fori_loop(0, n_steps, step, init)



def _polish_blocks(qx, qy):
    """One Newton-Schulz step on Q = [qx | qy] using in-kernel MXU matmuls:
    Q <- Q (1.5 I - 0.5 Q^T Q). Restores the accumulated product's
    orthogonality to the f32 floor without leaving VMEM (an XLA-level
    polish costs ~2x the kernel itself in critical-path latency)."""
    f32 = jnp.float32
    b = qx.shape[-1]
    mm = lambda a, bb, spec: jnp.einsum(
        spec, a, bb, precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=f32)
    gxx = mm(qx, qx, "kij,kil->kjl")
    gxy = mm(qx, qy, "kij,kil->kjl")
    gyy = mm(qy, qy, "kij,kil->kjl")
    eye = jnp.eye(b, dtype=f32)[None]
    mxx = 1.5 * eye - 0.5 * gxx
    myy = 1.5 * eye - 0.5 * gyy
    mxy = -0.5 * gxy
    myx = -0.5 * gxy.transpose(0, 2, 1)
    new_qx = mm(qx, mxx, "kij,kjl->kil") + mm(qy, myx, "kij,kjl->kil")
    new_qy = mm(qx, mxy, "kij,kjl->kil") + mm(qy, myy, "kij,kjl->kil")
    return new_qx, new_qy


def _cross_kernel(gxx_ref, c_ref, ct_ref, gyy_ref, qx_ref, qy_ref, *, n_steps,
                  polish, strip_vma=False, vma=None):
    f32 = jnp.float32
    kb, b, _ = gxx_ref.shape
    rows = jax.lax.broadcasted_iota(jnp.int32, (2 * b, b), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (2 * b, b), 1)
    qx0 = jnp.broadcast_to((rows == cols).astype(f32)[None], (kb, 2 * b, b))
    qy0 = jnp.broadcast_to((rows == cols + b).astype(f32)[None], (kb, 2 * b, b))
    _, _, _, _, qx, qy = _cross_blocks_body(
        _read(gxx_ref, strip_vma), _read(c_ref, strip_vma),
        _read(ct_ref, strip_vma), _read(gyy_ref, strip_vma),
        qx0, qy0, n_steps, vma=vma)
    if polish:
        qx, qy = _maybe_pvary(_polish_blocks(qx, qy), vma)
    qx_ref[...] = qx
    qy_ref[...] = qy


@functools.partial(jax.jit, static_argnames=("interpret", "block_k", "passes",
                                              "polish", "vma"))
def _cross_call(gxx, c, ct, gyy, *, interpret: bool, block_k: int, passes: int,
                polish: bool, vma=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, b, _ = gxx.shape
    kernel = functools.partial(_cross_kernel, n_steps=passes * b,
                               polish=polish, strip_vma=not interpret,
                               vma=vma if interpret else None)
    spec_in = pl.BlockSpec((block_k, b, b), lambda i: (i, 0, 0),
                           memory_space=pltpu.VMEM)
    spec_out = pl.BlockSpec((block_k, 2 * b, b), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    f32 = jnp.float32
    out = _out_struct((k, 2 * b, b), f32, vma)
    qx, qy = pl.pallas_call(
        kernel,
        grid=(k // block_k,),
        in_specs=[spec_in] * 4,
        out_specs=[spec_out] * 2,
        out_shape=[out] * 2,
        interpret=interpret,
    )(gxx.astype(f32), c.astype(f32), ct.astype(f32), gyy.astype(f32))
    return qx, qy


def _out_struct(shape, dtype, vma):
    """Output aval for pallas_call; under shard_map with variance checking
    the result's varying mesh axes must be declared explicitly."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))


def supported(platform: str | None = None) -> bool:
    if platform is None:
        platform = jax.default_backend()
    return platform in ("tpu", "axon")


# Scoped-VMEM model shared by the block picker (`_pick_block_k`), the
# oversized-panel gate (`kernel_fits`), and rounds._rotations' fallback
# dispatch — ONE set of constants so retuning cannot desynchronize them.
# A panel's live set is 4 (b2, b2) G-quadrants + 2 (2b2, b2) Q halves, but
# VMEM tiles pad the LANE (last) dimension to 128 — a (32, 32) array
# occupies a (32, 128) tile — so the per-panel footprint is
# 8 * b2 * max(b2, 128) * 4 bytes. Mosaic's double-buffering/temporaries
# multiply that by ~3 (cross) / ~4 (self, extra circle-move
# intermediates); measured: 32-panel b=64 cross chunks and 64-panel
# b2=32 self chunks both blew the 16 MB scoped limit at ~18 MB.
VMEM_BUDGET = 13 << 20
CROSS_FACTOR, SELF_FACTOR = 3, 4


def _panel_bytes(b2: int) -> int:
    return 8 * b2 * max(b2, 128) * 4


def kernel_fits(b2: int, factor: int) -> bool:
    """Whether even a SINGLE panel of half-width ``b2`` fits the scoped-VMEM
    budget (same model as `_pick_block_k`): b >= 512 panels exceed it at
    block_k = 1 and must fall back to the XLA reference bodies."""
    return _panel_bytes(b2) * factor <= VMEM_BUDGET


def _pick_block_k(k: int, b: int, factor: int = CROSS_FACTOR) -> int:
    """Panels per grid step, bounded by scoped VMEM (see the model above)."""
    per_panel = _panel_bytes(b)
    budget_panels = max(1, VMEM_BUDGET // (per_panel * factor))
    if k <= budget_panels:
        return k
    # Largest divisor of k within budget (the grid needs block_k | k; a
    # power-of-2-only halving would leave odd panel counts like k=17
    # unreduced and re-blow the scoped-VMEM limit).
    for d in range(budget_panels, 0, -1):
        if k % d == 0:
            return d
    return 1


def cross_rotations(g: jax.Array, *, interpret: bool | None = None,
                    block_k: int | None = None, passes: int = 1,
                    polish: bool = True, vma=None) -> jax.Array:
    """Rotation generator for a cross round: Gram panel stack G in,
    accumulated orthogonal Q out (see `reference_cross` for the semantics);
    4-block-array layout inside."""
    if g.ndim != 3 or g.shape[-1] != g.shape[-2] or g.shape[-1] % 2:
        raise ValueError(f"expected (k, n2, n2) panels with even n2, got {g.shape}")
    k, n2, _ = g.shape
    b = n2 // 2
    if block_k is None:
        block_k = _pick_block_k(k, b)
    if interpret is None:
        interpret = not supported()
    gxx, c = g[:, :b, :b], g[:, :b, b:]
    ct, gyy = g[:, b:, :b], g[:, b:, b:]
    qx, qy = _cross_call(gxx, c, ct, gyy, interpret=bool(interpret),
                         block_k=int(block_k), passes=int(passes),
                         polish=bool(polish),
                         vma=tuple(vma) if vma else None)
    return jnp.concatenate([qx, qy], axis=2)


# ---------------------------------------------------------------------------
# Full tournament (self coverage) in the same 4-block-array layout: every
# pair INSIDE each width-n2 panel exactly once via n2-1 circle-method steps.
# The circle move (slot 0 fixed) is expressed as full-array rolls + masked
# selects, so the hot loop stays free of sub-tile slicing.


def _circle_masks(b2):
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, b2), 1)[None]
    return ((lane == 0).astype(jnp.float32),
            (lane == 1).astype(jnp.float32),
            (lane == b2 - 1).astype(jnp.float32))


def _colmove(x, y, m0, m1, mlast, axis):
    """Circle-method slot move along ``axis``: X' = [x0, y0, x1..x_{b2-2}],
    Y' = [y1..y_{b2-1}, x_{b2-1}]. Masks are lane-shaped; for axis=1 pass
    their transposes. Width-1 halves are a fixed point (the single pair
    (x0, y0) just keeps meeting itself) — without this guard the m0/mlast
    masks coincide and Y would be overwritten with X."""
    if x.shape[axis] == 1:
        return x, y
    xr = _roll(x, 1, axis)
    yr1 = _roll(y, 1, axis)
    new_x = m0 * x + m1 * yr1 + (1.0 - m0 - m1) * xr
    new_y = mlast * x + (1.0 - mlast) * _roll(y, -1, axis)
    return new_x, new_y


def _self_blocks_body(gxx, c, ct, gyy, qx, qy, n_steps, vma=None):
    """n_steps circle-method tournament steps on the 4-block panels."""
    f32 = jnp.float32
    b2 = gxx.shape[-1]
    dmask = (jax.lax.broadcasted_iota(jnp.int32, (b2, b2), 0)
             == jax.lax.broadcasted_iota(jnp.int32, (b2, b2), 1)).astype(f32)[None]
    m0, m1, mlast = _circle_masks(b2)
    m0s, m1s, mlasts = (m.transpose(0, 2, 1) for m in (m0, m1, mlast))

    def step(_, carry):
        gxx, c, ct, gyy, qx, qy = carry
        alpha = jnp.sum(c * dmask, axis=1)[:, None, :]
        beta = jnp.sum(gxx * dmask, axis=1)[:, None, :]
        gamma = jnp.sum(gyy * dmask, axis=1)[:, None, :]
        co_l, si_l = _rutishauser(alpha, beta, gamma)
        co_s = co_l.transpose(0, 2, 1)
        si_s = si_l.transpose(0, 2, 1)

        gxx, c = co_l * gxx - si_l * c, si_l * gxx + co_l * c
        ct, gyy = co_l * ct - si_l * gyy, si_l * ct + co_l * gyy
        gxx, ct = co_s * gxx - si_s * ct, si_s * gxx + co_s * ct
        c, gyy = co_s * c - si_s * gyy, si_s * c + co_s * gyy
        qx, qy = co_l * qx - si_l * qy, si_l * qx + co_l * qy

        # Circle move: columns of both halves, then rows, then Q columns.
        gxx, c = _colmove(gxx, c, m0, m1, mlast, 2)
        ct, gyy = _colmove(ct, gyy, m0, m1, mlast, 2)
        gxx, ct = _colmove(gxx, ct, m0s, m1s, mlasts, 1)
        c, gyy = _colmove(c, gyy, m0s, m1s, mlasts, 1)
        qx, qy = _colmove(qx, qy, m0, m1, mlast, 2)
        return _maybe_pvary((gxx, c, ct, gyy, qx, qy), vma)

    init = _maybe_pvary((gxx, c, ct, gyy, qx, qy), vma)
    return jax.lax.fori_loop(0, n_steps, step, init)


def _self_kernel(gxx_ref, c_ref, ct_ref, gyy_ref, qx_ref, qy_ref, *, n_steps,
                 polish, strip_vma=False, vma=None):
    f32 = jnp.float32
    kb, b2, _ = gxx_ref.shape
    rows = jax.lax.broadcasted_iota(jnp.int32, (2 * b2, b2), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (2 * b2, b2), 1)
    qx0 = jnp.broadcast_to((rows == cols).astype(f32)[None], (kb, 2 * b2, b2))
    qy0 = jnp.broadcast_to((rows == cols + b2).astype(f32)[None], (kb, 2 * b2, b2))
    _, _, _, _, qx, qy = _self_blocks_body(
        _read(gxx_ref, strip_vma), _read(c_ref, strip_vma),
        _read(ct_ref, strip_vma), _read(gyy_ref, strip_vma),
        qx0, qy0, n_steps, vma=vma)
    if polish:
        qx, qy = _maybe_pvary(_polish_blocks(qx, qy), vma)
    qx_ref[...] = qx
    qy_ref[...] = qy


@functools.partial(jax.jit, static_argnames=("interpret", "block_k", "passes",
                                              "polish", "vma"))
def _self_call(gxx, c, ct, gyy, *, interpret: bool, block_k: int, passes: int,
               polish: bool, vma=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, b2, _ = gxx.shape
    kernel = functools.partial(_self_kernel,
                               n_steps=passes * max(2 * b2 - 1, 1),
                               polish=polish, strip_vma=not interpret,
                               vma=vma if interpret else None)
    spec_in = pl.BlockSpec((block_k, b2, b2), lambda i: (i, 0, 0),
                           memory_space=pltpu.VMEM)
    spec_out = pl.BlockSpec((block_k, 2 * b2, b2), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    f32 = jnp.float32
    out = _out_struct((k, 2 * b2, b2), f32, vma)
    qx, qy = pl.pallas_call(
        kernel,
        grid=(k // block_k,),
        in_specs=[spec_in] * 4,
        out_specs=[spec_out] * 2,
        out_shape=[out] * 2,
        interpret=interpret,
    )(gxx.astype(f32), c.astype(f32), ct.astype(f32), gyy.astype(f32))
    return qx, qy


def self_rotations(g: jax.Array, *, interpret: bool | None = None,
                   block_k: int | None = None, passes: int = 1,
                   polish: bool = True, vma=None) -> jax.Array:
    """Annihilate EVERY pair inside each (n2, n2) Gram panel exactly once
    (n2-1 circle-method steps); same G-in/Q-out contract as
    `reference_self`."""
    if g.ndim != 3 or g.shape[-1] != g.shape[-2] or g.shape[-1] % 2:
        raise ValueError(f"expected (k, n2, n2) panels with even n2, got {g.shape}")
    k, n2, _ = g.shape
    b2 = n2 // 2
    if block_k is None:
        block_k = _pick_block_k(k, b2, factor=SELF_FACTOR)
    if interpret is None:
        interpret = not supported()
    qx, qy = _self_call(g[:, :b2, :b2], g[:, :b2, b2:], g[:, b2:, :b2],
                        g[:, b2:, b2:], interpret=bool(interpret),
                        block_k=int(block_k), passes=int(passes),
                        polish=bool(polish),
                        vma=tuple(vma) if vma else None)
    return jnp.concatenate([qx, qy], axis=2)


def reference_self(g: jax.Array, polish: bool = False) -> jax.Array:
    """Pure-jnp reference (no Pallas) for tests and interpreter-backend
    mesh solves (see reference_cross)."""
    k, n2, _ = g.shape
    b2 = n2 // 2
    f32 = jnp.float32
    rows = jax.lax.broadcasted_iota(jnp.int32, (2 * b2, b2), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (2 * b2, b2), 1)
    qx0 = jnp.broadcast_to((rows == cols).astype(f32)[None], (k, 2 * b2, b2))
    qy0 = jnp.broadcast_to((rows == cols + b2).astype(f32)[None], (k, 2 * b2, b2))
    qx0 = qx0 + 0.0 * g[:, :1, :b2]
    qy0 = qy0 + 0.0 * g[:, :1, :b2]
    _, _, _, _, qx, qy = _self_blocks_body(
        g[:, :b2, :b2].astype(f32), g[:, :b2, b2:].astype(f32),
        g[:, b2:, :b2].astype(f32), g[:, b2:, b2:].astype(f32),
        qx0, qy0, max(n2 - 1, 1))
    if polish:
        qx, qy = _polish_blocks(qx, qy)
    return jnp.concatenate([qx, qy], axis=2)


def reference_cross(g: jax.Array, polish: bool = False) -> jax.Array:
    """Pure-jnp reference (no Pallas) for tests — and the compute body for
    mesh solves on interpreter backends, where plain ops keep the variance
    types consistent that the pallas_call machinery cannot."""
    k, n2, _ = g.shape
    b = n2 // 2
    f32 = jnp.float32
    rows = jax.lax.broadcasted_iota(jnp.int32, (2 * b, b), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (2 * b, b), 1)
    qx0 = jnp.broadcast_to((rows == cols).astype(f32)[None], (k, 2 * b, b))
    qy0 = jnp.broadcast_to((rows == cols + b).astype(f32)[None], (k, 2 * b, b))
    qx0 = qx0 + 0.0 * g[:, :1, :b]   # inherit the callers' variance type
    qy0 = qy0 + 0.0 * g[:, :1, :b]
    _, _, _, _, qx, qy = _cross_blocks_body(
        g[:, :b, :b].astype(f32), g[:, :b, b:].astype(f32),
        g[:, b:, :b].astype(f32), g[:, b:, b:].astype(f32), qx0, qy0, b)
    if polish:
        qx, qy = _polish_blocks(qx, qy)
    return jnp.concatenate([qx, qy], axis=2)
