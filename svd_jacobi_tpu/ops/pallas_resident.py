"""VMEM-resident multi-round sweep megakernel (pair_solver="resident").

The blocked-rotation lane (`rounds.block_round_fused`) already collapsed a
tournament round to eigh + ONE fused apply/exchange/gram kernel per stack
— but every round still makes a full HBM pass over the (k, m, b) panel
stacks, so one sweep re-streams the matrix ~2k-1 times (PROFILE items
8/29; BENCH_r04 sits at 1.7% MFU because of exactly this). This module is
the residency point of that design (cuSOLVER-gesvdj / Brent-Luk blocked
Jacobi taken to its TPU conclusion): solve R consecutive rounds' 2b x 2b
subproblems AGAINST A CARRIED SMALL-SIDE GRAM, then make ONE panel pass
that applies all R rounds' factors while the working set stays in VMEM.

How a group of R rounds runs:

  1. ``group_factors`` — n^2-scale, zero panel reads: the full pair-major
     Gram carry G (n_pad x n_pad, bootstrapped once per sweep as X^T X)
     yields each round's paired-diagonal 2b x 2b panels; the round's skip
     statistic and `block_rotate.accumulate` factor come from those, the
     skip gate folds to an identity factor (the exchange still happens,
     matching `block_round_fused`'s skip branch exactly), and G advances
     by G <- J^T G J plus the tournament block permutation. Factors never
     round-trip through a panel pass.
  2. ``apply_group`` — the single panel pass. On compiled TPU backends a
     Pallas megakernel grids over row chunks ONLY: the full 2k-block
     pair axis of the chunk plus all R factor stacks are resident in
     VMEM, the R rounds' rank-2b applies run back to back on the MXU
     (Mosaic's grid pipelining double-buffers the next row chunk's HBM
     loads behind them), and the tournament exchange is a SLOT REMAP of
     VMEM values — pure renaming at trace time, zero data movement.
     Elsewhere an XLA twin applies the composed group transform as one
     GEMM (R >= k_per, the FLOP-optimal regime) or R iterated jnp rounds
     (R < k_per — same values as the kernel, used by the equivalence
     tests).

HBM traffic per sweep drops from ~(2k-1) full passes over the stacks to
ceil((2k-1)/R) passes plus one Gram bootstrap pass — the R-fold
reduction `obs.costmodel.sweep_costs(pair_solver="resident")` models and
PERF001's byte acceptance checks. R == 1 (or k_per == 1) delegates to
`rounds.sweep_block` verbatim: the resident lane at R=1 IS the
blocked-rotation round chain, bitwise.

Accuracy contract: this is a BULK phase. The loop statistic derives from
the carried G (f32-HIGHEST updates, re-bootstrapped from the true panels
every sweep, so carry drift is bounded by one sweep's rounds); the
endgame always belongs to the unchanged pallas rel-criterion polish,
which re-measures from the real panels — sigma exactness, U
orthonormality and v_orth_live are inherited from that handoff, exactly
as on the block_rotation lane.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import block_rotate as br
from . import pallas_apply as pa
from . import rounds
from ..obs import metrics
from ..obs.scopes import scope
from ..parallel import schedule as sched

HI = jax.lax.Precision.HIGHEST

# Default residency depth when neither SVDConfig.rounds_resident nor a
# tuning-table row pins it: 4 rounds per panel pass quarters the sweep's
# panel traffic while the factor stacks (R * k * (2b)^2 f32) stay small
# next to the row-chunk working set at lane-sized b.
DEFAULT_ROUNDS = 4


# --------------------------------------------------------------------------
# Static VMEM-footprint model. Unlike pallas_apply's per-exchange kernel
# (13 MiB scoped budget), the resident kernel's whole point is to spend
# VMEM: the R rounds' factor stacks live in a single constant-index-map
# buffer (NOT double-buffered) while only the in/out row chunks pipeline.
# Budget = half the v5-lite 128 MiB VMEM, leaving the other half for
# Mosaic's own double-buffering of the four io chunk pairs, semaphores,
# and compiler scratch.
# --------------------------------------------------------------------------

VMEM_STEP_BUDGET = (128 << 20) // 2


def step_bytes(mc: int, k: int, b: int, r: int, itemsize: int = 4) -> int:
    """Per-grid-step VMEM bytes of the megakernel: top+bot in and out row
    chunks (double-buffered by the pipeline) plus the R resident factor
    stacks (single-buffered — their index map is constant across the
    grid)."""
    xio = 4 * k * mc * b * itemsize          # top+bot, in + out
    return 2 * xio + r * k * (2 * b) * (2 * b) * 4


def _pick_chunk(m: int, k: int, b: int, r: int, itemsize: int = 4) -> int:
    """Largest sublane-aligned divisor of m whose grid step fits the
    scoped-VMEM budget (the same divisor discipline as
    `pallas_apply._pick_chunk`). 0 if none is usable."""
    best = 0
    for c in range(8, m + 1, 8):
        if m % c:
            continue
        if step_bytes(c, k, b, r, itemsize) > VMEM_STEP_BUDGET:
            break
        best = c
    return best


def supported(m: int, b: int, k: int, r: int) -> bool:
    """Whether the compiled megakernel can take this geometry: lane-sized
    panels and a usable row chunk once the R factor stacks are resident."""
    return b % 128 == 0 and _pick_chunk(m, k, b, r) >= 128


def footprint(m: int, b: int, k: int, r: int, itemsize: int = 4) -> dict:
    """Static VMEM-budget report row for one geometry (the analysis
    pass's VMEM check renders these): the chosen chunk, its per-step
    bytes, the budget, and whether the lane fits."""
    mc = _pick_chunk(m, k, b, r, itemsize)
    return {
        "lane": "pallas_resident.apply_group",
        "m": int(m), "b": int(b), "k": int(k), "r": int(r),
        "row_chunk": int(mc),
        "step_bytes": int(step_bytes(max(mc, 8), k, b, r, itemsize)),
        "budget_bytes": int(VMEM_STEP_BUDGET),
        "fits": bool(mc > 0),
    }


# --------------------------------------------------------------------------
# Pair-major layout helpers. Block-column order [t_0, b_0, t_1, b_1, ...]
# so pair i's 2b x 2b Gram panel is the i-th diagonal block of G.
# --------------------------------------------------------------------------

def _pair_major_perm(kp: int) -> np.ndarray:
    """Old pair-major b-block position of each NEW position under one
    tournament rotation — derived by running the proven index simulation
    (`schedule.rotate_indices`) on position ids, so this table and the
    data rotation (`schedule.rotate_blocks`) cannot disagree."""
    top = 2 * np.arange(kp)
    bot = 2 * np.arange(kp) + 1
    ntop, nbot = sched.rotate_indices(top, bot)
    return np.stack([ntop, nbot], axis=1).reshape(-1)


def _to_pair_major(top, bot, batch: int = 1):
    """(k, m, b) stacks -> pair-major matrix: (m, 2*k*b) when batch == 1,
    else (batch, m, 2*k_per*b) per-member views."""
    k, m, b = top.shape
    x = jnp.stack([top, bot], axis=1).reshape(2 * k, m, b)
    if batch == 1:
        return x.transpose(1, 0, 2).reshape(m, 2 * k * b)
    kp = k // batch
    x = x.reshape(batch, 2 * kp, m, b)
    return x.transpose(0, 2, 1, 3).reshape(batch, m, 2 * kp * b)


def _from_pair_major(x, k: int, b: int, batch: int = 1):
    """Inverse of `_to_pair_major`."""
    if batch == 1:
        m = x.shape[0]
        pairs = x.reshape(m, 2 * k, b).transpose(1, 0, 2).reshape(k, 2, m, b)
        return pairs[:, 0], pairs[:, 1]
    m = x.shape[1]
    kp = k // batch
    pairs = x.reshape(batch, m, 2 * kp, b).transpose(0, 2, 1, 3)
    pairs = pairs.reshape(batch, kp, 2, m, b)
    return pairs[:, :, 0].reshape(k, m, b), pairs[:, :, 1].reshape(k, m, b)


def _full_gram(top, bot, batch: int = 1):
    """Pair-major full Gram of the padded working matrix, f32 HIGHEST —
    the once-per-sweep bootstrap that pins the carry to the true panels.
    Per-member (batch, n_p, n_p) on the batched lane (members are
    independent matrices; their cross terms do not exist)."""
    x = _to_pair_major(top, bot, batch)
    x = x.astype(jnp.float32)
    spec = "mi,mj->ij" if batch == 1 else "bmi,bmj->bij"
    return jnp.einsum(spec, x, x, precision=HI,
                      preferred_element_type=jnp.float32)


def _extract_pairs(g, k: int, b: int, batch: int = 1):
    """The k paired-diagonal (2b, 2b) panels of the pair-major carry."""
    w = 2 * b
    if batch == 1:
        gb = g.reshape(k, w, k, w)
        idx = jnp.arange(k)
        return gb[idx, :, idx, :]
    kp = k // batch

    def one(gm):
        gb = gm.reshape(kp, w, kp, w)
        idx = jnp.arange(kp)
        return gb[idx, :, idx, :]

    return jax.vmap(one)(g).reshape(k, w, w)


def _update_gram(g, q, k: int, b: int, batch: int = 1):
    """Advance the carry one round: G <- J^T G J (J = block-diagonal of
    the pair factors, in pair-major order) then the tournament block
    permutation on both sides. All n^2-scale f32-HIGHEST contractions —
    no panel touches."""
    w = 2 * b
    kp = k // batch
    n_p = 2 * kp * b
    # jnp.array, NOT jnp.asarray: asarray on a host constant lowers to a
    # device_put, and this runs inside the fused sweep loop (JAXPR003).
    perm = jnp.array(_pair_major_perm(kp))
    if batch == 1:
        gv = g.reshape(n_p, k, w)
        gv = jnp.einsum("mkj,kji->mki", gv, q, precision=HI,
                        preferred_element_type=jnp.float32)
        g = gv.reshape(n_p, n_p)
        gr = g.reshape(k, w, n_p)
        gr = jnp.einsum("kjm,kji->kim", gr, q, precision=HI,
                        preferred_element_type=jnp.float32)
        g = gr.reshape(n_p, n_p)
        g4 = g.reshape(2 * k, b, 2 * k, b)
        g4 = jnp.take(jnp.take(g4, perm, axis=0), perm, axis=2)
        return g4.reshape(n_p, n_p)
    qm = q.reshape(batch, kp, w, w)
    gv = g.reshape(batch, n_p, kp, w)
    gv = jnp.einsum("Bmkj,Bkji->Bmki", gv, qm, precision=HI,
                    preferred_element_type=jnp.float32)
    g = gv.reshape(batch, n_p, n_p)
    gr = g.reshape(batch, kp, w, n_p)
    gr = jnp.einsum("Bkjm,Bkji->Bkim", gr, qm, precision=HI,
                    preferred_element_type=jnp.float32)
    g = gr.reshape(batch, n_p, n_p)
    g4 = g.reshape(batch, 2 * kp, b, 2 * kp, b)
    g4 = jnp.take(jnp.take(g4, perm, axis=1), perm, axis=3)
    return g4.reshape(batch, n_p, n_p)


# --------------------------------------------------------------------------
# Group factor solve (n^2-scale; zero panel reads).
# --------------------------------------------------------------------------

def group_factors(g, dmax2, rtol, *, r: int, k: int, b: int,
                  batch: int = 1, last: bool = False):
    """(factors, g_out, stats, rotated) of the next ``r`` rounds.

    ``factors`` is (r, k, 2b, 2b) f32 — round rr's per-pair orthogonal
    transforms in THAT round's slot order (identity where the round-skip
    gate fired: the panels still exchange, matching `block_round_fused`'s
    skip branch, and an identity apply is bitwise-exact). ``stats`` is the
    per-round masked ABS coupling ((r,) scalar rounds, (r, batch)
    batched); ``rotated`` the int32 count of rounds whose gate fired.
    ``last``: the final group before the next sweep's fresh bootstrap —
    its last carry update would be dead work and is skipped."""
    with scope("resident_solve"):
        w = 2 * b
        factors, stats = [], []
        rotated = jnp.int32(0)
        for rr in range(r):
            gp = _extract_pairs(g, k, b, batch)
            if batch > 1:
                stat, skip = rounds.panel_stats(
                    gp, dmax2, members=rounds._members(batch, k // batch),
                    criterion="abs")
                skip = rounds._skip_stat(skip)
            else:
                stat, skip = rounds.panel_stats(gp, dmax2, criterion="abs")
            eye = jnp.broadcast_to(jnp.eye(w, dtype=jnp.float32),
                                   (k, w, w))
            q = jax.lax.cond(skip > rtol,
                             lambda p: br.accumulate(p),
                             lambda p: eye, gp)
            factors.append(q)
            stats.append(stat)
            rotated = rotated + (skip > rtol).astype(jnp.int32)
            if not (last and rr == r - 1):
                g = _update_gram(g, q, k, b, batch)
        return jnp.stack(factors), g, jnp.stack(stats), rotated


# --------------------------------------------------------------------------
# The panel pass: Pallas megakernel + XLA twins.
# --------------------------------------------------------------------------

def _kernel(top_ref, bot_ref, f_ref, out_t_ref, out_b_ref, *, k, b, r,
            batch, x3):
    """R rounds of rank-2b applies on one resident row chunk. The
    tournament exchange between rounds is a SLOT REMAP of the VMEM values
    (a trace-time renaming — zero moves, the megakernel's whole point);
    the (2b, 2b) factor is consumed as four (b, b) quadrants so each mm
    matches `pallas_apply._kernel`'s dot2 shapes exactly (the equivalence
    tests pin the two kernels bitwise against each other)."""
    f32 = jnp.float32
    bf16 = jnp.bfloat16

    def raw(x, wgt, prec):
        return jax.lax.dot_general(x, wgt, (((1,), (0,)), ((), ())),
                                   precision=prec,
                                   preferred_element_type=f32)

    def split(x):
        bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
        hi = jax.lax.bitcast_convert_type(bits & jnp.uint32(0xFFFF0000),
                                          f32)
        return hi.astype(bf16), (x - hi).astype(bf16)

    if top_ref.dtype == bf16:
        if x3:
            def mm(x, wgt):
                wh, wl = split(wgt)
                return raw(x, wh, None) + raw(x, wl, None)
        else:
            mm = lambda x, wgt: raw(x, wgt.astype(bf16), None)
    elif x3:
        def mm(x, wgt):
            xh, xl = split(x)
            wh, wl = split(wgt)
            return raw(xh, wh, None) + (raw(xl, wh, None)
                                        + raw(xh, wl, None))
    else:
        mm = lambda x, wgt: raw(x.astype(f32), wgt, HI)

    ts = [top_ref[i].astype(f32) for i in range(k)]
    bs = [bot_ref[i].astype(f32) for i in range(k)]
    kp = k // batch
    for rr in range(r):
        nts, nbs = [], []
        for i in range(k):
            q = f_ref[rr, i]
            nts.append(mm(ts[i], q[:b, :b]) + mm(bs[i], q[b:, :b]))
            nbs.append(mm(ts[i], q[:b, b:]) + mm(bs[i], q[b:, b:]))
        ts, bs = [], []
        for s in range(batch):
            t_seg = nts[s * kp:(s + 1) * kp]
            b_seg = nbs[s * kp:(s + 1) * kp]
            if kp > 1:
                t_seg, b_seg = ([t_seg[0], b_seg[0]] + t_seg[1:-1],
                                b_seg[1:] + [t_seg[-1]])
            ts += t_seg
            bs += b_seg
    for i in range(k):
        out_t_ref[i] = ts[i].astype(out_t_ref.dtype)
        out_b_ref[i] = bs[i].astype(out_b_ref.dtype)


def _apply_group_kernel(top, bot, factors, *, x3=False, batch=1,
                        interpret=False):
    """The megakernel launch: grid over row chunks only — the whole pair
    axis and every factor stack stay resident across the R in-kernel
    rounds, and the pipeline prefetches the next chunk behind the MXU
    work."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, m, b = top.shape
    r = int(factors.shape[0])
    mc = _pick_chunk(m, k, b, r, top.dtype.itemsize)
    if mc == 0:
        raise pa.VmemBudgetError(
            f"no usable VMEM row chunk for the resident megakernel at "
            f"(m, b, k, R) = ({m}, {b}, {k}, {r}) — the per-grid-step "
            f"working set exceeds the scoped-VMEM budget "
            f"({VMEM_STEP_BUDGET} bytes); lower rounds_resident or fall "
            f"back to pair_solver='block_rotation'",
            lane="pallas_resident.apply_group", fallback="block_rotation")
    x_spec = pl.BlockSpec((k, mc, b), lambda mi: (0, mi, 0),
                          memory_space=pltpu.VMEM)
    f_spec = pl.BlockSpec((r, k, 2 * b, 2 * b), lambda mi: (0, 0, 0, 0),
                          memory_space=pltpu.VMEM)
    out = jax.ShapeDtypeStruct((k, m, b), top.dtype)
    return pl.pallas_call(
        functools.partial(_kernel, k=k, b=b, r=r, batch=batch, x3=x3),
        grid=(m // mc,),
        in_specs=[x_spec, x_spec, f_spec],
        out_specs=[x_spec, x_spec],
        out_shape=[out, out],
        interpret=interpret,
    )(top, bot, factors.astype(jnp.float32))


def _apply_group_rounds(top, bot, factors, *, x3=False, batch=1):
    """XLA twin, iterated form: R jnp rounds of the same quadrant dot2 +
    `rotate_blocks` exchange — value-equivalent to the kernel (the
    interpret-mode tests pin it) and FLOP-optimal when R < k_per."""
    b = top.shape[-1]
    for rr in range(factors.shape[0]):
        q = factors[rr]
        nt = (rounds._einsum(top, q[:, :b, :b], "kmi,kij->kmj", x3=x3)
              + rounds._einsum(bot, q[:, b:, :b], "kmi,kij->kmj", x3=x3))
        nb = (rounds._einsum(top, q[:, :b, b:], "kmi,kij->kmj", x3=x3)
              + rounds._einsum(bot, q[:, b:, b:], "kmi,kij->kmj", x3=x3))
        top, bot = sched.rotate_blocks(nt.astype(top.dtype),
                                       nb.astype(bot.dtype), batch)
    return top, bot


def compose_w(factors, k: int, b: int, batch: int = 1):
    """The group's composed pair-major transform W (exchange permutations
    folded in): X_out = X_pair_major @ W. One n^2 * 2b-scale contraction
    per round — cheap next to the panel GEMM it amortizes."""
    kp = k // batch
    n_p = 2 * kp * b
    w2 = 2 * b
    perm = jnp.array(_pair_major_perm(kp))  # not asarray: see _update_gram
    r = factors.shape[0]
    if batch == 1:
        wmat = jnp.eye(n_p, dtype=jnp.float32)
        for rr in range(r):
            wv = wmat.reshape(n_p, k, w2)
            wv = jnp.einsum("mkj,kji->mki", wv, factors[rr], precision=HI,
                            preferred_element_type=jnp.float32)
            wmat = wv.reshape(n_p, 2 * k, b)
            wmat = jnp.take(wmat, perm, axis=1).reshape(n_p, n_p)
        return wmat
    wmat = jnp.broadcast_to(jnp.eye(n_p, dtype=jnp.float32),
                            (batch, n_p, n_p))
    fm = factors.reshape(r, batch, kp, w2, w2)
    for rr in range(r):
        wv = wmat.reshape(batch, n_p, kp, w2)
        wv = jnp.einsum("Bmkj,Bkji->Bmki", wv, fm[rr], precision=HI,
                        preferred_element_type=jnp.float32)
        wmat = wv.reshape(batch, n_p, 2 * kp, b)
        wmat = jnp.take(wmat, perm, axis=2).reshape(batch, n_p, n_p)
    return wmat


def _apply_group_composed(top, bot, factors, *, x3=False, batch=1):
    """XLA twin, composed form: ONE panel GEMM against `compose_w` —
    FLOP-optimal when R >= k_per, and the big-GEMM shape BLAS/XLA:CPU
    actually runs near peak (the measured source of the CPU lane win)."""
    k, m, b = top.shape
    wmat = compose_w(factors, k, b, batch)
    x = _to_pair_major(top, bot, batch)
    spec = "mi,ij->mj" if batch == 1 else "Bmi,Bij->Bmj"
    xn = rounds._einsum(x, wmat, spec, x3=x3).astype(top.dtype)
    return _from_pair_major(xn, k, b, batch)


def apply_group(top, bot, factors, *, interpret=False, x3=False,
                batch=1):
    """(new_top, new_bot) after the group's R rounds of applies and
    exchanges — the resident lane's one panel pass per R rounds."""
    k, m, b = top.shape
    r = int(factors.shape[0])
    with scope("resident_apply"):
        if not interpret and supported(m, b, k, r):
            return _apply_group_kernel(top, bot, factors, x3=x3,
                                       batch=batch)
        if r >= k // batch:
            return _apply_group_composed(top, bot, factors, x3=x3,
                                         batch=batch)
        return _apply_group_rounds(top, bot, factors, x3=x3, batch=batch)


# --------------------------------------------------------------------------
# Sweep + bulk iterate loops (the lane's drivers; mirror rounds.sweep_block
# / iterate_block so the solver's stage machinery treats both lanes alike).
# --------------------------------------------------------------------------

def sweep_resident(top, bot, vtop, vbot, dmax2, rtol, *, r_rounds: int,
                   interpret, apply_x3=False, telemetry=False, batch=1):
    """One resident-lane sweep: the 2k_per - 1 tournament rounds run in
    groups of ``r_rounds``, each group one `group_factors` + one
    `apply_group` panel pass per stack. Returns
    (top, bot, vtop, vbot, off[, rotated]) exactly like
    `rounds.sweep_block`. ``r_rounds <= 1`` (or a single pair) IS the
    blocked-rotation sweep — delegated verbatim, so R=1 is bitwise the
    `block_round_fused` chain."""
    k, m, b = top.shape
    kp = k // batch
    n_rounds = sched.num_rounds(2 * kp)
    r = max(1, min(int(r_rounds), n_rounds))
    if r <= 1 or kp == 1:
        return rounds.sweep_block(top, bot, vtop, vbot, dmax2, rtol,
                                  interpret=interpret, apply_x3=apply_x3,
                                  telemetry=telemetry, batch=batch)
    with_v = vtop is not None
    with scope("gram"):
        g0 = _full_gram(top, bot, batch)

    def group(carry, r_g, last):
        top, bot, vtop, vbot, g, mx = carry[:6]
        factors, g, stats, rotated = group_factors(
            g, dmax2, rtol, r=r_g, k=k, b=b, batch=batch, last=last)
        top, bot = apply_group(top, bot, factors, interpret=interpret,
                               x3=apply_x3, batch=batch)
        if with_v:
            vtop, vbot = apply_group(vtop, vbot, factors,
                                     interpret=interpret, x3=apply_x3,
                                     batch=batch)
        mx = jnp.maximum(mx, jnp.max(stats, axis=0))
        new = (top, bot, vtop, vbot, g, mx)
        if telemetry:
            new += (carry[6] + rotated,)
        return new

    if not with_v:
        vtop = vbot = jnp.zeros((k, 0, b), top.dtype)
    mx0 = (jnp.zeros((batch,), jnp.float32) if batch > 1
           else jnp.zeros((), jnp.float32))
    carry = (top, bot, vtop, vbot, g0, mx0)
    if telemetry:
        carry += (jnp.int32(0),)
    n_full, rem = divmod(n_rounds, r)
    # Equal-R groups ride one scan body (bounded trace size at any k);
    # the final group — the remainder, or the last full group when R
    # divides the round count — runs unrolled with the dead carry update
    # elided (the next sweep re-bootstraps G from the panels).
    n_scan, tail = (n_full, rem) if rem else (n_full - 1, r)
    if n_scan > 0:
        carry, _ = jax.lax.scan(lambda c, _: (group(c, r, False), None),
                                carry, None, length=n_scan)
    carry = group(carry, tail, True)
    top, bot, vtop, vbot, _, off = carry[:6]
    out = (top, bot, (vtop if with_v else None),
           (vbot if with_v else None), off)
    return out + (carry[6],) if telemetry else out


def iterate_resident(top, bot, vtop, vbot, *, r_rounds, abs_tol,
                     max_sweeps, interpret, apply_x3=False,
                     stall_detection=True, start_sweeps=0, telemetry=False,
                     stage="resident_bulk", nonfinite0=None,
                     chaos_nan_sweep=None):
    """`lax.while_loop` of `sweep_resident`s against the ABS criterion —
    the resident BULK phase (`rounds.iterate_block` semantics verbatim:
    stall gate 4*abs_tol / shrink 0.75, nonfinite rides the dmax2/off
    reductions, ``chaos_nan_sweep`` is the fault-injection hook). Returns
    (top, bot, vtop, vbot, off, sweeps, nonfinite)."""
    from ..resilience import chaos as _chaos
    with_v = vtop is not None
    k = top.shape[0]
    if vtop is None:
        vtop = vbot = jnp.zeros((k, 0, top.shape[2]), top.dtype)

    def cond(st):
        _, _, _, _, off, prev_off, sweeps, nonfinite = st
        return rounds.should_continue(
            off, prev_off, sweeps, tol=abs_tol, max_sweeps=max_sweeps,
            stall_detection=stall_detection, stall_gate=4.0 * abs_tol,
            stall_shrink=0.75, nonfinite=nonfinite)

    def body(st):
        top, bot, vtop, vbot, prev_off, _, sweeps, nonfinite = st
        if chaos_nan_sweep is not None:
            top = _chaos.poison(top, sweeps, chaos_nan_sweep)
        dmax2 = rounds._global_dmax2(top, bot)
        out = sweep_resident(
            top, bot, vtop if with_v else None, vbot if with_v else None,
            dmax2, abs_tol, r_rounds=r_rounds, interpret=interpret,
            apply_x3=apply_x3, telemetry=telemetry)
        top, bot, nvt, nvb, off = out[:5]
        nonfinite = nonfinite | ~jnp.isfinite(dmax2) | ~jnp.isfinite(off)
        if telemetry:
            metrics.emit("sweep",
                         meta={"path": "resident", "stage": stage},
                         sweep=sweeps + 1, off_rel=off,
                         rounds_rotated=out[5])
        if not with_v:
            nvt, nvb = st[2], st[3]
        return (top, bot, nvt, nvb, off, prev_off, sweeps + 1, nonfinite)

    inf = jnp.float32(jnp.inf)
    nf0 = (jnp.zeros((), jnp.bool_) if nonfinite0 is None
           else jnp.asarray(nonfinite0, jnp.bool_))
    state = (top, bot, vtop, vbot, inf, inf,
             jnp.asarray(start_sweeps, jnp.int32), nf0)
    top, bot, vtop, vbot, off, _, sweeps, nonfinite = jax.lax.while_loop(
        cond, body, state)
    return (top, bot, (vtop if with_v else None),
            (vbot if with_v else None), off, sweeps, nonfinite)


def iterate_resident_batched(top, bot, vtop, vbot, *, batch, r_rounds,
                             abs_tol, max_sweeps, interpret, apply_x3=False,
                             stall_detection=True, chaos_nan_sweep=None):
    """Batched resident bulk loop (`rounds.iterate_block_batched`
    semantics verbatim: per-member go-mask freezing, per-member health).
    Returns (top, bot, vtop, vbot, off (batch,), sweeps scalar,
    msweeps (batch,), nonfinite (batch,))."""
    from ..resilience import chaos as _chaos
    with_v = vtop is not None
    kb = top.shape[0]
    if vtop is None:
        vtop = vbot = jnp.zeros((kb, 0, top.shape[2]), top.dtype)

    def go_mask(off, prev_off, sweeps, nonfinite):
        return rounds.should_continue(
            off, prev_off, sweeps, tol=abs_tol, max_sweeps=max_sweeps,
            stall_detection=stall_detection, stall_gate=4.0 * abs_tol,
            stall_shrink=0.75, nonfinite=nonfinite)

    def cond(st):
        _, _, _, _, off, prev_off, sweeps, _, nonfinite = st
        return jnp.any(go_mask(off, prev_off, sweeps, nonfinite))

    def body(st):
        top, bot, vtop, vbot, off, prev_off, sweeps, msweeps, nonfinite = st
        go = go_mask(off, prev_off, sweeps, nonfinite)
        if chaos_nan_sweep is not None:
            top = _chaos.poison(top, sweeps, chaos_nan_sweep)
        dmax2 = rounds._global_dmax2(top, bot, batch=batch)
        out = sweep_resident(top, bot, vtop if with_v else None,
                             vbot if with_v else None, dmax2, abs_tol,
                             r_rounds=r_rounds, interpret=interpret,
                             apply_x3=apply_x3, batch=batch)
        top, bot, nvt, nvb, off_new = out[:5]
        nf_new = ~jnp.isfinite(dmax2) | ~jnp.isfinite(off_new)
        nonfinite = nonfinite | (go & nf_new)
        prev_off = jnp.where(go, off, prev_off)
        off = jnp.where(go, off_new, off)
        msweeps = msweeps + go.astype(jnp.int32)
        if not with_v:
            nvt, nvb = st[2], st[3]
        return (top, bot, nvt, nvb, off, prev_off, sweeps + 1, msweeps,
                nonfinite)

    inf = jnp.full((batch,), jnp.inf, jnp.float32)
    state = (top, bot, vtop, vbot, inf, inf, jnp.int32(0),
             jnp.zeros((batch,), jnp.int32),
             jnp.zeros((batch,), jnp.bool_))
    (top, bot, vtop, vbot, off, _, sweeps, msweeps,
     nonfinite) = jax.lax.while_loop(cond, body, state)
    return (top, bot, (vtop if with_v else None),
            (vbot if with_v else None), off, sweeps, msweeps, nonfinite)
