"""Utilities: matrix generation, validation oracles, reporting, checkpoints."""
