"""Sweep-boundary checkpoint/resume, hardened against real failure modes.

The reference has NO failure handling or checkpointing: MPI errors are
printed and execution carries on (reference: lib/JacobiMethods.cu:359-370,
614-616), and a killed job loses everything (SURVEY.md section 5). Here the
solver state between sweeps is just six arrays (SweepState), so snapshots
are cheap: `.npz` via numpy, atomic rename, with solver configuration and a
layout fingerprint stored alongside so a resume with mismatched shapes or
options fails fast instead of corrupting the solve.

Hardening (resilience PR; the `-m chaos` lane injects each failure):

  * every snapshot carries a SHA-256 payload checksum verified on load
    (zip CRCs catch torn files; the checksum additionally catches silent
    payload corruption and any partial-write the container survives);
  * writes are atomic AND durable: temp file fsync'd before the rename,
    parent directory fsync'd after it, temp removed on every failure path;
  * snapshots rotate (current + one previous generation): a corrupt or
    mismatched current snapshot is QUARANTINED (renamed aside for
    forensics, never deleted) and the resume falls back to the previous
    generation; only when no generation is loadable does the resume raise;
  * `svd_checkpointed` installs a SIGTERM handler for the duration of the
    solve: a preemption signal triggers one final snapshot at the next
    sweep boundary before the process dies (kill-then-resume loses at most
    the in-flight sweep, not ``every`` sweeps);
  * the multi-process save barrier has a TIMEOUT (a dead peer used to hang
    the barrier — and the job — forever).

Usage:
    r = svd_checkpointed(a, path="ckpt.npz", every=2)   # resumes if present
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import tempfile
import threading
import warnings
from pathlib import Path
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..config import SVDConfig
from ..resilience import chaos as _chaos
from ..solver import SolveStatus, SVDResult, SweepState, SweepStepper

_FORMAT = 3  # 3: payload checksum + snapshot rotation

# Multi-process save barrier deadline (seconds; SVDJ_CKPT_BARRIER_TIMEOUT_S
# overrides). A dead peer must fail the save loudly, not hang it forever.
_BARRIER_TIMEOUT_S = 300.0


class CheckpointCorruptError(ValueError):
    """A snapshot failed to load (torn/corrupt payload, checksum or
    fingerprint mismatch) and no rotated generation could take over.
    Subclasses ValueError: resume-validation failures have always raised
    ValueError here and callers match on that."""


def _proc_path(path) -> Path:
    """Per-process snapshot file for multi-process (pod-scale) runs."""
    import jax
    path = Path(path)
    return path.with_name(
        f"{path.name}.proc{jax.process_index()}of{jax.process_count()}")


def _is_multiprocess() -> bool:
    import jax
    return jax.process_count() > 1


def _sharded_snapshot(stepper) -> bool:
    """Per-process shard files are used only for MESH steppers in a
    multi-process runtime; a plain stepper's arrays are fully addressable
    and keep the single-file format even under a cluster (a shard-keyed
    file it could never reload would defeat the feature)."""
    return (_is_multiprocess()
            and getattr(stepper, "_sharding", None) is not None)


# One definition of the multi-host scalar readback (solver._host_scalar);
# re-exported because tests and workers reach for it here.
from ..solver import _host_scalar as _local_scalar


def _fingerprint(stepper: SweepStepper) -> dict:
    # The input content hash rejects a stale checkpoint from a *different*
    # matrix with the same layout (common when a parameter sweep reuses one
    # path); it is computed once and cached on the stepper.
    return {
        "format": _FORMAT,
        # Digest first: after a donate_input release this raises the loud
        # "input buffer was released" ValueError (checkpoint validation
        # needs the input content; release and checkpointing are
        # mutually exclusive by design).
        "input_sha256": stepper.input_digest(),
        "m": stepper.m, "n": stepper.n, "n_pad": stepper.n_pad,
        "nblocks": stepper.nblocks,
        "dtype": str(stepper.input_dtype),
        "compute_u": stepper.compute_u, "compute_v": stepper.compute_v,
        "full_matrices": stepper.full_matrices,
        "config": dataclasses.asdict(stepper.config),
        "stage": stepper.phase_info().stage,
        **stepper.fingerprint_extra(),
    }


def _fsync_dir(dirpath: Path) -> None:
    """fsync a directory so a completed rename is durable (an fsync'd FILE
    under a non-fsync'd directory entry can still vanish on power loss).
    Best-effort: some filesystems/platforms reject directory fsync."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _payload_checksum(payload: dict) -> str:
    """SHA-256 over every array's identity + bytes, key-sorted (stable
    regardless of np.savez's internal member order). Hashes through a
    zero-copy memoryview: the payload holds the FULL work stacks
    (multi-GB at the sizes that need checkpointing) and `.tobytes()`
    would transiently double host memory on every save."""
    h = hashlib.sha256()
    for key in sorted(payload):
        arr = np.ascontiguousarray(payload[key])
        h.update(key.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(memoryview(arr).cast("B"))
    return h.hexdigest()


def _write_npz_atomic(path: Path, payload: dict, pre_rename=None) -> None:
    fd, tmp = tempfile.mkstemp(dir=path.parent or Path("."),
                               suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
            # Flush to stable storage BEFORE the rename: without the fsync a
            # crash can leave an empty/truncated file under the final name —
            # the exact loss checkpointing exists to prevent.
            f.flush()
            os.fsync(f.fileno())
        if pre_rename is not None:
            pre_rename()
        os.replace(tmp, path)
        # ... and make the rename itself durable: the new directory entry
        # must reach stable storage too.
        _fsync_dir(path.parent or Path("."))
    finally:
        # Remove the temp file on EVERY failure path (np.savez error,
        # pre_rename/barrier failure, rename error) — a crash used to leak
        # `*.npz.tmp` files beside the snapshot.
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _prev_path(path: Path) -> Path:
    """The rotated previous-generation snapshot beside ``path``."""
    return path.with_name(path.name + ".prev")


def _quarantine(path: Path, why: str) -> Optional[Path]:
    """Move an unusable snapshot aside (never delete — it is forensic
    evidence) and warn. Destinations are uniquified so a later corruption
    event cannot overwrite earlier evidence. Returns the quarantine path,
    or None when the file was already gone."""
    if not path.exists():
        return None
    dest = path.with_name(path.name + ".quarantined")
    n = 1
    while dest.exists():
        dest = path.with_name(f"{path.name}.quarantined.{n}")
        n += 1
    os.replace(path, dest)
    warnings.warn(f"checkpoint {path} quarantined to {dest}: {why}",
                  RuntimeWarning, stacklevel=2)
    return dest


def _rotate(path: Path) -> None:
    """Keep one previous generation: current -> ``<name>.prev`` right
    before the fresh snapshot takes the final name."""
    if path.exists():
        os.replace(path, _prev_path(path))


def _run_barrier(fn, timeout: float, what: str) -> None:
    """Run a collective barrier with a deadline. The barrier itself cannot
    be cancelled (it blocks in native code), but a timed-out save must
    RAISE — an indefinitely hung save is strictly worse than a failed one
    (the job looks alive while making no progress and holding its TPUs)."""
    err = []

    def target():
        try:
            fn()
        except BaseException as e:  # re-raised on the caller thread
            err.append(e)

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise RuntimeError(
            f"{what} barrier timed out after {timeout:.0f}s — a peer "
            "process is unreachable (dead or wedged); aborting the save "
            "instead of hanging. Tune SVDJ_CKPT_BARRIER_TIMEOUT_S if the "
            "cluster is just slow.")
    if err:
        raise err[0]


def save_state(path, stepper: SweepStepper, state: SweepState) -> None:
    """Atomically snapshot ``state`` (write to temp file + rename).

    Single-process: one file holding the full arrays. Multi-process
    (pod-scale mesh solves — exactly the runs big enough to need
    snapshots): each process writes ONLY its addressable shards to its own
    ``<path>.procIofN`` file, so no host ever gathers a non-addressable
    global array (VERDICT r3 missing #3)."""
    path = Path(path)
    meta = json.dumps(_fingerprint(stepper))
    payload = {"meta": np.frombuffer(meta.encode(), dtype=np.uint8),
               "off_rel": _local_scalar(state.off_rel),
               "sweeps": _local_scalar(state.sweeps)}
    if not _sharded_snapshot(stepper):
        payload.update(top=np.asarray(state.top), bot=np.asarray(state.bot),
                       vtop=np.asarray(state.vtop),
                       vbot=np.asarray(state.vbot))
        payload["checksum"] = np.frombuffer(
            _payload_checksum(payload).encode(), dtype=np.uint8)
        _write_npz_atomic(path, payload, pre_rename=lambda: _rotate(path))
        return
    for name in ("top", "bot", "vtop", "vbot"):
        arr = getattr(state, name)
        # Addressable shards of the pair-slot-sharded stacks, keyed by
        # their global axis-0 offset (one shard per local device; the
        # reconstruction re-places each by offset; shards sharing an
        # offset are identical replicas and simply overwrite the key).
        for shard in arr.addressable_shards:
            start = shard.index[0].start or 0
            payload[f"{name}_{start}"] = np.asarray(shard.data)
    payload["checksum"] = np.frombuffer(
        _payload_checksum(payload).encode(), dtype=np.uint8)
    # Narrow the torn-snapshot window: every process finishes writing +
    # fsyncing its temp file BEFORE any renames land (barrier between the
    # two), so a kill during the long write phase leaves the previous
    # snapshot generation intact everywhere. A kill during the rename
    # syscalls themselves can still tear; load_state allgathers the
    # restored sweep counters and fails loudly on divergence. The barrier
    # runs behind a deadline: a dead peer fails the save instead of
    # hanging it (and the job) forever.
    from jax.experimental import multihost_utils

    ppath = _proc_path(path)
    timeout = float(os.environ.get("SVDJ_CKPT_BARRIER_TIMEOUT_S",
                                   _BARRIER_TIMEOUT_S))

    def pre_rename():
        _run_barrier(
            lambda: multihost_utils.sync_global_devices("svd_jacobi_ckpt_save"),
            timeout, "checkpoint save")
        _rotate(ppath)

    _write_npz_atomic(ppath, payload, pre_rename=pre_rename)


def _verify_checksum(z, path) -> None:
    """Recompute the payload checksum of an open npz and compare. Raises
    ValueError on mismatch or on a pre-checksum (format < 3) snapshot."""
    if "checksum" not in z.files:
        raise ValueError(f"checkpoint {path} has no payload checksum "
                         "(pre-format-3 snapshot)")
    want = bytes(z["checksum"]).decode()
    got = _payload_checksum({k: z[k] for k in z.files if k != "checksum"})
    if got != want:
        raise ValueError(
            f"checkpoint {path} failed its payload checksum "
            f"({got[:12]} != {want[:12]}): corrupt snapshot")


def _validate_meta(z, stepper, path) -> str:
    meta = json.loads(bytes(z["meta"]).decode())
    want = _fingerprint(stepper)
    stage = meta.pop("stage")
    want.pop("stage")
    if meta != want:
        raise ValueError(
            f"checkpoint {path} does not match this solve: "
            f"saved {meta}, expected {want}")
    return stage


def _load_single(path, stepper) -> SweepState:
    """Load + fully validate ONE single-process snapshot file (raises on
    any corruption/mismatch; the candidate loop in `load_state` decides
    what happens next)."""
    with np.load(path) as z:
        _verify_checksum(z, path)
        stage = _validate_meta(z, stepper, path)
        dtype = stepper.input_dtype
        state = SweepState(
            top=jnp.asarray(z["top"], dtype), bot=jnp.asarray(z["bot"], dtype),
            vtop=jnp.asarray(z["vtop"], dtype), vbot=jnp.asarray(z["vbot"], dtype),
            off_rel=jnp.float32(z["off_rel"]), sweeps=jnp.int32(z["sweeps"]))
    stepper.restore_stage(stage)
    return stepper.reshard(state)


def load_state(path, stepper: SweepStepper) -> SweepState:
    """Load a snapshot, validating checksum + layout/options fingerprint.

    A current snapshot that fails to load (torn file, checksum mismatch,
    fingerprint from a different solve) is QUARANTINED and the rotated
    previous generation takes over; only when no generation loads does
    this raise (the first failure's error, chained).

    Multi-process mesh solves: each process loads its own
    ``<path>.procIofN`` shard file and the global arrays are reassembled
    from per-device shards — the mirror of `save_state`'s per-process
    dump; the generation fallback is decided collectively so every
    process resumes the same sweep."""
    if _sharded_snapshot(stepper):
        return _load_state_multiprocess(path, stepper)
    path = Path(path)
    first_err = None
    for cand in (path, _prev_path(path)):
        if not cand.exists():
            continue
        try:
            return _load_single(cand, stepper)
        except Exception as e:  # noqa: BLE001 — any load failure is final
            first_err = first_err or e
            _quarantine(cand, f"{type(e).__name__}: {e}")
    raise CheckpointCorruptError(
        f"no loadable snapshot generation at {path} (unusable files were "
        f"quarantined beside it); first failure: {first_err}") from first_err


def _load_proc_file(ppath, stepper, sharding):
    """Load + fully validate THIS process's shard file of one snapshot
    generation; returns (SweepState, stage). Raises on any corruption."""
    import jax

    dtype = stepper.input_dtype
    k = stepper.nblocks // 2
    with np.load(ppath) as z:
        _verify_checksum(z, ppath)
        stage = _validate_meta(z, stepper, ppath)

        def shard_shape(name):
            # Block stacks are (k, rows, width): the sharded axis-0 extent
            # is global (k), the others are read off any saved shard.
            for key in z.files:
                if key.startswith(f"{name}_"):
                    return z[key].shape
            raise KeyError(f"snapshot {ppath} has no shards for {name!r}")

        state_arrays = {}
        for name in ("top", "bot", "vtop", "vbot"):
            _, rows, width = shard_shape(name)
            shape = (k, rows, width)
            imap = sharding.devices_indices_map(shape)
            arrs = []
            for dev in sharding.addressable_devices:
                start = imap[dev][0].start or 0
                arrs.append(jax.device_put(
                    jnp.asarray(z[f"{name}_{start}"], dtype), dev))
            state_arrays[name] = jax.make_array_from_single_device_arrays(
                shape, sharding, arrs)
        state = SweepState(
            top=state_arrays["top"], bot=state_arrays["bot"],
            vtop=state_arrays["vtop"], vbot=state_arrays["vbot"],
            off_rel=jnp.float32(z["off_rel"]), sweeps=jnp.int32(z["sweeps"]))
    return state, stage


def _load_state_multiprocess(path, stepper) -> SweepState:
    from jax.experimental import multihost_utils

    sharding = getattr(stepper, "_sharding", None)
    if sharding is None:
        raise ValueError("multi-process resume requires a mesh SweepStepper")
    ppath = _proc_path(path)
    first_err = None
    for cand in (ppath, _prev_path(ppath)):
        state = stage = err = None
        if cand.exists():
            try:
                state, stage = _load_proc_file(cand, stepper, sharding)
            except Exception as e:  # noqa: BLE001 — any load failure is final
                err = e
        # Generation fallback is a COLLECTIVE decision: every process must
        # have loaded this generation, else all quarantine it and fall
        # back together — a per-process fallback would mix generations and
        # silently diverge the sharded state.
        ok_all = bool(multihost_utils.process_allgather(
            np.asarray([state is not None])).all())
        if ok_all:
            # Torn-snapshot guard: a kill during save's rename phase can
            # leave processes holding files from DIFFERENT sweeps of the
            # same generation; resuming such a mix silently diverges the
            # sharded state (and can deadlock the collectives once
            # should_continue disagrees). Fail loudly instead.
            sweeps_all = multihost_utils.process_allgather(
                np.asarray([int(state.sweeps)]))
            if len(set(int(x) for x in sweeps_all.ravel())) != 1:
                raise RuntimeError(
                    f"torn multi-process checkpoint {path}: per-process "
                    f"snapshots are from different sweeps "
                    f"{sweeps_all.ravel().tolist()}; delete them and "
                    "restart the solve")
            stepper.restore_stage(stage)
            return state
        first_err = first_err or err
        if cand.exists():
            _quarantine(cand, "generation unusable on some process"
                        + (f" (here: {err})" if err else ""))
    raise CheckpointCorruptError(
        f"no loadable snapshot generation at {path} on every process "
        f"(unusable files were quarantined); first failure here: "
        f"{first_err}") from first_err


def svd_checkpointed(
    a,
    *,
    path,
    every: int = 1,
    mesh=None,
    compute_u: bool = True,
    compute_v: bool = True,
    full_matrices: bool = False,
    config: Optional[SVDConfig] = None,
    keep: bool = False,
) -> SVDResult:
    """`svd()` with sweep-boundary checkpointing and automatic resume.

    If ``path`` (or its rotated ``.prev`` generation) exists, the solve
    resumes from it (validating checksum + shape/config, quarantining
    corrupt generations — see `load_state`); otherwise it starts fresh. A
    snapshot is written every ``every`` sweeps, rotating the previous
    generation aside; the files are removed on successful completion
    unless ``keep``.

    SIGTERM (preemption) during the solve is intercepted: the current
    sweep finishes, ONE final snapshot is written, and the signal is
    re-delivered — so a preempted job loses at most the in-flight sweep
    and a plain re-run resumes where it died. (Handler installation is
    skipped off the main thread, where CPython forbids it.)

    ``mesh``: run the solve sharded over the given device mesh (the sharded
    `parallel.sharded.SweepStepper`); snapshots validate the mesh shape on
    resume. Single-controller scope (snapshots use fully-addressable
    arrays).
    """
    a = jnp.asarray(a)
    if a.ndim == 2 and a.shape[0] < a.shape[1]:
        r = svd_checkpointed(a.T, path=path, every=every, mesh=mesh,
                             compute_u=compute_v,
                             compute_v=compute_u, full_matrices=full_matrices,
                             config=config, keep=keep)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel, status=r.status)
    if mesh is not None:
        from ..parallel import sharded as _sharded
        stepper = _sharded.SweepStepper(
            a, mesh=mesh, compute_u=compute_u, compute_v=compute_v,
            full_matrices=full_matrices, config=config)
    else:
        stepper = SweepStepper(a, compute_u=compute_u, compute_v=compute_v,
                               full_matrices=full_matrices, config=config)
    path = Path(path)
    sharded_snap = _sharded_snapshot(stepper)
    local = _proc_path(path) if sharded_snap else path
    have = local.exists() or _prev_path(local).exists()
    if sharded_snap:
        # All-or-nothing: one process resuming while another starts fresh
        # would silently diverge the sharded state. One tiny allgather
        # decides for everyone.
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(np.asarray([have]))
        if bool(flags.any()) != bool(flags.all()):
            raise RuntimeError(
                "snapshot availability differs across processes "
                f"({flags.ravel().tolist()}); remove the stragglers or "
                "restore the missing per-process files before resuming")
        have = bool(flags.all())
    if have:
        state = load_state(path, stepper)
    else:
        state = stepper.init()

    # Preemption guard: note a SIGTERM, finish the in-flight sweep, write
    # one final snapshot, then re-deliver the signal with the previous
    # disposition so the process still dies a SIGTERM death.
    caught = {"sig": None}
    prev_handler, installed = None, False
    try:
        prev_handler = signal.signal(
            signal.SIGTERM, lambda sig, frame: caught.update(sig=sig))
        installed = True
    except ValueError:
        pass  # not the main thread: run without the handler

    def _restore_handler():
        # prev_handler is None when the old disposition was installed
        # from C (signal.signal cannot return it): fall back to SIG_DFL —
        # leaving OUR dead lambda installed would swallow every later
        # SIGTERM for the process lifetime.
        nonlocal installed
        if installed:
            signal.signal(signal.SIGTERM,
                          signal.SIG_DFL if prev_handler is None
                          else prev_handler)
            installed = False

    try:
        while stepper.should_continue(state):
            state = stepper.step(state)
            done = int(_local_scalar(state.sweeps))
            if done % every == 0:
                save_state(path, stepper, state)
            _chaos.maybe_sigterm(done)  # fault-injection hook (no-op unarmed)
            if caught["sig"] is not None:
                save_state(path, stepper, state)
                _restore_handler()
                os.kill(os.getpid(), signal.SIGTERM)
                # Only reached when the previous disposition ignored the
                # signal — still stop, snapshot is on disk.
                raise SystemExit(128 + int(caught["sig"]))
        result = stepper.finish(state)
    finally:
        was_installed = installed
        _restore_handler()
        if was_installed and caught["sig"] is not None:
            # SIGTERM landed in the final-sweep/finish window: the solve
            # is done, but the process was told to die — honor it after
            # restoring the previous disposition.
            os.kill(os.getpid(), signal.SIGTERM)
    if not keep:
        for f in (local, _prev_path(local)):
            if f.exists():
                f.unlink()
    return result
