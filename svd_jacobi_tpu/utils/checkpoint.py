"""Sweep-boundary checkpoint/resume.

The reference has NO failure handling or checkpointing: MPI errors are
printed and execution carries on (reference: lib/JacobiMethods.cu:359-370,
614-616), and a killed job loses everything (SURVEY.md section 5). Here the
solver state between sweeps is just six arrays (SweepState), so snapshots
are cheap: `.npz` via numpy, atomic rename, with solver configuration and a
layout fingerprint stored alongside so a resume with mismatched shapes or
options fails fast instead of corrupting the solve.

Usage:
    r = svd_checkpointed(a, path="ckpt.npz", every=2)   # resumes if present
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from pathlib import Path
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..config import SVDConfig
from ..solver import SVDResult, SweepState, SweepStepper

_FORMAT = 2


def _proc_path(path) -> Path:
    """Per-process snapshot file for multi-process (pod-scale) runs."""
    import jax
    path = Path(path)
    return path.with_name(
        f"{path.name}.proc{jax.process_index()}of{jax.process_count()}")


def _is_multiprocess() -> bool:
    import jax
    return jax.process_count() > 1


def _sharded_snapshot(stepper) -> bool:
    """Per-process shard files are used only for MESH steppers in a
    multi-process runtime; a plain stepper's arrays are fully addressable
    and keep the single-file format even under a cluster (a shard-keyed
    file it could never reload would defeat the feature)."""
    return (_is_multiprocess()
            and getattr(stepper, "_sharding", None) is not None)


# One definition of the multi-host scalar readback (solver._host_scalar);
# re-exported because tests and workers reach for it here.
from ..solver import _host_scalar as _local_scalar


def _fingerprint(stepper: SweepStepper) -> dict:
    # The input content hash rejects a stale checkpoint from a *different*
    # matrix with the same layout (common when a parameter sweep reuses one
    # path); it is computed once and cached on the stepper.
    return {
        "format": _FORMAT,
        # Digest first: after a donate_input release this raises the loud
        # "input buffer was released" ValueError (checkpoint validation
        # needs the input content; release and checkpointing are
        # mutually exclusive by design).
        "input_sha256": stepper.input_digest(),
        "m": stepper.m, "n": stepper.n, "n_pad": stepper.n_pad,
        "nblocks": stepper.nblocks,
        "dtype": str(stepper.input_dtype),
        "compute_u": stepper.compute_u, "compute_v": stepper.compute_v,
        "full_matrices": stepper.full_matrices,
        "config": dataclasses.asdict(stepper.config),
        "stage": stepper.phase_info().stage,
        **stepper.fingerprint_extra(),
    }


def _write_npz_atomic(path: Path, payload: dict, pre_rename=None) -> None:
    fd, tmp = tempfile.mkstemp(dir=path.parent or Path("."),
                               suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
            # Flush to stable storage BEFORE the rename: without the fsync a
            # crash can leave an empty/truncated file under the final name —
            # the exact loss checkpointing exists to prevent.
            f.flush()
            os.fsync(f.fileno())
        if pre_rename is not None:
            pre_rename()
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def save_state(path, stepper: SweepStepper, state: SweepState) -> None:
    """Atomically snapshot ``state`` (write to temp file + rename).

    Single-process: one file holding the full arrays. Multi-process
    (pod-scale mesh solves — exactly the runs big enough to need
    snapshots): each process writes ONLY its addressable shards to its own
    ``<path>.procIofN`` file, so no host ever gathers a non-addressable
    global array (VERDICT r3 missing #3)."""
    path = Path(path)
    meta = json.dumps(_fingerprint(stepper))
    payload = {"meta": np.frombuffer(meta.encode(), dtype=np.uint8),
               "off_rel": _local_scalar(state.off_rel),
               "sweeps": _local_scalar(state.sweeps)}
    if not _sharded_snapshot(stepper):
        payload.update(top=np.asarray(state.top), bot=np.asarray(state.bot),
                       vtop=np.asarray(state.vtop),
                       vbot=np.asarray(state.vbot))
        _write_npz_atomic(path, payload)
        return
    for name in ("top", "bot", "vtop", "vbot"):
        arr = getattr(state, name)
        # Addressable shards of the pair-slot-sharded stacks, keyed by
        # their global axis-0 offset (one shard per local device; the
        # reconstruction re-places each by offset; shards sharing an
        # offset are identical replicas and simply overwrite the key).
        for shard in arr.addressable_shards:
            start = shard.index[0].start or 0
            payload[f"{name}_{start}"] = np.asarray(shard.data)
    # Narrow the torn-snapshot window: every process finishes writing +
    # fsyncing its temp file BEFORE any renames land (barrier between the
    # two), so a kill during the long write phase leaves the previous
    # snapshot generation intact everywhere. A kill during the rename
    # syscalls themselves can still tear; load_state allgathers the
    # restored sweep counters and fails loudly on divergence.
    from jax.experimental import multihost_utils

    def barrier():
        multihost_utils.sync_global_devices("svd_jacobi_ckpt_save")

    _write_npz_atomic(_proc_path(path), payload, pre_rename=barrier)


def _validate_meta(z, stepper, path) -> str:
    meta = json.loads(bytes(z["meta"]).decode())
    want = _fingerprint(stepper)
    stage = meta.pop("stage")
    want.pop("stage")
    if meta != want:
        raise ValueError(
            f"checkpoint {path} does not match this solve: "
            f"saved {meta}, expected {want}")
    return stage


def load_state(path, stepper: SweepStepper) -> SweepState:
    """Load a snapshot, validating it matches this solve's layout/options.

    Multi-process mesh solves: each process loads its own
    ``<path>.procIofN`` shard file and the global arrays are reassembled
    from per-device shards — the mirror of `save_state`'s per-process
    dump."""
    if _sharded_snapshot(stepper):
        return _load_state_multiprocess(path, stepper)
    with np.load(path) as z:
        stage = _validate_meta(z, stepper, path)
        dtype = stepper.input_dtype
        state = SweepState(
            top=jnp.asarray(z["top"], dtype), bot=jnp.asarray(z["bot"], dtype),
            vtop=jnp.asarray(z["vtop"], dtype), vbot=jnp.asarray(z["vbot"], dtype),
            off_rel=jnp.float32(z["off_rel"]), sweeps=jnp.int32(z["sweeps"]))
    stepper.restore_stage(stage)
    return stepper.reshard(state)


def _load_state_multiprocess(path, stepper) -> SweepState:
    import jax

    sharding = getattr(stepper, "_sharding", None)
    if sharding is None:
        raise ValueError("multi-process resume requires a mesh SweepStepper")
    ppath = _proc_path(path)
    dtype = stepper.input_dtype
    k = stepper.nblocks // 2
    with np.load(ppath) as z:
        stage = _validate_meta(z, stepper, ppath)

        def shard_shape(name):
            # Block stacks are (k, rows, width): the sharded axis-0 extent
            # is global (k), the others are read off any saved shard.
            for key in z.files:
                if key.startswith(f"{name}_"):
                    return z[key].shape
            raise KeyError(f"snapshot {ppath} has no shards for {name!r}")

        state_arrays = {}
        for name in ("top", "bot", "vtop", "vbot"):
            _, rows, width = shard_shape(name)
            shape = (k, rows, width)
            imap = sharding.devices_indices_map(shape)
            arrs = []
            for dev in sharding.addressable_devices:
                start = imap[dev][0].start or 0
                arrs.append(jax.device_put(
                    jnp.asarray(z[f"{name}_{start}"], dtype), dev))
            state_arrays[name] = jax.make_array_from_single_device_arrays(
                shape, sharding, arrs)
        state = SweepState(
            top=state_arrays["top"], bot=state_arrays["bot"],
            vtop=state_arrays["vtop"], vbot=state_arrays["vbot"],
            off_rel=jnp.float32(z["off_rel"]), sweeps=jnp.int32(z["sweeps"]))
    # Torn-snapshot guard: a kill during save's rename phase can leave
    # processes holding files from DIFFERENT sweeps; resuming such a mix
    # silently diverges the sharded state (and can deadlock the
    # collectives once should_continue disagrees). Fail loudly instead.
    from jax.experimental import multihost_utils
    sweeps_all = multihost_utils.process_allgather(
        np.asarray([int(state.sweeps)]))
    if len(set(int(x) for x in sweeps_all.ravel())) != 1:
        raise RuntimeError(
            f"torn multi-process checkpoint {path}: per-process snapshots "
            f"are from different sweeps {sweeps_all.ravel().tolist()}; "
            "delete them and restart the solve")
    stepper.restore_stage(stage)
    return state


def svd_checkpointed(
    a,
    *,
    path,
    every: int = 1,
    mesh=None,
    compute_u: bool = True,
    compute_v: bool = True,
    full_matrices: bool = False,
    config: Optional[SVDConfig] = None,
    keep: bool = False,
) -> SVDResult:
    """`svd()` with sweep-boundary checkpointing and automatic resume.

    If ``path`` exists, the solve resumes from it (validating shape/config);
    otherwise it starts fresh. A snapshot is written every ``every`` sweeps;
    the file is removed on successful completion unless ``keep``.

    ``mesh``: run the solve sharded over the given device mesh (the sharded
    `parallel.sharded.SweepStepper`); snapshots validate the mesh shape on
    resume. Single-controller scope (snapshots use fully-addressable
    arrays).
    """
    a = jnp.asarray(a)
    if a.ndim == 2 and a.shape[0] < a.shape[1]:
        r = svd_checkpointed(a.T, path=path, every=every, mesh=mesh,
                             compute_u=compute_v,
                             compute_v=compute_u, full_matrices=full_matrices,
                             config=config, keep=keep)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel)
    if mesh is not None:
        from ..parallel import sharded as _sharded
        stepper = _sharded.SweepStepper(
            a, mesh=mesh, compute_u=compute_u, compute_v=compute_v,
            full_matrices=full_matrices, config=config)
    else:
        stepper = SweepStepper(a, compute_u=compute_u, compute_v=compute_v,
                               full_matrices=full_matrices, config=config)
    path = Path(path)
    sharded_snap = _sharded_snapshot(stepper)
    local = _proc_path(path) if sharded_snap else path
    have = local.exists()
    if sharded_snap:
        # All-or-nothing: one process resuming while another starts fresh
        # would silently diverge the sharded state. One tiny allgather
        # decides for everyone.
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(np.asarray([have]))
        if bool(flags.any()) != bool(flags.all()):
            raise RuntimeError(
                "snapshot availability differs across processes "
                f"({flags.ravel().tolist()}); remove the stragglers or "
                "restore the missing per-process files before resuming")
        have = bool(flags.all())
    if have:
        state = load_state(path, stepper)
    else:
        state = stepper.init()
    while stepper.should_continue(state):
        state = stepper.step(state)
        if int(_local_scalar(state.sweeps)) % every == 0:
            save_state(path, stepper, state)
    result = stepper.finish(state)
    if local.exists() and not keep:
        local.unlink()
    return result
