"""Sweep-boundary checkpoint/resume.

The reference has NO failure handling or checkpointing: MPI errors are
printed and execution carries on (reference: lib/JacobiMethods.cu:359-370,
614-616), and a killed job loses everything (SURVEY.md section 5). Here the
solver state between sweeps is just six arrays (SweepState), so snapshots
are cheap: `.npz` via numpy, atomic rename, with solver configuration and a
layout fingerprint stored alongside so a resume with mismatched shapes or
options fails fast instead of corrupting the solve.

Usage:
    r = svd_checkpointed(a, path="ckpt.npz", every=2)   # resumes if present
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from pathlib import Path
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..config import SVDConfig
from ..solver import SVDResult, SweepState, SweepStepper

_FORMAT = 2


def _fingerprint(stepper: SweepStepper) -> dict:
    # The input content hash rejects a stale checkpoint from a *different*
    # matrix with the same layout (common when a parameter sweep reuses one
    # path); it is computed once and cached on the stepper.
    return {
        "format": _FORMAT,
        "m": stepper.m, "n": stepper.n, "n_pad": stepper.n_pad,
        "nblocks": stepper.nblocks,
        "dtype": str(stepper.a.dtype),
        "input_sha256": stepper.input_digest(),
        "compute_u": stepper.compute_u, "compute_v": stepper.compute_v,
        "full_matrices": stepper.full_matrices,
        "config": dataclasses.asdict(stepper.config),
        "stage": stepper._stage,
        **stepper.fingerprint_extra(),
    }


def save_state(path, stepper: SweepStepper, state: SweepState) -> None:
    """Atomically snapshot ``state`` (write to temp file + rename)."""
    path = Path(path)
    meta = json.dumps(_fingerprint(stepper))
    fd, tmp = tempfile.mkstemp(dir=path.parent or Path("."),
                               suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, meta=np.frombuffer(meta.encode(), dtype=np.uint8),
                     top=np.asarray(state.top), bot=np.asarray(state.bot),
                     vtop=np.asarray(state.vtop), vbot=np.asarray(state.vbot),
                     off_rel=np.asarray(state.off_rel),
                     sweeps=np.asarray(state.sweeps))
            # Flush to stable storage BEFORE the rename: without the fsync a
            # crash can leave an empty/truncated file under the final name —
            # the exact loss checkpointing exists to prevent.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_state(path, stepper: SweepStepper) -> SweepState:
    """Load a snapshot, validating it matches this solve's layout/options."""
    with np.load(path) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        want = _fingerprint(stepper)
        stage = meta.pop("stage")
        want.pop("stage")
        if meta != want:
            raise ValueError(
                f"checkpoint {path} does not match this solve: "
                f"saved {meta}, expected {want}")
        dtype = stepper.a.dtype
        state = SweepState(
            top=jnp.asarray(z["top"], dtype), bot=jnp.asarray(z["bot"], dtype),
            vtop=jnp.asarray(z["vtop"], dtype), vbot=jnp.asarray(z["vbot"], dtype),
            off_rel=jnp.float32(z["off_rel"]), sweeps=jnp.int32(z["sweeps"]))
    stepper._stage = stage
    return stepper.reshard(state)


def svd_checkpointed(
    a,
    *,
    path,
    every: int = 1,
    mesh=None,
    compute_u: bool = True,
    compute_v: bool = True,
    full_matrices: bool = False,
    config: Optional[SVDConfig] = None,
    keep: bool = False,
) -> SVDResult:
    """`svd()` with sweep-boundary checkpointing and automatic resume.

    If ``path`` exists, the solve resumes from it (validating shape/config);
    otherwise it starts fresh. A snapshot is written every ``every`` sweeps;
    the file is removed on successful completion unless ``keep``.

    ``mesh``: run the solve sharded over the given device mesh (the sharded
    `parallel.sharded.SweepStepper`); snapshots validate the mesh shape on
    resume. Single-controller scope (snapshots use fully-addressable
    arrays).
    """
    a = jnp.asarray(a)
    if a.ndim == 2 and a.shape[0] < a.shape[1]:
        r = svd_checkpointed(a.T, path=path, every=every, mesh=mesh,
                             compute_u=compute_v,
                             compute_v=compute_u, full_matrices=full_matrices,
                             config=config, keep=keep)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel)
    if mesh is not None:
        from ..parallel import sharded as _sharded
        stepper = _sharded.SweepStepper(
            a, mesh=mesh, compute_u=compute_u, compute_v=compute_v,
            full_matrices=full_matrices, config=config)
    else:
        stepper = SweepStepper(a, compute_u=compute_u, compute_v=compute_v,
                               full_matrices=full_matrices, config=config)
    path = Path(path)
    if path.exists():
        state = load_state(path, stepper)
    else:
        state = stepper.init()
    while stepper.should_continue(state):
        state = stepper.step(state)
        if int(state.sweeps) % every == 0:
            save_state(path, stepper, state)
    result = stepper.finish(state)
    if path.exists() and not keep:
        path.unlink()
    return result
