"""Observability: per-sweep structured metrics and profiler tracing.

The reference's only instrumentation is a wall-clock bracket around the
solver call plus stdout prints mirrored to a report file (reference:
`omp_get_wtime` at main.cu:1586,1610; report at main.cu:1667-1669). Here:

  * `trace(dir)` — context manager around `jax.profiler` for XLA-level
    traces viewable in TensorBoard/Perfetto;
  * `instrumented_svd(a, ...)` — runs the solve sweep-by-sweep (SweepStepper)
    and records per-sweep off-norm, stage, and wall time, returning
    (result, SweepLog); `SweepLog.to_json()` is the structured successor of
    the reference's free-text report.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import List, NamedTuple, Optional

import numpy as np

from ..config import SVDConfig
from ..solver import SVDResult, SweepStepper


class SweepRecord(NamedTuple):
    sweep: int
    stage: str          # "bulk" | "polish" | "single"
    method: str
    off_norm: float     # convergence statistic AFTER this sweep
    time_s: float


class SweepLog(NamedTuple):
    records: List[SweepRecord]
    total_time_s: float

    def to_json(self) -> str:
        return json.dumps({
            "total_time_s": self.total_time_s,
            "sweeps": [r._asdict() for r in self.records],
        }, indent=2)


@contextlib.contextmanager
def trace(log_dir: str):
    """XLA profiler trace of the enclosed block (TensorBoard-viewable)."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def _sync(x) -> float:
    from ._exec import force
    return force(x)


def instrumented_svd(
    a,
    *,
    mesh=None,
    compute_u: bool = True,
    compute_v: bool = True,
    full_matrices: bool = False,
    config: Optional[SVDConfig] = None,
):
    """-> (SVDResult, SweepLog): the solve with per-sweep metrics.

    Runs one jitted sweep per host step, so each record's wall time is the
    real device time of that sweep (first sweep of each stage includes its
    compilation). ``mesh``: instrument the SHARDED solve over the given
    device mesh instead of the single-device one.
    """
    import jax.numpy as jnp
    a = jnp.asarray(a)
    if a.ndim == 2 and a.shape[0] < a.shape[1]:
        r, log = instrumented_svd(a.T, mesh=mesh, compute_u=compute_v,
                                  compute_v=compute_u,
                                  full_matrices=full_matrices, config=config)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel), log
    if mesh is not None:
        from ..parallel import sharded as _sharded
        stepper = _sharded.SweepStepper(
            a, mesh=mesh, compute_u=compute_u, compute_v=compute_v,
            full_matrices=full_matrices, config=config)
    else:
        stepper = SweepStepper(a, compute_u=compute_u, compute_v=compute_v,
                               full_matrices=full_matrices, config=config)
    state = stepper.init()
    records: List[SweepRecord] = []
    t_all = time.perf_counter()
    while stepper.should_continue(state):
        method, _, _ = stepper._phase()
        stage = stepper._stage
        t0 = time.perf_counter()
        state = stepper.step(state)
        _sync(state.off_rel)
        records.append(SweepRecord(
            sweep=int(state.sweeps), stage=stage, method=method,
            off_norm=float(state.off_rel), time_s=time.perf_counter() - t0))
    result = stepper.finish(state)
    _sync(result.s)
    log = SweepLog(records=records,
                   total_time_s=time.perf_counter() - t_all)
    return result, log
