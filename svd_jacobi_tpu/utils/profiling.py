"""Host-stepped per-sweep instrumentation (compat shim over `obs`).

The reference's only instrumentation is a wall-clock bracket around the
solver call plus stdout prints mirrored to a report file (reference:
`omp_get_wtime` at main.cu:1586,1610; report at main.cu:1667-1669).

This module predates the `svd_jacobi_tpu.obs` telemetry subsystem and is
now a thin layer over it:

  * `trace(dir)` — re-export of `obs.trace`: a robust `jax.profiler`
    context (creates the dir, warns instead of raising when the profiler
    is unavailable on the backend);
  * `instrumented_svd(a, ...)` — runs the solve sweep-by-sweep
    (SweepStepper) and records per-sweep off-norm, stage, and wall time,
    returning (result, SweepLog).

NOTE on methodology: `instrumented_svd` host-steps the solve, so it
measures a DIFFERENT program than the fused `solver.svd`/`sharded.svd`
paths (one jitted sweep per device execution vs. one fused while_loop;
see PROFILE.md's intra-jit section). Use it when you want real per-sweep
*wall times* under host control. To observe the fused solve itself
without perturbing it, use the in-graph event stream instead:

    with obs.metrics.capture() as events:
        r = sj.svd(a)          # fused solve, telemetry baked into the jit
"""

from __future__ import annotations

import json
import time
from typing import List, NamedTuple, Optional

from ..config import SVDConfig
from ..obs.trace import trace  # noqa: F401  (public re-export)
from ..solver import SVDResult, SweepStepper


class SweepRecord(NamedTuple):
    sweep: int
    stage: str          # "bulk" | "polish" | "single"
    method: str
    off_norm: float     # convergence statistic AFTER this sweep
    time_s: float


class SweepLog(NamedTuple):
    records: List[SweepRecord]
    total_time_s: float

    def to_json(self) -> str:
        return json.dumps({
            "total_time_s": self.total_time_s,
            "sweeps": [r._asdict() for r in self.records],
        }, indent=2)

    def to_events(self) -> List[dict]:
        """The log as `obs.manifest`-schema telemetry events (so a
        host-stepped run's sweep stream drops into the same manifest slot
        as a fused run's `obs.metrics.capture` stream)."""
        return [{"event": "sweep", "path": "stepped", "sweep": r.sweep,
                 "stage": r.stage, "method": r.method, "off_rel": r.off_norm,
                 "time_s": r.time_s} for r in self.records]


def _sync(x) -> float:
    from ._exec import force
    return force(x)


def instrumented_svd(
    a,
    *,
    mesh=None,
    compute_u: bool = True,
    compute_v: bool = True,
    full_matrices: bool = False,
    config: Optional[SVDConfig] = None,
):
    """-> (SVDResult, SweepLog): the solve with per-sweep metrics.

    Runs one jitted sweep per host step, so each record's wall time is the
    real device time of that sweep (first sweep of each stage includes its
    compilation). ``mesh``: instrument the SHARDED solve over the given
    device mesh instead of the single-device one.
    """
    import jax.numpy as jnp
    a = jnp.asarray(a)
    if a.ndim == 2 and a.shape[0] < a.shape[1]:
        r, log = instrumented_svd(a.T, mesh=mesh, compute_u=compute_v,
                                  compute_v=compute_u,
                                  full_matrices=full_matrices, config=config)
        return SVDResult(u=r.v, s=r.s, v=r.u, sweeps=r.sweeps,
                         off_rel=r.off_rel, status=r.status), log
    if mesh is not None:
        from ..parallel import sharded as _sharded
        stepper = _sharded.SweepStepper(
            a, mesh=mesh, compute_u=compute_u, compute_v=compute_v,
            full_matrices=full_matrices, config=config)
    else:
        stepper = SweepStepper(a, compute_u=compute_u, compute_v=compute_v,
                               full_matrices=full_matrices, config=config)
    state = stepper.init()
    records: List[SweepRecord] = []
    t_all = time.perf_counter()
    while stepper.should_continue(state):
        phase = stepper.phase_info(state)
        t0 = time.perf_counter()
        state = stepper.step(state)
        _sync(state.off_rel)
        records.append(SweepRecord(
            sweep=int(state.sweeps), stage=phase.stage, method=phase.method,
            off_norm=float(state.off_rel), time_s=time.perf_counter() - t0))
    result = stepper.finish(state)
    _sync(result.s)
    log = SweepLog(records=records,
                   total_time_s=time.perf_counter() - t_all)
    return result, log
