"""Force device execution without timing/paying host transfers.

`jax.block_until_ready` does not reliably synchronize through the axon TPU
tunnel, and a full device->host copy of large factors through the tunnel
would dominate any measurement — so every timing path (bench.py, cli.py,
utils/profiling.py) reduces outputs to ONE scalar on device and materializes
only that.
"""

from __future__ import annotations

import numpy as np


def force(tree) -> float:
    import jax
    import jax.numpy as jnp
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if x is not None]
    return float(np.asarray(sum(jnp.sum(x) for x in leaves)))


def host_scalar(x) -> float:
    """THE sanctioned device->host scalar read for library code.

    `float(x)` / `np.asarray(x)` on a `jax.Array` is a host sync, and on a
    multi-host mesh it simply raises for non-fully-addressable arrays even
    when every shard holds the same replicated value (pmax'd convergence
    statistics, sweep counters). Reading a scalar correctly therefore needs
    three cases, and scattering them across call sites is how
    solver.py grew its ad-hoc `addressable_shards[0]` pattern — so they
    live here once (the GRAFT001 lint points violators at this helper):

      * plain Python/numpy scalars and fully-addressable arrays: `float()`;
      * non-fully-addressable arrays with local shards: read this
        process's first addressable shard (replicated by contract — the
        caller must only pass mesh-replicated scalars, e.g. `P()` outputs);
      * non-fully-addressable arrays with NO local shard (a coordinator
        process outside the mesh, or an empty-shard process of an uneven
        assignment): there is nothing to read locally — raise a diagnosable
        error naming the fix instead of an opaque runtime failure.
    """
    import jax
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        shards = x.addressable_shards
        if not shards:
            raise RuntimeError(
                "host_scalar: array owns no addressable shard on this "
                "process, so its value cannot be read here. Replicate the "
                "scalar across the mesh (shard_map out_specs=P()) or "
                "gather it explicitly with "
                "jax.experimental.multihost_utils.process_allgather before "
                "reading.")
        return float(np.asarray(shards[0].data))
    return float(x)


def probe_devices(timeout: float):
    """(devices, error) — `jax.devices()` behind a deadline.

    Device discovery HANGS (never returns) when the attachment's device
    pool is down (PROFILE.md item 19's environment), so callers that must
    stay responsive (bench.py's watchdog, `dryrun_multichip`'s
    CPU-fallback decision) probe it on a daemon thread. Returns
    (devices, None) on success, (None, message) when discovery raised —
    reported verbatim, a fast error is NOT a hang — or (None, None) when
    it timed out."""
    import threading

    import jax

    out = {}

    def _discover():
        try:
            out["devices"] = jax.devices()
        except Exception as e:
            out["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_discover, daemon=True)
    t.start()
    t.join(timeout=timeout)
    return out.get("devices"), out.get("error")
