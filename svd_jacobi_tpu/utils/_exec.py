"""Force device execution without timing/paying host transfers.

`jax.block_until_ready` does not reliably synchronize through the axon TPU
tunnel, and a full device->host copy of large factors through the tunnel
would dominate any measurement — so every timing path (bench.py, cli.py,
utils/profiling.py) reduces outputs to ONE scalar on device and materializes
only that.
"""

from __future__ import annotations

import numpy as np


def force(tree) -> float:
    import jax
    import jax.numpy as jnp
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if x is not None]
    return float(np.asarray(sum(jnp.sum(x) for x in leaves)))


def probe_devices(timeout: float):
    """(devices, error) — `jax.devices()` behind a deadline.

    Device discovery HANGS (never returns) when the attachment's device
    pool is down (PROFILE.md item 19's environment), so callers that must
    stay responsive (bench.py's watchdog, `dryrun_multichip`'s
    CPU-fallback decision) probe it on a daemon thread. Returns
    (devices, None) on success, (None, message) when discovery raised —
    reported verbatim, a fast error is NOT a hang — or (None, None) when
    it timed out."""
    import threading

    import jax

    out = {}

    def _discover():
        try:
            out["devices"] = jax.devices()
        except Exception as e:
            out["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_discover, daemon=True)
    t.start()
    t.join(timeout=timeout)
    return out.get("devices"), out.get("error")
