"""Force device execution without timing/paying host transfers.

`jax.block_until_ready` does not reliably synchronize through the axon TPU
tunnel, and a full device->host copy of large factors through the tunnel
would dominate any measurement — so every timing path (bench.py, cli.py,
utils/profiling.py) reduces outputs to ONE scalar on device and materializes
only that.
"""

from __future__ import annotations

import numpy as np


def force(tree) -> float:
    import jax
    import jax.numpy as jnp
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if x is not None]
    return float(np.asarray(sum(jnp.sum(x) for x in leaves)))
