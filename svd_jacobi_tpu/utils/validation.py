"""Validation oracles: residual, orthogonality, sigma-error.

Replaces the reference's only correctness check — an O(N^3) OpenMP
triple-loop recomputation of ||A - U Sigma V^T||_F on the host
(reference: main.cu:1511-1533 warm-up, main.cu:1640-1665 MPI run) — with
jit-compiled device-side checks, and adds the orthogonality and sigma-oracle
checks the reference lacks (SURVEY.md section 4).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class ValidationReport(NamedTuple):
    residual_rel: Optional[jax.Array]  # ||A - U S V^T||_F / ||A||_F
    u_orth: Optional[jax.Array]        # ||U^T U - I||_F (all columns)
    v_orth: Optional[jax.Array]        # ||V^T V - I||_F
    sigma_err: Optional[jax.Array]     # max |s - s_ref| / s_ref[0]
    # ||U^T U - I||_F over numerically-live columns only (sigma above the
    # roundoff floor). For singular inputs — like the reference's
    # upper-triangular benchmark matrix (main.cu:1558-1567) — U columns for
    # null sigmas are noise BY CONSTRUCTION in any one-sided Jacobi
    # (including the reference's U = A Sigma^{-1},
    # lib/JacobiMethods.cu:1156-1173), so this is the meaningful metric.
    u_orth_live: Optional[jax.Array] = None
    # The same live-column metric for V. The factor read off the rotated
    # COLUMNS depends on the solver lane's bookkeeping: the XLA block
    # solvers read U off columns (hence u_orth_live), the preconditioned
    # kernel lanes read V off them — on numerically singular input the
    # column-side factor's dead columns are noise whichever side it is,
    # and abs-class bulk lanes (hybrid, gram-eigh, block_rotation) leave
    # them unorthogonalized by construction.
    v_orth_live: Optional[jax.Array] = None

    def as_dict(self):
        return {k: (None if v is None else float(v)) for k, v in self._asdict().items()}


@jax.jit
def relative_residual(a, u, s, v):
    """||A - U diag(s) V^T||_F / ||A||_F, computed on device.

    The subtraction is evaluated as (A - (U*s) V^T) with f32+ accumulation
    and HIGHEST matmul precision (TPU default f32 matmuls run through bf16
    passes, which would measure the validator's own noise, ~1e-3, instead of
    the factors') — same quantity as the reference's report metric
    (main.cu:1640-1665)."""
    acc = jnp.promote_types(a.dtype, jnp.float32)
    a = a.astype(acc)
    recon = jnp.einsum("mk,nk->mn", u.astype(acc) * s.astype(acc)[None, :],
                       v.astype(acc), precision=jax.lax.Precision.HIGHEST)
    return jnp.linalg.norm(a - recon) / jnp.maximum(jnp.linalg.norm(a), jnp.finfo(acc).tiny)


@jax.jit
def orthogonality_error(q):
    """||Q^T Q - I||_F over the column space."""
    acc = jnp.promote_types(q.dtype, jnp.float32)
    q = q.astype(acc)
    g = jnp.einsum("mi,mj->ij", q, q, precision=jax.lax.Precision.HIGHEST)
    return jnp.linalg.norm(g - jnp.eye(g.shape[0], dtype=acc))


def sigma_error(s, s_ref):
    """max |s - s_ref| normalized by the largest reference singular value."""
    s = jnp.asarray(s, jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    s_ref = jnp.asarray(s_ref, s.dtype)
    return jnp.max(jnp.abs(s - s_ref)) / jnp.maximum(s_ref[0], jnp.finfo(s.dtype).tiny)


def live_orthogonality_error(u, s):
    """||U^T U - I||_F over columns whose sigma is above the roundoff floor.

    Computed on device (zeroing the dead columns instead of slicing keeps
    the shape static under jit): a full-factor host transfer through the
    tunnel would be ~1 GB at 16384^2 on every CLI validate() call. f64
    factors with x64 disabled would be silently downcast by jit (an ~eps_f32
    measurement floor); route them through the host instead."""
    # NB raw input dtype, not jnp.asarray(...).dtype — the conversion itself
    # is what would downcast an f64 numpy array under disabled x64.
    if (str(getattr(s, "dtype", "")) == "float64"
            and not jax.config.jax_enable_x64):
        import numpy as np
        un = np.asarray(u, np.float64)
        sn = np.asarray(s, np.float64)
        eps = np.finfo(np.float64).eps
        live = sn > (sn[0] * max(un.shape[0], len(sn)) * eps * 10
                     if len(sn) else 0)
        ul = un[:, : len(sn)][:, live]
        g = ul.T @ ul - np.eye(ul.shape[1])
        return jnp.asarray(np.linalg.norm(g))
    return _live_orthogonality_error_jit(u, s)


@jax.jit
def _live_orthogonality_error_jit(u, s):
    # jnp.finfo understands ml_dtypes (bfloat16 has numpy kind 'V', so
    # np.finfo alone would mis-handle it).
    eps = jnp.finfo(jnp.asarray(s).dtype).eps
    acc = jnp.promote_types(u.dtype, jnp.float32)
    n = s.shape[0]
    u = u[:, :n].astype(acc)
    s = s.astype(acc)
    floor = s[0] * max(u.shape[0], n) * eps * 10 if n else jnp.zeros((), acc)
    live = s > floor
    ul = u * live[None, :].astype(acc)
    g = jnp.einsum("mi,mj->ij", ul, ul, precision=jax.lax.Precision.HIGHEST)
    eye = jnp.where(live, 1.0, 0.0).astype(acc)
    return jnp.linalg.norm(g - jnp.diag(eye))


def validate(a, result, s_ref=None) -> ValidationReport:
    """Full report for an SVDResult (entries None where factors are absent)."""
    u, s, v = result.u, result.s, result.v
    res = relative_residual(a, u, s, v) if (u is not None and v is not None) else None
    return ValidationReport(
        residual_rel=res,
        u_orth=orthogonality_error(u) if u is not None else None,
        v_orth=orthogonality_error(v) if v is not None else None,
        sigma_err=sigma_error(s, s_ref) if s_ref is not None else None,
        u_orth_live=live_orthogonality_error(u, s) if u is not None else None,
        v_orth_live=live_orthogonality_error(v, s) if v is not None else None,
    )
