"""Seeded test-matrix generation.

Replaces the reference driver's matrix builders (reference: seeded
upper-triangular N x N generation with std::default_random_engine(1000000),
main.cu:1445, 1558-1567; dense variant under #ifdef TESTS, main.cu:1569-1579;
non-reproducible mt19937(random_device()) warm-up matrix, main.cu:1483-1493 —
quirk #9, which we fix by seeding everything).

All generators are jit-compiled jax.random and produce device-resident
arrays; `sharded_random` builds the matrix directly into a NamedSharding so
large inputs never materialize on one host (the reference materializes the
full matrix on the MPI root, main.cu:1548-1556).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

DEFAULT_SEED = 1_000_000  # the reference's fixed seed, main.cu:1445


def random_dense(m: int, n: int, *, seed: int = DEFAULT_SEED, dtype=jnp.float32,
                 minval: float = 0.0, maxval: float = 1.0) -> jax.Array:
    """Uniform dense matrix (reference's #ifdef TESTS path, main.cu:1569-1579)."""
    key = jax.random.PRNGKey(seed)
    return jax.random.uniform(key, (m, n), dtype=dtype, minval=minval, maxval=maxval)


def random_upper_triangular(n: int, *, seed: int = DEFAULT_SEED,
                            dtype=jnp.float32) -> jax.Array:
    """Uniform upper-triangular N x N matrix — the reference's main benchmark
    input (main.cu:1558-1567)."""
    return jnp.triu(random_dense(n, n, seed=seed, dtype=dtype))


def with_known_spectrum(m: int, n: int, singular_values, *,
                        seed: int = DEFAULT_SEED, dtype=jnp.float32) -> jax.Array:
    """Matrix with a prescribed spectrum — oracle-free accuracy tests.

    Builds ``Q1 @ diag(s) @ Q2.T`` from Haar-ish orthogonal factors (QR of
    Gaussians). The reference has no such generator; its only oracle is the
    end-to-end residual (main.cu:1511-1533).
    """
    s = jnp.asarray(singular_values, dtype=dtype)
    r = s.shape[0]
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    q1, _ = jnp.linalg.qr(jax.random.normal(k1, (m, r), dtype=dtype))
    q2, _ = jnp.linalg.qr(jax.random.normal(k2, (n, r), dtype=dtype))
    return (q1 * s[None, :]) @ q2.T


# Generation granularity for sharded_random: values are generated per
# GRAIN x GRAIN subtile keyed by the subtile's GLOBAL origin, so the matrix
# is a pure function of (seed, m, n) — bit-identical across mesh shapes.
GRAIN = 128


def sharded_random(m: int, n: int, sharding, *, seed: int = DEFAULT_SEED,
                   dtype=jnp.float32, triangular: bool = False) -> jax.Array:
    """Generate a matrix directly into ``sharding`` (host-sharded on
    multi-host: each process only materializes its addressable shards).

    TPU-native replacement for root-rank generation + scatter
    (main.cu:1548-1567): `jax.make_array_from_callback` asks each device for
    its own tile. Each value is drawn from a key folded on the GLOBAL
    128-aligned subtile origin (not the shard origin), so the generated
    matrix is DECOMPOSITION-INVARIANT: the same (seed, m, n) produces
    bit-identical values on any mesh shape, on one device, or across hosts —
    distributed and single-chip benchmarks solve the same matrix.

    ``triangular=True`` zeroes the strictly-lower part per tile, producing
    the reference's upper-triangular benchmark input (main.cu:1558-1567)
    without any host materializing the full matrix.
    """
    shape = (m, n)
    base = jax.random.PRNGKey(seed)

    def _subtile(r, c):
        key = jax.random.fold_in(jax.random.fold_in(base, r), c)
        return jax.random.uniform(key, (GRAIN, GRAIN), dtype=dtype)

    def tile(index):
        row = index[0].start or 0
        col = index[1].start or 0
        h = (index[0].stop if index[0].stop is not None else m) - row
        w = (index[1].stop if index[1].stop is not None else n) - col
        r0 = (row // GRAIN) * GRAIN
        c0 = (col // GRAIN) * GRAIN
        nr = -(-(row + h - r0) // GRAIN)
        nc = -(-(col + w - c0) // GRAIN)
        rs = r0 + GRAIN * jnp.arange(nr)
        cs = c0 + GRAIN * jnp.arange(nc)
        grid = jax.vmap(lambda r: jax.vmap(lambda c: _subtile(r, c))(cs))(rs)
        full = grid.transpose(0, 2, 1, 3).reshape(nr * GRAIN, nc * GRAIN)
        t = jax.lax.dynamic_slice(full, (row - r0, col - c0), (h, w))
        if triangular:
            rows = row + jnp.arange(h)[:, None]
            cols = col + jnp.arange(w)[None, :]
            t = jnp.where(rows <= cols, t, jnp.zeros_like(t))
        return t

    return jax.make_array_from_callback(shape, sharding, tile)
