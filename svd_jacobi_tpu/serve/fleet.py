"""Solve-lane fleet — per-lane fault domains for the serving layer.

The reference funnels every rotation round through a single MPI root
rank (one process dies, the whole solve is lost), and the pre-fleet
`SVDService` reproduced that shape at the serving layer: ONE worker
thread driving one device was a single fault domain for the entire
service. This module is the fix: with ``ServeConfig.lanes > 1`` the
service runs N independent solve lanes, and the blast radius of a
wedged, killed, or numerically-poisoned lane is that lane alone.

**Lane** — one fault domain: its own `AdmissionQueue`, its own
`CircuitBreaker`, its own worker thread (respawnable: a lane survives
its thread), its own device (round-robin over `jax.devices()`, so each
lane's jit executables compile against its own device — the per-lane
compile cache the retrace contract budgets), and its own health
counters (heartbeat, consecutive NONFINITE/ERROR outcomes, dispatches
spent with the breaker stuck OPEN).

**Routing** — bucket affinity with work stealing: every declared bucket
has a home lane (bucket order modulo lane count), so a bucket's jit
cache stays hot on one lane; requests route to the home lane, falling
over to the next ACTIVE lane when the home is quarantined. An idle lane
steals the oldest non-probe request off the deepest ACTIVE sibling
queue — throughput is not left on the floor because the hot bucket's
home lane is backed up.

**Supervision** — the robustness core. A supervisor thread watches every
lane and EVICTS sick ones into QUARANTINED on any of the declared
causes:

  * ``lane_dead``       — the worker thread died (`chaos.kill_lane`,
    or any uncaught dispatch-loop error);
  * ``heartbeat_stale`` — no heartbeat for ``lane_heartbeat_timeout_s``
    (the per-lane watchdog around dispatch: the worker beats at pop,
    pre-dispatch, and every sweep — `chaos.wedge_lane` is exactly a
    heartbeat hole);
  * ``bad_outcomes``    — ``lane_failure_threshold`` consecutive
    NONFINITE/ERROR dispatch outcomes (`chaos.poison_lane`);
  * ``breaker_stuck_open`` — ``lane_open_threshold`` consecutive
    dispatches left the lane breaker OPEN (the ladder is not healing);
  * ``ladder_overrun``  — the escalation ladder's wall-clock watchdog
    fired on this lane (`resilience.escalate`, flagged via
    `flag_unhealthy`).

Eviction **rescues** the lane's requests: everything still queued, plus
the in-flight requests of a dead/stale/overrun lane, is re-routed onto
a healthy lane at the FRONT of its queue (they already waited their
turn). Rescue respects each request's remaining deadline budget — a
request whose deadline already passed finalizes DEADLINE on the spot,
a cancelled one CANCELLED, and when no healthy lane exists the request
finalizes ERROR loudly. A ticket can be finalized by the rescue path
and (later) by a wedged worker that finally wakes; `Ticket` finalizes
exactly once, first writer wins, so no request is ever double-served
or silently lost.

**Recovery** is outcome-caused, the same way the circuit breaker
recovers: the supervisor periodically sends a PROBE (a zeros solve of
the smallest bucket, pinned to the lane — never stolen) through the
quarantined lane's normal dispatch path, respawning the worker thread
if it died. A probe that solves OK returns the lane to ACTIVE; a
failing probe leaves it quarantined until the next one. No wall-clock
amnesty: a lane comes back because a dispatch SUCCEEDED on it.

Every transition, rescue, steal, and probe appends a schema-versioned
``"fleet"`` manifest record (`obs.manifest.build_fleet`), so the whole
eviction -> rescue -> recovery history reconstructs from the same JSONL
stream as the per-request ``"serve"`` records.
"""

from __future__ import annotations

import enum
import itertools
import sys
import threading
import time
from typing import List, Optional

from .queue import AdmissionError, AdmissionQueue, AdmissionReason, Request


class LaneState(enum.Enum):
    ACTIVE = "active"
    QUARANTINED = "quarantined"


def heartbeat_stale(now: float, heartbeat: float, *, busy: bool,
                    holds_work: bool, idle_timeout_s: float,
                    busy_timeout_s: float,
                    lease_until: Optional[float] = None) -> bool:
    """The two-tier heartbeat-staleness verdict, shared by the lane
    supervisor (`Fleet._tick`) and the replica router's supervisor one
    fault-domain up (`serve.router`): while ``busy`` (blocked inside a
    device/compile step — a cold-cache jit compile legitimately stalls
    for minutes on TPU) the longer ``busy_timeout_s`` governs; and
    staleness only matters while the subject HOLDS work — there is
    nothing to rescue off an idle one, and a loaded host can starve an
    idle poll loop past the timeout without anything being wrong
    (evicting it would just churn the fleet).

    ``lease_until`` adds the NETWORK ring's lease semantics
    (serve.transport): an unexpired lease is a liveness PROMISE the
    subject earned by answering a recent health RPC — while it holds,
    heartbeat age is never staleness (a transient RPC hiccup inside the
    lease window must not evict a healthy remote replica). Once the
    lease expires the ordinary two-tier verdict resumes: the subject is
    then "partitioned or dead", and for a remote replica those are
    indistinguishable by construction — the FENCING token (not this
    verdict) is what makes acting on the distinction safe."""
    if lease_until is not None and now < lease_until:
        return False
    if not holds_work:
        return False
    return now - heartbeat > (busy_timeout_s if busy else idle_timeout_s)


class Lane:
    """One solve lane: queue + breaker + worker thread + health state.

    Mutable health fields (`heartbeat`, `bad_streak`, `open_streak`,
    `unhealthy_flag`) are written by the lane's worker and read by the
    supervisor; each is a single reference assignment (atomic under the
    GIL), and the supervisor only ever acts on a *stale* view in the
    direction of caution (an extra tick of patience, never a lost
    eviction). State transitions themselves go through the fleet's
    lock."""

    def __init__(self, index: int, *, max_depth: int, budget_s: float,
                 breaker_threshold: int, device=None, qos=None,
                 ordering: str = "fifo"):
        from .breaker import CircuitBreaker
        self.index = int(index)
        # ``qos`` is the service's ONE shared TenantTable (or None):
        # per-lane tables would multiply each tenant's rate limit and
        # fair share by the lane count.
        self.queue = AdmissionQueue(max_depth, budget_s, qos=qos,
                                    ordering=ordering)
        self.breaker = CircuitBreaker(breaker_threshold)
        self.device = device          # None = default placement (lanes=1)
        self.state = LaneState.ACTIVE
        # Bumped at every eviction: a worker captures the generation at
        # spawn and exits when it no longer matches, so a wedged thread
        # that finally wakes cannot dispatch for a lane that moved on.
        self.generation = 0
        self.thread: Optional[threading.Thread] = None
        self.heartbeat = time.monotonic()
        # True while the worker is blocked inside a stepper/device call
        # (incl. cold-cache jit compiles): the supervisor then judges
        # staleness against the longer lane_step_timeout_s.
        self.in_step = False
        self.bad_streak = 0           # consecutive NONFINITE/ERROR outcomes
        self.open_streak = 0          # consecutive dispatches breaker OPEN
        self.unhealthy_flag: Optional[str] = None  # e.g. "ladder_overrun"
        self.in_flight: List[Request] = []  # guarded by the service lock
        self.dispatches = 0
        self.steals = 0               # requests this lane stole
        self.rescued_off = 0          # requests rescued OFF this lane
        self.probe_ticket = None
        self.last_probe = 0.0
        self.transitions: List[tuple] = []

    def beat(self) -> None:
        """Heartbeat: the worker proves liveness at pop, pre-dispatch,
        and every sweep boundary."""
        self.heartbeat = time.monotonic()

    def note_outcome(self, status_name: str, breaker_state) -> None:
        """Per-dispatch health bookkeeping (worker thread only)."""
        from .breaker import BreakerState
        self.dispatches += 1
        if status_name in ("NONFINITE", "ERROR"):
            self.bad_streak += 1
        else:
            self.bad_streak = 0
        self.open_streak = (self.open_streak + 1
                            if breaker_state is BreakerState.OPEN else 0)

    def snapshot(self) -> dict:
        """Health view of this lane (fleet healthz / manifest)."""
        return {
            "lane": self.index,
            "state": self.state.value,
            "device": None if self.device is None else str(self.device),
            "alive": bool(self.thread is not None
                          and self.thread.is_alive()),
            "queue_depth": self.queue.depth(),
            "breaker": self.breaker.state().value,
            "heartbeat_age_s": time.monotonic() - self.heartbeat,
            "in_step": self.in_step,
            "bad_streak": self.bad_streak,
            "open_streak": self.open_streak,
            "dispatches": self.dispatches,
            "steals": self.steals,
            "rescued_off": self.rescued_off,
            "in_flight": [r.id for r in self.in_flight],
        }


class Fleet:
    """The lane set + supervisor of one `SVDService` (see module
    docstring). Single-lane services get a trivial fleet — one always-
    ACTIVE lane, no supervisor, no stealing, no device pinning — so the
    lanes=1 behavior is exactly the pre-fleet service."""

    def __init__(self, service):
        cfg = service.config
        self.service = service
        self.size = int(cfg.lanes)
        devices = self._lane_devices(cfg)
        self.lanes = [
            Lane(i, max_depth=cfg.max_queue_depth,
                 budget_s=cfg.max_deadline_budget_s,
                 breaker_threshold=cfg.breaker_threshold,
                 device=devices[i],
                 qos=getattr(service, "tenant_table", None),
                 ordering=cfg.queue_ordering)
            for i in range(self.size)]
        # Bucket affinity: declaration order modulo lane count. Stable
        # across the service's lifetime so a bucket's jit cache stays
        # hot on one lane.
        self._bucket_home = {b: i % self.size
                             for i, b in enumerate(service.buckets)}
        self.total_steals = 0
        self.total_rescues = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._sup_thread: Optional[threading.Thread] = None
        self._probe_seq = itertools.count()

    def _lane_devices(self, cfg) -> list:
        """Per-lane device assignment: None everywhere for a single lane
        (default placement — the pre-fleet behavior), round-robin over
        `jax.devices()` otherwise, so each lane compiles and runs its
        own executables against its own device when the host has more
        than one."""
        if self.size == 1:
            return [None]
        import jax
        devices = jax.devices()
        return [devices[i % len(devices)] for i in range(self.size)]

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        for lane in self.lanes:
            self.service._spawn_worker(lane)
        if self.size > 1:
            self._sup_thread = threading.Thread(
                target=self._supervise, name="svdj-fleet-supervisor",
                daemon=True)
            self._sup_thread.start()

    def stop_supervisor(self, timeout: Optional[float] = None) -> None:
        self._stop.set()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout)

    def any_active_alive(self) -> bool:
        return any(l.state is LaneState.ACTIVE and l.thread is not None
                   and l.thread.is_alive() for l in self.lanes)

    # -- routing ------------------------------------------------------------

    def route(self, bucket) -> Lane:
        """The lane a request for ``bucket`` is queued on: its home lane
        when ACTIVE, else the next ACTIVE lane in index order. Raises
        `AdmissionError(NO_LANE)` when every lane is quarantined — the
        fleet cannot promise an answer and says so at the door."""
        home = self._bucket_home.get(bucket, 0)
        for k in range(self.size):
            lane = self.lanes[(home + k) % self.size]
            if lane.state is LaneState.ACTIVE:
                return lane
        raise AdmissionError(
            AdmissionReason.NO_LANE,
            f"all {self.size} solve lanes are quarantined")

    def steal_for(self, thief: Lane) -> Optional[Request]:
        """Work stealing: pop the oldest non-probe request off the
        deepest ACTIVE sibling queue for an idle ``thief`` lane."""
        victim, best = None, 0
        for lane in self.lanes:
            if lane is thief or lane.state is not LaneState.ACTIVE:
                continue
            d = lane.queue.depth()
            if d > best:
                victim, best = lane, d
        if victim is None:
            return None
        req = victim.queue.steal_oldest()
        if req is None:
            return None
        thief.steals += 1
        with self._lock:
            self.total_steals += 1
        self.service._record_fleet(event="steal", lane=thief.index,
                                   victim=victim.index, request_id=req.id)
        return req

    # -- eviction / rescue --------------------------------------------------

    def flag_unhealthy(self, lane: Lane, cause: str) -> None:
        """Mark a lane for eviction at the next supervisor tick (used by
        the escalation-ladder watchdog, which fires on a thread that is
        still inside the uncancellable ladder)."""
        lane.unhealthy_flag = str(cause)

    def evict(self, lane: Lane, cause: str) -> None:
        """Quarantine a sick lane and rescue its requests (see module
        docstring). Idempotent: a lane already quarantined is left
        alone."""
        with self._lock:
            if lane.state is not LaneState.ACTIVE:
                return
            lane.state = LaneState.QUARANTINED
            lane.generation += 1
            lane.unhealthy_flag = None
            lane.bad_streak = 0
            lane.open_streak = 0
            # The recovery-probe clock starts at EVICTION: the first
            # probe runs a full lane_probe_interval_s later, never in
            # the same supervisor tick (an instant probe would race the
            # rescue and, on a lane that died mid-compile, just die
            # again).
            lane.last_probe = time.monotonic()
        lane.transitions.append(("active", "quarantined", cause))
        self.service._record_fleet(
            event="lane_transition", lane=lane.index, from_state="active",
            to_state="quarantined", cause=cause)
        # Rescue scope: everything queued, always; the in-flight
        # requests only when the worker is not making progress (dead /
        # stale / stuck in the uncancellable ladder) — an alive worker
        # evicted for bad OUTCOMES finalizes its current dispatch itself
        # and exits at the generation check.
        rescued = lane.queue.drain()
        if cause in ("lane_dead", "heartbeat_stale", "ladder_overrun",
                     "stale_worker"):
            with self.service._lock:
                rescued += [r for r in lane.in_flight if r not in rescued]
                # A dead/stale worker never reaches its own clearing
                # finally-block: clear here or healthz reports the
                # rescued (long-terminal) request as in flight forever.
                lane.in_flight = []
        self.rescue_requests(lane, rescued, cause=cause)
        # Promotion-state rescue: retained sigma-phase states of the
        # evicted lane stay promotable (they are process-local arrays;
        # the promote-time finish jits run wherever the caller
        # dispatches), but the stream must show who was carried across
        # the eviction — one "cache" rescue event per retained state.
        for rid in self.service.promotions.retag_lane(lane.index):
            self.service._record_cache("promotion", "rescue",
                                       request_id=rid, lane=lane.index,
                                       cause=cause)
        self.service._record_fleet(event="healthz", lane=None,
                                   healthz=self.healthz())

    def rescue_requests(self, lane: Lane, reqs, *, cause: str) -> None:
        """Re-route a sick lane's requests onto healthy lanes: expired ->
        DEADLINE, cancelled -> CANCELLED, no healthy lane -> ERROR (all
        loud, none silent), otherwise requeued at the FRONT of the
        target lane's queue with the original deadline intact. Exactly-
        once is the ticket's guarantee: if the sick lane's worker later
        finalizes the same request, one of the two writes is a no-op."""
        svc = self.service
        now = time.monotonic()
        moved = []
        for req in reqs:
            if req.ticket is not None and req.ticket.done():
                continue
            if req.probe:
                # A probe never moves lanes — it exists to test THIS
                # lane. Finalize it failed; the supervisor sends a new
                # one later.
                svc._finalize_rescue(req, "ERROR",
                                     error=f"lane {lane.index} evicted "
                                           f"({cause}) during probe",
                                     lane=lane)
                continue
            if req.cancel.is_set():
                svc._finalize_rescue(req, "CANCELLED", lane=lane)
                continue
            if req.deadline is not None and now >= req.deadline:
                # The remaining deadline budget is spent — requeueing
                # would serve a request its client already gave up on.
                svc._finalize_rescue(req, "DEADLINE", lane=lane)
                continue
            target = self._route_excluding(req.bucket, lane)
            if target is None or not target.queue.requeue(req):
                svc._finalize_rescue(
                    req, "ERROR",
                    error=f"lane {lane.index} evicted ({cause}) and no "
                          f"healthy lane to rescue onto", lane=lane)
                continue
            moved.append(req.id)
        lane.rescued_off += len(moved)
        with self._lock:
            self.total_rescues += len(moved)
        svc._record_fleet(event="rescue", lane=lane.index, cause=cause,
                          count=len(moved), request_ids=moved)

    def _route_excluding(self, bucket, exclude: Lane) -> Optional[Lane]:
        home = self._bucket_home.get(bucket, 0)
        for k in range(self.size):
            lane = self.lanes[(home + k) % self.size]
            if lane is not exclude and lane.state is LaneState.ACTIVE:
                return lane
        return None

    # -- recovery -----------------------------------------------------------

    def restore(self, lane: Lane, cause: str) -> None:
        with self._lock:
            if lane.state is not LaneState.QUARANTINED:
                return
            lane.state = LaneState.ACTIVE
            lane.bad_streak = 0
            lane.open_streak = 0
            lane.unhealthy_flag = None
            lane.beat()
        lane.transitions.append(("quarantined", "active", cause))
        self.service._record_fleet(
            event="lane_transition", lane=lane.index,
            from_state="quarantined", to_state="active", cause=cause)
        self.service._record_fleet(event="healthz", lane=None,
                                   healthz=self.healthz())

    def _probe(self, lane: Lane, now: float) -> None:
        """Drive a quarantined lane's recovery probe (supervisor tick)."""
        svc = self.service
        ticket = lane.probe_ticket
        if ticket is not None:
            if not ticket.done():
                if lane.thread is None or not lane.thread.is_alive():
                    # The probe's worker died under it: probe failed.
                    lane.probe_ticket = None
                    svc._record_fleet(event="probe", lane=lane.index,
                                      ok=False,
                                      request_id=ticket.request_id,
                                      error="probe worker died")
                return
            res = ticket.result(0)
            lane.probe_ticket = None
            from ..solver import SolveStatus
            ok = res.error is None and res.status is SolveStatus.OK
            svc._record_fleet(event="probe", lane=lane.index, ok=bool(ok),
                              request_id=ticket.request_id, error=res.error)
            if ok:
                self.restore(lane, "probe success")
            return
        if now - lane.last_probe < svc.config.lane_probe_interval_s:
            return
        lane.last_probe = now
        if lane.thread is None or not lane.thread.is_alive():
            svc._spawn_worker(lane)    # a lane survives its thread
        import numpy as np
        from .service import Ticket
        b = min(svc.buckets, key=lambda b: b.cost)
        rid = f"probe-l{lane.index}-{next(self._probe_seq)}"
        ticket = Ticket(rid)
        req = Request(
            id=rid, a=np.zeros((b.m, b.n), np.dtype(b.dtype)), m=b.m,
            n=b.n, orig_shape=(b.m, b.n), transposed=False, bucket=b,
            compute_u=False, compute_v=False, degraded=False,
            deadline=now + svc.config.lane_probe_timeout_s,
            deadline_s=svc.config.lane_probe_timeout_s, submitted=now,
            cancel=ticket._cancel, ticket=ticket, probe=True,
            top_k=(b.k if b.kind == "topk" else None), rank_mode=b.kind)
        # Straight onto the lane's queue, bypassing admission: routing
        # excludes quarantined lanes, and THIS lane is the whole point.
        if lane.queue.requeue(req):
            lane.probe_ticket = ticket

    # -- supervision --------------------------------------------------------

    def _supervise(self) -> None:
        interval = self.service.config.supervise_interval_s
        while not self._stop.wait(interval):
            try:
                self._tick()
            except Exception as e:  # the supervisor must outlive surprises
                print(f"svdj-fleet: supervisor tick failed: {e}",
                      file=sys.stderr)

    def _tick(self, now: Optional[float] = None) -> None:
        cfg = self.service.config
        now = time.monotonic() if now is None else now
        for lane in self.lanes:
            if lane.state is LaneState.ACTIVE:
                cause = None
                if lane.unhealthy_flag is not None:
                    cause = lane.unhealthy_flag
                elif lane.thread is not None and not lane.thread.is_alive():
                    cause = "lane_dead"
                elif heartbeat_stale(
                        now, lane.heartbeat, busy=lane.in_step,
                        holds_work=bool(lane.in_flight
                                        or lane.queue.depth() > 0),
                        idle_timeout_s=cfg.lane_heartbeat_timeout_s,
                        busy_timeout_s=cfg.lane_step_timeout_s):
                    cause = "heartbeat_stale"
                elif lane.bad_streak >= cfg.lane_failure_threshold:
                    cause = "bad_outcomes"
                elif lane.open_streak >= cfg.lane_open_threshold:
                    cause = "breaker_stuck_open"
                if cause is not None:
                    self.evict(lane, cause)
            elif self.service._accepting:
                self._probe(lane, now)

    # -- views --------------------------------------------------------------

    def healthz(self) -> dict:
        lanes = [l.snapshot() for l in self.lanes]
        return {
            "lanes": lanes,
            "active": sum(1 for l in lanes if l["state"] == "active"),
            "quarantined": sum(1 for l in lanes
                               if l["state"] == "quarantined"),
            "steals": self.total_steals,
            "rescues": self.total_rescues,
        }
