"""The deadline-aware in-process SVD service.

`SVDService` turns the one-shot `svd()` entry points into a request
server with production overload behavior — the request-level robustness
layer on top of PR 3's solve-level one:

  * **admission control** (`queue.AdmissionQueue` + bucket routing +
    brownout): `submit` either returns a `Ticket` or raises
    `AdmissionError` with a machine-readable reason — never a silent
    drop;
  * **shape-bucketed dispatch** (`buckets.BucketSet`): every request is
    zero-padded to a declared (m, n, dtype) bucket BEFORE the solver
    sees it, so the stepper's jit entries compile once per bucket and
    every later dispatch is a cache hit (`config.RETRACE_BUDGETS`,
    proven by `analysis.recompile_guard.run_serve_sequence`);
  * **deadlines & cancellation**: per-request deadlines are enforced by
    the host-stepped `SweepStepper`'s cooperative control
    (`set_control` — checked between sweeps, no thread kills), decoded
    into `SolveStatus.DEADLINE` / `SolveStatus.CANCELLED`. A timed-out
    request returns a loud PARTIAL result within one sweep of its
    deadline while its queue neighbors are untouched;
  * **circuit breaker + brownout** (`breaker`): consecutive solve
    failures trip the breaker OPEN, routing dispatches through
    `resilience.resilient_svd`'s escalation ladder until a success
    probes the base path closed; queue-pressure brownout degrades
    full SVD -> sigma-only -> shed, in that declared order;
  * **observability**: every request (served OR rejected) appends one
    schema-versioned ``"serve"`` record (`obs.manifest.build_serve`) —
    bucket, queue wait, solve time, status, breaker state — so the whole
    service history reconstructs from the same manifest stream the rest
    of the tooling reads; `healthz`/`ready` expose live probes.

The worker is a single thread: the device executes one solve at a time
anyway, and a serial worker makes every breaker/brownout transition
deterministic. Clients are free-threaded; `Ticket.result` blocks with a
timeout.
"""

from __future__ import annotations

import dataclasses
import itertools
import sys
import threading
import time
from typing import Any, NamedTuple, Optional, Tuple

from ..config import DEFAULT_SERVE_BUCKETS, SVDConfig
from .breaker import BreakerState, Brownout, CircuitBreaker
from .buckets import BucketSet
from .queue import AdmissionError, AdmissionQueue, AdmissionReason, Request


class ServeResult(NamedTuple):
    """Terminal outcome of one served request.

    ``status`` is the solver's `SolveStatus` (DEADLINE/CANCELLED for
    control stops) or None when the dispatch died with ``error``;
    exactly one of the two is informative. ``degraded`` marks a
    sigma-only brownout response (u/v None even if requested)."""

    u: Any
    s: Any
    v: Any
    status: Any                   # Optional[SolveStatus]
    error: Optional[str]
    sweeps: int
    bucket: Optional[str]
    queue_wait_s: float
    solve_time_s: Optional[float]
    path: str                     # "base" | "ladder"
    degraded: bool
    request_id: str


class Ticket:
    """Client handle: blocks on `result`, requests cancellation with
    `cancel` (cooperative — takes effect at the next sweep boundary, or
    at dispatch when still queued)."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._done = threading.Event()
        self._result: Optional[ServeResult] = None
        self._cancel = threading.Event()

    def cancel(self) -> None:
        self._cancel.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not terminal after {timeout}s")
        return self._result


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving-layer configuration (the solver's own knobs ride in
    ``solver``)."""

    buckets: tuple = DEFAULT_SERVE_BUCKETS
    solver: SVDConfig = SVDConfig()
    max_queue_depth: int = 16
    # Cap on the aggregate remaining deadline budget of queued requests
    # (see queue.AdmissionQueue); inf = disabled.
    max_deadline_budget_s: float = float("inf")
    # Deadline applied to requests submitted without one; None = none.
    default_deadline_s: Optional[float] = None
    breaker_threshold: int = 3
    # Brownout thresholds on queue fill (depth / max_queue_depth) at
    # admission: fill >= sigma_only_at degrades to sigma-only, fill >=
    # shed_at rejects. Values > 1 disable a rung.
    brownout_sigma_only_at: float = 0.75
    brownout_shed_at: float = 0.95
    # JSONL manifest the per-request "serve" records append to; None
    # keeps them in memory only (`SVDService.records`).
    manifest_path: Optional[str] = None
    max_records: int = 1024


class SVDService:
    """Thread-safe in-process SVD server (see module docstring)."""

    def __init__(self, config: ServeConfig = ServeConfig()):
        if not (0.0 < config.brownout_sigma_only_at
                <= config.brownout_shed_at):
            raise ValueError(
                "brownout thresholds must satisfy 0 < sigma_only_at <= "
                f"shed_at, got {config.brownout_sigma_only_at} / "
                f"{config.brownout_shed_at}")
        self.config = config
        self.buckets = BucketSet(config.buckets)
        self.queue = AdmissionQueue(config.max_queue_depth,
                                    config.max_deadline_budget_s)
        self.breaker = CircuitBreaker(config.breaker_threshold)
        self._records: list = []
        self._stats: dict = {}
        self._lock = threading.Lock()
        self._accepting = False
        self._drain = True
        self._thread: Optional[threading.Thread] = None
        self._in_flight: Optional[Request] = None
        self._seq = itertools.count()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "SVDService":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                raise RuntimeError("service already started")
            if self.queue.closed_and_empty():
                raise RuntimeError(
                    "service was stopped; a stopped SVDService is not "
                    "restartable — build a new one")
            self._accepting = True
            self._drain = True
            self._thread = threading.Thread(target=self._worker,
                                            name="svdj-serve", daemon=True)
            self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None
             ) -> None:
        """Stop accepting; drain the queue (default) or finalize every
        queued request with CANCELLED — either way every admitted request
        reaches a terminal status."""
        with self._lock:
            self._accepting = False
            self._drain = bool(drain)
            thread = self._thread
        # Close BEFORE draining: admit and close share the queue lock, so
        # every submit either enqueued before this point (and is drained
        # below or served by the worker) or raises SHUTDOWN — no request
        # can be admitted onto a queue nobody will pop.
        self.queue.close()
        if not drain:
            self._cancel_queued()
            # Also cancel the IN-FLIGHT solve (cooperatively — it stops at
            # the next sweep boundary and finalizes CANCELLED), so a
            # no-drain stop is not blocked behind a long solve and the
            # running request still reaches a terminal status. The ladder
            # path cannot be interrupted mid-fused-solve; join() rides it
            # out up to ``timeout``.
            with self._lock:
                inflight = self._in_flight
            if inflight is not None:
                inflight.cancel.set()
        if thread is not None:
            thread.join(timeout)
            if not thread.is_alive():
                # Belt-and-braces: the worker is gone, so anything still
                # queued (it cannot be, by the close/drain protocol, short
                # of a worker crash) is finalized, never stranded.
                self._cancel_queued()

    def _cancel_queued(self) -> None:
        for req in self.queue.drain():
            wait = time.monotonic() - req.submitted
            self._finalize(req, status_name="CANCELLED",
                           result=self._control_result(
                               req, "CANCELLED", wait),
                           queue_wait=wait, solve_time=None, path="base",
                           breaker_state=self.breaker.state())

    def warmup(self, *, sigma_only: bool = True,
               timeout: float = 600.0) -> None:
        """Compile every bucket's solve variants before real traffic: one
        zeros solve per bucket and (default) per compute variant. Zeros
        deflate immediately — the solve itself is one sweep — so the cost
        is essentially the compiles. This matters for the SIGMA_ONLY
        brownout: its compute flags are STATIC jit arguments, so without
        warmup the first degraded dispatch per bucket pays a fresh
        compile mid-overload, exactly when the worker can least afford
        it. Call after `start()`; the warmup requests flow through the
        normal path and appear in the manifest like any other. Raises
        RuntimeError on any non-OK warmup outcome — a warmup that
        silently failed would mean serving real traffic uncompiled (and,
        worse, with warmup failures already counted into the breaker)."""
        import jax.numpy as jnp
        from ..solver import SolveStatus
        variants = [(True, True)] + ([(False, False)] if sigma_only else [])
        # Sequential (one in flight at a time): a burst of warmup submits
        # would itself raise the queue fill into the brownout rungs and
        # get the full-SVD variant degraded to sigma-only before it ever
        # compiled. deadline_s=inf: NO deadline, overriding any
        # default_deadline_s and exempt from the budget cap — neither a
        # short default nor a small max_deadline_budget_s may be allowed
        # to expire or refuse the compile warmup exists to front-load
        # (client-side `result(timeout)` still bounds the wait).
        for b in self.buckets:
            for cu, cv in variants:
                rid = f"warmup-{b.name}-{'vec' if cu else 'novec'}"
                res = self.submit(jnp.zeros((b.m, b.n), jnp.dtype(b.dtype)),
                                  compute_u=cu, compute_v=cv,
                                  deadline_s=float("inf"),
                                  request_id=rid).result(timeout)
                if (res.status is not SolveStatus.OK or res.degraded
                        or res.path != "base"):
                    # A degraded or ladder-routed warmup solved SOMETHING,
                    # but not the stepper variant it exists to compile —
                    # that is a failure too (warm up before traffic, with
                    # a closed breaker).
                    status = (res.error if res.error
                              else res.status.name if res.status else "?")
                    raise RuntimeError(
                        f"warmup request {rid} did not compile its "
                        f"variant (status={status}, degraded="
                        f"{res.degraded}, path={res.path}, breaker now "
                        f"{self.breaker.state().value})")

    def __enter__(self) -> "SVDService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=False, timeout=10.0)

    # -- probes -------------------------------------------------------------

    def ready(self) -> bool:
        """Readiness: accepting work with a live worker."""
        with self._lock:
            return bool(self._accepting and self._thread is not None
                        and self._thread.is_alive())

    def healthz(self) -> dict:
        """Liveness + load snapshot (cheap; safe to poll)."""
        with self._lock:
            alive = self._thread is not None and self._thread.is_alive()
            in_flight = (self._in_flight.id
                         if self._in_flight is not None else None)
            stats = dict(self._stats)
        return {
            "ok": alive,
            "ready": self.ready(),
            "breaker": self.breaker.state().value,
            "brownout": self._brownout().name,
            "queue_depth": self.queue.depth(),
            "deadline_budget_s": self.queue.deadline_budget(),
            "in_flight": in_flight,
            "stats": stats,
        }

    def records(self) -> list:
        """The in-memory per-request "serve" records (newest last)."""
        with self._lock:
            return list(self._records)

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)

    # -- admission ----------------------------------------------------------

    def _brownout(self) -> Brownout:
        fill = self.queue.depth() / self.queue.max_depth
        if fill >= self.config.brownout_shed_at:
            return Brownout.SHED
        if fill >= self.config.brownout_sigma_only_at:
            return Brownout.SIGMA_ONLY
        return Brownout.FULL

    def submit(self, a, *, compute_u: bool = True, compute_v: bool = True,
               deadline_s: Optional[float] = None,
               request_id: Optional[str] = None) -> Ticket:
        """Admit one request: returns a `Ticket` or raises
        `AdmissionError` (reason: SHUTDOWN | NO_BUCKET | BROWNOUT_SHED |
        QUEUE_FULL | DEADLINE_BUDGET). ``deadline_s`` is relative to now;
        the solve stops cooperatively within one sweep of it. None
        inherits ``default_deadline_s``; an explicit ``float("inf")``
        means NO deadline even when a default is configured (exempt from
        the deadline budget — `warmup` uses this so a compile can never
        expire the deadline that exists to front-load it)."""
        import math

        import jax.numpy as jnp
        in_dtype = getattr(a, "dtype", None)
        a = jnp.asarray(a)
        if a.ndim != 2:
            raise ValueError(f"expected a 2-D matrix, got shape {a.shape}")
        rid = request_id or f"r{next(self._seq):05d}"
        orig_shape = tuple(int(d) for d in a.shape)
        transposed = a.shape[0] < a.shape[1]
        if transposed:
            a = a.T
            compute_u, compute_v = compute_v, compute_u
        m, n = (int(d) for d in a.shape)
        dtype = str(a.dtype)
        # Normalize the deadline BEFORE any rejection path: a rejected
        # inf-deadline submit must not leak a non-JSON Infinity token
        # into its manifest record.
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        if deadline_s is not None and math.isinf(deadline_s):
            deadline_s = None
        brown = self._brownout()
        try:
            if not self.ready():
                raise AdmissionError(AdmissionReason.SHUTDOWN,
                                     "service is not accepting requests")
            if (in_dtype is not None
                    and jnp.dtype(a.dtype) != jnp.dtype(in_dtype)):
                # jnp.asarray silently downcasts (e.g. f64 -> f32 with
                # x64 disabled); serving a precision-degraded result
                # UNDECLARED would violate the layer's reject-or-record
                # policy, so refuse loudly instead.
                raise AdmissionError(
                    AdmissionReason.NO_BUCKET,
                    f"input dtype {jnp.dtype(in_dtype).name} is not "
                    f"representable in this runtime (jnp.asarray produced "
                    f"{a.dtype}; jax_enable_x64?) — refusing to silently "
                    f"downcast")
            bucket = self.buckets.route(m, n, dtype)
            if bucket is None:
                raise AdmissionError(
                    AdmissionReason.NO_BUCKET,
                    f"shape {orig_shape} dtype {dtype} fits no declared "
                    f"bucket {[b.name for b in self.buckets]}")
            if not bool(jnp.isfinite(a).all()):
                # resilience.guard's policy, enforced at the door: no
                # ladder can fix data, and solving NaN input would read
                # NONFINITE and feed the breaker — one buggy client must
                # not be able to trip every other client onto the
                # degraded ladder path.
                raise AdmissionError(
                    AdmissionReason.NONFINITE_INPUT,
                    "input contains NaN/Inf — rejected before any solve "
                    "is spent (resilience.guard policy)")
            if brown is Brownout.SHED:
                raise AdmissionError(
                    AdmissionReason.BROWNOUT_SHED,
                    f"queue fill {self.queue.depth()}/"
                    f"{self.queue.max_depth} at shed threshold")
            now = time.monotonic()
            ticket = Ticket(rid)
            req = Request(
                id=rid, a=a, m=m, n=n, orig_shape=orig_shape,
                transposed=transposed, bucket=bucket,
                compute_u=compute_u, compute_v=compute_v,
                degraded=(brown is Brownout.SIGMA_ONLY
                          and (compute_u or compute_v)),
                brownout=brown.name,
                deadline=(None if deadline_s is None
                          else now + float(deadline_s)),
                deadline_s=deadline_s, submitted=now,
                cancel=ticket._cancel, ticket=ticket)
            self.queue.admit(req)
        except AdmissionError as e:
            self._bump("rejected", f"rejected:{e.reason.value}")
            self._record(request_id=rid, orig_shape=orig_shape, dtype=dtype,
                         bucket=None, queue_wait_s=0.0, solve_time_s=None,
                         status=f"REJECTED_{e.reason.name}", path="rejected",
                         breaker=self.breaker.state().value,
                         brownout=brown.name, degraded=False,
                         deadline_s=deadline_s, error=e.detail)
            raise
        self._bump("submitted")
        return ticket

    # -- worker -------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            # Blocking pop — no idle polling; `admit` and `close` notify.
            req = self.queue.pop(None)
            if req is None:
                # Exit only when the queue is closed AND empty — atomic
                # with admission, so no admitted request is left behind.
                if self.queue.closed_and_empty():
                    break
                continue
            with self._lock:
                drain = self._drain or self._accepting
            try:
                if not drain:
                    # stop(drain=False) raced the pop: finalize, don't solve.
                    wait = time.monotonic() - req.submitted
                    self._finalize(
                        req, status_name="CANCELLED",
                        result=self._control_result(req, "CANCELLED", wait),
                        queue_wait=wait, solve_time=None, path="base",
                        breaker_state=self.breaker.state())
                else:
                    self._serve_one(req)
            except BaseException as e:  # last ditch: no undone tickets
                if not req.ticket._done.is_set():
                    self._finalize(
                        req, status_name="ERROR",
                        result=self._error_result(
                            req, f"{type(e).__name__}: {e}", 0.0, "base"),
                        queue_wait=time.monotonic() - req.submitted,
                        solve_time=None, path="base",
                        breaker_state=self.breaker.record(False))

    def _serve_one(self, req: Request) -> None:
        from ..solver import SolveStatus
        t_pop = time.monotonic()
        queue_wait = t_pop - req.submitted
        with self._lock:
            self._in_flight = req
            if not self._accepting and not self._drain:
                # stop(drain=False) raced the pop before _in_flight was
                # published (it could not see this request to cancel it);
                # publish-and-check shares stop()'s lock, so one side
                # always sets the cancel event.
                req.cancel.set()
        try:
            if req.cancel.is_set():
                # Cancelled while queued: terminal without spending a solve.
                self._finalize(req, status_name="CANCELLED",
                               result=self._control_result(
                                   req, "CANCELLED", queue_wait),
                               queue_wait=queue_wait, solve_time=None,
                               path="base",
                               breaker_state=self.breaker.state())
                return
            if req.deadline is not None and time.monotonic() >= req.deadline:
                # Deadline expired while QUEUED: terminal without spending
                # a sweep — on EITHER breaker path (the ladder runs fused
                # solves that cannot stop mid-flight, so dispatching an
                # already-dead request there would serve it long after the
                # client gave up). A queue-expired deadline is an OVERLOAD
                # symptom, not a backend failure, so it does not feed the
                # breaker — otherwise overload would trip the breaker onto
                # the slower ladder path and amplify itself.
                self._finalize(req, status_name="DEADLINE",
                               result=self._control_result(
                                   req, "DEADLINE", queue_wait),
                               queue_wait=queue_wait, solve_time=None,
                               path="base",
                               breaker_state=self.breaker.state())
                return
            path, _ = self.breaker.begin()
            cu = req.compute_u and not req.degraded
            cv = req.compute_v and not req.degraded
            t0 = time.monotonic()
            error = None
            r = None
            try:
                if path == "ladder":
                    r = self._solve_ladder(req, cu, cv)
                else:
                    r = self._solve_base(req, cu, cv)
                status = r.status_enum()
            except Exception as e:
                error = f"{type(e).__name__}: {e}"
                status = None
            solve_time = time.monotonic() - t0
            if status is SolveStatus.CANCELLED:
                # Client-initiated: neither a success nor a backend failure.
                breaker_state = self.breaker.state()
            else:
                breaker_state = self.breaker.record(
                    error is None and status is SolveStatus.OK)
            if error is not None:
                result = self._error_result(req, error, queue_wait, path,
                                            solve_time_s=solve_time)
                status_name = "ERROR"
            else:
                u, s, v, sweeps = self._slice(req, r, cu, cv)
                result = ServeResult(
                    u=u, s=s, v=v, status=status, error=None, sweeps=sweeps,
                    bucket=req.bucket.name, queue_wait_s=queue_wait,
                    solve_time_s=solve_time, path=path,
                    degraded=req.degraded, request_id=req.id)
                status_name = status.name
            self._finalize(req, status_name=status_name, result=result,
                           queue_wait=queue_wait, solve_time=solve_time,
                           path=path, breaker_state=breaker_state)
        finally:
            with self._lock:
                self._in_flight = None

    # -- solve paths --------------------------------------------------------

    def _solve_base(self, req: Request, cu: bool, cv: bool):
        """The normal path: pad to the bucket, run the host-stepped solver
        under cooperative control, one control check per sweep."""
        from ..resilience import chaos
        from ..solver import SweepStepper
        a_pad = self.buckets.pad(req.a, req.bucket)
        stall = chaos.consume_stuck()
        if stall is not None:
            self._stall(req, stall)
        slow = chaos.consume_slow()
        st = SweepStepper(a_pad, compute_u=cu, compute_v=cv,
                          config=self.config.solver)
        st.set_control(deadline=req.deadline,
                       should_cancel=req.cancel.is_set)
        state = st.init()
        while st.should_continue(state):
            if slow is not None:
                time.sleep(slow)
            state = st.step(state)
        return st.finish(state)

    def _solve_ladder(self, req: Request, cu: bool, cv: bool):
        """The OPEN-breaker path: route through the escalation ladder.
        The ladder runs the FUSED entry points, so the deadline cannot be
        checked mid-solve — acceptable for the recovery path (bounded by
        the ladder's own attempt cap), and the manifest records it as
        path="ladder"."""
        from ..resilience import resilient_svd
        a_pad = self.buckets.pad(req.a, req.bucket)
        return resilient_svd(a_pad, compute_u=cu, compute_v=cv,
                             config=self.config.solver,
                             manifest_path=self.config.manifest_path)

    @staticmethod
    def _stall(req: Request, stall_s: float) -> None:
        """chaos.stuck_backend: block cooperatively (polling the request's
        deadline/cancel control) for at most ``stall_s``; the stepper's
        own control check then turns an expired deadline into DEADLINE."""
        t_end = time.monotonic() + stall_s
        while time.monotonic() < t_end:
            if req.cancel.is_set():
                return
            if req.deadline is not None and time.monotonic() >= req.deadline:
                return
            time.sleep(0.002)

    def _slice(self, req: Request, r, cu: bool, cv: bool):
        """Recover the original-shape factors from the bucket-padded solve
        (exact — see buckets module docstring) and undo the tall
        orientation."""
        k = min(req.m, req.n)
        u = r.u[:req.m, :k] if (cu and r.u is not None) else None
        s = r.s[:k]
        v = r.v[:req.n, :k] if (cv and r.v is not None) else None
        if req.transposed:
            u, v = v, u
        return u, s, v, int(r.sweeps)

    # -- bookkeeping --------------------------------------------------------

    def _control_result(self, req: Request, status_name: str,
                        queue_wait: float) -> ServeResult:
        from ..solver import SolveStatus
        return ServeResult(
            u=None, s=None, v=None, status=SolveStatus[status_name],
            error=None, sweeps=0, bucket=req.bucket.name,
            queue_wait_s=queue_wait, solve_time_s=None, path="base",
            degraded=req.degraded, request_id=req.id)

    def _error_result(self, req: Request, error: str, queue_wait: float,
                      path: str, solve_time_s: Optional[float] = None
                      ) -> ServeResult:
        return ServeResult(
            u=None, s=None, v=None, status=None, error=error, sweeps=0,
            bucket=req.bucket.name, queue_wait_s=queue_wait,
            solve_time_s=solve_time_s, path=path, degraded=req.degraded,
            request_id=req.id)

    def _finalize(self, req: Request, *, status_name: str,
                  result: ServeResult, queue_wait: float,
                  solve_time: Optional[float], path: str,
                  breaker_state: BreakerState) -> None:
        req.ticket._result = result
        req.ticket._done.set()
        self._bump("served", f"status:{status_name}",
                   *(["path:ladder"] if path == "ladder" else []),
                   *(["degraded"] if req.degraded else []))
        self._record(
            request_id=req.id, orig_shape=req.orig_shape,
            dtype=req.bucket.dtype, bucket=req.bucket.name,
            queue_wait_s=queue_wait, solve_time_s=solve_time,
            status=status_name, path=path, breaker=breaker_state.value,
            brownout=req.brownout,
            degraded=req.degraded, deadline_s=req.deadline_s,
            sweeps=result.sweeps, error=result.error)

    def _bump(self, *keys: str) -> None:
        with self._lock:
            for k in keys:
                self._stats[k] = self._stats.get(k, 0) + 1

    def _record(self, *, request_id: str, orig_shape: Tuple[int, int],
                dtype: str, bucket: Optional[str], queue_wait_s: float,
                solve_time_s: Optional[float], status: str, path: str,
                breaker: str, brownout: str, degraded: bool,
                deadline_s: Optional[float], error: Optional[str] = None,
                sweeps: Optional[int] = None) -> None:
        from .. import obs
        record = obs.manifest.build_serve(
            request_id=request_id, m=orig_shape[0], n=orig_shape[1],
            dtype=dtype, bucket=bucket, queue_wait_s=float(queue_wait_s),
            solve_time_s=(None if solve_time_s is None
                          else float(solve_time_s)),
            status=status, path=path, breaker=breaker, brownout=brownout,
            degraded=bool(degraded),
            deadline_s=(None if deadline_s is None else float(deadline_s)),
            sweeps=sweeps, error=error)
        with self._lock:
            # max_records <= 0 means "manifest only, keep none in memory"
            # (the naive del lst[:-0] would silently invert the cap into
            # unbounded growth).
            if self.config.max_records > 0:
                self._records.append(record)
                del self._records[:-self.config.max_records]
        if self.config.manifest_path is not None:
            try:
                obs.manifest.append(self.config.manifest_path, record)
            except Exception as e:  # manifest I/O must not kill the worker
                self._bump("manifest_errors")
                print(f"svdj-serve: manifest append failed: {e}",
                      file=sys.stderr)
