"""The deadline-aware in-process SVD service.

`SVDService` turns the one-shot `svd()` entry points into a request
server with production overload behavior — the request-level robustness
layer on top of PR 3's solve-level one:

  * **admission control** (`queue.AdmissionQueue` + bucket routing +
    brownout): `submit` either returns a `Ticket` or raises
    `AdmissionError` with a machine-readable reason — never a silent
    drop;
  * **shape-bucketed dispatch** (`buckets.BucketSet`): every request is
    zero-padded to a declared (m, n, dtype) bucket BEFORE the solver
    sees it, so the stepper's jit entries compile once per bucket and
    every later dispatch is a cache hit (`config.RETRACE_BUDGETS`,
    proven by `analysis.recompile_guard.run_serve_sequence`);
  * **deadlines & cancellation**: per-request deadlines are enforced by
    the host-stepped `SweepStepper`'s cooperative control
    (`set_control` — checked between sweeps, no thread kills), decoded
    into `SolveStatus.DEADLINE` / `SolveStatus.CANCELLED`. A timed-out
    request returns a loud PARTIAL result within one sweep of its
    deadline while its queue neighbors are untouched;
  * **circuit breaker + brownout** (`breaker`): consecutive solve
    failures trip the breaker OPEN, routing dispatches through
    `resilience.resilient_svd`'s escalation ladder until a success
    probes the base path closed; queue-pressure brownout degrades
    full SVD -> sigma-only -> shed, in that declared order;
  * **observability**: every request (served OR rejected) appends one
    schema-versioned ``"serve"`` record (`obs.manifest.build_serve`) —
    bucket, queue wait, solve time, status, breaker state — so the whole
    service history reconstructs from the same manifest stream the rest
    of the tooling reads; `healthz`/`ready` expose live probes.

  * **restart survivability** (`registry` + `journal`): every
    compilable (lane, bucket, tier, variant) jit entry is enumerated by
    ONE registry that `warmup` AOT-compiles through a persistent
    executable cache (a restarted process warms from cache hits — zero
    fresh compiles), and with a journal configured every admitted
    request is write-ahead logged so `recover()` re-admits a killed
    process's unfinalized requests exactly-once; `reload()` swaps in a
    new bucket set with zero downtime (background AOT warm).

With ``lanes == 1`` (the default) the worker is a single thread: the
device executes one solve at a time anyway, and a serial worker makes
every breaker/brownout transition deterministic. With ``lanes > 1`` the
service is a **fleet** (`fleet.Fleet`): one solve lane per device, each
lane its own fault domain (own queue, own breaker, own jit executables,
own health state), bucket-affinity routing with work stealing, and a
supervisor that evicts sick lanes, rescues their requests onto healthy
ones, and probes them back to ACTIVE — see the `fleet` module
docstring. Clients are free-threaded; `Ticket.result` blocks with a
timeout.
"""

from __future__ import annotations

import dataclasses
import itertools
import re
import sys
import threading
import time
from typing import Any, NamedTuple, Optional, Tuple

from ..config import DEFAULT_BATCH_TIERS, DEFAULT_SERVE_BUCKETS, SVDConfig
from .breaker import BreakerState, Brownout
from .buckets import BucketSet
from .fleet import Fleet, Lane, LaneState
from .queue import (DEFAULT_TENANT, AdmissionError, AdmissionReason,
                    Request, TenantTable)


class _NullSLO:
    """Metrics-off stand-in for a per-tenant SLOTracker: accepts the
    same calls and does nothing, so tenant call sites never branch on
    the flight recorder (the OBS002 free-when-off contract — per-tenant
    trackers are only MINTED when metrics are on)."""

    def observe(self, *a, **k):
        pass

    def shed(self, *a, **k):
        pass


_NULL_SLO = _NullSLO()


class ServeResult(NamedTuple):
    """Terminal outcome of one served request.

    ``status`` is the solver's `SolveStatus` (DEADLINE/CANCELLED for
    control stops) or None when the dispatch died with ``error``;
    exactly one of the two is informative. ``degraded`` marks a
    sigma-only brownout response (u/v None even if requested)."""

    u: Any
    s: Any
    v: Any
    status: Any                   # Optional[SolveStatus]
    error: Optional[str]
    sweeps: int
    bucket: Optional[str]
    queue_wait_s: float
    solve_time_s: Optional[float]
    path: str                     # "base" | "ladder"
    degraded: bool
    request_id: str


class Ticket:
    """Client handle: blocks on `result`, requests cancellation with
    `cancel` (cooperative — takes effect at the next sweep boundary, or
    at dispatch when still queued). A sigma-phase ticket
    (``submit(phase="sigma")``) additionally carries `promote` — resume
    the SAME retained solve to full U/V — and `release` — drop the
    retained state when the factors will never be wanted."""

    def __init__(self, request_id: str, service=None, phase: str = "full"):
        self.request_id = request_id
        self.phase = phase
        # SHA-256 of the oriented input bytes — the ResultCache /
        # replica-router resubmit key, set at admission when digesting
        # is on (``result_cache_bytes > 0`` or
        # ``ServeConfig.compute_digest``); None otherwise. Clients key
        # byte-identical resubmits off this instead of re-hashing.
        self.digest: Optional[str] = None
        self._service = service
        self._done = threading.Event()
        self._result: Optional[ServeResult] = None
        self._cancel = threading.Event()
        self._finalize_lock = threading.Lock()

    def cancel(self) -> None:
        self._cancel.set()

    def done(self) -> bool:
        return self._done.is_set()

    def promote(self, timeout: Optional[float] = None) -> ServeResult:
        """Resume THIS sigma-phase request's retained solve to full
        U/Σ/V — never a fresh solve: the promotion runs the finish-stage
        jits (already bucket-compiled) on the checkpointed column/
        rotation stacks, or returns the already-finished factors when
        the sigma dispatch went through a fused path (escalation ladder,
        mixed coalesced batch). Blocks up to ``timeout`` for the sigma
        result first. Raises `serve.cache.PromotionError` when no state
        is retained (not a sigma request, already promoted/released,
        evicted under the byte budget, non-OK sigma solve, or a
        restarted process) — the loud fallback is a fresh full submit,
        which the result cache may then serve."""
        from .cache import PromotionError
        if self._service is None:
            raise PromotionError(
                f"ticket {self.request_id!r} is not promotable (no "
                f"owning service — e.g. a journal-recovered handle)")
        sigma = self.result(timeout)
        return self._service._promote(self, sigma)

    def release(self) -> bool:
        """Drop the retained promotion state (the factors will never be
        wanted); True when something was held."""
        if self._service is None:
            return False
        return self._service._release_promotion(self.request_id)

    def _finalize_once(self, result: ServeResult) -> bool:
        """Install the terminal result EXACTLY once; False when another
        finalizer already won. In fleet mode the same request can be
        finalized by its (sick) original lane AND by the rescue path —
        first writer wins, the loser's write is a no-op, and the caller
        skips its stats/manifest bookkeeping on False so every request
        appears terminal exactly once everywhere."""
        with self._finalize_lock:
            if self._done.is_set():
                return False
            self._result = result
            self._done.set()
            return True

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not terminal after {timeout}s")
        return self._result


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving-layer configuration (the solver's own knobs ride in
    ``solver``)."""

    buckets: tuple = DEFAULT_SERVE_BUCKETS
    solver: SVDConfig = SVDConfig()
    max_queue_depth: int = 16
    # Cap on the aggregate remaining deadline budget of queued requests
    # (see queue.AdmissionQueue); inf = disabled.
    max_deadline_budget_s: float = float("inf")
    # Deadline applied to requests submitted without one; None = none.
    default_deadline_s: Optional[float] = None
    breaker_threshold: int = 3
    # Brownout thresholds on queue fill (depth / max_queue_depth) at
    # admission: fill >= sigma_only_at degrades to sigma-only, fill >=
    # shed_at rejects. Values > 1 disable a rung.
    brownout_sigma_only_at: float = 0.75
    brownout_shed_at: float = 0.95
    # JSONL manifest the per-request "serve" records append to; None
    # keeps them in memory only (`SVDService.records`).
    manifest_path: Optional[str] = None
    max_records: int = 1024
    # --- restart survivability (serve.registry / serve.journal) ----------
    # Durable request journal: a write-ahead JSONL log (fsync per record)
    # of admit/dispatch/finalize events — every admitted request is
    # journaled BEFORE it is enqueued and marked at finalize, so a
    # SIGKILL'd process re-admits its unfinalized requests on restart
    # (`SVDService.recover`) at queue front with deadline budgets intact.
    # None disables (no durability promise). Journaling copies each input
    # to host and fsyncs per lifecycle event — a measured durability tax
    # (PROFILE.md item 26).
    journal_path: Optional[str] = None
    # Journal payload mode: "full" journals the input BYTES (base64 —
    # ~21 MB per 2048² float32 request, item 26's dominant cost) so a
    # crashed request replays as a re-solve; "digest" journals only the
    # SHA-256 + shape/dtype — the per-request tax drops to O(100 B), and
    # a crashed request whose bytes are gone finalizes ERROR
    # path="recovery" LOUDLY on replay, never silently.
    journal_payload: str = "full"
    # Root directory of the persistent executable cache: warmup's AOT
    # compiles land in ``<dir>/<config-hash>/`` via JAX's persistent
    # compilation cache (`registry.enable_persistent_cache`; the
    # namespace hash covers the solver config, the ACTIVE tuning table's
    # content hash, and the jax/backend identity — a table regeneration
    # or config change can never serve a stale executable), so a
    # restarted worker's warmup is cache hits instead of fresh compiles.
    # None disables the cache (and AOT warmup by default; see
    # ``SVDService.warmup(aot=...)``).
    compile_cache_dir: Optional[str] = None
    # --- request coalescing (the micro-batched solve lane) ---
    # Up to ``max_batch`` same-bucket requests are popped per dispatch and
    # solved as ONE batched solve (`solver.BatchedSweepStepper`): the
    # rotation kernel is latency-bound, so B small same-bucket solves
    # stacked along the pair axis cost close to one — a near-B× throughput
    # win on a small/medium-bucket request mix. 1 = the pre-batching
    # strictly-serial behavior.
    max_batch: int = 1
    # Bounded batching window: after popping the FIRST request of a
    # dispatch the worker waits at most this long for same-bucket
    # followers (never past the first request's own deadline). 0 = only
    # coalesce what is already queued.
    batch_window_s: float = 0.0
    # Static batch-size tiers: a popped batch snaps UP to the smallest
    # tier holding it, zero-padding the tail slots (exact for the SVD —
    # an all-zero member deflates in one sweep), so the batched stepper
    # jits compile once per (bucket, tier) and the compile cache stays
    # bounded. Tiers above ``max_batch`` are simply never used. The
    # string "auto" resolves each BUCKET's tier set through the active
    # tuning table at declaration time (`tune.resolve(...).batch_tiers`
    # — which batch sizes amortize is a measured, backend-dependent
    # verdict; PROFILE.md item 22) — still static per bucket, so the
    # compile-cache contract is unchanged.
    batch_tiers: tuple = DEFAULT_BATCH_TIERS
    # Anti-starvation bound on the coalescing window: once the oldest
    # queued request of ANOTHER bucket has waited this long, same-bucket
    # coalescing may not bypass it any further (see
    # `AdmissionQueue.pop_same_bucket`). None disables the bound.
    batch_bypass_age_s: Optional[float] = 0.5
    # --- fleet mode (`fleet` module): per-lane fault domains -------------
    # Solve lanes: 1 = the single-worker service (exact pre-fleet
    # behavior); > 1 = one worker per lane, each lane its own fault
    # domain with its own queue/breaker/device, bucket-affinity routing,
    # work stealing, and the lane supervisor (eviction -> rescue ->
    # probe recovery). max_queue_depth / max_deadline_budget_s are
    # PER-LANE limits.
    lanes: int = 1
    # Evict a lane whose worker has not heartbeat (pop / pre-dispatch /
    # per-sweep) for this long — the wedged-lane watchdog. Applies to
    # the HOST-SIDE dispatch loop; while the worker is blocked inside a
    # stepper/device call (`lane.in_step`, which legitimately stalls for
    # a full jit COMPILE on a cold cache) the longer
    # ``lane_step_timeout_s`` governs instead.
    lane_heartbeat_timeout_s: float = 2.0
    # Heartbeat budget while blocked inside one device/compile step:
    # must exceed the worst-case legitimate compile (minutes-class on
    # TPU; `warmup()` front-loads them). A lane whose thread is stuck in
    # a runtime call PAST this is unrecoverable in-process — it is
    # evicted, its requests rescued, and the probe respawns a fresh
    # worker thread for the lane (a lane survives its thread).
    lane_step_timeout_s: float = 300.0
    # Evict after this many CONSECUTIVE NONFINITE/ERROR dispatch
    # outcomes on one lane (a poisoned device keeps failing solves that
    # succeed elsewhere).
    lane_failure_threshold: int = 3
    # Evict after this many consecutive dispatches that left the lane's
    # breaker OPEN (the escalation ladder is not healing this lane).
    lane_open_threshold: int = 4
    # Supervisor tick; also bounds eviction-detection latency.
    supervise_interval_s: float = 0.05
    # Quarantined-lane recovery probes: at most one probe per lane per
    # interval, each a zeros solve of the smallest bucket with this
    # deadline.
    lane_probe_interval_s: float = 0.25
    lane_probe_timeout_s: float = 60.0
    # Work stealing: an idle lane pops the oldest request off the
    # deepest ACTIVE sibling queue.
    steal: bool = True
    # Wall-clock watchdog on the uncancellable escalation ladder: when a
    # ladder dispatch runs past this, a `ladder_overrun` fleet manifest
    # record is written and (fleet mode) the dispatching lane is flagged
    # unhealthy — evicted with its queued requests rescued — instead of
    # wedging the service behind it. None disables.
    ladder_watchdog_s: Optional[float] = None
    # --- two-phase serving + result cache (serve.cache module) -----------
    # Byte budget of the `PromotionStore` retaining sigma-phase solve
    # state (`submit(phase="sigma")` -> `Ticket.promote()`): column/
    # rotation stacks + preconditioning factors per retained request,
    # LRU-evicted under the budget (an evicted client's promote raises
    # `PromotionError` loudly). 0 disables retention — sigma requests
    # still serve σ, promotion always raises.
    promotion_store_bytes: int = 256 * 1024 * 1024
    # Byte budget of the content-addressed `ResultCache`: completed full
    # decompositions keyed by SHA-256 input digest + bucket + resolved
    # solver-config hash; a hit finalizes at admission with ZERO solver
    # dispatch and no queue slot. 0 disables (no digesting — the exact
    # pre-cache submit path).
    result_cache_bytes: int = 0
    # Digest every admitted input even with the result cache OFF: the
    # oriented-input SHA-256 (the ResultCache key ingredient) is then
    # exposed on `Ticket.digest` and in the per-request serve records,
    # so clients and the replica router (`serve.router`) can key
    # byte-identical resubmits without re-hashing. Implied by
    # ``result_cache_bytes > 0``.
    compute_digest: bool = False
    # --- serving flight recorder (obs.registry / obs.spans) --------------
    # Live metrics registry + per-request span timelines + SLO
    # accounting. OFF by default and FREE when off: no registry object
    # exists, every instrumentation site is behind one None check, and
    # the OBS002 analysis pass proves zero registry mutations on the
    # metrics-off hot path (plus metrics-off HLO byte-identity — the
    # recorder is host-side only and never enters a trace).
    metrics: bool = False
    # Start a stdlib HTTP listener serving GET /metrics (Prometheus text
    # exposition) and /healthz (JSON) on this port at `start()`; 0 binds
    # an ephemeral port (see `SVDService.http_address`). None disables.
    metrics_port: Optional[int] = None
    # SLO availability objective: the error-budget burn gauge reads
    # miss_rate / (1 - objective) over the rolling outcome window.
    slo_objective: float = 0.99
    # --- multi-tenant front door (per-tenant QoS; serve.queue) -----------
    # Declared tenants: name -> TenantPolicy (or a mapping of its fields
    # weight / rate / burst / priority / budget_share). None/empty keeps
    # the single-caller queue byte-identical (no TenantTable exists);
    # callers may still pass any tenant name — undeclared tenants get
    # the default policy (weight 1, no rate limit, priority 1).
    tenants: Optional[dict] = None
    # API-token identity map for the wire: token -> tenant name
    # (`serve.transport` resolves the submit record's ``api_token``
    # through this; an unknown token is rejected UNKNOWN_TENANT, never
    # silently defaulted). None = no token auth: the wire's optional
    # ``tenant`` field is trusted as-is, like an in-process caller.
    api_tokens: Optional[dict] = None
    # Dequeue ordering: "fifo" (arrival order — the pre-tenancy
    # behavior) or "edf" (earliest deadline first; deadline-less
    # requests sort last, ties stay FIFO). With declared tenants the
    # ordering applies WITHIN the weighted-fair tenant pick.
    queue_ordering: str = "fifo"
    # Result-cache tenant isolation: by default the content-addressed
    # cache key includes the tenant, so a byte-identical resubmit from a
    # DIFFERENT tenant never observes another tenant's cached result
    # (or its sub-millisecond timing signature). True restores
    # cross-tenant sharing for deployments where all tenants are one
    # trust domain.
    shared_result_cache: bool = False


class SVDService:
    """Thread-safe in-process SVD server (see module docstring)."""

    def __init__(self, config: ServeConfig = ServeConfig()):
        if not (0.0 < config.brownout_sigma_only_at
                <= config.brownout_shed_at):
            raise ValueError(
                "brownout thresholds must satisfy 0 < sigma_only_at <= "
                f"shed_at, got {config.brownout_sigma_only_at} / "
                f"{config.brownout_shed_at}")
        if config.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got "
                             f"{config.max_batch}")
        # Tuning-table resolution, ONCE per bucket at declaration: every
        # dispatch path (all lanes — they inherit this map) reads the
        # per-bucket resolved solver config instead of re-resolving per
        # request, and `batch_tiers="auto"` takes each bucket's measured
        # tier set from the same table. Factored out so `reload` can
        # resolve a NEW bucket set identically before the atomic swap.
        (self.buckets, self._bucket_solver, self._bucket_tiers,
         tiers) = self._resolve_bucket_maps(config)
        if config.batch_window_s < 0:
            raise ValueError("batch_window_s must be >= 0")
        if config.lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {config.lanes}")
        if (config.lane_heartbeat_timeout_s <= 0
                or config.lane_step_timeout_s <= 0
                or config.supervise_interval_s <= 0):
            raise ValueError("lane_heartbeat_timeout_s, "
                             "lane_step_timeout_s and "
                             "supervise_interval_s must be > 0")
        if config.lane_failure_threshold < 1 or config.lane_open_threshold < 1:
            raise ValueError("lane_failure_threshold and "
                             "lane_open_threshold must be >= 1")
        if config.journal_payload not in ("full", "digest"):
            raise ValueError(f"journal_payload must be 'full' or "
                             f"'digest', got {config.journal_payload!r}")
        if config.queue_ordering not in ("fifo", "edf"):
            raise ValueError(f"queue_ordering must be 'fifo' or 'edf', "
                             f"got {config.queue_ordering!r}")
        self._tiers = tiers
        self.config = config
        # Multi-tenant QoS: ONE TenantTable shared by every lane's queue
        # (rates and fairness are per-service promises), None when no
        # tenant is declared so the single-caller queue stays
        # byte-identical. Construction validates every declared policy.
        self.tenant_table = (TenantTable(config.tenants)
                            if config.tenants else None)
        # Per-tenant outcome counters (admitted / served / rejected:*),
        # guarded by self._lock like `_stats` — the healthz()["tenants"]
        # and fairness-drill substrate, live regardless of metrics.
        self._tenant_stats: dict = {}
        # Per-tenant SLO trackers (lazily minted per first outcome) —
        # only when the flight recorder is ON, like `self.slo`.
        self.tenant_slo: dict = {}
        self._records: list = []
        self._stats: dict = {}
        self._lock = threading.Lock()
        self._accepting = False
        self._drain = True
        # chaos.kill_replica's in-process SIGKILL simulation: once set,
        # workers exit at their next pop WITHOUT serving or finalizing —
        # queued requests stay stranded as journal debt, exactly what a
        # process loss leaves behind (`_chaos_kill`).
        self._killed = False
        self._seq = itertools.count()
        self._batch_seq = itertools.count()
        # The lane set (a trivial one-lane fleet when lanes == 1) owns
        # the queues, breakers, worker threads, and — in fleet mode —
        # the supervisor. Built last: it reads config/buckets above.
        self.fleet = Fleet(self)
        # The entry registry: the ONE authoritative enumeration of every
        # compilable (lane, bucket, tier, variant) jit entry — warmup
        # (both its AOT and zero-solve phases), reload's pre-warm, and
        # the AOT001 analysis pass all walk this instead of private
        # approximations (serve.registry module docstring).
        from .registry import EntryRegistry
        self.registry = EntryRegistry.for_service(self)
        self._cache_ns = None
        self._cache_hash: Optional[str] = None
        if config.compile_cache_dir is not None:
            from . import registry as _registry
            self._cache_ns, meta = _registry.enable_persistent_cache(
                config.compile_cache_dir, config.solver)
            self._cache_hash = meta["config_sha256"]
        # Two-phase serving + content-addressed result cache: the
        # PromotionStore retains sigma-phase solve state for
        # `Ticket.promote`; the ResultCache finalizes byte-identical
        # resubmits at admission with zero dispatch (serve.cache module
        # docstring). Both byte-budgeted LRU, both observable through
        # "cache" manifest records.
        from .cache import PromotionStore, ResultCache
        self.promotions = PromotionStore(config.promotion_store_bytes)
        self.result_cache = ResultCache(config.result_cache_bytes)
        # Per-bucket resolved-config content hashes (the PR 9
        # `config_hash` discipline) for the result-cache key — memoized
        # at first use, cleared by `reload`'s swap (a reloaded solver
        # config must never serve a stale cached result).
        self._bucket_cfg_hash: dict = {}
        # Durable request journal (write-ahead; see `recover`). Opened
        # EXCLUSIVE: the service is this path's one live writer, and a
        # second live service on the same path fails loudly with
        # `JournalLockedError` (two replicas interleaving fsync'd
        # records into one journal would silently corrupt the
        # exactly-once story — serve.journal module docstring).
        from .journal import Journal, read_fence_token
        self.journal = (Journal(config.journal_path, exclusive=True)
                        if config.journal_path is not None else None)
        # This replica's OWN fault-domain fencing token, acknowledged at
        # boot: a respawn after a cross-machine rescue adopts whatever
        # token the rescuer minted (its debt is tombstoned, so adopting
        # is safe). `_journal_finalize` refuses to append once the disk
        # token outruns this — a zombie worker whose solve outlived a
        # fenced rescue must NOT land a duplicate FINALIZE in a journal
        # another host already scanned and compacted.
        self._own_fence_token = (
            read_fence_token(config.journal_path)
            if config.journal_path is not None else 0)
        # request_id -> Ticket of journal-recovered requests (`recover`).
        self.recovered: dict = {}
        # Fencing-token ledger of the cross-machine rescue lane
        # (`admit_journal_debt`): fault domain (the dead journal's path)
        # -> (highest fencing token accepted, rids already admitted
        # under that domain). A batch carrying a LOWER token than the
        # ledger's is a stale rescuer — refused loudly (StaleFenceError
        # + a "fence_refused" journal audit record); an equal/newer
        # token's duplicate rid is an idempotent replay and is skipped.
        # Guarded by self._lock.
        self._rescue_fences: dict = {}
        self._last_reload_error: Optional[str] = None
        # Serving flight recorder (obs.registry / obs.spans): live
        # metrics + SLO accounting + per-request span timelines. None
        # when off — the instrumentation sites all guard on that one
        # attribute, so the off path constructs nothing and mutates
        # nothing (the OBS002 contract).
        self.metrics = None
        self.slo = None
        self.spans = None
        if config.metrics:
            from ..obs.registry import MetricsRegistry, SLOTracker
            from ..obs.spans import SpanRecorder
            self.metrics = MetricsRegistry()
            self.slo = SLOTracker(objective=config.slo_objective)
            self.spans = SpanRecorder()
            self.metrics.add_collector(self._collect_metrics)
        # Armed one-request XProf windows (`capture_request_trace`).
        self._trace_arms: dict = {}
        # Perf observatory feed: the latest per-bucket convergence block
        # (off_rel decay, sweeps-to-tol) off the host-stepped loop's own
        # stopping reads — zero extra device readback — surfaced under
        # healthz()["perf"]. Roofline device constants resolve lazily
        # (first healthz), with provenance.
        self._last_convergence: dict = {}
        self._perf_device: Optional[dict] = None
        self._http = None
        self._http_addr: Optional[Tuple[str, int]] = None

    @staticmethod
    def _resolve_bucket_maps(config: ServeConfig):
        """Declaration-time bucket resolution: the bucket set, its
        per-bucket tuning-table-resolved solver configs, and the
        coalescing tier maps — shared by `__init__` and `reload` so a
        reloaded bucket set resolves exactly like a declared one."""
        buckets = BucketSet(config.buckets)
        bucket_solver = buckets.resolve_solver_configs(config.solver)
        if config.batch_tiers == "auto":
            bucket_tiers = buckets.resolved_batch_tiers()
            tiers = tuple(sorted(set(
                t for ts in bucket_tiers.values() for t in ts)))
        else:
            tiers = tuple(sorted(set(int(t) for t in config.batch_tiers)))
            bucket_tiers = {b: tiers for b in buckets}
        if not tiers or tiers[0] < 1:
            raise ValueError(f"batch_tiers must be a non-empty set of "
                             f"positive ints, got {config.batch_tiers!r}")
        return buckets, bucket_solver, bucket_tiers, tiers

    # -- lane-0 views (the whole service when lanes == 1) -------------------

    @property
    def queue(self):
        """Lane 0's admission queue — THE queue when ``lanes == 1`` (the
        pre-fleet surface tests and tooling poke); one lane of several
        in fleet mode (see ``fleet.lanes`` for all of them)."""
        return self.fleet.lanes[0].queue

    @property
    def breaker(self):
        """Lane 0's circuit breaker (see `queue`)."""
        return self.fleet.lanes[0].breaker

    # -- tuning-table resolution (declaration-time, bucket-granular) --------

    def _solver_for(self, bucket) -> SVDConfig:
        """The bucket's declaration-time resolved solver config (falls
        back to the base config for a bucket outside the declared set —
        only warmup/probe internals could ever pass one)."""
        return self._bucket_solver.get(bucket, self.config.solver)

    def _tiers_for(self, bucket) -> tuple:
        """The bucket's coalescing tier set (global unless
        ``batch_tiers="auto"`` resolved per-bucket tiers)."""
        return self._bucket_tiers.get(bucket, self._tiers)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "SVDService":
        with self._lock:
            if any(l.thread is not None and l.thread.is_alive()
                   for l in self.fleet.lanes):
                raise RuntimeError("service already started")
            if self.queue.closed_and_empty():
                raise RuntimeError(
                    "service was stopped; a stopped SVDService is not "
                    "restartable — build a new one")
            self._accepting = True
            self._drain = True
            self.fleet.start()
        if self.config.metrics_port is not None and self._http is None:
            self.start_http(port=self.config.metrics_port)
        return self

    def _spawn_worker(self, lane: Lane) -> None:
        """(Re)spawn a lane's worker thread for its CURRENT generation
        (the fleet probes call this to revive a dead lane)."""
        thread = threading.Thread(
            target=self._worker_entry, args=(lane,),
            name=f"svdj-serve-l{lane.index}", daemon=True)
        lane.thread = thread
        thread.start()

    def stop(self, drain: bool = True, timeout: Optional[float] = None
             ) -> None:
        """Stop accepting; drain the queues (default) or finalize every
        queued request with CANCELLED — either way every admitted request
        reaches a terminal status."""
        with self._lock:
            self._accepting = False
            self._drain = bool(drain)
            threads = [l.thread for l in self.fleet.lanes
                       if l.thread is not None]
        # Supervisor first: a rescue racing shutdown would requeue onto
        # a queue that is about to close (requeue refuses and the rescue
        # finalizes ERROR — loud but misleading at shutdown).
        self.fleet.stop_supervisor(timeout=timeout)
        # Close BEFORE draining: admit and close share the queue lock, so
        # every submit either enqueued before this point (and is drained
        # below or served by a worker) or raises SHUTDOWN — no request
        # can be admitted onto a queue nobody will pop.
        for lane in self.fleet.lanes:
            lane.queue.close()
        if not drain:
            self._cancel_queued()
            # Also cancel the IN-FLIGHT solves (cooperatively — each
            # stops at the next sweep boundary and finalizes CANCELLED),
            # so a no-drain stop is not blocked behind a long solve and
            # running requests still reach a terminal status. The ladder
            # path cannot be interrupted mid-fused-solve; join() rides
            # it out up to ``timeout``.
            with self._lock:
                inflight = [r for l in self.fleet.lanes
                            for r in l.in_flight]
            for req in inflight:
                req.cancel.set()
        deadline = None if timeout is None else time.monotonic() + timeout
        for thread in threads:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            thread.join(remaining)
        # Belt-and-braces: anything still queued anywhere (a crashed or
        # quarantined lane's leftovers the supervisor no longer rescues)
        # is finalized, never stranded.
        workers_gone = all(not t.is_alive() for t in threads)
        if workers_gone:
            self._cancel_queued()
        self.stop_http()
        if self.journal is not None and workers_gone:
            # The stopped service is single-use: drop the journal path's
            # exclusivity lock so a successor (restart, or the replica
            # router's rescue) can claim it without breaking anything.
            # ONLY once every worker thread is dead — a worker that
            # outlived the join timeout is still a live writer, and
            # releasing under it would let a successor interleave with
            # its final appends (the exact corruption the lock exists
            # to prevent; the stale-lock auto-break covers the eventual
            # cleanup if this process then dies holding it).
            self.journal.release()

    def _chaos_kill(self) -> None:
        """In-process SIGKILL simulation (`chaos.kill_replica` /
        `serve.router`): stop accepting, close every lane queue (wakes
        blocked pops), bump every lane generation, and flag the service
        killed so workers exit at their next loop turn WITHOUT serving,
        finalizing, or rescuing anything — queued requests stay exactly
        where a process loss would leave them: as unfinalized write-ahead
        journal debt. A dispatch already inside a solve completes and
        finalizes normally (a thread cannot be interrupted mid-solve
        in-process; the journal-scan rescue skips it as finalized). The
        journal lock is NOT released — a SIGKILL'd process releases
        nothing, which is what `Journal.break_lock` exists for."""
        with self._lock:
            self._accepting = False
            self._killed = True
            for lane in self.fleet.lanes:
                lane.generation += 1
        self.fleet.stop_supervisor(timeout=1.0)
        for lane in self.fleet.lanes:
            lane.queue.close()

    def _cancel_queued(self) -> None:
        for lane in self.fleet.lanes:
            for req in lane.queue.drain():
                wait = time.monotonic() - req.submitted
                self._finalize(req, status_name="CANCELLED",
                               result=self._control_result(
                                   req, "CANCELLED", wait),
                               queue_wait=wait, solve_time=None,
                               path="base",
                               breaker_state=lane.breaker.state(),
                               lane=lane.index)

    def warmup(self, *, sigma_only: bool = True,
               timeout: float = 600.0,
               aot: Optional[bool] = None) -> None:
        """Compile every registry entry before real traffic, in (up to)
        two phases driven by the ONE authoritative enumeration
        (`self.registry.entries()` — every (lane, bucket, tier, variant)
        the dispatch paths can request):

          1. **AOT** (default iff ``compile_cache_dir`` is set, override
             with ``aot=``): each entry's whole jit plan is compiled via
             ``jit.lower(specs).compile()`` — no sweep executes — which
             populates (or, on a restart, HITS) the persistent
             executable cache. Per-entry compile-vs-cache-hit timing is
             appended as ONE schema-versioned ``"coldstart"`` manifest
             record, so every restart's cold-start cost is measurable
             from the stream; an entry already in the persistent cache
             costs a deserialization, not a compile — that IS the skip.
          2. **Execution**: one zeros solve per entry through the normal
             dispatch paths (zeros deflate immediately — the solve is
             one sweep), so the live per-lane jit caches are warm too.
             After phase 1 these solves' compile requests are served by
             the persistent cache.

        The sigma-only variants matter for the SIGMA_ONLY brownout: its
        compute flags are STATIC jit arguments, so without warmup the
        first degraded dispatch per bucket pays a fresh compile
        mid-overload, exactly when the worker can least afford it. Call
        after `start()`; the home-lane warmup requests flow through the
        normal submit path and appear in the manifest like any other.
        Raises RuntimeError on any non-OK warmup outcome — a warmup that
        silently failed would mean serving real traffic uncompiled (and,
        worse, with warmup failures already counted into the breaker)."""
        from . import registry as _registry
        if aot is None:
            aot = self.config.compile_cache_dir is not None
        t_start = time.perf_counter()
        entry_infos: list = []
        with _registry.CompileCounter() as cc:
            aot_s = 0.0
            if aot:
                t0 = time.perf_counter()
                entry_infos = self.registry.aot_warm(sigma_only=sigma_only)
                aot_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            self._exec_warm(sigma_only=sigma_only, timeout=timeout)
            exec_s = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.inc("svdj_aot_backend_compiles_total",
                             cc.backend_compiles,
                             help="AOT warmup backend compile requests")
            self.metrics.inc("svdj_aot_cache_hits_total", cc.cache_hits,
                             help="AOT warmup persistent-cache hits")
            self.metrics.inc("svdj_aot_fresh_compiles_total", cc.fresh,
                             help="AOT warmup compiles the cache "
                                  "did not serve")
        if aot:
            from .. import obs
            self._store(obs.manifest.build_coldstart(
                entries=entry_infos,
                total_s=time.perf_counter() - t_start,
                backend_compiles=cc.backend_compiles,
                cache_hits=cc.cache_hits, fresh_compiles=cc.fresh,
                cache_dir=(None if self._cache_ns is None
                           else str(self._cache_ns)),
                config_sha256=self._cache_hash,
                aot_s=float(aot_s), exec_s=float(exec_s),
                lanes=self.fleet.size))

    def _exec_warm(self, *, sigma_only: bool, timeout: float) -> None:
        """Warmup phase 2: one zeros solve per registry entry. Home-lane
        single dispatches go through the normal submit path (sequential
        — a burst of warmup submits would raise the queue fill into the
        brownout rungs and get the full-SVD variant degraded before it
        ever compiled; deadline_s=inf overrides any default_deadline_s
        and is exempt from the budget cap, so neither can expire or
        refuse the compile warmup exists to front-load). Sibling-lane
        and batched-tier entries use direct zero solves pinned to their
        lane (a deterministic tier-T dispatch cannot be forced through
        the admission queue without racing the batching window) — so the
        first affinity move, steal, rescue, or coalesced dispatch is not
        a compile stall mid-traffic."""
        import jax.numpy as jnp
        import numpy as _np

        from ..solver import SolveStatus
        for key in self.registry.entries(sigma_only=sigma_only):
            b, cu, cv = key.bucket, key.compute_u, key.compute_v
            if key.tier is None and key.lane == self.registry.home(b):
                rid = f"warmup-{b.name}-{'vec' if cu else 'novec'}"
                res = self.submit(jnp.zeros((b.m, b.n), jnp.dtype(b.dtype)),
                                  compute_u=cu, compute_v=cv,
                                  deadline_s=float("inf"),
                                  request_id=rid,
                                  top_k=(b.k if b.kind == "topk"
                                         else None)).result(timeout)
                if (res.status is not SolveStatus.OK or res.degraded
                        or res.path != "base"):
                    # A degraded or ladder-routed warmup solved SOMETHING,
                    # but not the stepper variant it exists to compile —
                    # that is a failure too (warm up before traffic, with
                    # a closed breaker).
                    status = (res.error if res.error
                              else res.status.name if res.status else "?")
                    raise RuntimeError(
                        f"warmup request {rid} did not compile its "
                        f"variant (status={status}, degraded="
                        f"{res.degraded}, path={res.path}, breaker now "
                        f"{self.breaker.state().value})")
            elif key.tier is None:
                lane = self.fleet.lanes[key.lane]
                res = self._direct_zero_solve(lane, b, cu, cv)
                if res.status_enum() is not SolveStatus.OK:
                    raise RuntimeError(
                        f"fleet warmup (lane {lane.index}, bucket "
                        f"{b.name}, vec={cu}/{cv}) did not solve OK: "
                        f"{res.status_enum().name}")
            else:
                lane = self.fleet.lanes[key.lane]
                res = self._direct_zero_solve(lane, b, cu, cv,
                                              batch=key.tier)
                codes = [int(c) for c in _np.asarray(res.status)]
                if any(c != int(SolveStatus.OK) for c in codes):
                    raise RuntimeError(
                        f"batched warmup (lane {lane.index}, bucket "
                        f"{b.name}, tier {key.tier}, vec={cu}/{cv}) did "
                        f"not solve OK: statuses {codes}")

    # -- restart survivability ---------------------------------------------

    def recover(self) -> dict:
        """Replay the durable request journal of a PREVIOUS process: every
        journaled-but-unfinalized request is re-admitted at the FRONT of
        its bucket's lane queue (it already waited its turn before the
        crash) with its remaining wall-clock deadline budget intact —
        a request whose deadline already expired finalizes DEADLINE
        loudly instead, a corrupt payload or unroutable bucket ERROR,
        never a silent drop. Exactly-once across the restart: replay
        skips finalized ids, the journal is atomically REWRITTEN to hold
        exactly the re-admitted debt (attempt-bumped, original admit
        times preserved so budgets keep decaying from the client's real
        submit), and in-process double finalization is already
        `Ticket._finalize_once`'s guarantee. Returns (and stores in
        ``self.recovered``) ``{request_id: Ticket}`` — the restarted
        process serves these like any other request. Call between
        construction and first traffic (before or right after
        `start()`)."""
        if self.journal is None:
            raise ValueError("recover() requires ServeConfig.journal_path")
        tickets: dict = {}
        queued: list = []     # (lane, req, admit_record) in admit order
        terminal: list = []   # (ticket, rec, status, error) — applied last
        now_wall = time.time()
        now_mono = time.monotonic()
        # Scan + compaction are ATOMIC against concurrent appends (the
        # journal's own lock): a request finalized or submitted while we
        # compact would otherwise have its fsync'd record erased by the
        # rewrite — a silent durability hole. Requeueing happens only
        # AFTER the compacted journal is on disk, so no recovered
        # request can finalize before its admit record is settled.
        with self.journal.exclusive():
            state = self.journal.scan()
            # Auto request-ids count from 0 in EVERY process; the journal
            # (and the manifest) key by id, so a fresh process reusing a
            # journaled id would fold two distinct requests into one
            # exactly-once slot — a finalize of the new one erases the
            # recovered one's debt. Advance the counter past every id the
            # dead process minted (finalized ones included: their serve
            # records persist even after compaction drops their admits).
            auto = [int(m.group(1)) for m in
                    (re.match(r"^r(\d+)$", rid) for rid in state.admits)
                    if m is not None]
            if auto:
                self._seq = itertools.count(max(auto) + 1)
            debt = state.unfinalized
            for rec in debt:
                ticket, req, status, error = self._debt_request(
                    rec, now_wall, now_mono)
                tickets[rec["id"]] = ticket
                if req is None:
                    terminal.append((ticket, rec, status, error))
                    continue
                try:
                    lane = self.fleet.route(req.bucket)
                except AdmissionError as e:
                    terminal.append((ticket, rec, "ERROR", e.detail))
                    continue
                queued.append((lane, req, rec))
            # Terminalize the expired/corrupt/unroutable debt BEFORE the
            # rewrite erases its admit records: each gets its finalize
            # (and serve manifest record) on disk first, so a crash at
            # any point leaves either admit+finalize (not replayed) or
            # no trace at all — never an admit silently dropped without
            # its terminal record (the re-entrant journal lock admits
            # the nested finalize appends).
            for ticket, rec, status, error in terminal:
                # graftlock: ok(journal->service inversion is startup-only — recover() runs single-threaded between construction and first traffic, so no thread can hold the service lock while waiting on this journal; the finalizes must stay inside the exclusive section for scan+compact atomicity)
                self._recover_terminal(ticket, rec, status, error=error)
            # Compact to exactly the re-admitted debt (attempt-bumped,
            # original admit times kept): a second crash replays only
            # what is still owed, finalized history is gone.
            self.journal.rewrite([
                {**rec, "attempt": int(rec.get("attempt", 1)) + 1,
                 "seq": i}
                for i, (_, _, rec) in enumerate(queued)])
        # Requeue in REVERSE admit order: each lands at the queue FRONT,
        # so the oldest journaled request ends up first — recovered FIFO.
        # A refused requeue (queue already closed) finalizes loudly; its
        # compacted admit record pairs with the finalize, so it is not
        # replayed again either.
        for lane, req, rec in reversed(queued):
            if not lane.queue.requeue(req):
                self._recover_terminal(req.ticket, rec, "CANCELLED")
        survivors = [rec for _, _, rec in queued]
        self.recovered = tickets
        dispatched = [rec["id"] for _, _, rec in queued
                      if rec["id"] in state.dispatched]
        self._record_fleet(event="journal_recover", lane=None,
                           count=len(survivors),
                           request_ids=[r["id"] for r in survivors],
                           was_in_flight=dispatched,
                           terminalized=sum(1 for t in tickets.values()
                                            if t.done()),
                           torn=state.torn)
        return tickets

    def _debt_request(self, rec: dict, now_wall: float,
                      now_mono: float) -> tuple:
        """Rebuild one journaled admit record into a live `Request`
        (ticket attached, remaining wall-clock deadline budget intact),
        or a terminal verdict when it cannot be re-admitted. Returns
        ``(ticket, req, status_name, error)`` — ``req`` is None iff the
        record terminalizes instead (expired deadline -> DEADLINE,
        corrupt payload / unroutable bucket -> ERROR). Shared by
        `recover` (this process's own journal) and `admit_journal_debt`
        (another replica's journal, handed over by the router's
        rescue)."""
        from .journal import decode_array
        rid = rec["id"]
        ticket = Ticket(rid, self, str(rec.get("phase", "full")))
        deadline_s = rec.get("deadline_s")
        try:
            a = decode_array(rec["input"])
        except Exception as e:
            return ticket, None, "ERROR", f"journal payload: {e}"
        remaining = None
        if deadline_s is not None:
            remaining = rec["t_wall"] + float(deadline_s) - now_wall
            if remaining <= 0:
                # The promise expired with the dead process — honor the
                # budget, loudly, without a sweep.
                return ticket, None, "DEADLINE", None
        bucket = self.buckets.route(rec["m"], rec["n"], str(a.dtype),
                                    top_k=rec.get("top_k"))
        if bucket is None:
            return ticket, None, "ERROR", (
                f"journaled bucket {rec.get('bucket')} no longer "
                f"routable in this configuration")
        # The journal payload's SHA-256 IS the oriented-input digest the
        # result cache / router key by (same bytes, same definition) —
        # carry it so a rescued clean solve still lands in the receiving
        # replica's result cache and serve records.
        digest = (rec.get("input") or {}).get("data_sha256")
        ticket.digest = digest
        req = Request(
            id=rid, a=a, m=int(rec["m"]), n=int(rec["n"]),
            orig_shape=tuple(rec["orig_shape"]),
            transposed=bool(rec["transposed"]), bucket=bucket,
            compute_u=bool(rec["compute_u"]),
            compute_v=bool(rec["compute_v"]),
            degraded=bool(rec.get("degraded", False)),
            brownout=str(rec.get("brownout", "FULL")),
            deadline=(None if remaining is None else now_mono + remaining),
            deadline_s=deadline_s, submitted=now_mono,
            cancel=ticket._cancel, ticket=ticket,
            top_k=rec.get("top_k"), rank_mode=bucket.kind,
            phase=str(rec.get("phase", "full")), digest=digest,
            tenant=str(rec.get("tenant", DEFAULT_TENANT)))
        return ticket, req, None, None

    def admit_journal_debt(self, records, *,
                           via: str = "replica_rescue",
                           fence_token: Optional[int] = None,
                           fence_domain: Optional[str] = None) -> dict:
        """Re-admit ANOTHER replica's journaled-but-unfinalized requests
        onto THIS service — the replica router's rescue lane
        (`serve.router`), mirroring the lane supervisor's rescue one
        fault domain up. Each record is write-ahead journaled HERE
        (attempt-bumped, ORIGINAL admit wall time preserved so deadline
        budgets keep decaying from the client's real submit) before
        being requeued at the FRONT of its bucket's lane queue — the
        rescued request already waited its turn on the replica that
        died. Expired deadlines finalize DEADLINE, corrupt payloads /
        unroutable buckets ERROR — loud, with ``via`` as the serve-record
        path — and exactly-once is the existing composition: the caller
        scans the dead journal under its (broken-then-reacquired) lock
        and skips finalized ids, this journal's write-ahead admit makes
        a second rescue replayable, and `Ticket._finalize_once` wins
        in-process races. Returns ``{request_id: Ticket}``.

        ``fence_token``/``fence_domain`` are the CROSS-MACHINE rescue
        discipline (serve.transport): the token the rescuer minted for
        the dead replica's fault domain (`journal.bump_fence_token`,
        ``fence_domain`` = the dead journal's path). A token older than
        one this service already accepted for the domain raises
        `StaleFenceError` loudly (plus a ``fence_refused`` journal
        audit record) — two rescuers racing over the same debt resolve
        to exactly-once: the newer token wins, an equal token's
        duplicate rids are skipped as idempotent replays."""
        from .journal import StaleFenceError
        tickets: dict = {}
        queued: list = []
        records = list(records)
        if fence_token is not None:
            domain = str(fence_domain or "_default")
            token = int(fence_token)
            with self._lock:
                held, seen = self._rescue_fences.get(domain,
                                                     (0, set()))
                stale = token < held
                dups: list = []
                if not stale:
                    fresh = []
                    for rec in records:
                        rid = str(rec["id"])
                        if rid in seen:
                            dups.append(rid)
                        else:
                            seen.add(rid)
                            fresh.append(rec)
                    self._rescue_fences[domain] = (max(held, token),
                                                   seen)
                    records = fresh
            if stale:
                self._bump("fence_refused")
                if self.journal is not None:
                    self.journal.append_audit(
                        "fence_refused", domain=domain, token=token,
                        held_token=held, via=via,
                        ids=[str(r.get("id")) for r in records])
                raise StaleFenceError(
                    f"rescue batch for domain {domain} carries fencing "
                    f"token {token} < accepted {held}: a newer rescue "
                    f"owns this debt — refusing to double-admit "
                    f"{len(records)} record(s)")
            if dups:
                self._bump(*(["fence_dup_skipped"] * len(dups)))
                if self.journal is not None:
                    self.journal.append_audit(
                        "fence_dup_skipped", domain=domain, token=token,
                        via=via, ids=dups)
        now_wall, now_mono = time.time(), time.monotonic()
        for rec in records:
            rid = rec["id"]
            if rid in tickets:
                continue
            ticket, req, status, error = self._debt_request(
                rec, now_wall, now_mono)
            tickets[rid] = ticket
            if req is None:
                self._recover_terminal(ticket, rec, status, error=error,
                                       path=via)
                continue
            req.via = via
            try:
                lane = self.fleet.route(req.bucket)
            except AdmissionError as e:
                self._recover_terminal(ticket, rec, "ERROR",
                                       error=e.detail, path=via)
                continue
            if self.journal is not None:
                # Write-ahead on the RECEIVING replica: once this append
                # returns, the rescued request survives a second crash
                # here too (original admit time kept, attempt bumped).
                self._observe_journal_append(self.journal.append_admit(
                    req, attempt=int(rec.get("attempt", 1)) + 1,
                    admitted_wall=rec["t_wall"],
                    payload_mode=self.config.journal_payload))
            queued.append((lane, req, rec))
        # Reverse admit order so the oldest rescued request ends up at
        # the very front — recovered FIFO, like `recover`.
        for lane, req, rec in reversed(queued):
            if not lane.queue.requeue(req):
                self._recover_terminal(req.ticket, rec, "CANCELLED",
                                       path=via)
        self._bump(*([f"rescued_in"] * len(queued)))
        return tickets

    def _recover_terminal(self, ticket: Ticket, rec: dict,
                          status_name: str,
                          error: Optional[str] = None,
                          path: str = "recovery") -> bool:
        """Terminalize a journal-recovered request WITHOUT re-admitting
        it (expired deadline, corrupt payload, unroutable bucket) —
        loud: a serve record with path="recovery" (or the router
        rescue's "replica_rescue"), a journal finalize, never a silent
        drop."""
        from ..solver import SolveStatus
        result = ServeResult(
            u=None, s=None, v=None,
            status=(None if error is not None
                    else SolveStatus[status_name]),
            error=error, sweeps=0, bucket=rec.get("bucket"),
            queue_wait_s=0.0, solve_time_s=None, path=path,
            degraded=bool(rec.get("degraded", False)), request_id=rec["id"])
        if not ticket._finalize_once(result):
            return False
        self._journal_finalize(rec["id"], status_name)
        self._bump("served", f"status:{status_name}", f"path:{path}")
        self._record(
            request_id=rec["id"],
            orig_shape=tuple(rec.get("orig_shape", (0, 0))),
            dtype=str(rec.get("input", {}).get("dtype", "?")),
            bucket=rec.get("bucket"), queue_wait_s=0.0, solve_time_s=None,
            status=status_name, path=path,
            breaker=self.breaker.state().value,
            brownout=str(rec.get("brownout", "FULL")), degraded=False,
            deadline_s=rec.get("deadline_s"), error=error,
            k=rec.get("top_k"), phase=str(rec.get("phase", "full")),
            tenant=str(rec.get("tenant", DEFAULT_TENANT)))
        return True

    def reload(self, *, buckets=None, solver: Optional[SVDConfig] = None,
               batch_tiers=None, sigma_only: bool = True,
               warm: bool = True,
               background: bool = True) -> threading.Event:
        """Zero-downtime configuration reload: resolve a NEW bucket set
        (and/or solver config / coalescing tiers) exactly like
        declaration time, AOT-warm its registry entries in the
        BACKGROUND (pure ``lower().compile()`` — nothing executes, live
        traffic keeps flowing), then atomically swap the routing maps
        under the service lock. Requests already queued against an OLD
        bucket keep serving: the old per-bucket resolved configs are
        retained in the merged map (so their jit keys — and executables
        — are unchanged), and the old executables simply drain from the
        jit caches as traffic moves. Lanes and max_batch are fixed at
        construction and cannot be reloaded.

        Returns a `threading.Event` set when the swap has completed (or
        the reload failed — check ``self._last_reload_error``; a failed
        reload changes NOTHING and the event still sets so callers never
        hang). ``background=False`` runs inline and returns the already-
        set event."""
        import dataclasses as _dc
        overrides = {k: v for k, v in (("buckets", buckets),
                                       ("solver", solver),
                                       ("batch_tiers", batch_tiers))
                     if v is not None}
        if not overrides:
            raise ValueError("reload() needs at least one of buckets= / "
                             "solver= / batch_tiers=")
        new_cfg = _dc.replace(self.config, **overrides)
        done = threading.Event()

        def _work():
            from . import registry as _registry
            from .registry import EntryRegistry
            repointed = False
            try:
                (nb, nsolver, ntiers_map,
                 ntiers) = self._resolve_bucket_maps(new_cfg)
                new_registry = EntryRegistry(
                    nb, nsolver, ntiers_map, new_cfg.solver,
                    max_batch=new_cfg.max_batch, lanes=new_cfg.lanes,
                    default_tiers=ntiers)
                new_ns, new_hash = self._cache_ns, self._cache_hash
                if (new_cfg.compile_cache_dir is not None
                        and "solver" in overrides):
                    # A solver change is a different cache namespace
                    # (its hash covers the solver config): re-point the
                    # persistent cache BEFORE the warm, so the new
                    # executables land where the next restart of the
                    # new config will look for them.
                    new_ns, meta = _registry.enable_persistent_cache(
                        new_cfg.compile_cache_dir, new_cfg.solver)
                    new_hash = meta["config_sha256"]
                    repointed = True
                infos = (new_registry.aot_warm(sigma_only=sigma_only)
                         if warm else [])
                with self._lock:
                    old_solver = self._bucket_solver
                    old_tiers = self._bucket_tiers
                    # Drain grace is ONE generation deep: buckets current
                    # at this swap keep their resolved configs (their
                    # in-flight requests finish under them), anything
                    # older was drained during the previous generation —
                    # without the cut the maps grow by every retired
                    # bucket per reload, forever. New declarations win
                    # on collision.
                    live = set(self.buckets)
                    self.buckets = nb
                    self._bucket_solver = {
                        **{b: c for b, c in old_solver.items()
                           if b in live}, **nsolver}
                    self._bucket_tiers = {
                        **{b: t for b, t in old_tiers.items()
                           if b in live}, **ntiers_map}
                    self._tiers = ntiers
                    self.config = new_cfg
                    self.registry = new_registry
                    self._cache_ns, self._cache_hash = new_ns, new_hash
                    # Result-cache identity memo: a reloaded solver
                    # config re-hashes at next use — old entries' keys
                    # simply never match again (LRU drains them).
                    self._bucket_cfg_hash = {}
                    self.fleet._bucket_home = {
                        b: i % self.fleet.size for i, b in enumerate(nb)}
                self._last_reload_error = None
                self._bump("reloads")
                self._record_fleet(
                    event="reload", lane=None,
                    buckets=[b.name for b in nb],
                    warmed=len(infos),
                    fresh_compiles=sum(i["fresh_compiles"]
                                       for i in infos))
            except Exception as e:
                self._last_reload_error = f"{type(e).__name__}: {e}"
                self._bump("reload_errors")
                if repointed and self.config.compile_cache_dir is not None:
                    # The cache dir was already re-pointed for the new
                    # solver; restore the OLD config's namespace so the
                    # unswapped service keeps caching where it reads.
                    try:
                        _registry.enable_persistent_cache(
                            self.config.compile_cache_dir,
                            self.config.solver)
                    except Exception:
                        pass
                print(f"svdj-serve: reload failed (nothing swapped): "
                      f"{self._last_reload_error}", file=sys.stderr)
            finally:
                done.set()

        if background:
            threading.Thread(target=_work, name="svdj-serve-reload",
                             daemon=True).start()
        else:
            _work()
        return done

    def __enter__(self) -> "SVDService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=False, timeout=10.0)

    # -- probes -------------------------------------------------------------

    def ready(self) -> bool:
        """Readiness: accepting work with at least one ACTIVE lane whose
        worker is alive (every lane, when ``lanes == 1``)."""
        with self._lock:
            return bool(self._accepting and self.fleet.any_active_alive())

    def healthz(self) -> dict:
        """Liveness + load snapshot (cheap; safe to poll). Top-level
        keys keep their single-worker meaning (``breaker`` is lane 0's,
        depth/budget aggregate over lanes); ``fleet`` carries the
        per-lane detail — states, heartbeat ages, streaks, steal/rescue
        counts."""
        with self._lock:
            alive = any(l.thread is not None and l.thread.is_alive()
                        for l in self.fleet.lanes)
            in_flight = next((r.id for l in self.fleet.lanes
                              for r in l.in_flight), None)
            stats = dict(self._stats)
            tenant_stats = {t: dict(s)
                            for t, s in self._tenant_stats.items()}
            tenant_slo = dict(self.tenant_slo)
        out = {
            "ok": alive,
            "ready": self.ready(),
            "breaker": self.breaker.state().value,
            "brownout": self._brownout().name,
            "queue_depth": sum(l.queue.depth() for l in self.fleet.lanes),
            "deadline_budget_s": sum(l.queue.deadline_budget()
                                     for l in self.fleet.lanes),
            "in_flight": in_flight,
            "stats": stats,
            "fleet": self.fleet.healthz(),
            "result_cache": self.result_cache.snapshot(),
            "promotions": self.promotions.snapshot(),
            # The ACTUAL bound (host, port) of the metrics listener —
            # with ``metrics_port=0`` (ephemeral: the only collision-free
            # choice for several replicas on one host) this is where a
            # scraper/router must look, since the configured port says 0.
            "http": (None if self._http_addr is None
                     else {"host": self._http_addr[0],
                           "port": self._http_addr[1]}),
        }
        if self.slo is not None:
            # SLO accounting rides the liveness probe: per-bucket
            # latency quantiles, deadline-miss/shed counts, and the
            # rolling error-budget burn (flight recorder on only).
            # Quantiles below their documented minimum sample count
            # read null, with snapshot["quantile_min_samples"] saying
            # why.
            out["slo"] = self.slo.snapshot()
        if self.tenant_table is not None or tenant_stats:
            # Per-tenant QoS view: declared policy + live token-bucket
            # level (QoS on), the always-live per-tenant counters, and
            # the per-tenant error-budget burn (flight recorder on).
            # Every tenant that DECLARED a policy or TOUCHED the
            # service appears — a flooded tenant's rate_limited count
            # and burn are visible even while it is being rejected.
            tenants: dict = {}
            qos_snap = (self.tenant_table.snapshot()
                        if self.tenant_table is not None else {})
            for t in sorted(set(qos_snap) | set(tenant_stats)
                            | set(tenant_slo)):
                entry: dict = {}
                if t in qos_snap:
                    entry["qos"] = qos_snap[t]
                entry["stats"] = tenant_stats.get(t, {})
                slo_t = tenant_slo.get(t)
                if slo_t is not None:
                    entry["slo"] = slo_t.snapshot()
                tenants[t] = entry
            out["tenants"] = tenants
        # Perf observatory view: roofline device constants (with
        # "table" vs estimate provenance) + the latest per-bucket
        # convergence telemetry from the host-stepped sweep loop.
        with self._lock:
            conv = dict(self._last_convergence)
        out["perf"] = {"device": self._perf_device_block(),
                       "convergence": conv}
        return out

    def _perf_device_block(self) -> Optional[dict]:
        """Roofline constants for this process's device, resolved once
        (healthz stays poll-cheap); None until a device is reachable."""
        if self._perf_device is None:
            try:
                import jax
                kind = jax.devices()[0].device_kind
            except Exception:
                return None
            from ..obs.perf import device_block
            self._perf_device = device_block(kind)
        return self._perf_device

    def _record_convergence(self, bucket: str, st) -> None:
        """Fold one host-stepped solve's convergence history into the
        healthz perf feed and the `svdj_sweeps_to_tol` gauge. The
        history is the (off_rel, stage) pairs `should_continue` already
        read for its stopping decisions — nothing extra crossed the
        host link for this."""
        hist = getattr(st, "convergence_history", None)
        if not hist:
            return
        from ..obs.perf import ConvergenceRecorder
        rec = ConvergenceRecorder(spectrum=bucket)
        for off, stage in hist:
            rec.record(off, stage)
        tol = float(getattr(st, "tol", 0.0)) or None
        block = rec.block(tol=tol)
        with self._lock:
            self._last_convergence[bucket] = block
        if (self.metrics is not None
                and block.get("sweeps_to_tol") is not None):
            self.metrics.set(
                "svdj_sweeps_to_tol", block["sweeps_to_tol"],
                bucket=bucket,
                help="sweeps to requested tolerance (host-stepped loop)")

    def records(self) -> list:
        """The in-memory per-request "serve" records (newest last)."""
        with self._lock:
            return list(self._records)

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
        if self._http_addr is not None:
            # The live listener's REAL port (metrics_port=0 binds an
            # ephemeral one); counters only otherwise.
            out["http_port"] = self._http_addr[1]
        return out

    # -- serving flight recorder (obs.registry / obs.spans) -----------------

    def metrics_text(self) -> str:
        """Prometheus text exposition of the live registry (collectors
        refreshed), or a one-comment body when the recorder is off —
        a scrape of a metrics-off service is explicit, not a 404."""
        if self.metrics is None:
            return "# svdj metrics disabled (ServeConfig.metrics=False)\n"
        return self.metrics.render()

    def _collect_metrics(self, reg) -> None:
        """Scrape-time collector: every DERIVED gauge — queue depth and
        deadline budget per lane, lane/breaker state, brownout level,
        cache sizes, journal fsync accounting, SLO quantiles/burn — is
        sampled when someone scrapes, so live-state changes cost the hot
        path nothing. Avoids the service lock except for one O(tenants)
        dict copy (collectors run OUTSIDE the registry lock, and
        service->obs is the sanctioned tier order, so a scrape can
        never deadlock a finalize)."""
        from .fleet import LaneState as _LS
        _BREAKER_CODE = {BreakerState.CLOSED: 0, BreakerState.HALF_OPEN: 1,
                         BreakerState.OPEN: 2}
        for lane in self.fleet.lanes:
            li = str(lane.index)
            reg.set("svdj_queue_depth", lane.queue.depth(), lane=li,
                    help="queued requests per lane")
            budget = lane.queue.deadline_budget()
            if budget != float("inf"):
                reg.set("svdj_deadline_budget_seconds", budget, lane=li,
                        help="aggregate remaining deadline budget queued")
            reg.set("svdj_lane_state",
                    1.0 if lane.state is _LS.ACTIVE else 0.0, lane=li,
                    help="1 = ACTIVE, 0 = QUARANTINED")
            reg.set("svdj_breaker_state",
                    float(_BREAKER_CODE[lane.breaker.state()]), lane=li,
                    help="0 = closed, 1 = half-open, 2 = open")
        reg.set("svdj_brownout_level", float(self._brownout().value),
                help="0 = FULL, 1 = SIGMA_ONLY, 2 = SHED")
        for name, snap in (("result_cache", self.result_cache.snapshot()),
                           ("promotion_store", self.promotions.snapshot())):
            for key in ("entries", "bytes", "hits", "misses", "stores",
                        "evictions", "promotes", "retains"):
                if key in snap:
                    reg.set(f"svdj_{name}_{key}", float(snap[key]),
                            help=f"{name.replace('_', ' ')} {key}")
        if self.journal is not None:
            io = self.journal.io_stats()
            reg.set("svdj_journal_appends_total", float(io["appends"]),
                    help="journal lifecycle appends (each one fsync)")
            reg.set("svdj_journal_append_seconds_total",
                    float(io["append_total_s"]),
                    help="cumulative journal append+fsync time")
        if self.slo is not None:
            self.slo.export_to(reg)
        if self.tenant_table is not None:
            for t, q in self.tenant_table.snapshot().items():
                reg.set("svdj_tenant_weight", float(q["weight"]),
                        tenant=t, help="declared WFQ weight per tenant")
                if q.get("tokens") is not None:
                    reg.set("svdj_tenant_tokens", float(q["tokens"]),
                            tenant=t,
                            help="live rate-limit token-bucket level")
        with self._lock:
            trackers = dict(self.tenant_slo)
        for t, slo in trackers.items():
            reg.set("svdj_tenant_error_budget_burn", slo.burn_rate(),
                    tenant=t,
                    help="per-tenant rolling error-budget burn rate")

    # The span-event emitter every lifecycle site funnels through: one
    # attribute check on the off path, nothing else.
    def _span(self, request_id: str, name: str, **meta) -> None:
        if self.spans is not None:
            self.spans.event(request_id, name, **meta)

    def _observe_journal_append(self, dt: Optional[float]) -> None:
        """Feed ONE journal append's fsync latency into the histogram.
        The duration is the append call's own return value, not a
        re-read of the journal's shared last-append field — a concurrent
        append from another thread could have overwritten that between
        the write and the read."""
        if self.metrics is not None and dt is not None:
            self.metrics.observe("svdj_journal_fsync_seconds", dt,
                                 help="per-append journal fsync latency")

    def timeline(self, request_id: str) -> list:
        """The request's LIVE span timeline (empty when the recorder is
        off or the request aged out of the bounded store). The offline
        equivalent is `obs.spans.timeline_from_manifest(records, id)`."""
        if self.spans is None:
            return []
        return self.spans.timeline(request_id)

    def capture_request_trace(self, request_id: str, log_dir) -> None:
        """Arm a one-request XProf window: when ``request_id`` is next
        dispatched, its dispatch..finish window runs under a
        `jax.profiler` trace into ``log_dir`` — a targeted capture of
        exactly one request instead of a whole serving session. Arming
        is best-effort by design: a request dispatched on a QUARANTINED
        lane (a recovery probe, or an eviction racing the dispatch)
        skips the capture with a warning instead of raising
        mid-supervisor-tick, and profiler failures degrade to warnings
        (`obs.spans.XprofWindow`)."""
        from ..obs.spans import XprofWindow
        with self._lock:
            self._trace_arms[str(request_id)] = XprofWindow(log_dir)

    def _trace_window_for(self, req: Request, lane: Lane):
        """Pop the armed XProf window for this dispatch (None when not
        armed). A quarantined dispatching lane — a probe solve, or an
        eviction that raced the pop — skips the capture LOUDLY-but-
        gently: profiling is observe-only and must never add an
        exception to a supervisor tick that is already handling a sick
        lane."""
        if not self._trace_arms:      # benign unlocked fast path
            return None
        with self._lock:
            win = self._trace_arms.pop(req.id, None)
        if win is None:
            return None
        if lane.state is not LaneState.ACTIVE:
            import warnings
            warnings.warn(
                f"capture_request_trace({req.id!r}): lane {lane.index} is "
                f"{lane.state.value}; skipping the XProf capture (the "
                f"request still serves)", RuntimeWarning, stacklevel=3)
            return None
        return win

    # -- /metrics + /healthz HTTP listener (stdlib) -------------------------

    @property
    def http_address(self) -> Optional[Tuple[str, int]]:
        """(host, port) of the live metrics listener, or None."""
        return self._http_addr

    def start_http(self, host: str = "127.0.0.1", port: int = 0
                   ) -> Tuple[str, int]:
        """Start the stdlib HTTP listener: GET /metrics returns the
        Prometheus exposition (content type version=0.0.4), GET /healthz
        the `healthz()` JSON (inf/nan sanitized to strings). One daemon
        thread; idempotent; `stop()` shuts it down."""
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        if self._http is not None:
            return self._http_addr
        svc = self

        def _json_safe(obj):
            if isinstance(obj, dict):
                return {str(k): _json_safe(v) for k, v in obj.items()}
            if isinstance(obj, (list, tuple)):
                return [_json_safe(v) for v in obj]
            if isinstance(obj, float) and (obj != obj or obj in (
                    float("inf"), float("-inf"))):
                return str(obj)
            return obj

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?", 1)[0] == "/metrics":
                    body = svc.metrics_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?", 1)[0] == "/healthz":
                    body = _json.dumps(
                        _json_safe(svc.healthz())).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # scrapes must not spam stderr
                pass

        self._http = ThreadingHTTPServer((host, int(port)), Handler)
        self._http_addr = (self._http.server_address[0],
                           self._http.server_address[1])
        threading.Thread(target=self._http.serve_forever,
                         name="svdj-serve-http", daemon=True).start()
        return self._http_addr

    def stop_http(self) -> None:
        http, self._http, self._http_addr = self._http, None, None
        if http is not None:
            http.shutdown()
            http.server_close()

    # -- admission ----------------------------------------------------------

    def _brownout(self, tenant: str = DEFAULT_TENANT) -> Brownout:
        # Aggregate fill over the fleet: brownout is an overload signal,
        # and a fleet with one backed-up lane but idle siblings is not
        # overloaded (stealing will drain it).
        fill = (sum(l.queue.depth() for l in self.fleet.lanes)
                / sum(l.queue.max_depth for l in self.fleet.lanes))
        # Priced brownout: a tenant's priority SCALES the fill it may
        # ride out — priority 1.0 (the default policy, and every tenant
        # when no table exists) hits the rungs exactly at the configured
        # thresholds, priority 0.5 is degraded to σ-only and shed at
        # half the fill (low-priority traffic pays for headroom first),
        # priority 2.0 stays full-service twice as deep.
        price = (1.0 if self.tenant_table is None
                 else self.tenant_table.policy(tenant).priority)
        if fill >= self.config.brownout_shed_at * price:
            return Brownout.SHED
        if fill >= self.config.brownout_sigma_only_at * price:
            return Brownout.SIGMA_ONLY
        return Brownout.FULL

    def _resolve_tenant(self, tenant: Optional[str],
                        api_token: Optional[str]) -> str:
        """The request's tenant identity: an explicit name wins (an
        in-process caller is one trust domain), else the API token
        resolves through `ServeConfig.api_tokens` — an unknown token is
        rejected UNKNOWN_TENANT, never silently defaulted — else the
        default tenant (today's single-caller surface)."""
        if tenant is not None:
            return str(tenant)
        if api_token is not None:
            mapped = (self.config.api_tokens or {}).get(str(api_token))
            if mapped is None:
                raise AdmissionError(
                    AdmissionReason.UNKNOWN_TENANT,
                    "api token resolves to no tenant in "
                    "ServeConfig.api_tokens")
            return str(mapped)
        return DEFAULT_TENANT

    def submit(self, a, *, compute_u: bool = True, compute_v: bool = True,
               deadline_s: Optional[float] = None,
               request_id: Optional[str] = None,
               top_k: Optional[int] = None,
               phase: str = "full",
               digest: Optional[str] = None,
               tenant: Optional[str] = None,
               api_token: Optional[str] = None) -> Ticket:
        """Admit one request: returns a `Ticket` or raises
        `AdmissionError` (reason: SHUTDOWN | NO_BUCKET | BROWNOUT_SHED |
        QUEUE_FULL | DEADLINE_BUDGET | RATE_LIMITED | UNKNOWN_TENANT).
        ``tenant`` names the caller for QoS/attribution (omitted = the
        default tenant — the exact pre-tenancy surface); ``api_token``
        instead resolves through `ServeConfig.api_tokens` (the wire's
        identity path). ``deadline_s`` is relative to now;
        the solve stops cooperatively within one sweep of it. None
        inherits ``default_deadline_s``; an explicit ``float("inf")``
        means NO deadline even when a default is configured (exempt from
        the deadline budget — `warmup` uses this so a compile can never
        expire the deadline that exists to front-load it).

        ``top_k`` requests a TRUNCATED decomposition: only the top-k
        factors come back (`ServeResult.u` (m, k) / ``s`` (k,) / ``v``
        (n, k)), solved through the randomized range-finder lane of a
        "topk" bucket whose rank class covers k (`buckets` module
        docstring; no declared topk bucket -> NO_BUCKET). Clamped to
        min(m, n). The accuracy contract is `solver.svd_topk`'s.

        ``phase="sigma"`` is the two-phase lane: the response carries σ
        only (u/v None — interactive latency, the finish stage's factor
        recombination/refinement matmuls are DEFERRED), and the solve's
        checkpointed stage is retained under the promotion byte budget
        so `Ticket.promote()` can resume it to full U/V later; the
        compute flags declare which factors a promote should produce.
        With the result cache enabled (``result_cache_bytes > 0``), a
        full-phase submit whose input digest + config identity hits a
        completed prior result finalizes HERE — zero solver dispatch, no
        queue slot — and the ticket returns already done."""
        import math

        import jax
        import jax.numpy as jnp
        import numpy as _np
        in_dtype = getattr(a, "dtype", None)
        # numpy input STAYS on host through admission: the screen is a
        # free host check and device placement happens at dispatch —
        # where a coalesced batch pays ONE transfer for all members
        # instead of a per-submit device_put on the client thread (those
        # ops concentrate into the worker's solve window and were a
        # measurable throughput tax at small buckets). The effective
        # dtype is what asarray WOULD produce under the current x64
        # setting — a mismatch (e.g. f64 with x64 off) takes the same
        # loud silent-downcast refusal below. Device/other input keeps
        # the original asarray + device-screen path.
        host_finite = None
        if (isinstance(a, _np.ndarray)
                and _np.issubdtype(a.dtype, _np.floating)):
            host_finite = bool(_np.isfinite(a).all())
            eff_dtype = jnp.dtype(jax.dtypes.canonicalize_dtype(a.dtype))
        else:
            a = jnp.asarray(a)
            eff_dtype = jnp.dtype(a.dtype)
        if a.ndim != 2:
            raise ValueError(f"expected a 2-D matrix, got shape {a.shape}")
        if top_k is not None:
            top_k = int(top_k)
            if top_k < 1:
                raise ValueError(f"top_k must be >= 1, got {top_k}")
            # A rank beyond min(m, n) adds only exact-zero sigmas —
            # clamp, so clients need not know the orientation rules.
            top_k = min(top_k, int(min(a.shape)))
        if phase not in ("full", "sigma"):
            raise ValueError(f"phase must be 'full' or 'sigma', got "
                             f"{phase!r}")
        rid = request_id or f"r{next(self._seq):05d}"
        orig_shape = tuple(int(d) for d in a.shape)
        transposed = a.shape[0] < a.shape[1]
        if transposed:
            a = a.T
            compute_u, compute_v = compute_v, compute_u
        m, n = (int(d) for d in a.shape)
        dtype = str(eff_dtype)
        # Normalize the deadline BEFORE any rejection path: a rejected
        # inf-deadline submit must not leak a non-JSON Infinity token
        # into its manifest record.
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        if deadline_s is not None and math.isinf(deadline_s):
            deadline_s = None
        brown = self._brownout()
        journaled = False
        bucket_name: Optional[str] = None   # set once routing succeeds
        tenant_name = DEFAULT_TENANT        # until identity resolves
        try:
            # Identity first: everything below (brownout price, rate
            # limit, cache key, attribution) hangs off the tenant.
            tenant_name = self._resolve_tenant(tenant, api_token)
            if self.tenant_table is not None:
                brown = self._brownout(tenant_name)   # priced rungs
            if not self.ready():
                raise AdmissionError(AdmissionReason.SHUTDOWN,
                                     "service is not accepting requests")
            if (in_dtype is not None
                    and eff_dtype != jnp.dtype(in_dtype)):
                # jnp.asarray silently downcasts (e.g. f64 -> f32 with
                # x64 disabled); serving a precision-degraded result
                # UNDECLARED would violate the layer's reject-or-record
                # policy, so refuse loudly instead.
                raise AdmissionError(
                    AdmissionReason.NO_BUCKET,
                    f"input dtype {jnp.dtype(in_dtype).name} is not "
                    f"representable in this runtime (jnp.asarray produces "
                    f"{eff_dtype}; jax_enable_x64?) — refusing to "
                    f"silently downcast")
            bucket = self.buckets.route(m, n, dtype, top_k=top_k)
            if bucket is None:
                what = (f"shape {orig_shape} dtype {dtype}"
                        + (f" top_k={top_k}" if top_k is not None else ""))
                raise AdmissionError(
                    AdmissionReason.NO_BUCKET,
                    f"{what} fits no declared bucket "
                    f"{[b.name for b in self.buckets]}")
            bucket_name = bucket.name
            finite = (host_finite if host_finite is not None
                      else bool(jnp.isfinite(a).all()))
            if not finite:
                # resilience.guard's policy, enforced at the door: no
                # ladder can fix data, and solving NaN input would read
                # NONFINITE and feed the breaker — one buggy client must
                # not be able to trip every other client onto the
                # degraded ladder path.
                raise AdmissionError(
                    AdmissionReason.NONFINITE_INPUT,
                    "input contains NaN/Inf — rejected before any solve "
                    "is spent (resilience.guard policy)")
            # Content-addressed fast-path: with the result cache on,
            # digest the oriented input and try to finalize HERE — a hit
            # costs zero solver dispatch and no queue slot, so it also
            # (deliberately) bypasses the SHED rung below: serving it
            # adds no load. Only full-phase requests consult the cache;
            # the promotion store is the sigma phase's own reuse lane.
            # ``digest`` may arrive precomputed (the replica router
            # hashes the oriented bytes to key its ring; re-hashing the
            # same megabytes here would double the admission tax —
            # PROFILE item 30's hot path). Trusted like any caller
            # input: a wrong digest mis-keys the cache exactly as a
            # caller hashing the wrong bytes would.
            if not (self.result_cache.max_bytes > 0
                    or self.config.compute_digest):
                digest = None
            elif digest is None:
                digest = self._input_digest(a)
            if digest is not None:
                if phase == "full" and self.result_cache.max_bytes > 0:
                    hit = self._cache_lookup(
                        rid, digest, bucket, m=m, n=n,
                        orig_shape=orig_shape,
                        transposed=transposed, compute_u=compute_u,
                        compute_v=compute_v, top_k=top_k, brown=brown,
                        deadline_s=deadline_s, tenant=tenant_name)
                    if hit is not None:
                        return hit
            if brown is Brownout.SHED:
                raise AdmissionError(
                    AdmissionReason.BROWNOUT_SHED,
                    f"queue fill {self.queue.depth()}/"
                    f"{self.queue.max_depth} at shed threshold"
                    + (f" (tenant {tenant_name!r} priced)"
                       if tenant_name != DEFAULT_TENANT else ""))
            now = time.monotonic()
            ticket = Ticket(rid, self, phase)
            ticket.digest = digest
            req = Request(
                id=rid, a=a, m=m, n=n, orig_shape=orig_shape,
                transposed=transposed, bucket=bucket,
                compute_u=compute_u, compute_v=compute_v,
                degraded=(brown is Brownout.SIGMA_ONLY
                          and (compute_u or compute_v)),
                brownout=brown.name,
                deadline=(None if deadline_s is None
                          else now + float(deadline_s)),
                deadline_s=deadline_s, submitted=now,
                cancel=ticket._cancel, ticket=ticket,
                top_k=top_k, rank_mode=bucket.kind,
                phase=phase, digest=digest, tenant=tenant_name)
            # Bucket-affinity routing: the bucket's home lane, or the
            # next ACTIVE one (lane 0 always, when lanes == 1). Raises
            # NO_LANE when the whole fleet is quarantined.
            lane = self.fleet.route(bucket)
            if self.journal is not None:
                # WRITE-AHEAD: journal before the enqueue, so there is
                # no window in which a client holds a ticket for a
                # request the journal never heard of. A journal write
                # failure propagates loudly (the request is NOT admitted
                # — a durability promise that cannot be recorded must
                # not be made). A post-journal queue rejection appends a
                # finalize record below so replay never resurrects it.
                dt_journal = self.journal.append_admit(
                    req, payload_mode=self.config.journal_payload)
                journaled = True
                self._observe_journal_append(dt_journal)
            lane.queue.admit(req)
            if self.metrics is not None:
                self.metrics.inc("svdj_requests_admitted_total",
                                 bucket=bucket.name, phase=phase,
                                 tenant=tenant_name,
                                 help="requests admitted to a lane queue")
                self._span(rid, "admit", bucket=bucket.name, phase=phase)
                self._span(rid, "queued", lane=lane.index)
            if lane.state is not LaneState.ACTIVE:
                # Admission raced an eviction: evict() flips the state
                # BEFORE draining, so either its rescue drain saw this
                # request (ordinary rescue) or we see the quarantined
                # state here — re-drain so nothing is stranded on a lane
                # whose worker is gone until a probe revives it.
                stranded = lane.queue.drain()
                if stranded:
                    self.fleet.rescue_requests(lane, stranded,
                                               cause="admit_race")
        except AdmissionError as e:
            if journaled:
                self._journal_finalize(rid, f"REJECTED_{e.reason.name}")
            self._bump("rejected", f"rejected:{e.reason.value}")
            self._bump_tenant(tenant_name, "rejected",
                              f"rejected:{e.reason.value}")
            if self.metrics is not None:
                self.metrics.inc("svdj_requests_rejected_total",
                                 reason=e.reason.value, tenant=tenant_name,
                                 help="requests rejected at admission")
                self._span(rid, "admit", rejected=True,
                           reason=e.reason.value)
                if e.reason in (AdmissionReason.BROWNOUT_SHED,
                                AdmissionReason.QUEUE_FULL,
                                AdmissionReason.DEADLINE_BUDGET,
                                AdmissionReason.RATE_LIMITED,
                                AdmissionReason.NO_LANE):
                    # Load-class rejections burn the error budget; a
                    # client error (NO_BUCKET, NONFINITE_INPUT,
                    # UNKNOWN_TENANT) does not.
                    self.slo.shed(None if bucket_name is None
                                  else bucket_name)
                    self._tenant_slo_for(tenant_name).shed(bucket_name)
            self._record(request_id=rid, orig_shape=orig_shape, dtype=dtype,
                         bucket=None, queue_wait_s=0.0, solve_time_s=None,
                         status=f"REJECTED_{e.reason.name}", path="rejected",
                         breaker=self.breaker.state().value,
                         brownout=brown.name, degraded=False,
                         deadline_s=deadline_s, error=e.detail,
                         rank_mode="topk" if top_k is not None else "full",
                         k=top_k, phase=phase, tenant=tenant_name)
            raise
        self._bump("submitted")
        self._bump_tenant(tenant_name, "submitted")
        return ticket

    # -- content-addressed result cache (serve.cache.ResultCache) -----------

    @staticmethod
    def _input_digest(a) -> str:
        """SHA-256 of the ORIENTED input bytes (`serve.cache.input_digest`
        — ONE definition shared with the journal payload checksum and
        the replica router's ring key)."""
        from .cache import input_digest
        return input_digest(a)

    def _cfg_hash_for(self, bucket) -> str:
        """Content hash of the bucket's declaration-time resolved solver
        config — the PR 9 `config_hash` discipline in the cache key: a
        config or tuning-table change resolves to a different hash, so a
        stale result can never be served (memo cleared on `reload`)."""
        h = self._bucket_cfg_hash.get(bucket)
        if h is None:
            from .. import obs
            h = obs.manifest.config_hash(self._solver_for(bucket))
            self._bucket_cfg_hash[bucket] = h
        return h

    def _cache_key(self, digest: str, bucket, *, m: int, n: int,
                   transposed: bool, compute_u: bool, compute_v: bool,
                   top_k: Optional[int],
                   tenant: str = DEFAULT_TENANT) -> tuple:
        """The result-cache identity: everything that shapes the answer.
        The digest covers the oriented bytes and ``(m, n)`` their
        LOGICAL shape (byte-identical buffers reshaped differently can
        route to the same padded bucket — their factors differ);
        ``transposed`` keeps an A-vs-Aᵀ client pair from sharing; the
        bucket + resolved-config hash cover routing and every solver
        knob; the flags/k cover which factors exist at what rank. The
        TENANT is part of the identity by default — a byte-identical
        resubmit from another tenant must not observe a hit (the hit
        itself leaks "someone else already submitted these bytes", a
        timing/result side channel). `ServeConfig.shared_result_cache`
        opts back into cross-tenant sharing by collapsing the slot to
        None. Appended LAST: `ResultCache.invalidate` matches on
        ``key[0] == digest`` and must keep flushing every tenant's
        entries for a changed matrix."""
        return (digest, int(m), int(n), bucket.name,
                self._cfg_hash_for(bucket),
                bool(transposed), bool(compute_u), bool(compute_v),
                None if top_k is None else int(top_k),
                None if self.config.shared_result_cache else str(tenant))

    def _cache_store(self, *, request_id: str, digest: str, bucket,
                     m: int, n: int, transposed: bool, compute_u: bool,
                     compute_v: bool, top_k: Optional[int],
                     u, s, v, status, sweeps: int,
                     tenant: str = DEFAULT_TENANT) -> None:
        """The ONE result-cache store path (full-phase finalize AND
        promote): host-copy the factors, store under the content key,
        and record the event — but only when the cache actually took
        the entry (an over-budget entry is refused; recording a store
        that never happened would make the stream lie)."""
        import numpy as _np
        entry = {
            "u": None if u is None else _np.asarray(u),
            "s": _np.asarray(s),
            "v": None if v is None else _np.asarray(v),
            "status": int(status),
            "sweeps": int(sweeps),
        }
        key = self._cache_key(digest, bucket, m=m, n=n,
                              transposed=transposed, compute_u=compute_u,
                              compute_v=compute_v, top_k=top_k,
                              tenant=tenant)
        stored, evicted = self.result_cache.put(key, entry)
        if stored:
            self._record_cache(
                "result", "store", request_id=request_id, digest=digest,
                nbytes=self.result_cache.entry_nbytes(entry))
        for k_ev in evicted:
            self._record_cache("result", "evict", digest=k_ev[0])

    def _cache_lookup(self, rid: str, digest: str, bucket, *,
                      m: int, n: int,
                      orig_shape, transposed: bool, compute_u: bool,
                      compute_v: bool, top_k: Optional[int], brown,
                      deadline_s,
                      tenant: str = DEFAULT_TENANT) -> Optional[Ticket]:
        """The admission fast-path: a cache hit finalizes the request
        right here — an O(ms) host-copy finalize, zero solver dispatch,
        no queue slot — with a "cache" hit event and an ordinary "serve"
        record (path="cache") in the stream. None on miss. The tenant
        is part of the lookup key (see `_cache_key`), so a resubmit
        from a different tenant misses by default."""
        from ..solver import SolveStatus
        key = self._cache_key(digest, bucket, m=m, n=n,
                              transposed=transposed,
                              compute_u=compute_u, compute_v=compute_v,
                              top_k=top_k, tenant=tenant)
        entry = self.result_cache.get(key)
        if entry is None:
            return None
        ticket = Ticket(rid, self, "full")
        ticket.digest = digest
        result = ServeResult(
            u=entry["u"], s=entry["s"], v=entry["v"],
            status=SolveStatus(int(entry["status"])), error=None,
            sweeps=int(entry["sweeps"]), bucket=bucket.name,
            queue_wait_s=0.0, solve_time_s=0.0, path="cache",
            degraded=False, request_id=rid)
        ticket._finalize_once(result)
        self._record_cache("result", "hit", request_id=rid, digest=digest)
        self._bump("submitted", "served", "cache_hits", "status:OK",
                   "path:cache")
        self._bump_tenant(tenant, "submitted", "served", "cache_hits",
                          "status:OK")
        if self.metrics is not None:
            self._span(rid, "admit", bucket=bucket.name)
            self._span(rid, "cache_hit", digest=digest[:12])
            self._span(rid, "finalize", status="OK", path="cache")
            self.metrics.inc("svdj_requests_finalized_total", status="OK",
                             path="cache", phase="full", tenant=tenant,
                             help="requests reaching a terminal status")
            self.slo.observe(bucket.name, 0.0, ok=True)
            self._tenant_slo_for(tenant).observe(bucket.name, 0.0, ok=True)
        self._record(request_id=rid, orig_shape=orig_shape,
                     dtype=bucket.dtype, bucket=bucket.name,
                     queue_wait_s=0.0, solve_time_s=0.0, status="OK",
                     path="cache", breaker=self.breaker.state().value,
                     brownout=brown.name, degraded=False,
                     deadline_s=deadline_s, sweeps=int(entry["sweeps"]),
                     rank_mode=bucket.kind, k=top_k, digest=digest,
                     tenant=tenant)
        return ticket

    def _maybe_cache_result(self, req: Request, result: ServeResult,
                            status_name: str, path: str) -> None:
        """Store a completed full-phase OK result under its content key
        (called from `_finalize` after the exactly-once write wins).
        Only clean base/ladder full solves are cacheable: degraded,
        partial (DEADLINE/CANCELLED), errored, or sigma-phase outcomes
        must never satisfy a future full request."""
        if (req.digest is None or req.phase == "sigma" or req.degraded
                or status_name != "OK"
                or path in ("rejected", "recovery", "rescue")
                or result.s is None):
            return
        self._cache_store(request_id=req.id, digest=req.digest,
                          bucket=req.bucket, m=req.m, n=req.n,
                          transposed=req.transposed,
                          compute_u=req.compute_u,
                          compute_v=req.compute_v, top_k=req.top_k,
                          u=result.u, s=result.s, v=result.v,
                          status=int(result.status),
                          sweeps=int(result.sweeps),
                          tenant=getattr(req, "tenant", DEFAULT_TENANT))

    def invalidate_cached(self, digest: Optional[str] = None) -> int:
        """Explicit cache invalidation — the client's "this matrix
        changed" signal (one input digest) or a full flush (None).
        Returns the number of entries dropped; appends one "cache"
        invalidate event either way."""
        n = self.result_cache.invalidate(digest)
        self._record_cache("result", "invalidate", digest=digest, count=n)
        return n

    # -- worker -------------------------------------------------------------

    # Fleet-mode pop timeout: lanes must wake to steal work and notice
    # eviction; a single lane keeps the blocking no-idle-polling pop.
    _FLEET_POLL_S = 0.05

    def _worker_entry(self, lane: Lane) -> None:
        """Thread target: run the lane worker; a `chaos.LaneKilled`
        injection (a BaseException no dispatch handler may swallow)
        terminates the thread here, with its request stranded in flight
        — recovering it is the fleet supervisor's job, which is the
        property the injector exists to test."""
        from ..resilience import chaos
        try:
            self._worker(lane)
        except chaos.LaneKilled:
            pass

    def _worker(self, lane: Lane) -> None:
        from ..resilience import chaos
        gen = lane.generation
        single = self.fleet.size == 1
        poll = None if single else self._FLEET_POLL_S
        while True:
            if self._killed:
                # chaos.kill_replica: simulated process loss — exit
                # without serving, finalizing, or rescuing anything.
                return
            if lane.generation != gen:
                return     # evicted: a respawned worker owns this lane now
            lane.beat()
            # Blocking pop when single (no idle polling; `admit` and
            # `close` notify); bounded in fleet mode so an idle lane can
            # steal and a superseded one can exit.
            stolen = False
            req = lane.queue.pop(poll)
            if req is None:
                if lane.queue.closed_and_empty():
                    return
                if (not single and self.config.steal
                        and lane.state is LaneState.ACTIVE
                        and lane.generation == gen):
                    req = self.fleet.steal_for(lane)
                    stolen = req is not None
                if req is None:
                    continue
            if self._killed:
                # Simulated process loss AFTER the pop: the request is
                # dropped un-finalized (its write-ahead admit record IS
                # the durable debt a rescuer replays) — finalizing or
                # rescuing here would be work a SIGKILL'd process could
                # never have done.
                return
            if lane.generation != gen:
                # Evicted between pop and dispatch: this worker may not
                # serve anymore — hand the request to the rescue path.
                self.fleet.rescue_requests(lane, [req],
                                           cause="stale_worker")
                return
            batch = [req]
            if self.config.max_batch > 1:
                # Coalesce same-bucket followers under the bounded
                # batching window: first-request wait <= batch_window_s,
                # never past the first request's own deadline (members
                # that expire DURING the window finalize pre-dispatch
                # without spending a sweep, as today), and never
                # bypassing another bucket's request older than
                # batch_bypass_age_s (anti-starvation).
                limit = min(self.config.max_batch,
                            self._tiers_for(req.bucket)[-1]) - 1
                # A STOLEN head request's same-bucket followers live on
                # the victim's queue, not this one (which was empty —
                # that is why the lane stole): take only what is queued
                # NOW instead of blocking an already-delayed request for
                # a window that cannot fill.
                window = (None if stolen
                          else time.monotonic() + self.config.batch_window_s)
                if window is not None and req.deadline is not None:
                    window = min(window, req.deadline)
                batch += lane.queue.pop_same_bucket(
                    req.bucket, limit, window,
                    max_bypass_age=self.config.batch_bypass_age_s)
            # Lane chaos (fleet tests): a kill strands the batch in
            # flight and dies — published FIRST so the supervisor's
            # dead-lane rescue has something to find; a wedge blocks
            # with no heartbeat until evicted (stale generation) or the
            # bound passes.
            if chaos.consume_kill(lane.index):
                with self._lock:
                    lane.in_flight = list(batch)
                raise chaos.LaneKilled(f"chaos kill_lane({lane.index})")
            wedge = chaos.consume_wedge(lane.index)
            if wedge is not None:
                with self._lock:
                    lane.in_flight = list(batch)
                t_end = time.monotonic() + wedge
                while time.monotonic() < t_end and lane.generation == gen:
                    time.sleep(0.005)
                with self._lock:
                    lane.in_flight = []
                if lane.generation != gen:
                    return   # evicted while wedged; batch already rescued
            with self._lock:
                drain = self._drain or self._accepting
            try:
                if not drain:
                    # stop(drain=False) raced the pop: finalize, don't solve.
                    for r in batch:
                        wait = time.monotonic() - r.submitted
                        self._finalize(
                            r, status_name="CANCELLED",
                            result=self._control_result(r, "CANCELLED",
                                                        wait),
                            queue_wait=wait, solve_time=None, path="base",
                            breaker_state=lane.breaker.state(),
                            lane=lane.index)
                elif len(batch) == 1:
                    self._serve_one(lane, req)
                else:
                    self._serve_batch(lane, batch)
            except BaseException as e:  # last ditch: no undone tickets
                for r in batch:
                    if not r.ticket.done():
                        self._finalize(
                            r, status_name="ERROR",
                            result=self._error_result(
                                r, f"{type(e).__name__}: {e}", 0.0,
                                "base"),
                            queue_wait=time.monotonic() - r.submitted,
                            solve_time=None, path="base",
                            breaker_state=lane.breaker.record(False),
                            lane=lane.index)

    def _serve_one(self, lane: Lane, req: Request) -> None:
        from ..ops.pallas_apply import VmemBudgetError
        from ..resilience import chaos
        from ..solver import SolveStatus
        t_pop = time.monotonic()
        queue_wait = t_pop - req.submitted
        with self._lock:
            lane.in_flight = [req]
            if not self._accepting and not self._drain:
                # stop(drain=False) raced the pop before in_flight was
                # published (it could not see this request to cancel it);
                # publish-and-check shares stop()'s lock, so one side
                # always sets the cancel event.
                req.cancel.set()
        self._journal_dispatch([req], lane)
        # The armed process-kill fires AFTER the dispatch is journaled:
        # the durable state a restarted service replays is exactly "this
        # request was in flight when the process died".
        chaos.maybe_sigkill()
        try:
            if req.cancel.is_set():
                # Cancelled while queued: terminal without spending a solve.
                self._finalize(req, status_name="CANCELLED",
                               result=self._control_result(
                                   req, "CANCELLED", queue_wait),
                               queue_wait=queue_wait, solve_time=None,
                               path="base",
                               breaker_state=lane.breaker.state(),
                               lane=lane.index)
                return
            if req.deadline is not None and time.monotonic() >= req.deadline:
                # Deadline expired while QUEUED: terminal without spending
                # a sweep — on EITHER breaker path (the ladder runs fused
                # solves that cannot stop mid-flight, so dispatching an
                # already-dead request there would serve it long after the
                # client gave up). A queue-expired deadline is an OVERLOAD
                # symptom, not a backend failure, so it does not feed the
                # breaker — otherwise overload would trip the breaker onto
                # the slower ladder path and amplify itself.
                self._finalize(req, status_name="DEADLINE",
                               result=self._control_result(
                                   req, "DEADLINE", queue_wait),
                               queue_wait=queue_wait, solve_time=None,
                               path="base",
                               breaker_state=lane.breaker.state(),
                               lane=lane.index)
                return
            path, _ = lane.breaker.begin()
            cu = req.compute_u and not req.degraded
            cv = req.compute_v and not req.degraded
            # Sigma phase: the solve still accumulates rotations (the
            # request's own flags — promotion needs them) but terminates
            # sigma-first, capturing the checkpointed stage here.
            cap = ({} if (req.phase == "sigma" and not req.degraded)
                   else None)
            if self.metrics is not None:
                self.metrics.inc("svdj_dispatches_total", lane=lane.index,
                                 path=path, help="solver dispatches")
                self.metrics.observe(
                    "svdj_queue_wait_seconds", queue_wait,
                    bucket=req.bucket.name,
                    tenant=getattr(req, "tenant", DEFAULT_TENANT),
                    help="admission-to-dispatch queue wait")
                self._span(req.id, "dispatch", lane=lane.index, path=path)
            win = self._trace_window_for(req, lane)
            if win is not None:
                win.start()
            t0 = time.monotonic()
            error = None
            r = None
            try:
                if path == "ladder":
                    r = self._solve_ladder(lane, req, cu, cv)
                else:
                    try:
                        r = self._solve_base(lane, req, cu, cv,
                                             sigma_capture=cap)
                    except VmemBudgetError as ve:
                        # A Pallas lane's per-grid-step working set
                        # over-ran its scoped-VMEM budget (geometry the
                        # VMEM001 analysis check exists to catch before
                        # it ships). A planning failure, not a backend
                        # fault: re-dispatch through the escalation
                        # ladder's unfused solve instead of erroring the
                        # request.
                        path = "ladder"
                        self._bump("vmem_escalations")
                        if self.metrics is not None:
                            self.metrics.inc(
                                "svdj_vmem_escalations_total",
                                lane=lane.index,
                                help="VMEM-budget ladder escalations")
                            self._span(req.id, "vmem_escalate",
                                       lane=lane.index,
                                       vmem_lane=getattr(ve, "lane", ""),
                                       fallback=getattr(ve, "fallback",
                                                        ""))
                        print(f"svdj-serve: {ve} — escalating "
                              f"request {req.id} to the ladder",
                              file=sys.stderr)
                        r = self._solve_ladder(lane, req, cu, cv)
                status = r.status_enum()
            except Exception as e:
                error = f"{type(e).__name__}: {e}"
                status = None
            finally:
                if win is not None:
                    win.stop()
            solve_time = time.monotonic() - t0
            if status is SolveStatus.CANCELLED:
                # Client-initiated: neither a success nor a backend failure.
                breaker_state = lane.breaker.state()
            else:
                breaker_state = lane.breaker.record(
                    error is None and status is SolveStatus.OK)
            if error is not None:
                result = self._error_result(req, error, queue_wait, path,
                                            solve_time_s=solve_time)
                status_name = "ERROR"
            else:
                u, s, v, sweeps = self._slice(req, r, cu, cv)
                if (req.phase == "sigma" and not req.degraded
                        and status is SolveStatus.OK):
                    # Retain the promotion state: the captured stage on
                    # the base path, or the already-finished factors on
                    # the fused ladder path (kind="result" — promote
                    # then costs nothing).
                    payload = None if cap is None else cap.get("payload")
                    self._retain_promotion(
                        req, lane, payload=payload,
                        lift=None if cap is None else cap.get("lift"),
                        factors=(u, s, v), status=status, sweeps=sweeps)
                if req.phase == "sigma":
                    u = v = None
                result = ServeResult(
                    u=u, s=s, v=v, status=status, error=None, sweeps=sweeps,
                    bucket=req.bucket.name, queue_wait_s=queue_wait,
                    solve_time_s=solve_time, path=path,
                    degraded=req.degraded, request_id=req.id)
                status_name = status.name
            lane.note_outcome(status_name, breaker_state)
            self._finalize(req, status_name=status_name, result=result,
                           queue_wait=queue_wait, solve_time=solve_time,
                           path=path, breaker_state=breaker_state,
                           lane=lane.index)
        finally:
            with self._lock:
                lane.in_flight = []

    def _serve_batch(self, lane: Lane, reqs) -> None:
        """Serve a coalesced same-bucket batch as ONE batched dispatch.

        Pre-dispatch, each member gets the same queued-cancel /
        queued-deadline finalization as a single request. The dispatch
        runs under the BATCH control: effective deadline = min over
        members (no member is served past its own promise — the whole
        batch stops within one sweep of the earliest deadline; members
        already at tolerance decode OK, the rest DEADLINE), cancellation
        fires only when every member cancelled. An OPEN breaker disables
        coalescing — the escalation ladder is a single-solve recovery
        path, so members dispatch sequentially through it. The breaker
        records ONE outcome per batched dispatch (all non-cancelled
        members OK)."""
        from ..ops.pallas_apply import VmemBudgetError
        from ..solver import SolveStatus
        t_pop = time.monotonic()
        live = []
        for req in reqs:
            wait = t_pop - req.submitted
            if req.cancel.is_set():
                self._finalize(req, status_name="CANCELLED",
                               result=self._control_result(
                                   req, "CANCELLED", wait),
                               queue_wait=wait, solve_time=None,
                               path="base",
                               breaker_state=lane.breaker.state(),
                               lane=lane.index)
            elif req.deadline is not None and t_pop >= req.deadline:
                # Queue-expired: overload symptom, not backend failure —
                # never fed to the breaker (cf. _serve_one).
                self._finalize(req, status_name="DEADLINE",
                               result=self._control_result(
                                   req, "DEADLINE", wait),
                               queue_wait=wait, solve_time=None,
                               path="base",
                               breaker_state=lane.breaker.state(),
                               lane=lane.index)
            else:
                live.append(req)
        if not live:
            return
        path, _ = lane.breaker.begin()
        if path == "ladder" or len(live) == 1:
            # Recovery path (or a batch that collapsed to one member):
            # strictly sequential single dispatches.
            for req in live:
                self._serve_one(lane, req)
            return
        batch_id = f"b{next(self._batch_seq):05d}"
        batch_size = len(live)
        bucket = live[0].bucket
        tier = min((t for t in self._tiers_for(bucket) if t >= batch_size),
                   default=batch_size)
        with self._lock:
            lane.in_flight = list(live)
        self._journal_dispatch(live, lane, batch_id=batch_id)
        if self.metrics is not None:
            self.metrics.inc("svdj_dispatches_total", lane=lane.index,
                             path="base", help="solver dispatches")
            self.metrics.inc("svdj_batched_dispatches_total", tier=tier,
                             help="coalesced batched dispatches")
            t_d = time.monotonic()
            for rq in live:
                self.metrics.observe(
                    "svdj_queue_wait_seconds", t_d - rq.submitted,
                    bucket=rq.bucket.name,
                    tenant=getattr(rq, "tenant", DEFAULT_TENANT),
                    help="admission-to-dispatch queue wait")
                self._span(rq.id, "dispatch", lane=lane.index,
                           path="base", batch_id=batch_id)
        from ..resilience import chaos
        chaos.maybe_sigkill()   # after journaling, like _serve_one
        try:
            cu = any(r.compute_u and not r.degraded for r in live)
            cv = any(r.compute_v and not r.degraded for r in live)
            # A batch whose EVERY member defers (sigma phase, degraded,
            # or factor-free) terminates sigma-first with ONE payload per
            # member (`BatchedSweepStepper.sigma_finish`); a mixed batch
            # runs the full batched finish and sigma members retain
            # their already-finished factors instead (kind="result").
            all_sigma = all((rq.phase == "sigma") or rq.degraded
                            or not (rq.compute_u or rq.compute_v)
                            for rq in live)
            cap = {} if all_sigma else None
            deadlines = [r.deadline for r in live if r.deadline is not None]
            deadline = min(deadlines) if deadlines else None
            should_cancel = lambda: all(r.cancel.is_set() for r in live)
            t0 = time.monotonic()
            error = None
            r = None
            try:
                r = self._solve_batched(lane, live, bucket, tier, cu, cv,
                                        deadline, should_cancel,
                                        sigma_capture=cap)
            except VmemBudgetError as ve:
                # Over-budget kernel geometry (see _serve_one): a
                # planning failure, not a backend fault — the breaker
                # records nothing. Members re-dispatch sequentially;
                # each single dispatch escalates itself to the ladder
                # if the unbatched geometry over-runs too.
                self._bump("vmem_escalations")
                if self.metrics is not None:
                    self.metrics.inc("svdj_vmem_escalations_total",
                                     lane=lane.index,
                                     help="VMEM-budget ladder escalations")
                print(f"svdj-serve: {ve} — re-dispatching batch "
                      f"{batch_id} members sequentially",
                      file=sys.stderr)
                with self._lock:
                    lane.in_flight = []
                for req in live:
                    self._serve_one(lane, req)
                return
            except Exception as e:
                error = f"{type(e).__name__}: {e}"
            solve_time = time.monotonic() - t0
            if error is not None:
                breaker_state = lane.breaker.record(False)
                lane.note_outcome("ERROR", breaker_state)
                for req in live:
                    wait = t0 - req.submitted
                    self._finalize(
                        req, status_name="ERROR",
                        result=self._error_result(req, error, wait, "base",
                                                  solve_time_s=solve_time),
                        queue_wait=wait, solve_time=solve_time,
                        path="base", breaker_state=breaker_state,
                        batch_id=batch_id, batch_size=batch_size,
                        batch_tier=tier, lane=lane.index)
                return
            import numpy as np
            # One host pull of the whole batched result: per-member
            # factor slicing then costs numpy views instead of 2-3 tiny
            # device ops + a scalar sync PER MEMBER (measured ~tens of ms
            # per dispatch at small buckets — real throughput).
            r = r._replace(
                u=None if r.u is None else np.asarray(r.u),
                s=np.asarray(r.s),
                v=None if r.v is None else np.asarray(r.v),
                sweeps=np.asarray(r.sweeps),
                status=np.asarray(r.status))
            statuses = []
            for j, req in enumerate(live):
                status_j = SolveStatus(int(r.status[j]))
                if (req.cancel.is_set()
                        and status_j is not SolveStatus.OK):
                    # Individual mid-solve cancel: the batch rightly kept
                    # sweeping for the neighbors, but THIS member's
                    # terminal status honors the cancel — unless it
                    # reached tolerance first (tolerance wins, matching
                    # the single lane's decode order).
                    status_j = SolveStatus.CANCELLED
                statuses.append(status_j)
            if all(st is SolveStatus.CANCELLED for st in statuses):
                breaker_state = lane.breaker.state()
            else:
                breaker_state = lane.breaker.record(all(
                    st is SolveStatus.OK for st in statuses
                    if st is not SolveStatus.CANCELLED))
                # One lane-health outcome per batched dispatch (bad =
                # any member NONFINITE; dispatch ERROR handled above).
                lane.note_outcome(
                    "NONFINITE" if any(st is SolveStatus.NONFINITE
                                       for st in statuses) else "OK",
                    breaker_state)
            for j, req in enumerate(live):
                wait = t0 - req.submitted
                status_j = statuses[j]
                # Factors are returned even for DEADLINE/CANCELLED
                # members — the same loud PARTIAL result the serial
                # lane's mid-solve control stops produce.
                u, s, v, sweeps_j = self._slice_member(req, r, j, cu, cv)
                if (req.phase == "sigma" and not req.degraded
                        and status_j is SolveStatus.OK):
                    payload = lift_j = None
                    if cap is not None and cap.get("payloads"):
                        payload = cap["payloads"][j]
                        lift_j = self._member_lift(cap.get("lift"), j)
                    self._retain_promotion(
                        req, lane, payload=payload, lift=lift_j,
                        factors=(u, s, v), status=status_j,
                        sweeps=sweeps_j)
                if req.phase == "sigma":
                    u = v = None
                result = ServeResult(
                    u=u, s=s, v=v, status=status_j, error=None,
                    sweeps=sweeps_j, bucket=req.bucket.name,
                    queue_wait_s=wait, solve_time_s=solve_time,
                    path="base", degraded=req.degraded, request_id=req.id)
                self._finalize(req, status_name=status_j.name,
                               result=result, queue_wait=wait,
                               solve_time=solve_time, path="base",
                               breaker_state=breaker_state,
                               batch_id=batch_id, batch_size=batch_size,
                               batch_tier=tier, lane=lane.index)
            self._bump("batched_dispatches", f"batch_tier:{tier}")
        finally:
            with self._lock:
                lane.in_flight = []

    def _solve_batched(self, lane, live, bucket, tier, cu, cv, deadline,
                       should_cancel, sigma_capture: Optional[dict] = None):
        """One coalesced dispatch: pad each member to the bucket, stack,
        zero-pad the tail slots to the batch tier (exact — an all-zero
        member deflates in one sweep), and run the batched host-stepped
        solve under the composed control. With ``sigma_capture`` (an
        all-sigma batch) the finish stage defers: one member-sliced
        promotion payload per member lands in the capture dict
        (cf. `_solve_base`)."""
        import jax.numpy as jnp
        import numpy as np

        from ..resilience import chaos
        from ..solver import BatchedSweepStepper
        # in_step from the first device op (cf. `_solve_base`): the
        # stack pad/placement compiles on a cold lane too.
        lane.in_step = True
        try:
            if all(isinstance(r.a, np.ndarray) for r in live):
                # Host-admitted members: build the padded tier stack in
                # one host buffer and pay ONE device transfer for the
                # whole batch.
                buf = np.zeros((tier, bucket.m, bucket.n),
                               np.dtype(bucket.dtype))
                for j, r in enumerate(live):
                    buf[j, :r.a.shape[0], :r.a.shape[1]] = r.a
                a = jnp.asarray(buf)
            else:
                stack = [self.buckets.pad(r.a, bucket) for r in live]
                if tier > len(stack):
                    pad = jnp.zeros((bucket.m, bucket.n),
                                    jnp.dtype(bucket.dtype))
                    stack += [pad] * (tier - len(stack))
                a = jnp.stack(stack)
            a = self._place(a, lane)
            if chaos.consume_poison(lane.index):
                a = a.at[0, 0, 0].set(jnp.nan)
            stall = chaos.consume_stuck()
            if stall is not None:
                self._stall(live[0], stall, lane)
            slow = chaos.consume_slow()
            scfg = self._solver_for(bucket)
            ccu, ccv = self._core_flags(bucket, cu, cv)
            core_in, lift = self._pre_core(bucket, a, scfg, batched=True)
            st = BatchedSweepStepper(core_in, compute_u=ccu, compute_v=ccv,
                                     config=scfg)
            st.set_control(deadline=deadline, should_cancel=should_cancel)
            # Pin the whole init state (see _solve_base).
            state = self._place(st.init(), lane)
            while st.should_continue(state):
                lane.beat()
                if self.metrics is not None:
                    # One tick per BATCHED sweep (all members advance
                    # together); per-member attribution stays with the
                    # serve records.
                    self.metrics.inc("svdj_sweeps_total",
                                     bucket=bucket.name,
                                     help="solver sweeps executed")
                if slow is not None:
                    time.sleep(slow)
                state = st.step(state)
            # Explicit sigma_refine runs the full batched finish (see
            # `_solve_base`) — sigma members retain finished factors.
            if ((sigma_capture is not None or not (ccu or ccv))
                    and not bool(scfg.sigma_refine)):
                res, payloads = st.sigma_finish(state)
                if sigma_capture is not None:
                    sigma_capture["payloads"] = payloads
                    sigma_capture["lift"] = lift
                return self._post_core(bucket, lift, res, cu, cv,
                                       batched=True)
            return self._post_core(bucket, lift, st.finish(state),
                                   cu, cv, batched=True)
        finally:
            lane.in_step = False
            lane.beat()

    def _slice_member(self, req: Request, r, j: int, cu: bool, cv: bool):
        """Member ``j``'s original-shape factors out of a batched result
        (slice the bucket padding, undo the tall orientation, drop
        factors the member did not ask for or was degraded out of).
        A top-k member additionally truncates to ITS OWN requested rank
        (the batched solve ran at the bucket's rank class)."""
        k = min(req.m, req.n)
        if req.top_k is not None:
            k = min(k, req.top_k)
        want_u = req.compute_u and not req.degraded
        want_v = req.compute_v and not req.degraded
        u = (r.u[j][:req.m, :k]
             if (cu and want_u and r.u is not None) else None)
        s = r.s[j][:k]
        v = (r.v[j][:req.n, :k]
             if (cv and want_v and r.v is not None) else None)
        if req.transposed:
            u, v = v, u
        return u, s, v, int(r.sweeps[j])

    # -- solve paths --------------------------------------------------------

    @staticmethod
    def _place(a, lane: Lane):
        """Pin the padded working set to the lane's device (fleet mode:
        each lane compiles and executes its own per-device executables —
        the per-lane jit cache). No-op for the default single lane."""
        if lane.device is None:
            return a
        import jax
        return jax.device_put(a, lane.device)

    # -- bucket-family staging (full | tall | topk) -------------------------

    @staticmethod
    def _core_flags(bucket, cu: bool, cv: bool):
        """Compute flags for the CORE solve of a bucket family: the
        top-k lane solves B^T, whose left factor is A's RIGHT one and
        vice versa, so the flags swap."""
        return (cv, cu) if bucket.kind == "topk" else (cu, cv)

    def _pre_core(self, bucket, a, scfg, *, batched: bool):
        """Bucket-family pre-stage on the PADDED working set: identity
        for the full family; blocked TSQR for the tall family (the core
        then solves the n x n triangle R only); randomized sketch +
        projection for the top-k family (the core solves the (n, l)
        B^T, l = bucket.k + oversample — BUCKET-static, so the jit key
        is the bucket, never the request's k). Returns
        ``(core_input, lift)`` with ``lift`` None or the context
        `_post_core` needs (range basis + the stage's nonfinite flag).
        All sketch knobs come from the bucket's declaration-time
        resolved config ``scfg``."""
        from .. import solver
        if bucket.kind == "tall":
            fn = (solver._tsqr_batched_jit if batched
                  else solver._tsqr_jit)
            q, r, nf = fn(a, chunk=scfg.tsqr_chunk)
            return r, {"kind": "tall", "q": q, "nf": nf}
        if bucket.kind == "topk":
            l = min(bucket.k + int(scfg.oversample), bucket.n)
            fn = (solver._sketch_project_batched_jit if batched
                  else solver._sketch_project_jit)
            q, bt, nf = fn(a, l=l, power_iters=int(scfg.power_iters),
                           chunk=scfg.tsqr_chunk, seed=0)
            return bt, {"kind": "topk", "q": q, "nf": nf}
        return a, None

    def _post_core(self, bucket, lift, r, cu: bool, cv: bool, *,
                   batched: bool = False):
        """Lift a core result back through the range basis and fold the
        pre-stage health flag into the status word (a poisoned
        sketch/TSQR reads NONFINITE whatever the small solve decoded).
        Top-k results are truncated to the BUCKET's rank class here; the
        request's own k slices further in `_slice`/`_slice_member`. One
        body for both dispatch shapes: ``batched`` selects the vmapped
        lift, and the Ellipsis slices apply to (l,)/(B, l) factors
        alike."""
        from .. import solver
        if lift is None:
            return r
        lift_fn = (solver._lift_q_batched_jit if batched
                   else solver._lift_q_jit)
        status = solver._combine_sketch_status(lift["nf"], r.status)
        if lift["kind"] == "tall":
            u = (lift_fn(lift["q"], r.u)
                 if cu and r.u is not None else None)
            return r._replace(u=u, status=status)
        # topk: the core solved B^T = W S Z^T — its U (W) is A's right
        # factor, its V (Z) lifts to A's left one through Q.
        kb = bucket.k
        u = (lift_fn(lift["q"], r.v[..., :kb])
             if cu and r.v is not None else None)
        v = r.u[..., :kb] if cv and r.u is not None else None
        from ..solver import SVDResult
        return SVDResult(u=u, s=r.s[..., :kb], v=v, sweeps=r.sweeps,
                         off_rel=r.off_rel, status=status)

    def _direct_zero_solve(self, lane: Lane, bucket, cu: bool, cv: bool,
                           batch: Optional[int] = None):
        """One zeros solve of a bucket through the full staging +
        stepper path, pinned to ``lane`` — warmup's direct pre-compile
        lane (a deterministic dispatch that cannot race the admission
        queue or the batching window). Zeros deflate in one sweep, so
        the cost is the compiles."""
        import jax.numpy as jnp

        from ..solver import BatchedSweepStepper, SweepStepper
        scfg = self._solver_for(bucket)
        shape = ((bucket.m, bucket.n) if batch is None
                 else (batch, bucket.m, bucket.n))
        a = self._place(jnp.zeros(shape, jnp.dtype(bucket.dtype)), lane)
        core_in, lift = self._pre_core(bucket, a, scfg,
                                       batched=batch is not None)
        ccu, ccv = self._core_flags(bucket, cu, cv)
        cls = SweepStepper if batch is None else BatchedSweepStepper
        st = cls(core_in, compute_u=ccu, compute_v=ccv, config=scfg)
        state = self._place(st.init(), lane)
        while st.should_continue(state):
            state = st.step(state)
        # Factor-free variants terminate sigma-first, exactly like the
        # live dispatch paths (`_solve_base`) — so the warmup compiles
        # the sigma-extraction jits the brownout/sigma-phase traffic
        # will actually request, not a finish variant it never runs.
        # (With explicit sigma_refine the live paths run the full
        # finish instead — mirror that here or warmup under-compiles.)
        r = (st.sigma_finish(state)[0]
             if not (ccu or ccv) and not bool(scfg.sigma_refine)
             else st.finish(state))
        return self._post_core(bucket, lift, r, cu, cv,
                               batched=batch is not None)

    def _solve_base(self, lane: Lane, req: Request, cu: bool, cv: bool,
                    sigma_capture: Optional[dict] = None):
        """The normal path: pad to the bucket, run the bucket family's
        pre-stage (`_pre_core`: TSQR for tall, sketch+project for topk,
        identity for full), then the host-stepped solver under
        cooperative control — one control check (and one lane heartbeat)
        per sweep — and the family's lift (`_post_core`).

        Sigma-first termination: with ``sigma_capture`` given (a
        sigma-phase request) — or whenever NO factors are wanted (the
        SIGMA_ONLY brownout rung and factor-free submits reuse the sigma
        phase verbatim) — the finish stage's recombination/refinement
        matmuls are SKIPPED: σ is read straight off the converged stacks
        (`SweepStepper.sigma_finish`) and the checkpointed stage lands
        in ``sigma_capture`` for `Ticket.promote` to resume later."""
        import jax.numpy as jnp

        from ..resilience import chaos
        from ..solver import SweepStepper
        # in_step from the very first device op: the bucket PAD is a jit
        # too, and on a cold replica its compile can outlast the idle
        # heartbeat bound — a compiling lane must be judged by the step
        # bound, not evicted as wedged (the supervisor's two-tier rule).
        lane.in_step = True
        try:
            a_pad = self._place(self.buckets.pad(req.a, req.bucket), lane)
            if chaos.consume_poison(lane.index):
                # NaN-poison the working set so the solve surfaces
                # NONFINITE through the production health word
                # (chaos.poison_lane) — on the tall/topk families
                # through the sketch-stage flag.
                a_pad = a_pad.at[0, 0].set(jnp.nan)
            stall = chaos.consume_stuck()
            if stall is not None:
                self._stall(req, stall, lane)
            slow = chaos.consume_slow()
            scfg = self._solver_for(req.bucket)
            ccu, ccv = self._core_flags(req.bucket, cu, cv)
            # The pre-stage runs under in_step too: its first dispatch
            # per (bucket, lane) is a legitimate compile stall.
            core_in, lift = self._pre_core(req.bucket, a_pad, scfg,
                                           batched=False)
            st = SweepStepper(core_in, compute_u=ccu, compute_v=ccv,
                              config=scfg)
            st.set_control(deadline=req.deadline,
                           should_cancel=req.cancel.is_set)
            # The whole init state pinned, not just the input: init
            # creates fresh accumulators (uncommitted, default device),
            # and a committed/uncommitted mix would give the first sweep
            # a different jit cache key than every later one — one
            # silent extra compile per (bucket, lane).
            state = self._place(st.init(), lane)
            while st.should_continue(state):
                lane.beat()
                if self.metrics is not None:
                    # Per-sweep progress off the existing host-stepped
                    # hook: a counter tick + a span point, NO device
                    # readback (syncing state here would serialize the
                    # sweep pipeline on the host link).
                    self.metrics.inc("svdj_sweeps_total",
                                     bucket=req.bucket.name,
                                     help="solver sweeps executed")
                    self._span(req.id, "sweep",
                               stage=st.phase_info(state).stage)
                if slow is not None:
                    time.sleep(slow)
                state = st.step(state)
            self._record_convergence(req.bucket.name, st)
            # Explicit SVDConfig(sigma_refine=True) runs the FULL finish
            # even for sigma/factor-free termination: the compensated
            # refinement needs the recombined factors, and sigma-first
            # would silently serve unrefined σ the operator asked to
            # refine. Sigma-phase requests then retain the finished
            # factors (kind="result") instead of a deferred stage.
            if ((sigma_capture is not None or not (ccu or ccv))
                    and not bool(scfg.sigma_refine)):
                res, payload = st.sigma_finish(state)
                if sigma_capture is not None:
                    sigma_capture["payload"] = payload
                    sigma_capture["lift"] = lift
                return self._post_core(req.bucket, lift, res, cu, cv)
            return self._post_core(req.bucket, lift, st.finish(state),
                                   cu, cv)
        finally:
            lane.in_step = False
            lane.beat()

    def _solve_ladder(self, lane: Lane, req: Request, cu: bool, cv: bool):
        """The OPEN-breaker path: route through the escalation ladder.
        The ladder runs the FUSED entry points, so the deadline cannot be
        checked mid-solve — acceptable for the recovery path (bounded by
        the ladder's own attempt cap), and the manifest records it as
        path="ladder". Tall/top-k bucket requests run the FULL padded
        solve here (the ladder is a correctness-first recovery path; a
        top-k request's truncation happens in `_slice`, which is exact —
        more accurate than the sketch, just slower). ``ladder_watchdog_s`` arms the wall-clock overrun
        watchdog: it cannot abort the fused solve, but it records a
        `ladder_overrun` fleet event and flags THIS lane unhealthy, so
        the supervisor evicts it and rescues its queued requests instead
        of the whole fleet blocking behind an unbounded ladder."""
        import jax.numpy as jnp

        from ..resilience import chaos, resilient_svd
        on_overrun = None
        if self.fleet.size > 1:
            on_overrun = (lambda info:
                          self.fleet.flag_unhealthy(lane, "ladder_overrun"))
        lane.in_step = True     # the fused ladder blocks for whole solves
        try:
            a_pad = self._place(self.buckets.pad(req.a, req.bucket), lane)
            if chaos.consume_poison(lane.index):
                a_pad = jnp.asarray(a_pad).at[0, 0].set(jnp.nan)
            return resilient_svd(a_pad, compute_u=cu, compute_v=cv,
                                 config=self._solver_for(req.bucket),
                                 manifest_path=self.config.manifest_path,
                                 watchdog_s=self.config.ladder_watchdog_s,
                                 on_overrun=on_overrun)
        finally:
            lane.in_step = False
            lane.beat()

    @staticmethod
    def _stall(req: Request, stall_s: float,
               lane: Optional[Lane] = None) -> None:
        """chaos.stuck_backend: block cooperatively (polling the request's
        deadline/cancel control) for at most ``stall_s``; the stepper's
        own control check then turns an expired deadline into DEADLINE.
        The lane heartbeat keeps beating — a stuck BACKEND is the circuit
        breaker's fault class; a stuck LANE (no heartbeat) is
        `chaos.wedge_lane` and the supervisor's."""
        t_end = time.monotonic() + stall_s
        while time.monotonic() < t_end:
            if lane is not None:
                lane.beat()
            if req.cancel.is_set():
                return
            if req.deadline is not None and time.monotonic() >= req.deadline:
                return
            time.sleep(0.002)

    def _slice(self, req: Request, r, cu: bool, cv: bool):
        """Recover the original-shape factors from the bucket-padded solve
        (exact — see buckets module docstring) and undo the tall
        orientation. A top-k request truncates to its requested rank
        (the solve ran at the bucket's rank class — or at full rank on
        the ladder recovery path, where truncation is equally exact)."""
        k = min(req.m, req.n)
        if req.top_k is not None:
            k = min(k, req.top_k)
        u = r.u[:req.m, :k] if (cu and r.u is not None) else None
        s = r.s[:k]
        v = r.v[:req.n, :k] if (cv and r.v is not None) else None
        if req.transposed:
            u, v = v, u
        return u, s, v, int(r.sweeps)

    # -- two-phase promotion (serve.cache.PromotionStore) -------------------

    @staticmethod
    def _member_lift(lift: Optional[dict], j: int) -> Optional[dict]:
        """Member ``j``'s slice of a batched pre-stage lift context (the
        range basis Q and the stage health flag are member-major)."""
        if lift is None:
            return None
        return {"kind": lift["kind"], "q": lift["q"][j],
                "nf": lift["nf"][j]}

    def _retain_promotion(self, req: Request, lane: Lane, *,
                          payload: Optional[dict], lift: Optional[dict],
                          factors: tuple, status, sweeps: int) -> None:
        """Retain one OK sigma-phase solve for `Ticket.promote`: the
        deferred-finish payload when the dispatch terminated sigma-first
        (kind="state"), else — fused ladder path, mixed coalesced batch
        — the already-sliced factors (kind="result"). A solve that
        accumulated no rotation product (flags off) retains nothing:
        there is nothing to resume, and promote says so loudly."""
        from .cache import PromotionState
        common = dict(bucket=req.bucket, m=req.m, n=req.n,
                      transposed=req.transposed, compute_u=req.compute_u,
                      compute_v=req.compute_v, top_k=req.top_k,
                      digest=req.digest, lane=lane.index,
                      tenant=getattr(req, "tenant", DEFAULT_TENANT))
        if payload is not None and payload.get("promotable"):
            ps = PromotionState(
                kind="state", path=payload["path"], top=payload["top"],
                bot=payload["bot"], vtop=payload["vtop"],
                vbot=payload["vbot"], work=payload["work"],
                q1=payload["q1"], order=payload["order"],
                core_n=payload["n"], precondition=payload["precondition"],
                refine=payload["refine"], core_u=payload["compute_u"],
                core_v=payload["compute_v"], lift=lift,
                off_rel=payload["off_rel"], sweeps=payload["sweeps"],
                status=payload["status"], **common)
        else:
            u, s, v = factors
            if u is None and v is None:
                return    # nothing a promote could add (flags off)
            ps = PromotionState(kind="result", u=u, s=s, v=v,
                                status=int(status), sweeps=int(sweeps),
                                **common)
        evicted = self.promotions.put(req.id, ps)
        if req.id not in evicted:
            self._bump("promotion_retained")
            self._record_cache("promotion", "retain", request_id=req.id,
                               nbytes=ps.nbytes, lane=lane.index)
        for rid in evicted:
            self._bump("promotion_evicted")
            self._record_cache("promotion", "evict", request_id=rid)

    def _promote(self, ticket: Ticket, sigma: ServeResult) -> ServeResult:
        """Resume a retained sigma-phase solve to full U/Σ/V (the
        `Ticket.promote` body): pop the state exactly-once, run the SAME
        already-compiled finish jits on the checkpointed stage (or
        return the already-finished factors, kind="result"), lift
        through the bucket family's pre-stage context, slice to the
        request — never a sweep, never a fresh solve. Appends a "cache"
        promote event plus an ordinary "serve" record whose ``phase`` is
        "promote" and whose ``promoted_from`` names the sigma request it
        resumed."""
        from .cache import PromotionError
        from ..solver import SolveStatus
        rid = ticket.request_id
        if ticket.phase != "sigma":
            raise PromotionError(
                f"request {rid!r} was not submitted with phase='sigma' "
                f"(nothing was retained to resume)")
        if sigma.status is not SolveStatus.OK or sigma.error is not None:
            # take() below would also miss (non-OK solves retain
            # nothing); say why instead of a generic "no state".
            raise PromotionError(
                f"sigma-phase request {rid!r} did not solve OK "
                f"(status={getattr(sigma.status, 'name', None)}, "
                f"error={sigma.error!r}); promote has nothing to resume "
                f"— fall back to a full re-submit")
        ps = self.promotions.take(rid)   # raises PromotionError if gone
        t0 = time.perf_counter()
        if ps.kind == "result":
            u, s, v = ps.u, ps.s, ps.v
            status = SolveStatus(int(ps.status))
            sweeps = int(ps.sweeps)
        else:
            from .. import solver
            r = solver.finish_from_payload(dict(
                path=ps.path, top=ps.top, bot=ps.bot, vtop=ps.vtop,
                vbot=ps.vbot, work=ps.work, q1=ps.q1, order=ps.order,
                n=ps.core_n, compute_u=ps.core_u, compute_v=ps.core_v,
                full_u=False, precondition=ps.precondition,
                refine=ps.refine, v0=None, status=ps.status,
                sweeps=ps.sweeps, off_rel=ps.off_rel))
            r = self._post_core(ps.bucket, ps.lift, r,
                                ps.compute_u, ps.compute_v)
            u, s, v, sweeps = self._slice_ps(ps, r)
            status = r.status_enum()
        solve_time = time.perf_counter() - t0
        pid = f"{rid}+p"
        result = ServeResult(
            u=u, s=s, v=v, status=status, error=None, sweeps=sweeps,
            bucket=ps.bucket.name, queue_wait_s=0.0,
            solve_time_s=solve_time, path="base", degraded=False,
            request_id=pid)
        self._record_cache("promotion", "promote", request_id=rid)
        # A promoted result IS a clean full solve of these bytes — store
        # it so a byte-identical full resubmit after a σ→promote flow
        # hits instead of re-solving (same admission guard as
        # `_maybe_cache_result`: clean OK full factors only).
        if (ps.digest is not None and status is SolveStatus.OK
                and s is not None and self.result_cache.max_bytes > 0):
            self._cache_store(request_id=pid, digest=ps.digest,
                              bucket=ps.bucket, m=ps.m, n=ps.n,
                              transposed=ps.transposed,
                              compute_u=ps.compute_u,
                              compute_v=ps.compute_v, top_k=ps.top_k,
                              u=u, s=s, v=v, status=int(status),
                              sweeps=sweeps,
                              tenant=getattr(ps, "tenant", DEFAULT_TENANT))
        self._bump("served", "promotions", f"status:{status.name}")
        if self.metrics is not None:
            self.metrics.inc("svdj_promotions_total", status=status.name,
                             kind=ps.kind,
                             help="sigma-phase promotions resumed")
            self.metrics.observe("svdj_promote_seconds", solve_time,
                                 bucket=ps.bucket.name,
                                 help="promote (finish-resume) latency")
            self._span(rid, "promote", kind=ps.kind, status=status.name)
        orig_shape = ((ps.n, ps.m) if ps.transposed else (ps.m, ps.n))
        self._record(request_id=pid, orig_shape=orig_shape,
                     dtype=ps.bucket.dtype, bucket=ps.bucket.name,
                     queue_wait_s=0.0, solve_time_s=solve_time,
                     status=status.name, path="base",
                     breaker=self.breaker.state().value, brownout="FULL",
                     degraded=False, deadline_s=None, sweeps=sweeps,
                     rank_mode=ps.bucket.kind, k=ps.top_k,
                     phase="promote", promoted_from=rid,
                     tenant=getattr(ps, "tenant", DEFAULT_TENANT))
        return result

    @staticmethod
    def _slice_ps(ps, r):
        """`_slice` over a PromotionState's retained request identity
        (the Request object is long gone by promote time)."""
        k = min(ps.m, ps.n)
        if ps.top_k is not None:
            k = min(k, ps.top_k)
        u = (r.u[:ps.m, :k]
             if (ps.compute_u and r.u is not None) else None)
        s = r.s[:k]
        v = (r.v[:ps.n, :k]
             if (ps.compute_v and r.v is not None) else None)
        if ps.transposed:
            u, v = v, u
        return u, s, v, int(r.sweeps)

    def _release_promotion(self, request_id: str) -> bool:
        ok = self.promotions.release(request_id)
        if ok:
            self._bump("promotion_released")
            self._record_cache("promotion", "release",
                               request_id=request_id)
        return ok

    # -- bookkeeping --------------------------------------------------------

    def _control_result(self, req: Request, status_name: str,
                        queue_wait: float,
                        path: str = "base") -> ServeResult:
        from ..solver import SolveStatus
        return ServeResult(
            u=None, s=None, v=None, status=SolveStatus[status_name],
            error=None, sweeps=0, bucket=req.bucket.name,
            queue_wait_s=queue_wait, solve_time_s=None, path=path,
            degraded=req.degraded, request_id=req.id)

    def _error_result(self, req: Request, error: str, queue_wait: float,
                      path: str, solve_time_s: Optional[float] = None
                      ) -> ServeResult:
        return ServeResult(
            u=None, s=None, v=None, status=None, error=error, sweeps=0,
            bucket=req.bucket.name, queue_wait_s=queue_wait,
            solve_time_s=solve_time_s, path=path, degraded=req.degraded,
            request_id=req.id)

    def _finalize(self, req: Request, *, status_name: str,
                  result: ServeResult, queue_wait: float,
                  solve_time: Optional[float], path: str,
                  breaker_state: BreakerState,
                  batch_id: Optional[str] = None,
                  batch_size: Optional[int] = None,
                  batch_tier: Optional[int] = None,
                  lane: Optional[int] = None) -> bool:
        """Install the terminal result and its bookkeeping EXACTLY once.

        Returns False (and does nothing — no stats bump, no manifest
        record) when the ticket was already finalized: in fleet mode a
        request can legitimately be finalized twice-over — once by the
        rescue path, once by a sick worker that eventually woke up — and
        only the first writer may count."""
        # Cache BEFORE the exactly-once install: the client unblocks the
        # moment the ticket flips, and a resubmit racing in must find
        # the entry already stored. Storing on the losing side of a
        # rescue race is harmless — the guard admits only clean
        # base/ladder OK results, which are correct for these bytes no
        # matter which finalizer won the ticket.
        self._maybe_cache_result(req, result, status_name, path)
        if not req.ticket._finalize_once(result):
            return False
        self._journal_finalize(req.id, status_name)
        tenant = getattr(req, "tenant", DEFAULT_TENANT)
        if self.metrics is not None:
            self.metrics.inc("svdj_requests_finalized_total",
                             status=status_name, path=path,
                             phase=req.phase, tenant=tenant,
                             help="requests reaching a terminal status")
            if solve_time is not None:
                self.metrics.observe("svdj_solve_seconds", solve_time,
                                     bucket=req.bucket.name, tenant=tenant,
                                     help="dispatch-to-finish solve time")
                self._span(req.id, "finish", status=status_name)
            latency = queue_wait + (solve_time or 0.0)
            self.metrics.observe("svdj_request_latency_seconds", latency,
                                 bucket=req.bucket.name, tenant=tenant,
                                 help="end-to-end request latency")
            if status_name == "DEADLINE":
                self.metrics.inc("svdj_deadline_miss_total",
                                 bucket=req.bucket.name, tenant=tenant,
                                 help="requests finalized DEADLINE")
            self._span(req.id, "finalize", status=status_name, path=path)
            self.slo.observe(req.bucket.name, latency,
                             ok=(status_name == "OK"),
                             deadline_miss=(status_name == "DEADLINE"),
                             error=(status_name == "ERROR"))
            self._tenant_slo_for(tenant).observe(
                req.bucket.name, latency, ok=(status_name == "OK"),
                deadline_miss=(status_name == "DEADLINE"),
                error=(status_name == "ERROR"))
        self._bump("served", f"status:{status_name}",
                   *(["path:ladder"] if path == "ladder" else []),
                   *(["degraded"] if req.degraded else []),
                   *([f"phase:{req.phase}"] if req.phase != "full"
                     else []),
                   *([f"rank_mode:{req.rank_mode}"]
                     if req.rank_mode != "full" else []))
        self._bump_tenant(tenant, "served", f"status:{status_name}",
                          *(["degraded"] if req.degraded else []))
        # A router-rescued request's record path carries its provenance
        # ("replica_rescue") instead of the generic "base" — the ladder
        # and control paths stay visible as themselves.
        record_path = (req.via if (req.via is not None and path == "base")
                       else path)
        self._record(
            request_id=req.id, orig_shape=req.orig_shape,
            dtype=req.bucket.dtype, bucket=req.bucket.name,
            queue_wait_s=queue_wait, solve_time_s=solve_time,
            status=status_name, path=record_path,
            breaker=breaker_state.value,
            brownout=req.brownout,
            degraded=req.degraded, deadline_s=req.deadline_s,
            sweeps=result.sweeps, error=result.error,
            batch_id=batch_id, batch_size=batch_size,
            batch_tier=batch_tier, lane=lane,
            rank_mode=req.rank_mode, k=req.top_k, phase=req.phase,
            digest=req.digest, tenant=tenant)
        return True

    def _finalize_rescue(self, req: Request, status_name: str,
                         error: Optional[str] = None,
                         lane: Optional[Lane] = None) -> bool:
        """Terminalize a request on the RESCUE path (no solve spent):
        CANCELLED / DEADLINE for requests whose control already fired,
        ERROR when there is no healthy lane left — all loud, recorded
        with path="rescue" and attributed to the EVICTED lane (whose
        failure produced this terminal), so the manifest stream
        distinguishes a rescue-finalized request from a served one and
        still reconstructs which lane failed it."""
        wait = time.monotonic() - req.submitted
        if error is not None:
            result = self._error_result(req, error, wait, "rescue")
        else:
            result = self._control_result(req, status_name, wait,
                                          path="rescue")
        breaker = (lane.breaker if lane is not None else self.breaker)
        return self._finalize(
            req, status_name=status_name if error is None else "ERROR",
            result=result, queue_wait=wait, solve_time=None,
            path="rescue", breaker_state=breaker.state(),
            lane=None if lane is None else lane.index)

    def _journal_dispatch(self, reqs, lane: Lane,
                          batch_id: Optional[str] = None) -> None:
        """Best-effort dispatch journaling: a journal I/O failure here
        must not kill the worker (the admit record — the durability
        promise — is already on disk; the dispatch record is recovery
        diagnostics)."""
        if self.journal is None:
            return
        try:
            for r in reqs:
                self._observe_journal_append(self.journal.append_dispatch(
                    r.id, lane=lane.index, batch_id=batch_id))
        except Exception as e:
            self._bump("journal_errors")
            print(f"svdj-serve: journal dispatch append failed: {e}",
                  file=sys.stderr)

    def _journal_finalize(self, request_id: str, status: str) -> None:
        """Best-effort finalize journaling (see `_journal_dispatch`): a
        lost finalize record means one extra replay next restart, which
        exactly-once finalization absorbs — a crashed worker would be
        strictly worse."""
        if self.journal is None:
            return
        # Fence gate (the STALE-FINALIZATION refusal of the rescue
        # discipline): if a rescuer bumped this journal's fencing token
        # since boot, another host has scanned + compacted this journal
        # and re-homed its debt — a late finalize from a zombie worker
        # here would be a DUPLICATE in the federation's exactly-once
        # accounting. Refuse loudly: audit record instead of finalize
        # (scan ignores audit kinds, so the tombstone story is intact).
        from .journal import read_fence_token
        try:
            disk_token = read_fence_token(self.config.journal_path)
        except Exception:
            disk_token = 0
        if disk_token > self._own_fence_token:
            self._bump("stale_finalize_refused")
            try:
                self.journal.append_audit(
                    "stale_finalize_refused", id=request_id,
                    status=status, token=disk_token,
                    held_token=self._own_fence_token)
            except Exception:
                pass
            return
        try:
            self._observe_journal_append(
                self.journal.append_finalize(request_id, status))
        except Exception as e:
            self._bump("journal_errors")
            print(f"svdj-serve: journal finalize append failed: {e}",
                  file=sys.stderr)

    def _bump(self, *keys: str) -> None:
        with self._lock:
            for k in keys:
                self._stats[k] = self._stats.get(k, 0) + 1

    def _bump_tenant(self, tenant: str, *keys: str) -> None:
        """Per-tenant counters, mirroring `_bump`'s aggregate ones.
        Always live (like `_stats`) — they feed `healthz()["tenants"]`
        and the fairness drills even with the flight recorder off."""
        with self._lock:
            stats = self._tenant_stats.setdefault(str(tenant), {})
            for k in keys:
                stats[k] = stats.get(k, 0) + 1

    def _tenant_slo_for(self, tenant: str):
        """The lazily-minted per-tenant SLOTracker (metrics-on only,
        mirroring `self.slo`; a no-op stub when the flight recorder is
        off so call sites never branch). Lazy because the tenant set is
        open — undeclared tenants get the default policy AND their own
        error budget."""
        if self.metrics is None:
            return _NULL_SLO
        tenant = str(tenant)
        with self._lock:
            tracker = self.tenant_slo.get(tenant)
            if tracker is None:
                from .. import obs
                tracker = obs.registry.SLOTracker(
                    objective=self.config.slo_objective)
                self.tenant_slo[tenant] = tracker
            return tracker

    def _record(self, *, request_id: str, orig_shape: Tuple[int, int],
                dtype: str, bucket: Optional[str], queue_wait_s: float,
                solve_time_s: Optional[float], status: str, path: str,
                breaker: str, brownout: str, degraded: bool,
                deadline_s: Optional[float], error: Optional[str] = None,
                sweeps: Optional[int] = None,
                batch_id: Optional[str] = None,
                batch_size: Optional[int] = None,
                batch_tier: Optional[int] = None,
                lane: Optional[int] = None,
                rank_mode: str = "full",
                k: Optional[int] = None,
                phase: str = "full",
                promoted_from: Optional[str] = None,
                digest: Optional[str] = None,
                tenant: str = DEFAULT_TENANT) -> None:
        from .. import obs
        record = obs.manifest.build_serve(
            request_id=request_id, m=orig_shape[0], n=orig_shape[1],
            dtype=dtype, bucket=bucket, queue_wait_s=float(queue_wait_s),
            solve_time_s=(None if solve_time_s is None
                          else float(solve_time_s)),
            status=status, path=path, breaker=breaker, brownout=brownout,
            degraded=bool(degraded),
            deadline_s=(None if deadline_s is None else float(deadline_s)),
            sweeps=sweeps, error=error, batch_id=batch_id,
            batch_size=batch_size, batch_tier=batch_tier,
            lane=(None if lane is None else int(lane)),
            rank_mode=str(rank_mode), k=(None if k is None else int(k)),
            phase=str(phase), promoted_from=promoted_from,
            digest=(None if digest is None else str(digest)),
            tenant=str(tenant))
        self._store(record)

    def _record_cache(self, store: str, event: str, *,
                      request_id: Optional[str] = None,
                      digest: Optional[str] = None,
                      nbytes: Optional[int] = None, **extra) -> None:
        """Append one schema-versioned "cache" record (result-cache
        store/hit/evict/invalidate, promotion retain/promote/release/
        evict/rescue) to the same stream as the "serve" records."""
        from .. import obs
        if self.metrics is not None:
            self.metrics.inc("svdj_cache_events_total", store=store,
                             event=event,
                             help="result-cache / promotion-store events")
            if request_id is not None and event == "retain":
                # "promote" gets its richer span from `_promote` itself.
                self._span(request_id, "retain", store=store)
        self._store(obs.manifest.build_cache(
            store=store, event=event, request_id=request_id,
            digest=digest, nbytes=nbytes, **extra))

    def _record_fleet(self, *, event: str, lane: Optional[int] = None,
                      **extra) -> None:
        """Append one schema-versioned "fleet" record (lane transitions,
        rescues, steals, probes, healthz snapshots) to the same stream
        as the per-request "serve" records. With the flight recorder on,
        the same event feeds the live fleet counters — same series names
        as `obs.registry.registry_from_manifest` derives offline, so a
        live scrape and a manifest reconstruction are directly
        comparable (the chaos-soak test asserts they agree)."""
        from .. import obs
        if self.metrics is not None:
            li = "" if lane is None else str(lane)
            if event == "lane_transition":
                self.metrics.inc("svdj_lane_transitions_total", lane=li,
                                 to_state=str(extra.get("to_state", "?")),
                                 help="lane state transitions")
            elif event == "steal":
                self.metrics.inc("svdj_steals_total", lane=li,
                                 help="requests stolen by an idle lane")
            elif event == "rescue":
                self.metrics.inc("svdj_rescued_total",
                                 float(extra.get("count", 0) or 0),
                                 lane=li,
                                 help="requests rescued off an evicted "
                                      "lane")
            elif event == "probe":
                self.metrics.inc("svdj_probes_total",
                                 ok=str(bool(extra.get("ok"))).lower(),
                                 lane=li,
                                 help="quarantined-lane recovery probes")
        self._store(obs.manifest.build_fleet(event=event, lane=lane,
                                             **extra))

    def _store(self, record: dict) -> None:
        with self._lock:
            # max_records <= 0 means "manifest only, keep none in memory"
            # (the naive del lst[:-0] would silently invert the cap into
            # unbounded growth).
            if self.config.max_records > 0:
                self._records.append(record)
                del self._records[:-self.config.max_records]
        if self.config.manifest_path is not None:
            try:
                from .. import obs
                obs.manifest.append(self.config.manifest_path, record)
            except Exception as e:  # manifest I/O must not kill the worker
                self._bump("manifest_errors")
                print(f"svdj-serve: manifest append failed: {e}",
                      file=sys.stderr)
