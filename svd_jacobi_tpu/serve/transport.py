"""Multi-host HTTP replica transport: the federation over an UNRELIABLE
network.

`serve.router` federates replicas over two transports that both assume
a reliable substrate: `LocalReplica` (shared memory) and `SpoolReplica`
(a local filesystem rename is atomic and never times out). This module
adds the third shape — `HttpReplica` — where every hop can be dropped,
delayed, duplicated, or blackholed, and "the replica is dead" is
indistinguishable from "the network is partitioned". The discipline:

  * **Versioned wire protocol** (``WIRE_VERSION``): versioned JSON over
    stdlib HTTP, mapping 1:1 onto the Ticket lifecycle — ``/v1/submit``
    ``/v1/status`` ``/v1/result`` ``/v1/promote`` ``/v1/cancel``
    ``/v1/debt`` ``/v1/fence`` ``/v1/lease`` ``/v1/stop`` ``/healthz``.
    Every endpoint answers HTTP 200 with ``{"ok": bool, ...}`` so an
    HTTP-level error always means TRANSPORT failure, never an
    application verdict — retries stay safe.
  * **Deadline-budget decay**: the remaining wall-clock budget of the
    REQUEST (``t_wall + deadline_s - now``), not a fresh per-hop clock,
    bounds every RPC attempt and every backoff sleep across hops.
  * **Bounded retries with decorrelated jitter**: `launch
    ._backoff_delay` (the parallel launcher's tested backoff), capped
    by the remaining budget.
  * **Idempotency keys**: the request id + oriented-input digest ride
    every submit; the receiver dedupes against its own write-ahead
    journal and live bookkeeping, so a retry after a lost ACK is
    exactly-once (the duplicate gets ``{"ok": true, "dup": true}``).
  * **Leases, not pings**: a successful healthz renews a client-side
    lease (``lease_ttl_s``); an unexpired lease is a liveness promise
    (`fleet.heartbeat_stale` consumes it), an expired one means
    "partitioned OR dead" — the router may not know which, and does
    not need to: the fencing token makes acting on it safe.
  * **Fencing tokens** (`journal.bump_fence_token`): the rescuer bumps
    the dead fault domain's monotonic token BEFORE breaking the journal
    lock; `SVDService.admit_journal_debt` refuses stale tokens loudly
    (`StaleFenceError` + a ``fence_refused`` audit record), and a
    partitioned-but-alive replica self-fences the moment it observes a
    newer token on disk (`HttpReplicaServer._check_fence`) — it can
    come back, but it cannot double-serve debt that was rescued away.
  * **Half-open connection quarantine**: ``quarantine_threshold``
    consecutive transport errors open the client breaker (submits fail
    with ZERO network I/O -> instant ring failover); after a cooldown
    one probe flows half-open, and a success closes it (``heal``).
  * **Partition-healed reconciliation**: the first successful healthz
    after a lease lapse emits ``partition_heal`` and re-grants the
    lease via a formal ``/v1/lease`` RPC; a replica that was rescued
    meanwhile reports ``fenced`` instead and stays dead until respawn.

Every network event appends an offline-reconstructable ``"net"``
manifest record (`obs.manifest.build_net` -> ``svdj_rpc_*`` metric
families via `obs.registry.registry_from_manifest`).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from ..parallel import launch as _launch
from .journal import (Journal, StaleFenceError, bump_fence_token,
                      decode_array, host_boot_id, host_identity,
                      read_fence_token)
from .queue import AdmissionError, AdmissionReason
from .router import (ReplicaHandle, ReplicaUnavailable, _decode_result,
                     _encode_result, _trim_healthz, _write_json_atomic)
from .service import SVDService

WIRE_VERSION = 1

# Results kept addressable after finalization (a consumed-but-unforgotten
# window; the client `cleanup()` forgets eagerly, this bound is the leak
# backstop for clients that never do).
_RESULT_WINDOW = 512


class TransportError(RuntimeError):
    """An RPC failed at the TRANSPORT level after its retry budget
    (connect refused / reset / timed out / torn response) — the
    application verdict is unknown, which is exactly why every write
    carries an idempotency key."""


# -- wire helpers --------------------------------------------------------------


def _http_json(url: str, *, method: str = "GET",
               body: Optional[dict] = None,
               timeout: float = 1.0) -> dict:
    """One JSON-over-HTTP exchange. Raises OSError/URLError flavors on
    transport failure; a non-JSON or non-dict body is a transport
    failure too (a proxy tore the response)."""
    data = None
    headers = {"Accept": "application/json"}
    if body is not None:
        data = json.dumps(body).encode()
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=headers,
                                 method=method)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        payload = json.loads(resp.read().decode())
    if not isinstance(payload, dict):
        raise TransportError(f"torn response from {url}: "
                             f"{type(payload).__name__}")
    return payload


# -- the server side (one replica process / thread) ----------------------------


class _Handler(BaseHTTPRequestHandler):
    """Stdlib request handler dispatching into the owning
    `HttpReplicaServer` (``self.server.owner``). Always 200 + JSON."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):     # noqa: N802 (stdlib name)
        pass    # chaos drills flood connections; stderr stays quiet

    def _reply(self, payload: dict) -> None:
        data = json.dumps(payload).encode()
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass    # the client (or the fault proxy) hung up mid-reply

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        try:
            rec = json.loads(raw.decode()) if raw else {}
        except (json.JSONDecodeError, UnicodeDecodeError):
            return {}
        return rec if isinstance(rec, dict) else {}

    def do_GET(self):      # noqa: N802
        owner = self.server.owner
        parsed = urllib.parse.urlsplit(self.path)
        q = urllib.parse.parse_qs(parsed.query)
        rid = (q.get("id") or [None])[0]
        try:
            if parsed.path == "/healthz":
                self._reply(owner.handle_healthz())
            elif parsed.path == "/v1/status":
                self._reply(owner.handle_status(rid))
            elif parsed.path == "/v1/result":
                self._reply(owner.handle_result(rid))
            else:
                self._reply({"ok": False,
                             "error": f"unknown path {parsed.path}"})
        except Exception as e:
            self._reply({"ok": False,
                         "error": f"{type(e).__name__}: {e}"})

    def do_POST(self):     # noqa: N802
        owner = self.server.owner
        path = urllib.parse.urlsplit(self.path).path
        body = self._body()
        try:
            if path == "/v1/submit":
                self._reply(owner.handle_submit(body))
            elif path == "/v1/debt":
                self._reply(owner.handle_debt(body))
            elif path == "/v1/promote":
                self._reply(owner.handle_promote(body))
            elif path == "/v1/cancel":
                self._reply(owner.handle_cancel(body))
            elif path == "/v1/forget":
                self._reply(owner.handle_forget(body))
            elif path == "/v1/fence":
                self._reply(owner.handle_fence(body))
            elif path == "/v1/lease":
                self._reply(owner.handle_lease(body))
            elif path == "/v1/stop":
                self._reply(owner.handle_stop())
            else:
                self._reply({"ok": False,
                             "error": f"unknown path {path}"})
        except StaleFenceError as e:
            self._reply({"ok": False, "stale_fence": True,
                         "error": str(e)})
        except AdmissionError as e:
            self._reply({"ok": False, "rejected": e.reason.name,
                         "error": e.detail})
        except Exception as e:
            self._reply({"ok": False,
                         "error": f"{type(e).__name__}: {e}"})


class _Listener(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    owner: "HttpReplicaServer" = None

    def handle_error(self, request, client_address):
        pass    # connection chaos is the POINT of the net drills


class HttpReplicaServer:
    """One replica fault domain behind the versioned HTTP wire protocol
    — `run_spool_replica`'s counterpart for a network transport. Boot
    replays the journal (a restarted replica recovers its OWN remaining
    debt before taking new work), then every endpoint maps onto the
    Ticket lifecycle. Run it in-process (tests, the two-"host" drill:
    `start()` / `stop()` / `simulate_kill()`) or as a process main via
    `run_http_replica`.

    Lock discipline (graftlock CONC001): ``self._lock`` guards ONLY the
    bookkeeping dicts (outstanding / results / reservation); it is never
    held across a service call, a ticket wait, journal I/O, or a
    response write."""

    def __init__(self, config, *, host: str = "127.0.0.1", port: int = 0,
                 warmup: bool = False, subprocess_mode: bool = False):
        if config.journal_path is None:
            raise ValueError("an HTTP replica needs its own journal_path "
                             "(the fencing contract lives there)")
        self.config = config
        self.host = str(host)
        self.port = int(port)
        self.warmup = bool(warmup)
        self.subprocess_mode = bool(subprocess_mode)
        self.boot_wall = time.time()
        self.svc: Optional[SVDService] = None
        self.coldstart: Optional[dict] = None
        self._lock = threading.Lock()
        self._outstanding: Dict[str, Any] = {}      # rid -> live Ticket
        self._done_tickets: "OrderedDict[str, Any]" = OrderedDict()
        self._results: "OrderedDict[str, dict]" = OrderedDict()
        self._transpose: Dict[str, bool] = {}
        self._reserved: set = set()     # rids mid-admission (dup race)
        self._journal_seen: set = set()
        self._finalized_prev: Dict[str, str] = {}
        self._fenced = False
        self._stop_requested = False
        # Fence token this boot acknowledged: a HIGHER token on disk
        # means a rescuer claimed this domain's debt while we were
        # partitioned — self-fence, never double-serve.
        self._fence_ack = 0
        self._fence_checked = 0.0
        self._httpd: Optional[_Listener] = None
        self._http_thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "HttpReplicaServer":
        cfg = self.config
        if Path(cfg.journal_path).exists():
            st0 = Journal(cfg.journal_path).scan(quarantine=False)
            self._journal_seen = set(st0.admits) | set(st0.finalized)
            self._finalized_prev = dict(st0.finalized)
        self.svc = SVDService(cfg)
        self._fence_ack = read_fence_token(cfg.journal_path)
        if self._journal_seen:
            self._outstanding.update(self.svc.recover())
        self.svc.start()
        if self.warmup:
            self.svc.warmup(timeout=600.0)
            cold = [r for r in self.svc.records()
                    if r.get("kind") == "coldstart"]
            if cold:
                self.coldstart = {
                    "fresh_compiles": cold[-1]["fresh_compiles"],
                    "cache_hits": cold[-1]["cache_hits"],
                    "backend_compiles": cold[-1]["backend_compiles"],
                    "total_s": cold[-1]["total_s"]}
        self._httpd = _Listener((self.host, self.port), _Handler)
        self._httpd.owner = self
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="svdj-http-replica", daemon=True)
        self._http_thread.start()
        return self

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def stop(self, drain: bool = True,
             timeout: Optional[float] = 30.0) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self.svc is not None and not self._fenced:
            try:
                self.svc.stop(drain=drain, timeout=timeout)
            except Exception:
                pass

    def simulate_kill(self) -> None:
        """The in-process SIGKILL twin for the two-"host" drill: the
        service dies mid-work (queued requests stay as journal debt,
        the journal lock stays held) AND the listener goes away — every
        subsequent RPC is a connection error, exactly like a dead
        host."""
        self._fenced = True
        if self.svc is not None:
            self.svc._chaos_kill()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    # -- fencing ------------------------------------------------------------

    def _check_fence(self) -> bool:
        """Self-fence when the DISK token outran the acknowledged one: a
        rescuer claimed this fault domain's debt while this process was
        partitioned away. Rate-limited (a disk stat per RPC would be
        silly); the subprocess run loop also calls it so a fully
        HTTP-partitioned replica still notices via the shared
        filesystem."""
        if self._fenced:
            return True
        now = time.monotonic()
        if now - self._fence_checked < 0.05:
            return self._fenced
        self._fence_checked = now
        disk = read_fence_token(self.config.journal_path)
        if disk > self._fence_ack:
            try:
                if self.svc is not None and self.svc.journal is not None:
                    self.svc.journal.append_audit(
                        "self_fence", token=disk,
                        held_token=self._fence_ack)
            except Exception:
                pass
            self._fence_now()
        return self._fenced

    def _fence_now(self) -> None:
        """STONITH on the serving side: stop finalizing ANYTHING. The
        workers exit without serving (`_chaos_kill` — queued work stays
        as journal debt for the rescuer); the listener stays up so
        healthz can answer ``fenced: true`` (the router's reconciliation
        reads it), but submit/debt refuse."""
        if self._fenced:
            return
        self._fenced = True
        if self.svc is not None:
            try:
                self.svc._chaos_kill()
            except Exception:
                pass

    # -- bookkeeping --------------------------------------------------------

    def _collect(self) -> None:
        """Move finalized tickets into the bounded result window.
        Encoding happens OUTSIDE the lock (factors can be megabytes)."""
        with self._lock:
            done = [(rid, t) for rid, t in self._outstanding.items()
                    if t.done()]
        for rid, t in done:
            res = t.result(0)
            enc = _encode_result(res)
            enc["transposed"] = self._transpose.get(rid, False)
            with self._lock:
                self._outstanding.pop(rid, None)
                self._results[rid] = enc
                self._done_tickets[rid] = t
                while len(self._results) > _RESULT_WINDOW:
                    old, _ = self._results.popitem(last=False)
                    self._transpose.pop(old, None)
                while len(self._done_tickets) > _RESULT_WINDOW:
                    self._done_tickets.popitem(last=False)

    def _busy(self) -> bool:
        if self.svc is None or self._fenced:
            return False
        return any(l.in_step for l in self.svc.fleet.lanes)

    def _holds_work(self) -> bool:
        if self.svc is None:
            return False
        with self._lock:
            if self._outstanding:
                return True
        if self._fenced:
            return False
        return any(l.in_flight or l.queue.depth() > 0
                   for l in self.svc.fleet.lanes)

    # -- endpoint handlers --------------------------------------------------

    def handle_healthz(self) -> dict:
        self._check_fence()
        hz = None
        if self.svc is not None and not self._fenced:
            try:
                hz = _trim_healthz(self.svc)
            except Exception:
                hz = None
        return {
            "ok": not self._fenced,
            "wire_version": WIRE_VERSION,
            "fenced": self._fenced,
            "pid": os.getpid(),
            "boot_id": host_boot_id(),
            "host": host_identity(),
            "t_wall": time.time(),
            "busy": self._busy(),
            "holds_work": self._holds_work(),
            "fence_token": self._fence_ack,
            "coldstart": self.coldstart,
            "healthz": hz,
        }

    def handle_submit(self, rec: dict) -> dict:
        if self._check_fence():
            return {"ok": False, "fenced": True}
        rid = str(rec.get("id"))
        if int(rec.get("wire_version", WIRE_VERSION)) != WIRE_VERSION:
            return {"ok": False,
                    "error": (f"wire version "
                              f"{rec.get('wire_version')} != "
                              f"{WIRE_VERSION}")}
        # Idempotency gate: a retried submit after a lost ACK (or a
        # proxy-duplicated one racing on another handler thread) must
        # admit EXACTLY once. Check-and-reserve under the lock; the
        # admission itself runs outside it.
        with self._lock:
            if (rid in self._outstanding or rid in self._results
                    or rid in self._reserved):
                return {"ok": True, "dup": True}
            if rid in self._journal_seen:
                dup = True
            else:
                dup = False
                self._reserved.add(rid)
        if dup:
            # A previous life journaled this id. A finalized-but-lost
            # result is reported LOUDLY (exactly-once forbids a silent
            # re-solve); an admitted-but-unfinalized one is already
            # back in flight via the boot-time recover().
            st = self._finalized_prev.get(rid)
            if st is not None:
                with self._lock:
                    absent = (rid not in self._results
                              and rid not in self._outstanding)
                    if absent:
                        self._results[rid] = {
                            "id": rid, "status": None,
                            "error": (f"request finalized {st} before a "
                                      f"crash; the result did not "
                                      f"survive the restart (journal "
                                      f"exactly-once forbids a silent "
                                      f"re-solve)"),
                            "sweeps": 0, "bucket": None,
                            "queue_wait_s": 0.0, "solve_time_s": None,
                            "path": "recovery", "degraded": False,
                            "u": None, "s": None, "v": None}
            return {"ok": True, "dup": True}
        try:
            a = decode_array(rec["input"])        # ORIENTED payload
            deadline_s = rec.get("deadline_s")
            if deadline_s is not None:
                # Deadline-budget decay across the hop: the budget
                # decays from the CLIENT's submit wall time, so retries
                # and queueing on the far side all spend the same
                # clock.
                deadline_s = (float(rec["t_wall"]) + float(deadline_s)
                              - time.time())
            t = self.svc.submit(
                a, request_id=rid,
                compute_u=bool(rec.get("compute_u", True)),
                compute_v=bool(rec.get("compute_v", True)),
                deadline_s=deadline_s,
                top_k=rec.get("top_k"),
                phase=str(rec.get("phase", "full")),
                digest=(rec.get("input") or {}).get("data_sha256"),
                # Tenant identity over the wire: an explicit tenant
                # name wins, else an api_token resolved against the
                # RECEIVING service's ServeConfig.api_tokens; both
                # absent -> the default tenant (pre-tenancy clients
                # keep working byte-for-byte).
                tenant=rec.get("tenant"),
                api_token=rec.get("api_token"))
            with self._lock:
                self._outstanding[rid] = t
                self._transpose[rid] = bool(rec.get("transposed", False))
                self._reserved.discard(rid)
            return {"ok": True, "dup": False}
        except BaseException:
            with self._lock:
                self._reserved.discard(rid)
            raise       # _Handler maps AdmissionError / errors to JSON

    def handle_debt(self, body: dict) -> dict:
        if self._check_fence():
            return {"ok": False, "fenced": True}
        records = list(body.get("records") or ())
        fence_token = body.get("fence_token")
        fence_domain = body.get("fence_domain")
        # Receiver-side rid dedupe closes the failover-after-lost-ACK
        # hole: a request the router already failed over HERE (same
        # idempotency key) must not be admitted a second time when its
        # first home dies and the rescue re-homes the journal debt.
        fresh, dups = [], []
        with self._lock:
            for rec in records:
                rid = str(rec.get("id"))
                if (rid in self._outstanding or rid in self._results
                        or rid in self._reserved
                        or rid in self._journal_seen):
                    dups.append(rid)
                else:
                    fresh.append(rec)
        admitted: List[str] = []
        if fresh or fence_token is not None:
            tickets = self.svc.admit_journal_debt(
                fresh,
                fence_token=(None if fence_token is None
                             else int(fence_token)),
                fence_domain=fence_domain)
            with self._lock:
                self._outstanding.update(tickets)
            admitted = sorted(tickets)
        if dups and fence_token is not None:
            # A fenced rescue replaying rids already live HERE (the
            # equal-token idempotent case, caught by the transport-level
            # dedupe before the service's fence ledger could see it):
            # still audited — the journal must show every dup the
            # exactly-once discipline skipped, whichever layer caught it.
            self.svc._bump(*(["fence_dup_skipped"] * len(dups)))
            if self.svc.journal is not None:
                self.svc.journal.append_audit(
                    "fence_dup_skipped",
                    domain=str(fence_domain or "_default"),
                    token=int(fence_token), via="transport_dedupe",
                    ids=sorted(dups))
        return {"ok": True, "admitted": admitted, "dups": sorted(dups)}

    def handle_status(self, rid: Optional[str]) -> dict:
        self._collect()
        rid = str(rid)
        with self._lock:
            if rid in self._results:
                return {"ok": True, "known": True, "done": True}
            if rid in self._outstanding:
                return {"ok": True, "known": True, "done": False}
        return {"ok": True, "known": False, "done": False}

    def handle_result(self, rid: Optional[str]) -> dict:
        self._collect()
        rid = str(rid)
        with self._lock:
            enc = self._results.get(rid)
            pending = rid in self._outstanding
        if enc is not None:
            return {"ok": True, "result": enc}
        return {"ok": False, "pending": pending,
                "known": pending}

    def handle_promote(self, body: dict) -> dict:
        if self._check_fence():
            return {"ok": False, "fenced": True}
        rid = str(body.get("id"))
        timeout_s = body.get("timeout_s")
        self._collect()
        with self._lock:
            t = self._outstanding.get(rid) or self._done_tickets.get(rid)
            transposed = self._transpose.get(rid, False)
        if t is None:
            return {"ok": False,
                    "error": f"unknown or expired request {rid!r}"}
        res = t.promote(None if timeout_s is None else float(timeout_s))
        enc = _encode_result(res)
        enc["transposed"] = transposed
        return {"ok": True, "result": enc}

    def handle_cancel(self, body: dict) -> dict:
        rid = str(body.get("id"))
        with self._lock:
            t = self._outstanding.get(rid)
        if t is not None:
            t.cancel()
        return {"ok": True, "known": t is not None}

    def handle_forget(self, body: dict) -> dict:
        rid = str(body.get("id"))
        with self._lock:
            known = self._results.pop(rid, None) is not None
            self._done_tickets.pop(rid, None)
            self._transpose.pop(rid, None)
        return {"ok": True, "known": known}

    def handle_fence(self, body: dict) -> dict:
        t_wall = float(body.get("t_wall", 0.0))
        if t_wall < self.boot_wall:
            # A fence older than this boot targeted a PAST life; the
            # respawn must not re-die on it.
            return {"ok": True, "ignored": True}
        token = body.get("token")
        if token is not None and int(token) > self._fence_ack:
            # An explicit fence RPC carries the rescuer's token; ack'ing
            # it here means a later _check_fence of the SAME token does
            # not double-audit.
            self._fence_ack = int(token)
        self._fence_now()
        return {"ok": True, "fenced": True}

    def handle_lease(self, body: dict) -> dict:
        self._check_fence()
        return {
            "ok": not self._fenced,
            "fenced": self._fenced,
            "ttl_s": float(body.get("ttl_s", 0.0)),
            "fence_token": self._fence_ack,
            "boot_id": host_boot_id(),
            "pid": os.getpid(),
            "t_wall": time.time(),
        }

    def handle_stop(self) -> dict:
        self._stop_requested = True
        if not self.subprocess_mode:
            # In-thread servers stop synchronously from the test
            # harness; a wire-level stop only flags.
            pass
        return {"ok": True}


def run_http_replica(config, *, host: str = "127.0.0.1", port: int = 0,
                     warmup: bool = False, announce_path=None,
                     max_runtime_s: Optional[float] = None,
                     poll_s: float = 0.05) -> int:
    """Process main for one HTTP replica (`tests/_http_worker.py` and
    ``cli serve-demo --transport=http`` spawn this). Binds, announces
    the REAL (ephemeral) port atomically, then loops watching the fence
    token on the shared filesystem — a replica partitioned at the HTTP
    layer still notices its domain was rescued. Exit codes: 0 clean
    stop, 4 runtime fuse, 5 fenced."""
    server = HttpReplicaServer(config, host=host, port=port,
                               warmup=warmup, subprocess_mode=True)
    server.start()
    if announce_path is not None:
        _write_json_atomic(Path(announce_path), {
            "host": server.host, "port": server.port,
            "pid": os.getpid(), "boot_id": host_boot_id(),
            "t_wall": time.time()})
    t_end = (None if max_runtime_s is None
             else time.monotonic() + max_runtime_s)
    rc: Optional[int] = None
    try:
        while rc is None:
            if server._check_fence():
                rc = 5
                break
            if server._stop_requested:
                rc = 0
                break
            if t_end is not None and time.monotonic() > t_end:
                rc = 4
                break
            time.sleep(poll_s)
    finally:
        # A fenced replica must NOT drain (finalizing rescued work
        # would double-serve it) — `stop` already skips the service
        # when fenced.
        server.stop(drain=rc == 0, timeout=30.0)
    return int(rc or 0)


# -- the client side (the router's handle) -------------------------------------


class _HttpSub:
    """Uniform poll surface over a request living on an HTTP replica.
    Every poll is a single-attempt RPC that BYPASSES the breaker (the
    ticket's own deadline/wall bound governs how long a client keeps
    asking a blackholed host)."""

    _MIN_POLL_S = 0.02

    def __init__(self, replica: "HttpReplica", request_id: str):
        self.replica = replica
        self.request_id = str(request_id)
        self._last = 0.0

    def done(self) -> bool:
        try:
            resp = self.replica._rpc(
                "status", f"/v1/status?id={self.request_id}",
                method="GET", attempts=1, record_failures=False,
                probe=True)
        except Exception:
            return False
        return bool(resp.get("done"))

    def poll(self, slice_s: float) -> Optional[Any]:
        now = time.monotonic()
        gap = self._MIN_POLL_S - (now - self._last)
        if gap > 0:
            time.sleep(min(gap, max(slice_s, 0.0)))
        self._last = time.monotonic()
        try:
            resp = self.replica._rpc(
                "result", f"/v1/result?id={self.request_id}",
                method="GET", attempts=1, record_failures=False,
                probe=True)
        except Exception:
            time.sleep(min(slice_s, 0.05))
            return None
        if not resp.get("ok"):
            if resp.get("pending"):
                time.sleep(min(slice_s, self._MIN_POLL_S))
            return None
        return _decode_result(resp["result"])

    def cancel(self) -> None:
        try:
            self.replica._rpc("cancel", "/v1/cancel", method="POST",
                              body={"id": self.request_id}, attempts=1,
                              record_failures=False, probe=True)
        except Exception:
            pass

    def cleanup(self) -> None:
        """Forget the consumed result server-side (a result can carry
        megabytes of base64 factors; the federation must not hold one
        per served request until the window evicts it)."""
        try:
            self.replica._rpc("forget", "/v1/forget", method="POST",
                              body={"id": self.request_id}, attempts=1,
                              record_failures=False, probe=True)
        except Exception:
            pass


class HttpReplica(ReplicaHandle):
    """The router's handle on a replica across an unreliable network
    (module docstring for the full discipline). ``address`` is
    ``(host, port)``; ``journal_path`` must be the replica's journal on
    a filesystem THIS process can reach — the fencing token lives next
    to it, and cross-machine rescue without a shared (or replicated)
    journal namespace is not a thing this transport pretends to do."""

    kind = "http"
    # A finalized result lives only in the server's in-memory window:
    # it does NOT survive the replica's death (unlike a spool outbox
    # file) — the router's rescue resolves finalized-but-unfetched
    # requests loudly instead of polling a dead host forever.
    results_survive_death = False

    def __init__(self, index: int, address: Tuple[str, int],
                 journal_path, *,
                 lease_ttl_s: float = 2.0,
                 rpc_timeout_s: float = 1.0,
                 rpc_attempts: int = 3,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 1.0,
                 quarantine_threshold: int = 3,
                 quarantine_cooldown_s: float = 1.0,
                 boot_grace_s: float = 10.0,
                 hz_interval_s: float = 0.1,
                 respawn_cmd=None,
                 manifest_path=None,
                 max_net_records: int = 2048):
        super().__init__(index, journal_path)
        self.address = (str(address[0]), int(address[1]))
        self.lease_ttl_s = float(lease_ttl_s)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.rpc_attempts = max(1, int(rpc_attempts))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.quarantine_threshold = max(1, int(quarantine_threshold))
        self.quarantine_cooldown_s = float(quarantine_cooldown_s)
        self.boot_grace_s = float(boot_grace_s)
        self.hz_interval_s = float(hz_interval_s)
        self.manifest_path = manifest_path
        self.max_net_records = int(max_net_records)
        self._respawn_cmd = respawn_cmd
        self._lock = threading.Lock()
        self.net_records: List[dict] = []
        self.net_stats: Dict[str, int] = {}
        # Connection breaker (half-open quarantine).
        self._fail_streak = 0
        self._breaker = "closed"        # closed | open | half-open
        self._open_until = 0.0
        # Lease (monotonic clock — leases are a LOCAL promise).
        self._lease_until = 0.0
        self._lease_ever = False
        self._lease_lapse_logged = False
        self._remote_fenced = False
        self._hz_cache: dict = {}
        self._hz_read = 0.0

    @property
    def base_url(self) -> str:
        return f"http://{self.address[0]}:{self.address[1]}"

    # -- net observability --------------------------------------------------

    def _net(self, event: str, **extra) -> None:
        """One ``"net"`` manifest record (never raises; observability
        must not take down the transport). Called OUTSIDE self._lock."""
        try:
            from .. import obs
            rec = obs.manifest.build_net(event=event,
                                         replica=self.index, **extra)
            with self._lock:
                self.net_stats[event] = self.net_stats.get(event, 0) + 1
                if self.max_net_records > 0:
                    self.net_records.append(rec)
                    del self.net_records[:-self.max_net_records]
            if self.manifest_path is not None:
                obs.manifest.append(self.manifest_path, rec)
        except Exception:
            pass

    # -- breaker ------------------------------------------------------------

    def _breaker_gate(self, probe: bool) -> None:
        """Raise `ReplicaUnavailable` with ZERO network I/O while the
        breaker is open (probes bypass: they ARE the half-open path)."""
        if probe:
            return
        now = time.monotonic()
        with self._lock:
            if self._breaker == "open":
                if now < self._open_until:
                    raise ReplicaUnavailable(
                        f"replica {self.index} connection quarantined "
                        f"({self._fail_streak} consecutive transport "
                        f"errors; half-open in "
                        f"{self._open_until - now:.2f}s)")
                self._breaker = "half-open"    # let THIS call probe

    def _note_success(self) -> None:
        healed = False
        with self._lock:
            if self._breaker != "closed":
                healed = True
            self._breaker = "closed"
            self._fail_streak = 0
        if healed:
            self._net("heal")

    def _note_failure(self) -> None:
        opened = False
        with self._lock:
            self._fail_streak += 1
            if self._breaker == "half-open":
                self._breaker = "open"
                self._open_until = (time.monotonic()
                                    + self.quarantine_cooldown_s)
            elif (self._breaker == "closed"
                    and self._fail_streak >= self.quarantine_threshold):
                self._breaker = "open"
                self._open_until = (time.monotonic()
                                    + self.quarantine_cooldown_s)
                opened = True
        if opened:
            self._net("quarantine", streak=self._fail_streak)

    # -- the RPC core -------------------------------------------------------

    def _rpc(self, op: str, path: str, *, method: str = "POST",
             body: Optional[dict] = None,
             attempts: Optional[int] = None,
             timeout_s: Optional[float] = None,
             budget_end: Optional[float] = None,
             record_failures: bool = True,
             probe: bool = False) -> dict:
        """One RPC under the full network discipline: breaker gate,
        per-attempt timeout bounded by the REMAINING request budget
        (wall clock — ``budget_end``), bounded retries with
        decorrelated jitter (`launch._backoff_delay`), and a ``net``
        record per retry/terminal failure."""
        self._breaker_gate(probe)
        attempts = self.rpc_attempts if attempts is None else attempts
        timeout_s = self.rpc_timeout_s if timeout_s is None else timeout_s
        url = self.base_url + path
        prev_delay = 0.0
        last: Optional[BaseException] = None
        for attempt in range(1, attempts + 1):
            per_attempt = timeout_s
            if budget_end is not None:
                remaining = budget_end - time.time()
                if remaining <= 0:
                    last = TransportError(
                        f"{op}: deadline budget exhausted before "
                        f"attempt {attempt}")
                    break
                per_attempt = min(per_attempt, remaining)
            try:
                payload = _http_json(url, method=method, body=body,
                                     timeout=max(per_attempt, 1e-3))
                if record_failures or probe:
                    self._note_success()
                return payload
            except (urllib.error.URLError, ConnectionError,
                    socket.timeout, TimeoutError, OSError,
                    json.JSONDecodeError, TransportError) as e:
                last = e
                if attempt >= attempts:
                    break
                delay = _launch._backoff_delay(
                    self.backoff_base_s, prev_delay, self.backoff_cap_s)
                if budget_end is not None:
                    delay = min(delay, max(0.0,
                                           budget_end - time.time()))
                prev_delay = delay
                self._net("rpc_retry", op=op, attempt=attempt,
                          error=type(e).__name__)
                if delay > 0:
                    _launch._sleep(delay)
        timed_out = isinstance(last, (socket.timeout, TimeoutError)) or (
            isinstance(last, urllib.error.URLError)
            and isinstance(getattr(last, "reason", None),
                           (socket.timeout, TimeoutError)))
        if isinstance(last, TransportError) and "budget" in str(last):
            timed_out = True
        if record_failures:
            self._note_failure()
            self._net("rpc_timeout" if timed_out else "rpc_error",
                      op=op, attempt=attempts,
                      error=type(last).__name__)
        raise TransportError(
            f"{op} to replica {self.index} ({url}) failed after "
            f"{attempts} attempt(s): {type(last).__name__}: {last}"
        ) from last

    # -- submit / debt ------------------------------------------------------

    def submit(self, a, *, compute_u=True, compute_v=True,
               deadline_s=None, request_id=None, top_k=None,
               phase="full", digest=None, tenant=None, api_token=None):
        """Submit one request over the wire. Orientation happens HERE
        (like `SpoolReplica.submit` — the worker solves the oriented
        payload verbatim, the result decode swaps the factors back);
        the record is admit-shaped and carries the idempotency key
        (id + oriented digest) so ANY number of retries admits once.
        ``tenant``/``api_token`` ride the wire verbatim and resolve on
        the RECEIVING side (against its ServeConfig.api_tokens); both
        None keeps the record byte-identical to the pre-tenancy wire.
        Transport failure -> `ReplicaUnavailable` (the router fails
        over along the ring — a ``failover`` net record marks it)."""
        import numpy as _np
        rid = str(request_id)
        a = _np.asarray(a)
        transposed = a.ndim == 2 and a.shape[0] < a.shape[1]
        oriented = a.T if transposed else a
        if transposed:
            compute_u, compute_v = compute_v, compute_u
        m, n = (int(d) for d in oriented.shape)
        from .journal import _encode_array
        t_wall = time.time()
        rec = {
            "kind": "submit", "wire_version": WIRE_VERSION, "id": rid,
            "t_wall": t_wall, "attempt": 1,
            "deadline_s": (None if deadline_s is None
                           else float(deadline_s)),
            "m": m, "n": n,
            "orig_shape": [int(d) for d in a.shape],
            "transposed": bool(transposed),
            "bucket": None,
            "compute_u": bool(compute_u), "compute_v": bool(compute_v),
            "degraded": False, "brownout": "FULL",
            "top_k": None if top_k is None else int(top_k),
            "phase": str(phase),
            "input": _encode_array(oriented, digest=digest),
        }
        if tenant is not None:
            rec["tenant"] = str(tenant)
        if api_token is not None:
            rec["api_token"] = str(api_token)
        budget_end = None
        if deadline_s is not None and deadline_s != float("inf"):
            budget_end = t_wall + float(deadline_s)
        try:
            resp = self._rpc("submit", "/v1/submit", body=rec,
                             budget_end=budget_end)
        except (TransportError, ReplicaUnavailable) as e:
            self._net("failover", op="submit",
                      error=type(e).__name__)
            raise ReplicaUnavailable(
                f"replica {self.index} unreachable for submit: {e}"
            ) from e
        if resp.get("ok"):
            return _HttpSub(self, rid)
        if resp.get("fenced"):
            with self._lock:
                self._remote_fenced = True
            self._net("failover", op="submit", error="fenced")
            raise ReplicaUnavailable(
                f"replica {self.index} is fenced (mid-rescue)")
        rejected = resp.get("rejected")
        if rejected is not None:
            raise AdmissionError(AdmissionReason[rejected],
                                 str(resp.get("error") or rejected))
        raise ReplicaUnavailable(
            f"replica {self.index} refused submit: "
            f"{resp.get('error')}")

    def admit_debt(self, records, *, fence_token=None,
                   fence_domain=None) -> Dict[str, Any]:
        """Re-home rescued journal debt onto this replica, carrying the
        fencing token the rescuer minted. `StaleFenceError` propagates
        (a LOSING rescuer must hear it loudly); receiver-side dups are
        fine — they are already being served here."""
        body = {
            "wire_version": WIRE_VERSION,
            "records": list(records),
            "fence_token": (None if fence_token is None
                            else int(fence_token)),
            "fence_domain": (None if fence_domain is None
                             else str(fence_domain)),
        }
        resp = self._rpc("debt", "/v1/debt", body=body,
                         timeout_s=max(self.rpc_timeout_s, 5.0))
        if resp.get("stale_fence"):
            raise StaleFenceError(
                str(resp.get("error") or "stale fence token"))
        if resp.get("fenced"):
            raise ReplicaUnavailable(
                f"replica {self.index} is fenced (cannot take debt)")
        if not resp.get("ok"):
            raise ReplicaUnavailable(
                f"replica {self.index} refused debt: "
                f"{resp.get('error')}")
        return {str(rec["id"]): _HttpSub(self, str(rec["id"]))
                for rec in records}

    # -- liveness: leases ---------------------------------------------------

    def _refresh(self, force: bool = False) -> dict:
        """Rate-limited healthz poll; a SUCCESS renews the lease. The
        first grant (and every re-grant after a lapse — the partition
        healed) goes through the formal ``/v1/lease`` RPC and emits the
        lease/heal net records."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._hz_read < self.hz_interval_s:
                return self._hz_cache
            self._hz_read = now      # rate-limit failures too
        try:
            hz = self._rpc("healthz", "/healthz", method="GET",
                           attempts=1, record_failures=False,
                           probe=True)
        except Exception:
            return self._hz_cache
        fenced = bool(hz.get("fenced"))
        first = healed = newly_fenced = False
        with self._lock:
            self._hz_cache = hz
            self._hz_read = time.monotonic()
            if fenced:
                newly_fenced = not self._remote_fenced
                self._remote_fenced = True
            else:
                lapsed = (self._lease_ever
                          and time.monotonic() >= self._lease_until)
                first = not self._lease_ever
                healed = lapsed
                self._lease_until = (time.monotonic()
                                     + self.lease_ttl_s)
                self._lease_ever = True
                self._lease_lapse_logged = False
        if newly_fenced:
            self._net("fence", token=hz.get("fence_token"))
        if first or healed:
            try:
                self._rpc("lease", "/v1/lease", method="POST",
                          body={"ttl_s": self.lease_ttl_s},
                          attempts=1, record_failures=False, probe=True)
            except Exception:
                pass    # the healthz success already renewed it
            self._net("lease_grant", ttl=self.lease_ttl_s)
            if healed:
                self._net("partition_heal")
        return hz

    def alive(self) -> bool:
        self._refresh()
        now = time.monotonic()
        with self._lock:
            if self._remote_fenced:
                return False
            if self._lease_ever and now < self._lease_until:
                return True
            ever = self._lease_ever
            log_lapse = ever and not self._lease_lapse_logged
            if log_lapse:
                self._lease_lapse_logged = True
        if not ever:
            # Never contacted: alive-by-grace while it boots.
            return (now - self._created) < self.boot_grace_s
        if log_lapse:
            self._net("lease_expired",
                      ttl=self.lease_ttl_s)
        return False

    def death_cause(self) -> str:
        with self._lock:
            if self._remote_fenced:
                return "replica_fenced"
            if self._lease_ever:
                return "lease_expired"
        return "replica_dead"

    def lease_until(self, now: float) -> Optional[float]:
        """The unexpired-lease liveness promise on the supervisor's
        monotonic clock (`fleet.heartbeat_stale(lease_until=...)`);
        None before first contact."""
        with self._lock:
            return self._lease_until if self._lease_ever else None

    # -- health surfaces (cached; the supervisor polls these hot) -----------

    def heartbeat_age(self, now: float) -> float:
        self._refresh()
        with self._lock:
            t = self._hz_cache.get("t_wall")
        if not isinstance(t, (int, float)):
            return now - self._created
        return max(0.0, time.time() - float(t))

    def busy(self) -> bool:
        self._refresh()
        with self._lock:
            return bool(self._hz_cache.get("busy"))

    def holds_work(self) -> bool:
        if self.outstanding:
            return True
        self._refresh()
        with self._lock:
            return bool(self._hz_cache.get("holds_work"))

    def healthz(self) -> Optional[dict]:
        self._refresh()
        with self._lock:
            return self._hz_cache.get("healthz")

    # -- lifecycle / rescue surfaces ----------------------------------------

    def start(self) -> None:
        pass    # the process is started by the harness / supervisor

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        try:
            self._rpc("stop", "/v1/stop", method="POST", body={},
                      attempts=1, record_failures=False, probe=True)
        except Exception:
            pass

    def fence(self, token: Optional[int] = None) -> Optional[int]:
        """STONITH across the network: mint (or receive) the fault
        domain's next fencing token, then best-effort TELL the replica.
        The FILE is authoritative — a partitioned replica that never
        hears this RPC still self-fences when it next reads the token
        (`HttpReplicaServer._check_fence`); the RPC just makes the
        common case fast."""
        if token is None:
            token = bump_fence_token(
                self.journal_path,
                minted_by=f"router-fence-{self.index}")
        self._net("fence", token=int(token))
        try:
            self._rpc("fence", "/v1/fence", method="POST",
                      body={"t_wall": time.time(), "token": int(token)},
                      attempts=1,
                      timeout_s=min(self.rpc_timeout_s, 0.5),
                      record_failures=False, probe=True)
        except Exception:
            pass
        with self._lock:
            self._remote_fenced = True
        return int(token)

    def quiesce(self, timeout: float = 2.0) -> None:
        """Bounded wait for the fenced replica to stop answering as a
        live server (fenced healthz or no answer at all) — raw probes
        bypassing the breaker, so quarantine state cannot wedge the
        rescue."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                hz = self._rpc("healthz", "/healthz", method="GET",
                               attempts=1,
                               timeout_s=min(self.rpc_timeout_s, 0.5),
                               record_failures=False, probe=True)
            except Exception:
                return          # unreachable == quiesced for our purposes
            if hz.get("fenced") or not hz.get("ok"):
                return
            time.sleep(0.05)

    def respawn(self) -> None:
        if self._respawn_cmd is None:
            return    # the harness owns process lifecycle
        addr = self._respawn_cmd()
        if (isinstance(addr, tuple) and len(addr) == 2):
            self.address = (str(addr[0]), int(addr[1]))
        with self._lock:
            self._remote_fenced = False
            self._lease_ever = False
            self._lease_until = 0.0
            self._lease_lapse_logged = False
            self._fail_streak = 0
            self._breaker = "closed"
            self._hz_cache = {}
            self._hz_read = 0.0
        self._created = time.monotonic()
        self.generation += 1

    def unconsumed_debt(self, exclude) -> List[dict]:
        """Empty by construction: an HTTP submit is ACKed only AFTER
        the receiver journaled it (`SVDService.submit` write-ahead),
        so there is no accepted-but-unjournaled seam like the spool
        inbox — an un-ACKed submit was never handed over, and the
        router failed it over at submit time."""
        return []
