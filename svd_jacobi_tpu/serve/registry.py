"""Entry registry + AOT compilation + persistent executable cache.

Before this module, THREE places independently approximated "the set of
programs a serving process compiles": `SVDService.warmup()` walked its
own (bucket, variant) x lane x tier loops, the analysis serve pass
hand-listed stepper jits, and `config.RETRACE_BUDGETS` declared entry
names nothing cross-checked. This module is the one authoritative
enumeration, and everything else is refactored onto it:

  * `jit_entries()` — the canonical ``entry name -> live jit object``
    map (exactly the keys of `config.RETRACE_BUDGETS`;
    `analysis.recompile_guard.default_entries` delegates here, and the
    AOT001 analysis pass asserts the two sets are EQUAL in both
    directions, so a new jit entry cannot ship unbudgeted and a stale
    budget cannot linger undeclared).
  * `EntryRegistry` — enumerates every compilable
    ``(lane, bucket, tier, variant)`` serving entry of one service
    configuration (`entries()`), and for each can produce the exact jit
    call plan (`aot_plan`: ``(entry_name, jit_fn, ShapeDtypeStruct
    args, static kwargs)`` tuples derived by the steppers' own
    `aot_entries` via `jax.eval_shape` — no drift from the executed
    programs) and compile it AHEAD OF TIME
    (`aot_compile`: ``jit_fn.lower(*specs, **statics).compile()`` — no
    sweep is ever executed). `SVDService.warmup()` drives both its AOT
    phase and its zero-solve execution phase off this enumeration.
  * **persistent executable cache** (`enable_persistent_cache`): JAX's
    persistent compilation cache, pointed at a NAMESPACED subdirectory
    keyed by the `obs.manifest.config_hash` content hash of the solver
    configuration + the ACTIVE TUNING TABLE's content hash + the
    jax/jaxlib/backend/device identity (`cache_namespace`). A tuning
    table regeneration or config change therefore lands in a fresh
    namespace — stale executables can never be served. Each namespace
    carries a ``CACHE_MANIFEST.json``; a manifest that fails to parse
    or disagrees with the expected identity means the directory was
    corrupted or reused, and the whole namespace is QUARANTINED (renamed
    aside) with a loud `RuntimeWarning` — fresh compilation, never a
    crash, never a mismatched executable. Individual corrupt cache
    ENTRIES are degraded by JAX itself to a fresh compile with a
    warning (`jax._src.compiler._cache_read`), which
    `resilience.chaos.corrupt_compile_cache` exists to prove.

**Measuring cold starts.** In current JAX the
``/jax/core/compile/backend_compile_duration`` monitoring event wraps
``compile_or_get_cached`` — it fires on persistent-cache HITS too. The
honest "fresh compilations" count is therefore ``backend_compiles -
cache_hits`` (`CompileCounter.fresh`), which is what the restart
acceptance asserts is ZERO on a warm cache and what the "coldstart"
manifest record breaks down per entry.
"""

from __future__ import annotations

import json
import os
import time
import warnings
from pathlib import Path
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from .buckets import Bucket, BucketSet

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

CACHE_MANIFEST_NAME = "CACHE_MANIFEST.json"


def jit_entries() -> Dict[str, object]:
    """The authoritative ``entry name -> live jit object`` map — one name
    per `config.RETRACE_BUDGETS` key. `analysis.recompile_guard` resolves
    its guard entries here and the AOT001 pass asserts exact two-way
    coverage against the budgets, so this enumeration IS the declared
    compile surface of the package."""
    from .. import solver
    from ..grad import rules as _grad_rules
    from ..parallel import sharded
    return {
        # Fused one-shot entries (svd() / the escalation ladder).
        "solver._svd_padded": solver._svd_padded,
        "solver._svd_pallas": solver._svd_pallas,
        "solver._svd_pallas_donated": solver._svd_pallas_donated,
        # Blocked-rotation lane (pair_solver="block_rotation"): fused
        # entries + the host-stepped bulk-sweep twins (the polish stage
        # reuses the pallas sweep/finish entries below).
        "solver._svd_block_rotation": solver._svd_block_rotation,
        "solver._svd_block_rotation_donated":
            solver._svd_block_rotation_donated,
        "solver._svd_block_rotation_batched":
            solver._svd_block_rotation_batched,
        "solver._sweep_step_block_jit": solver._sweep_step_block_jit,
        "solver._sweep_step_block_batched_jit":
            solver._sweep_step_block_batched_jit,
        # VMEM-resident lane (pair_solver="resident"): fused entries +
        # the host-stepped bulk-sweep twins (the polish stage reuses the
        # pallas sweep/finish entries below, like the block lane).
        "solver._svd_resident": solver._svd_resident,
        "solver._svd_resident_donated": solver._svd_resident_donated,
        "solver._svd_resident_batched": solver._svd_resident_batched,
        "solver._sweep_step_resident_jit":
            solver._sweep_step_resident_jit,
        "solver._sweep_step_resident_batched_jit":
            solver._sweep_step_resident_batched_jit,
        "sharded._svd_sharded_jit": sharded._svd_sharded_jit,
        # Host-stepped serving entries (SweepStepper).
        "solver._precondition_qr_jit": solver._precondition_qr_jit,
        "solver._sweep_step_pallas_jit": solver._sweep_step_pallas_jit,
        "solver._finish_pallas_jit": solver._finish_pallas_jit,
        "solver._nonfinite_probe_jit": solver._nonfinite_probe_jit,
        "solver._sweep_step_jit": solver._sweep_step_jit,
        "solver._finish_jit": solver._finish_jit,
        # Batched (coalesced-dispatch) lane: fused + stepper entries.
        "solver._svd_pallas_batched": solver._svd_pallas_batched,
        "solver._svd_padded_batched": solver._svd_padded_batched,
        "solver._precondition_qr_batched_jit":
            solver._precondition_qr_batched_jit,
        "solver._sweep_step_pallas_batched_jit":
            solver._sweep_step_pallas_batched_jit,
        "solver._sweep_step_xla_batched_jit":
            solver._sweep_step_xla_batched_jit,
        "solver._finish_pallas_batched_jit":
            solver._finish_pallas_batched_jit,
        "solver._finish_xla_batched_jit": solver._finish_xla_batched_jit,
        "solver._nonfinite_probe_batched_jit":
            solver._nonfinite_probe_batched_jit,
        # Top-k / tall lane stage jits.
        "solver._tsqr_jit": solver._tsqr_jit,
        "solver._tsqr_batched_jit": solver._tsqr_batched_jit,
        "solver._sketch_project_jit": solver._sketch_project_jit,
        "solver._sketch_project_batched_jit":
            solver._sketch_project_batched_jit,
        "solver._lift_q_jit": solver._lift_q_jit,
        "solver._lift_q_batched_jit": solver._lift_q_batched_jit,
        # Warm-start lane (svd(v0=...) / svd_update): pre-rotation and
        # exact factor composition around the existing entry points.
        "solver._apply_v0_jit": solver._apply_v0_jit,
        "solver._compose_v0_jit": solver._compose_v0_jit,
        # Two-phase serving's sigma-first extraction: sigma read off the
        # retained sweep state, deferring the finish stage to promotion.
        "solver._sigma_from_state_jit": solver._sigma_from_state_jit,
        "solver._sigma_from_state_batched_jit":
            solver._sigma_from_state_batched_jit,
        # Differentiable-solver entries (grad.rules): the jitted gradient
        # math the custom VJP/JVP rules dispatch — enumerated here so the
        # AOT001 two-way ledger covers the training-loop compile surface
        # like every serving entry (the GRAD001 pass double-checks).
        **_grad_rules.jit_entries(),
    }


class CompileCounter:
    """Context manager counting backend compile requests and
    persistent-cache hits over its lifetime via JAX's monitoring stream.
    ``fresh`` = compiles the cache did NOT serve (the cold-start cost);
    see the module docstring for why the subtraction is needed."""

    def __init__(self):
        self.backend_compiles = 0
        self.cache_hits = 0
        self._on = False

    @property
    def fresh(self) -> int:
        return max(0, self.backend_compiles - self.cache_hits)

    def _on_duration(self, name: str, duration: float, **kw) -> None:
        if self._on and name == _COMPILE_EVENT:
            self.backend_compiles += 1

    def _on_event(self, name: str, **kw) -> None:
        if self._on and name == _CACHE_HIT_EVENT:
            self.cache_hits += 1

    def __enter__(self) -> "CompileCounter":
        import jax.monitoring
        jax.monitoring.register_event_duration_secs_listener(
            self._on_duration)
        jax.monitoring.register_event_listener(self._on_event)
        self._on = True
        return self

    def __exit__(self, *exc) -> None:
        # Gate off first: if unregistration is unavailable (private jax
        # API moved) the still-registered bound methods go inert instead
        # of mutating an exited counter forever.
        self._on = False
        try:
            from jax._src import monitoring as _m
            _m._unregister_event_duration_listener_by_callback(
                self._on_duration)
            _m._unregister_event_listener_by_callback(self._on_event)
        except Exception:
            pass


class EntryKey(NamedTuple):
    """One compilable serving entry: the (lane, bucket, tier, variant)
    coordinate of a distinct executable set. ``tier`` is None for the
    single-dispatch lane and a static batch tier otherwise; the variant
    is the compute-flag pair (the sigma-only brownout variant flips both
    off — static jit arguments, hence a distinct compile)."""

    lane: int
    bucket: Bucket
    tier: Optional[int]
    compute_u: bool
    compute_v: bool

    @property
    def name(self) -> str:
        vec = "vec" if (self.compute_u or self.compute_v) else "novec"
        tier = "" if self.tier is None else f"/t{self.tier}"
        return f"l{self.lane}/{self.bucket.name}/{vec}{tier}"

    @property
    def device_free(self) -> "EntryKey":
        """The lane-independent coordinate; `EntryRegistry.aot_warm`
        pairs it with the lane's DEVICE to dedup — lanes sharing a
        device share executables, lanes with distinct devices each get
        their own pinned compile."""
        return self._replace(lane=0)


class EntryRegistry:
    """The authoritative enumeration of one service configuration's
    compilable entries (see module docstring). Built either from a live
    `SVDService` (`for_service`) or from the raw pieces — which is how
    `SVDService.reload` pre-warms a NEW bucket set before swapping it
    in, and how the AOT001 analysis pass enumerates without a service."""

    def __init__(self, buckets: BucketSet, solver_map: dict,
                 tiers_map: dict, base_solver, *, max_batch: int = 1,
                 lanes: int = 1, default_tiers: Tuple[int, ...] = (1,),
                 lane_devices: Optional[list] = None):
        self.buckets = buckets
        self._solver_map = dict(solver_map)
        self._tiers_map = dict(tiers_map)
        self._base = base_solver
        self.max_batch = int(max_batch)
        self.lanes = int(lanes)
        self._default_tiers = tuple(default_tiers)
        # Per-lane device assignment (fleet mode pins each lane's working
        # set with device_put): AOT plans carry the lane's device as a
        # SingleDeviceSharding on every spec, so `lower().compile()`
        # warms the per-lane executable caches too — not just
        # device-unpinned programs whose zero-solve dispatches would
        # otherwise pay the per-lane compiles live. None entries (or a
        # missing list) keep the device-free lowering (lanes == 1).
        self._lane_devices = (list(lane_devices)
                              if lane_devices is not None else None)
        # Bucket affinity, mirroring fleet routing: declaration order
        # (the BucketSet's cost-sorted order) modulo lane count.
        self._home = {b: i % self.lanes for i, b in enumerate(buckets)}

    @classmethod
    def for_service(cls, service) -> "EntryRegistry":
        cfg = service.config
        return cls(service.buckets, service._bucket_solver,
                   service._bucket_tiers, cfg.solver,
                   max_batch=cfg.max_batch, lanes=cfg.lanes,
                   default_tiers=service._tiers,
                   lane_devices=[l.device for l in service.fleet.lanes])

    # -- enumeration --------------------------------------------------------

    def home(self, bucket: Bucket) -> int:
        return self._home.get(bucket, 0)

    def solver_for(self, bucket: Bucket):
        return self._solver_map.get(bucket, self._base)

    def tiers_for(self, bucket: Bucket) -> Tuple[int, ...]:
        return tuple(self._tiers_map.get(bucket, self._default_tiers))

    def reachable_tiers(self, bucket: Bucket) -> Tuple[int, ...]:
        """The batch tiers a coalesced dispatch of this bucket can snap
        to under ``max_batch`` (each is a distinct compile)."""
        if self.max_batch <= 1:
            return ()
        tiers = self.tiers_for(bucket)
        cap = min(self.max_batch, tiers[-1])
        return tuple(sorted({min(t for t in tiers if t >= c)
                             for c in range(2, cap + 1)}))

    def entries(self, *, sigma_only: bool = True) -> Tuple[EntryKey, ...]:
        """Deterministic enumeration of every compilable entry, in
        warmup dispatch order: home-lane single dispatches first (the
        submit-path warm lane), then sibling lanes, then the batched
        tiers — per bucket, per compute variant (full factors plus the
        sigma-only brownout variant unless ``sigma_only=False``)."""
        variants = [(True, True)] + ([(False, False)] if sigma_only
                                     else [])
        out: List[EntryKey] = []
        for b in self.buckets:
            for cu, cv in variants:
                out.append(EntryKey(self.home(b), b, None, cu, cv))
        if self.lanes > 1:
            for lane in range(self.lanes):
                for b in self.buckets:
                    if lane == self.home(b):
                        continue
                    for cu, cv in variants:
                        out.append(EntryKey(lane, b, None, cu, cv))
        if self.max_batch > 1:
            for lane in range(self.lanes):
                for b in self.buckets:
                    for cu, cv in variants:
                        for tier in self.reachable_tiers(b):
                            out.append(EntryKey(lane, b, tier, cu, cv))
        return tuple(out)

    # -- the AOT compile plan ----------------------------------------------

    def lane_device(self, lane: int):
        """The device lane ``lane`` pins its working set to (None when
        unpinned — single-lane services and registries built without a
        fleet, e.g. the analysis passes)."""
        if self._lane_devices is None or lane >= len(self._lane_devices):
            return None
        return self._lane_devices[lane]

    @staticmethod
    def _pin_spec(spec, device):
        """Attach a lane's device to one ShapeDtypeStruct as a
        SingleDeviceSharding, so the AOT lowering compiles the SAME
        device-pinned executable the live dispatch (whose inputs went
        through ``jax.device_put(x, lane.device)``) will request. Falls
        back to the unpinned spec on a jax without sharded
        ShapeDtypeStruct construction."""
        if spec is None or device is None:
            return spec
        import jax
        try:
            from jax.sharding import SingleDeviceSharding
            return jax.ShapeDtypeStruct(spec.shape, spec.dtype,
                                        sharding=SingleDeviceSharding(
                                            device))
        except (ImportError, TypeError):
            return spec

    def aot_plan(self, key: EntryKey) -> List[tuple]:
        """The exact jit call plan of one entry: ``(entry_name, jit_fn,
        args, kwargs)`` with `jax.ShapeDtypeStruct` args, covering the
        bucket family's pre-stage (TSQR / sketch), the core stepper's
        whole loop (via `SweepStepper.aot_entries` /
        `BatchedSweepStepper.aot_entries`), and the factor lift — every
        program the live dispatch path will request, none it won't.
        Nothing is executed; shapes come from `jax.eval_shape` over the
        live helpers. When the registry carries per-lane devices (fleet
        mode), every spec is pinned to ``key.lane``'s device
        (`_pin_spec`), so the compiled executable matches the one the
        live dispatch — whose inputs went through ``device_put(x,
        lane.device)`` — will request from the persistent cache."""
        import functools

        import jax
        import jax.numpy as jnp

        from .. import solver
        b = key.bucket
        scfg = self.solver_for(b)
        batched = key.tier is not None
        # Mirror service._core_flags: the top-k lane solves B^T, whose
        # left factor is A's right one — the flags swap.
        ccu, ccv = ((key.compute_v, key.compute_u) if b.kind == "topk"
                    else (key.compute_u, key.compute_v))
        dtype = jnp.dtype(b.dtype)
        shape = (b.m, b.n) if not batched else (key.tier, b.m, b.n)
        a_spec = jax.ShapeDtypeStruct(shape, dtype)
        plan: List[tuple] = []
        lift_q_spec = None
        if b.kind == "tall":
            fn = (solver._tsqr_batched_jit if batched else solver._tsqr_jit)
            name = ("solver._tsqr_batched_jit" if batched
                    else "solver._tsqr_jit")
            kwargs = dict(chunk=scfg.tsqr_chunk)
            plan.append((name, fn, (a_spec,), kwargs))
            q_s, r_s, _ = jax.eval_shape(
                functools.partial(fn, **kwargs), a_spec)
            core_spec, lift_q_spec = r_s, q_s
        elif b.kind == "topk":
            l = min(b.k + int(scfg.oversample), b.n)
            fn = (solver._sketch_project_batched_jit if batched
                  else solver._sketch_project_jit)
            name = ("solver._sketch_project_batched_jit" if batched
                    else "solver._sketch_project_jit")
            kwargs = dict(l=l, power_iters=int(scfg.power_iters),
                          chunk=scfg.tsqr_chunk, seed=0)
            plan.append((name, fn, (a_spec,), kwargs))
            q_s, bt_s, _ = jax.eval_shape(
                functools.partial(fn, **kwargs), a_spec)
            core_spec, lift_q_spec = bt_s, q_s
        else:
            core_spec = a_spec
        # The core stepper: constructed on a zeros array of the CORE
        # shape (post pre-stage) — construction resolves every static
        # exactly as the live dispatch does and costs one allocation,
        # no compile, no sweep.
        zeros = jnp.zeros(core_spec.shape, core_spec.dtype)
        cls = (solver.BatchedSweepStepper if batched
               else solver.SweepStepper)
        st = cls(zeros, compute_u=ccu, compute_v=ccv, config=scfg)
        stepper_plan = list(st.aot_entries())
        plan += stepper_plan
        if b.kind in ("tall", "topk") and key.compute_u:
            # The factor lift (service._post_core): U = Q @ Z. Z's spec
            # comes from the finish entry's abstract result — tall lifts
            # the core's U, top-k the core's V truncated to the bucket's
            # rank class. Looked up by NAME (the plan's tail also carries
            # the nonfinite probe and the sigma-first extraction, so a
            # positional pick would grab the wrong entry).
            fin_name, fin_fn, fin_args, fin_kwargs = next(
                e for e in stepper_plan if "finish" in e[0])
            u_s, s_s, v_s = jax.eval_shape(
                functools.partial(fin_fn, **fin_kwargs), *fin_args)
            z_s = u_s if b.kind == "tall" else v_s
            if z_s is not None:
                if b.kind == "topk":
                    z_s = jax.ShapeDtypeStruct(
                        z_s.shape[:-1] + (b.k,), z_s.dtype)
                lf = (solver._lift_q_batched_jit if batched
                      else solver._lift_q_jit)
                lname = ("solver._lift_q_batched_jit" if batched
                         else "solver._lift_q_jit")
                plan.append((lname, lf, (lift_q_spec, z_s), {}))
        dev = self.lane_device(key.lane)
        if dev is not None:
            plan = [(name, fn,
                     tuple(self._pin_spec(s, dev) for s in args), kwargs)
                    for name, fn, args, kwargs in plan]
        return plan

    def aot_compile(self, key: EntryKey) -> dict:
        """Ahead-of-time compile one entry's whole plan via
        ``jit_fn.lower(*specs, **statics).compile()`` — populating (or
        hitting) the persistent compilation cache without executing a
        sweep. Returns the per-entry coldstart stats the "coldstart"
        manifest record carries."""
        t0 = time.perf_counter()
        names = []
        with CompileCounter() as cc:
            for name, fn, args, kwargs in self.aot_plan(key):
                fn.lower(*args, **kwargs).compile()
                names.append(name)
        dt = time.perf_counter() - t0
        return {"entry": key.name, "jits": names,
                "time_s": float(dt),
                "backend_compiles": int(cc.backend_compiles),
                "cache_hits": int(cc.cache_hits),
                "fresh_compiles": int(cc.fresh),
                "cache_hit": cc.fresh == 0}

    def aot_warm(self, *, sigma_only: bool = True,
                 progress: Optional[Callable[[dict], None]] = None
                 ) -> List[dict]:
        """AOT-compile every enumerated entry, deduplicating the lane
        axis BY DEVICE: lanes sharing a device (or a registry with no
        lane devices at all) share executables, so one compile per
        (bucket, tier, variant, device) covers the fleet — and with
        distinct per-lane devices the plan's pinned specs warm each
        lane's own executables too, not just device-unpinned programs
        (whose zero-solve dispatches would otherwise pay the per-lane
        compiles live). Returns the per-entry stats list for the
        coldstart record."""
        seen = set()
        out = []
        for key in self.entries(sigma_only=sigma_only):
            dedup = (key.device_free, self.lane_device(key.lane))
            if dedup in seen:
                continue
            seen.add(dedup)
            info = self.aot_compile(key)
            out.append(info)
            if progress is not None:
                progress(info)
        return out


# ---------------------------------------------------------------------------
# Persistent executable cache management.


def cache_namespace(base_solver, *, buckets=None) -> Tuple[str, dict]:
    """The cache namespace of one solver configuration: the
    `obs.manifest.config_hash` content hash over the base solver config,
    the ACTIVE tuning table's id + content hash (a table regeneration
    must invalidate — resolved knobs are static jit args), and the
    jax/jaxlib/backend/device identity. Returns ``(hash16, meta)`` with
    ``meta`` the full identity dict written to ``CACHE_MANIFEST.json``.
    The bucket SET is deliberately excluded: adding a bucket adds
    executables, it does not invalidate existing ones (so
    `SVDService.reload` keeps its warm cache)."""
    import dataclasses

    import jax
    import jaxlib

    from ..obs import manifest as _manifest
    from ..tune import tables as _tables
    del buckets  # documented exclusion; accepted for call-site symmetry
    table = _tables.active_table()
    devices = jax.devices()
    meta = {
        "solver_config": {
            k: (v if v is None or isinstance(v, (bool, int, float, str))
                else str(v))
            for k, v in dataclasses.asdict(base_solver).items()},
        "table_id": table.table_id,
        "table_sha256": table.sha256,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": devices[0].platform if devices else "unknown",
        "device_kind": devices[0].device_kind if devices else "unknown",
    }
    meta["config_sha256"] = _manifest.config_hash(meta)
    return meta["config_sha256"][:16], meta


def _fsync_write(path: Path, data: str) -> None:
    with path.open("w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def verify_cache(ns_dir, meta: dict) -> bool:
    """Validate a namespace directory's ``CACHE_MANIFEST.json`` against
    the expected identity. A missing directory or manifest is simply a
    cold cache (True). A manifest that fails to parse, or that declares
    a DIFFERENT identity than the hash-named directory it lives in, means
    the cache was corrupted or reused across configs: the whole namespace
    is quarantined (renamed aside, never deleted) with a loud
    `RuntimeWarning`, and the caller starts a fresh one — fall back to
    compilation, never crash, never serve a mismatched executable.
    Returns False when the namespace was quarantined."""
    ns_dir = Path(ns_dir)
    mf = ns_dir / CACHE_MANIFEST_NAME
    if not ns_dir.exists() or not mf.exists():
        return True
    problem = None
    try:
        found = json.loads(mf.read_text())
    except (json.JSONDecodeError, OSError) as e:
        problem = f"manifest unreadable ({e})"
        found = None
    if found is not None and found.get("config_sha256") != \
            meta.get("config_sha256"):
        problem = (f"manifest identity "
                   f"{str(found.get('config_sha256'))[:12]}... != expected "
                   f"{str(meta.get('config_sha256'))[:12]}...")
    if problem is None:
        return True
    quarantine = ns_dir.with_name(
        ns_dir.name + f".quarantined-{os.getpid()}-{int(time.time())}")
    try:
        ns_dir.rename(quarantine)
    except OSError:
        quarantine = "(rename failed; left in place)"
    warnings.warn(
        f"persistent compile cache {ns_dir} is stale or corrupt "
        f"({problem}); quarantined to {quarantine} and falling back to "
        f"fresh compilation", RuntimeWarning, stacklevel=2)
    return False


def enable_persistent_cache(cache_dir, base_solver) -> Tuple[Path, dict]:
    """Point JAX's persistent compilation cache at the namespaced
    subdirectory of ``cache_dir`` for this configuration (see
    `cache_namespace`), with the min-compile-time/min-entry-size gates
    opened so every serving executable is cached (the defaults skip
    sub-second compiles — most of a CPU warmup). Verifies (and if needed
    quarantines) the namespace first, writes its manifest, and resets
    JAX's in-process cache handle so the new directory takes effect
    immediately. Returns ``(namespace_path, identity_meta)`` — the meta
    is the one actually enabled (callers record its ``config_sha256``
    rather than re-deriving, which could race a table change)."""
    import jax
    ns, meta = cache_namespace(base_solver)
    ns_dir = Path(cache_dir) / ns
    verify_cache(ns_dir, meta)
    ns_dir.mkdir(parents=True, exist_ok=True)
    mf = ns_dir / CACHE_MANIFEST_NAME
    if not mf.exists():
        _fsync_write(mf, json.dumps(meta, indent=2, sort_keys=True) + "\n")
    # The compilation-cache dir is PROCESS-GLOBAL jax state: enabling a
    # second namespace re-points every already-constructed service's
    # future AOT compiles at THIS directory, so their warm restarts
    # would find their own namespace empty. There is no per-service
    # scope to offer — detect the hijack and say so loudly.
    prev = getattr(jax.config, "jax_compilation_cache_dir", None)
    if prev not in (None, "", str(ns_dir)):
        warnings.warn(
            f"persistent compile cache re-pointed from {prev!r} to "
            f"{str(ns_dir)!r}: the jax compilation-cache dir is "
            "process-global, so executables of any service still using "
            "the previous namespace will now land here and its warm "
            "restart will pay fresh compiles. Run one cache-enabled "
            "SVDService per process.", RuntimeWarning, stacklevel=2)
    jax.config.update("jax_compilation_cache_dir", str(ns_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # knob absent on this jax; size gating stays default
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:
        pass  # private API moved; the dir applies from first init instead
    return ns_dir, meta
