"""Bounded admission queue — loud load shedding, never silent drops.

The reference staged oversized runs through a SLURM queue with wall-clock
limits (`build/runSVDMPICUDA.slurm`); this is the in-process equivalent:
a bounded FIFO whose `admit` either enqueues the request or raises
`AdmissionError` with a machine-readable `AdmissionReason` — a rejected
request is a REPLY, not a drop. Two limits live here (the queue's own
state); the service layers the bucket-routing / brownout / shutdown
rejections on top before calling `admit`:

  * ``QUEUE_FULL`` — depth has reached ``max_depth``;
  * ``DEADLINE_BUDGET`` — the aggregate remaining deadline budget of the
    QUEUED requests (sum of ``max(0, deadline_i - now)``) would exceed
    ``max_deadline_budget_s``. This caps how much future work the service
    may promise: every queued deadline is a promise to answer by then,
    and a service that keeps promising past its throughput converts every
    deadline into a DEADLINE status — better to reject at the door.

Multi-tenant QoS (the caller-ring fault domain) also lives here: every
`Request` carries a ``tenant`` identity, and a queue built with a shared
`TenantTable` adds

  * ``RATE_LIMITED`` — the tenant's token-bucket rate limit is
    exhausted (checked LAST, so a rejection for any other reason never
    burns a token — rejections must never leak budget of any kind);
  * a per-tenant SHARE of the deadline-budget cap
    (`TenantPolicy.budget_share`), so one deadline-abusing tenant
    cannot promise away the whole queue's future;
  * weighted-fair dequeue across tenants (`TenantPolicy.weight`,
    cost-weighted via `buckets.admission_cost`, work-conserving: with
    one live tenant the pick degenerates to plain FIFO/EDF);
  * an EDF-vs-FIFO ordering knob (`ServeConfig.queue_ordering`).

With no table and the default ordering, every path below is
byte-identical to the pre-tenancy queue — today's single-caller
surface is the ``tenant="default"`` special case.
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import threading
import time
from typing import Any, Dict, List, Mapping, Optional

from .buckets import Bucket, admission_cost

#: The implicit tenant of every caller that never says otherwise: all
#: pre-tenancy surfaces (bare ``submit``, old journals, v1 wire records
#: without a tenant key) resolve here, so the single-caller behavior is
#: the default tenant's behavior, byte for byte.
DEFAULT_TENANT = "default"


class AdmissionReason(enum.Enum):
    """Why a request was rejected at admission (AdmissionError.reason)."""

    QUEUE_FULL = "queue_full"
    DEADLINE_BUDGET = "deadline_budget"
    # Per-tenant QoS: the tenant's token-bucket rate limit is exhausted.
    # Checked LAST in `admit` (after depth and both budget rules) so a
    # rejection for any other reason never consumes a token.
    RATE_LIMITED = "rate_limited"
    # The API token on the wire resolves to no tenant in
    # `ServeConfig.api_tokens` — an identity failure, not a load
    # condition: never a router failover reason, never an SLO shed.
    UNKNOWN_TENANT = "unknown_tenant"
    NO_BUCKET = "no_bucket"
    NONFINITE_INPUT = "nonfinite_input"
    BROWNOUT_SHED = "brownout_shed"
    SHUTDOWN = "shutdown"
    # Fleet mode only: every solve lane is quarantined/dead — the fleet
    # cannot promise an answer, so it rejects loudly instead of queueing
    # onto a lane nobody will pop.
    NO_LANE = "no_lane"
    # Federated (router) mode only (`serve.router`): every replica of
    # the federation is quarantined/dead — the router cannot promise an
    # answer and says so at the door, one fault-domain ring above
    # NO_LANE.
    NO_REPLICA = "no_replica"


class AdmissionError(RuntimeError):
    """Loud admission rejection: carries the reason and a human detail."""

    def __init__(self, reason: AdmissionReason, detail: str):
        super().__init__(f"request rejected ({reason.value}): {detail}")
        self.reason = reason
        self.detail = detail


@dataclasses.dataclass
class Request:
    """One admitted unit of work (tall-oriented; the service transposes
    wide inputs at submit and swaps the factors back on completion)."""

    id: str
    a: Any                        # tall-oriented (m, n) device array
    m: int                        # oriented logical rows (pre-padding)
    n: int                        # oriented logical cols (pre-padding)
    orig_shape: tuple             # shape exactly as submitted
    transposed: bool
    bucket: Bucket
    compute_u: bool
    compute_v: bool
    degraded: bool                # factors dropped by SIGMA_ONLY brownout
    deadline: Optional[float]     # absolute time.monotonic() second
    deadline_s: Optional[float]   # as requested (relative, for records)
    submitted: float              # time.monotonic() at admission
    brownout: str = "FULL"        # Brownout level NAME at admission
    cancel: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    ticket: Any = None
    # Fleet-internal lane-recovery probe (serve.fleet): pinned to its
    # quarantined lane — never stolen, never rescued onto another lane.
    probe: bool = False
    # Truncated top-k request (`submit(..., top_k=k)`): the requested
    # rank; None = full decomposition. The BUCKET's rank class fixes the
    # solve's static sketch width — top_k only slices the result.
    top_k: Optional[int] = None
    # Workload family of the routed bucket ("full" | "tall" | "topk"),
    # recorded per-request in the serve manifest (`rank_mode`).
    rank_mode: str = "full"
    # Two-phase serving (`submit(phase=...)`): "full" solves to U/Σ/V as
    # always; "sigma" returns σ only and RETAINS the solve's checkpointed
    # stage for `Ticket.promote()` (serve.cache.PromotionStore).
    phase: str = "full"
    # SHA-256 of the oriented input bytes, computed at admission when the
    # content-addressed result cache is enabled (None otherwise): the
    # finalize path stores a successful full result under it.
    digest: Optional[str] = None
    # How this request reached the queue when NOT via plain admission:
    # "replica_rescue" marks a request re-admitted from a dead replica's
    # journal by the router's rescue (`SVDService.admit_journal_debt`) —
    # its eventual serve record carries this as ``path`` so the rescue
    # reconstructs from the stream. None for ordinary submits.
    via: Optional[str] = None
    # First-class caller identity (multi-tenant front door): resolved at
    # submit (explicit name, or `ServeConfig.api_tokens` on the wire),
    # carried through the journal, debt rescue, and every serve record
    # so per-tenant attribution survives replica death.
    tenant: str = DEFAULT_TENANT


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """Declared QoS of one tenant (`ServeConfig.tenants` values).

    Every field defaults to the single-caller behavior — an undeclared
    tenant is indistinguishable from today's sole caller: weight 1.0,
    no rate limit, priority 1.0 (brownout rungs exactly at the
    configured thresholds), no reserved deadline-budget share.
    """

    weight: float = 1.0               # weighted-fair dequeue share
    rate: Optional[float] = None      # sustained admits/second (None = off)
    burst: Optional[float] = None     # bucket capacity (None -> max(rate, 1))
    priority: float = 1.0             # brownout price: < 1 degrades EARLIER
    budget_share: Optional[float] = None  # fraction of the queue budget cap

    def __post_init__(self):
        if not self.weight > 0:
            raise ValueError(f"tenant weight must be > 0, got {self.weight}")
        if self.rate is not None and not self.rate > 0:
            raise ValueError(f"tenant rate must be > 0, got {self.rate}")
        if self.burst is not None and not self.burst > 0:
            raise ValueError(f"tenant burst must be > 0, got {self.burst}")
        if not self.priority > 0:
            raise ValueError(
                f"tenant priority must be > 0, got {self.priority}")
        if (self.budget_share is not None
                and not 0.0 < self.budget_share <= 1.0):
            raise ValueError(f"tenant budget_share must be in (0, 1], "
                             f"got {self.budget_share}")


_DEFAULT_POLICY = TenantPolicy()


def as_tenant_policy(spec) -> TenantPolicy:
    """Coerce a `ServeConfig.tenants` value: a TenantPolicy, or a
    mapping of its field names (the config-file-friendly spelling)."""
    if isinstance(spec, TenantPolicy):
        return spec
    if isinstance(spec, Mapping):
        unknown = set(spec) - {f.name for f in
                               dataclasses.fields(TenantPolicy)}
        if unknown:
            raise ValueError(f"unknown TenantPolicy fields: "
                             f"{sorted(unknown)}")
        return TenantPolicy(**spec)
    raise TypeError(f"cannot coerce {type(spec).__name__} to TenantPolicy")


class TokenBucket:
    """Deterministic token bucket: refill is a pure function of the
    monotonic clock the caller passes IN (never read here), so tests
    replay exactly. Guarded by `TenantTable._lock`."""

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = float(now)

    def _refill(self, now: float) -> None:
        if now > self.stamp:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.stamp) * self.rate)
            self.stamp = now

    def peek(self, now: float) -> float:
        """Tokens available at ``now`` (refills; consumes nothing)."""
        self._refill(now)
        return self.tokens

    def take(self, now: float) -> None:
        """Consume one token. The caller gates on `peek` first; under a
        cross-lane peek/take race the level may transiently dip a hair
        below zero (bounded by the lane count) and the next refill
        absorbs it — deterministic single-lane runs never see it."""
        self._refill(now)
        self.tokens -= 1.0


class TenantTable:
    """Shared per-tenant QoS state of ONE service: the token buckets and
    the weighted-fair virtual clock. A single table is shared by every
    lane's `AdmissionQueue` — rates and fairness are per-SERVICE
    promises; per-lane buckets would multiply a tenant's rate by the
    lane count — so it carries its own leaf lock (config.LOCK_ORDER
    ``tenant_table``, cache tier): acquired under a queue's condition,
    never the reverse, never held across anything that blocks."""

    def __init__(self, policies: Optional[Mapping] = None,
                 now: Optional[float] = None):
        now = time.monotonic() if now is None else float(now)
        self.policies: Dict[str, TenantPolicy] = {
            str(name): as_tenant_policy(spec)
            for name, spec in (policies or {}).items()}
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {
            name: TokenBucket(p.rate,
                              p.burst if p.burst is not None
                              else max(p.rate, 1.0), now)
            for name, p in self.policies.items() if p.rate is not None}
        # WFQ virtual finish times. The floor tracks the clock of the
        # last-served start: an idle tenant's clock is clamped up to it
        # on its next dequeue, so idleness banks no credit (a returning
        # tenant is served promptly but cannot starve the others back).
        self._vtime: Dict[str, float] = {}
        self._vfloor = 0.0

    def policy(self, tenant: str) -> TenantPolicy:
        return self.policies.get(tenant, _DEFAULT_POLICY)

    def has_tokens(self, tenant: str, now: float) -> bool:
        b = self._buckets.get(tenant)
        if b is None:
            return True               # no rate limit declared
        with self._lock:
            return b.peek(now) >= 1.0

    def take_token(self, tenant: str, now: float) -> None:
        b = self._buckets.get(tenant)
        if b is not None:
            with self._lock:
                b.take(now)

    def pick(self, live: List[str]) -> str:
        """The WFQ tenant to serve next among ``live`` (tenant names in
        FIFO order of their head request): smallest effective virtual
        time wins, ties to the earliest queued head — deterministic,
        and work-conserving because the caller only ever passes tenants
        that HAVE queued work."""
        with self._lock:
            best, best_v = live[0], None
            for t in live:
                v = max(self._vtime.get(t, 0.0), self._vfloor)
                if best_v is None or v < best_v:
                    best, best_v = t, v
            return best

    def charge(self, tenant: str, cost: float) -> None:
        """Advance the tenant's virtual finish time by ``cost`` over its
        weight — called at EVERY dequeue path (plain pop, coalescing
        follower, steal), so bypass pops still spend the share."""
        w = self.policy(tenant).weight
        with self._lock:
            start = max(self._vtime.get(tenant, 0.0), self._vfloor)
            self._vtime[tenant] = start + float(cost) / w
            self._vfloor = start

    def snapshot(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Per-tenant QoS view (healthz): declared policy + live bucket
        level + virtual clock."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            out: Dict[str, dict] = {}
            for name, p in self.policies.items():
                b = self._buckets.get(name)
                out[name] = {
                    "weight": p.weight, "priority": p.priority,
                    "rate": p.rate, "budget_share": p.budget_share,
                    "tokens": None if b is None else round(b.peek(now), 3),
                    "vtime": round(self._vtime.get(name, 0.0), 6),
                }
            return out


class AdmissionQueue:
    """Thread-safe bounded queue with the queue-level admission rules.

    Plain FIFO by default; a shared `TenantTable` (``qos``) adds the
    per-tenant rate/budget-share admission rules and weighted-fair
    dequeue, and ``ordering="edf"`` dequeues earliest-deadline-first
    (within the WFQ pick when a table is live, across the whole queue
    otherwise; deadline-less requests sort last, ties stay FIFO)."""

    def __init__(self, max_depth: int,
                 max_deadline_budget_s: float = float("inf"), *,
                 qos: Optional[TenantTable] = None,
                 ordering: str = "fifo"):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        if ordering not in ("fifo", "edf"):
            raise ValueError(f"ordering must be 'fifo' or 'edf', "
                             f"got {ordering!r}")
        self.max_depth = int(max_depth)
        self.max_deadline_budget_s = float(max_deadline_budget_s)
        self.qos = qos
        self.ordering = str(ordering)
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._closed = False

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def close(self) -> None:
        """Stop admitting — atomically with `admit` (same lock), so every
        request is EITHER enqueued before the close (and therefore seen by
        a worker draining to `closed_and_empty`) OR rejected with
        SHUTDOWN. Closes the submit-vs-stop race that could otherwise
        strand an admitted request on a queue nobody will ever pop."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def closed_and_empty(self) -> bool:
        """Atomic worker-exit predicate: once this is True no admitted
        request can still be queued (admit and close share the lock)."""
        with self._cond:
            return self._closed and not self._q

    def deadline_budget(self, now: Optional[float] = None) -> float:
        """Aggregate remaining deadline budget of the queued requests.

        A request cancelled WHILE QUEUED no longer promises an answer, so
        its deadline is released the moment `Ticket.cancel()` sets the
        request's cancel event — not held until the worker pops it (the
        pre-fix behavior: a full-budget queue stayed full-budget after
        every queued client gave up, rejecting new admissions against
        promises nobody was waiting on)."""
        now = time.monotonic() if now is None else now
        with self._cond:
            return sum(max(0.0, r.deadline - now) for r in self._q
                       if r.deadline is not None
                       and not r.cancel.is_set())

    def admit(self, req: Request) -> None:
        """Enqueue or raise AdmissionError — the only two outcomes."""
        now = time.monotonic()
        with self._cond:
            if self._closed:
                raise AdmissionError(AdmissionReason.SHUTDOWN,
                                     "queue is closed")
            if len(self._q) >= self.max_depth:
                raise AdmissionError(
                    AdmissionReason.QUEUE_FULL,
                    f"queue depth {len(self._q)} at max_depth "
                    f"{self.max_depth}")
            tenant = getattr(req, "tenant", DEFAULT_TENANT)
            if req.deadline is not None:
                # Condition's default lock is an RLock, so the re-entrant
                # read of the one budget definition is safe.
                budget = self.deadline_budget(now)
                add = max(0.0, req.deadline - now)
                if budget + add > self.max_deadline_budget_s:
                    raise AdmissionError(
                        AdmissionReason.DEADLINE_BUDGET,
                        f"aggregate queued deadline budget "
                        f"{budget + add:.3f}s would exceed "
                        f"{self.max_deadline_budget_s:.3f}s")
                # Per-tenant share of the same cap: one deadline-abusing
                # tenant may only promise away its declared slice.
                pol = (self.qos.policy(tenant) if self.qos is not None
                       else None)
                if (pol is not None and pol.budget_share is not None
                        and self.max_deadline_budget_s != float("inf")):
                    cap = pol.budget_share * self.max_deadline_budget_s
                    mine = sum(max(0.0, r.deadline - now) for r in self._q
                               if r.deadline is not None
                               and not r.cancel.is_set()
                               and getattr(r, "tenant",
                                           DEFAULT_TENANT) == tenant)
                    if mine + add > cap:
                        raise AdmissionError(
                            AdmissionReason.DEADLINE_BUDGET,
                            f"tenant {tenant!r} queued deadline budget "
                            f"{mine + add:.3f}s would exceed its "
                            f"{pol.budget_share:.0%} share "
                            f"({cap:.3f}s) of the cap")
            # Token-bucket rate limit, LAST: a rejection for any reason
            # above must never have consumed a token (the budget-leak
            # audit of every rejection path), and nothing after the take
            # can fail.
            if self.qos is not None:
                if not self.qos.has_tokens(tenant, now):
                    pol = self.qos.policy(tenant)
                    raise AdmissionError(
                        AdmissionReason.RATE_LIMITED,
                        f"tenant {tenant!r} is over its "
                        f"{pol.rate:g} admits/s rate limit")
                self.qos.take_token(tenant, now)
            self._q.append(req)
            self._cond.notify()

    def _select(self) -> int:
        """Index of the next request to dequeue under the tenancy policy
        (caller holds the condition, ``_q`` non-empty). Index 0 — the
        plain FIFO head — whenever the policy cannot change the answer,
        so tenancy-off dequeue is byte-identical to the pre-tenancy
        queue and WFQ is work-conserving with one live tenant."""
        idxs = list(range(len(self._q)))
        if self.qos is not None:
            live: List[str] = []
            for r in self._q:
                t = getattr(r, "tenant", DEFAULT_TENANT)
                if t not in live:
                    live.append(t)
            if len(live) > 1:
                pick = self.qos.pick(live)
                idxs = [i for i in idxs
                        if getattr(self._q[i], "tenant",
                                   DEFAULT_TENANT) == pick]
        if self.ordering == "edf":
            inf = float("inf")
            return min(idxs, key=lambda i: (
                inf if self._q[i].deadline is None
                else self._q[i].deadline, i))
        return idxs[0]

    def _account(self, req: Request) -> None:
        """Charge the dequeued request's tenant on the shared WFQ clock
        — every removal path that hands work to a worker (plain pop,
        coalescing follower, steal) spends the share."""
        if self.qos is not None:
            self.qos.charge(getattr(req, "tenant", DEFAULT_TENANT),
                            admission_cost(req.bucket))

    def pop(self, timeout: Optional[float] = None) -> Optional[Request]:
        """Next request under the dequeue policy (FIFO head by default);
        blocks until one arrives or the queue closes (``timeout=None`` —
        no idle polling: `admit` and `close` notify the condition).
        Returns None when closed-and-empty, or after an explicit
        ``timeout`` expires."""
        with self._cond:
            while not self._q and not self._closed:
                if not self._cond.wait(timeout):
                    return None
            if not self._q:
                return None          # closed and drained
            i = self._select()
            req = self._q[i]
            del self._q[i]
            self._account(req)
            return req

    def pop_same_bucket(self, bucket: Bucket, limit: int,
                        deadline: Optional[float] = None,
                        max_bypass_age: Optional[float] = None
                        ) -> List[Request]:
        """Pop up to ``limit`` queued requests routed to ``bucket`` — the
        coalescing window pop of the batched serving lane. Blocks until
        ``limit`` are collected, the absolute `time.monotonic()`
        ``deadline`` passes (None = take only what is queued NOW), or the
        queue closes; returns the (possibly empty) batch tail in FIFO
        order. Requests of OTHER buckets stay queued in order — a
        coalesced same-bucket request can therefore be served ahead of an
        earlier other-bucket one, the documented reordering the batching
        window trades for the coalescing win.

        ``max_bypass_age`` bounds that reordering (anti-starvation): once
        the oldest queued request of ANOTHER bucket has waited longer
        than this many seconds, coalescing may not bypass it any further
        — same-bucket requests queued BEHIND it are left alone and the
        window closes immediately, so the starved request is the next
        plain `pop`. None disables the bound (the pre-fleet behavior:
        a hot bucket could starve a rarely-requested one for as long as
        the hot stream kept the window busy)."""
        out: List[Request] = []
        if limit <= 0:
            return out
        with self._cond:
            while True:
                now = time.monotonic()
                snapshot = list(self._q)
                barrier = None
                if max_bypass_age is not None:
                    for i, r in enumerate(snapshot):
                        if (r.bucket != bucket
                                and now - r.submitted > max_bypass_age):
                            barrier = i
                            break
                for i, r in enumerate(snapshot):
                    if len(out) >= limit:
                        break
                    if barrier is not None and i >= barrier:
                        break
                    if r.bucket == bucket:
                        self._q.remove(r)
                        self._account(r)
                        out.append(r)
                if len(out) >= limit or self._closed or barrier is not None:
                    return out
                timeout = (None if deadline is None
                           else deadline - time.monotonic())
                if timeout is None or timeout <= 0:
                    return out
                if not self._cond.wait(timeout):
                    return out

    def requeue(self, req: Request) -> bool:
        """Re-enqueue a RESCUED request at the FRONT of the queue (it
        already waited its turn on the lane that failed it), bypassing
        the depth/budget admission rules — rescue must never turn into a
        silent drop because the healthy lane happens to be busy. Returns
        False when the queue is closed (the service is stopping; the
        caller finalizes the request loudly instead)."""
        with self._cond:
            if self._closed:
                return False
            self._q.appendleft(req)
            self._cond.notify()
            return True

    def steal_oldest(self) -> Optional[Request]:
        """Pop the oldest NON-PROBE queued request for an idle sibling
        lane (work stealing). Probe requests are pinned to their
        quarantined lane — stealing one would let a healthy lane
        'recover' a lane it never ran on. Returns None when nothing is
        stealable; never blocks."""
        with self._cond:
            for r in self._q:
                if not r.probe:
                    self._q.remove(r)
                    self._account(r)
                    return r
            return None

    def drain(self) -> List[Request]:
        """Remove and return everything queued (shutdown without drain:
        the service finalizes each with CANCELLED — still not silent)."""
        with self._cond:
            out = list(self._q)
            self._q.clear()
            return out
