"""Bounded admission queue — loud load shedding, never silent drops.

The reference staged oversized runs through a SLURM queue with wall-clock
limits (`build/runSVDMPICUDA.slurm`); this is the in-process equivalent:
a bounded FIFO whose `admit` either enqueues the request or raises
`AdmissionError` with a machine-readable `AdmissionReason` — a rejected
request is a REPLY, not a drop. Two limits live here (the queue's own
state); the service layers the bucket-routing / brownout / shutdown
rejections on top before calling `admit`:

  * ``QUEUE_FULL`` — depth has reached ``max_depth``;
  * ``DEADLINE_BUDGET`` — the aggregate remaining deadline budget of the
    QUEUED requests (sum of ``max(0, deadline_i - now)``) would exceed
    ``max_deadline_budget_s``. This caps how much future work the service
    may promise: every queued deadline is a promise to answer by then,
    and a service that keeps promising past its throughput converts every
    deadline into a DEADLINE status — better to reject at the door.
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import threading
import time
from typing import Any, List, Optional

from .buckets import Bucket


class AdmissionReason(enum.Enum):
    """Why a request was rejected at admission (AdmissionError.reason)."""

    QUEUE_FULL = "queue_full"
    DEADLINE_BUDGET = "deadline_budget"
    NO_BUCKET = "no_bucket"
    NONFINITE_INPUT = "nonfinite_input"
    BROWNOUT_SHED = "brownout_shed"
    SHUTDOWN = "shutdown"
    # Fleet mode only: every solve lane is quarantined/dead — the fleet
    # cannot promise an answer, so it rejects loudly instead of queueing
    # onto a lane nobody will pop.
    NO_LANE = "no_lane"
    # Federated (router) mode only (`serve.router`): every replica of
    # the federation is quarantined/dead — the router cannot promise an
    # answer and says so at the door, one fault-domain ring above
    # NO_LANE.
    NO_REPLICA = "no_replica"


class AdmissionError(RuntimeError):
    """Loud admission rejection: carries the reason and a human detail."""

    def __init__(self, reason: AdmissionReason, detail: str):
        super().__init__(f"request rejected ({reason.value}): {detail}")
        self.reason = reason
        self.detail = detail


@dataclasses.dataclass
class Request:
    """One admitted unit of work (tall-oriented; the service transposes
    wide inputs at submit and swaps the factors back on completion)."""

    id: str
    a: Any                        # tall-oriented (m, n) device array
    m: int                        # oriented logical rows (pre-padding)
    n: int                        # oriented logical cols (pre-padding)
    orig_shape: tuple             # shape exactly as submitted
    transposed: bool
    bucket: Bucket
    compute_u: bool
    compute_v: bool
    degraded: bool                # factors dropped by SIGMA_ONLY brownout
    deadline: Optional[float]     # absolute time.monotonic() second
    deadline_s: Optional[float]   # as requested (relative, for records)
    submitted: float              # time.monotonic() at admission
    brownout: str = "FULL"        # Brownout level NAME at admission
    cancel: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    ticket: Any = None
    # Fleet-internal lane-recovery probe (serve.fleet): pinned to its
    # quarantined lane — never stolen, never rescued onto another lane.
    probe: bool = False
    # Truncated top-k request (`submit(..., top_k=k)`): the requested
    # rank; None = full decomposition. The BUCKET's rank class fixes the
    # solve's static sketch width — top_k only slices the result.
    top_k: Optional[int] = None
    # Workload family of the routed bucket ("full" | "tall" | "topk"),
    # recorded per-request in the serve manifest (`rank_mode`).
    rank_mode: str = "full"
    # Two-phase serving (`submit(phase=...)`): "full" solves to U/Σ/V as
    # always; "sigma" returns σ only and RETAINS the solve's checkpointed
    # stage for `Ticket.promote()` (serve.cache.PromotionStore).
    phase: str = "full"
    # SHA-256 of the oriented input bytes, computed at admission when the
    # content-addressed result cache is enabled (None otherwise): the
    # finalize path stores a successful full result under it.
    digest: Optional[str] = None
    # How this request reached the queue when NOT via plain admission:
    # "replica_rescue" marks a request re-admitted from a dead replica's
    # journal by the router's rescue (`SVDService.admit_journal_debt`) —
    # its eventual serve record carries this as ``path`` so the rescue
    # reconstructs from the stream. None for ordinary submits.
    via: Optional[str] = None


class AdmissionQueue:
    """Thread-safe bounded FIFO with the two queue-level admission rules."""

    def __init__(self, max_depth: int,
                 max_deadline_budget_s: float = float("inf")):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = int(max_depth)
        self.max_deadline_budget_s = float(max_deadline_budget_s)
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._closed = False

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def close(self) -> None:
        """Stop admitting — atomically with `admit` (same lock), so every
        request is EITHER enqueued before the close (and therefore seen by
        a worker draining to `closed_and_empty`) OR rejected with
        SHUTDOWN. Closes the submit-vs-stop race that could otherwise
        strand an admitted request on a queue nobody will ever pop."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def closed_and_empty(self) -> bool:
        """Atomic worker-exit predicate: once this is True no admitted
        request can still be queued (admit and close share the lock)."""
        with self._cond:
            return self._closed and not self._q

    def deadline_budget(self, now: Optional[float] = None) -> float:
        """Aggregate remaining deadline budget of the queued requests.

        A request cancelled WHILE QUEUED no longer promises an answer, so
        its deadline is released the moment `Ticket.cancel()` sets the
        request's cancel event — not held until the worker pops it (the
        pre-fix behavior: a full-budget queue stayed full-budget after
        every queued client gave up, rejecting new admissions against
        promises nobody was waiting on)."""
        now = time.monotonic() if now is None else now
        with self._cond:
            return sum(max(0.0, r.deadline - now) for r in self._q
                       if r.deadline is not None
                       and not r.cancel.is_set())

    def admit(self, req: Request) -> None:
        """Enqueue or raise AdmissionError — the only two outcomes."""
        now = time.monotonic()
        with self._cond:
            if self._closed:
                raise AdmissionError(AdmissionReason.SHUTDOWN,
                                     "queue is closed")
            if len(self._q) >= self.max_depth:
                raise AdmissionError(
                    AdmissionReason.QUEUE_FULL,
                    f"queue depth {len(self._q)} at max_depth "
                    f"{self.max_depth}")
            if req.deadline is not None:
                # Condition's default lock is an RLock, so the re-entrant
                # read of the one budget definition is safe.
                budget = self.deadline_budget(now)
                add = max(0.0, req.deadline - now)
                if budget + add > self.max_deadline_budget_s:
                    raise AdmissionError(
                        AdmissionReason.DEADLINE_BUDGET,
                        f"aggregate queued deadline budget "
                        f"{budget + add:.3f}s would exceed "
                        f"{self.max_deadline_budget_s:.3f}s")
            self._q.append(req)
            self._cond.notify()

    def pop(self, timeout: Optional[float] = None) -> Optional[Request]:
        """Oldest request; blocks until one arrives or the queue closes
        (``timeout=None`` — no idle polling: `admit` and `close` notify
        the condition). Returns None when closed-and-empty, or after an
        explicit ``timeout`` expires."""
        with self._cond:
            while not self._q and not self._closed:
                if not self._cond.wait(timeout):
                    return None
            if not self._q:
                return None          # closed and drained
            return self._q.popleft()

    def pop_same_bucket(self, bucket: Bucket, limit: int,
                        deadline: Optional[float] = None,
                        max_bypass_age: Optional[float] = None
                        ) -> List[Request]:
        """Pop up to ``limit`` queued requests routed to ``bucket`` — the
        coalescing window pop of the batched serving lane. Blocks until
        ``limit`` are collected, the absolute `time.monotonic()`
        ``deadline`` passes (None = take only what is queued NOW), or the
        queue closes; returns the (possibly empty) batch tail in FIFO
        order. Requests of OTHER buckets stay queued in order — a
        coalesced same-bucket request can therefore be served ahead of an
        earlier other-bucket one, the documented reordering the batching
        window trades for the coalescing win.

        ``max_bypass_age`` bounds that reordering (anti-starvation): once
        the oldest queued request of ANOTHER bucket has waited longer
        than this many seconds, coalescing may not bypass it any further
        — same-bucket requests queued BEHIND it are left alone and the
        window closes immediately, so the starved request is the next
        plain `pop`. None disables the bound (the pre-fleet behavior:
        a hot bucket could starve a rarely-requested one for as long as
        the hot stream kept the window busy)."""
        out: List[Request] = []
        if limit <= 0:
            return out
        with self._cond:
            while True:
                now = time.monotonic()
                snapshot = list(self._q)
                barrier = None
                if max_bypass_age is not None:
                    for i, r in enumerate(snapshot):
                        if (r.bucket != bucket
                                and now - r.submitted > max_bypass_age):
                            barrier = i
                            break
                for i, r in enumerate(snapshot):
                    if len(out) >= limit:
                        break
                    if barrier is not None and i >= barrier:
                        break
                    if r.bucket == bucket:
                        self._q.remove(r)
                        out.append(r)
                if len(out) >= limit or self._closed or barrier is not None:
                    return out
                timeout = (None if deadline is None
                           else deadline - time.monotonic())
                if timeout is None or timeout <= 0:
                    return out
                if not self._cond.wait(timeout):
                    return out

    def requeue(self, req: Request) -> bool:
        """Re-enqueue a RESCUED request at the FRONT of the queue (it
        already waited its turn on the lane that failed it), bypassing
        the depth/budget admission rules — rescue must never turn into a
        silent drop because the healthy lane happens to be busy. Returns
        False when the queue is closed (the service is stopping; the
        caller finalizes the request loudly instead)."""
        with self._cond:
            if self._closed:
                return False
            self._q.appendleft(req)
            self._cond.notify()
            return True

    def steal_oldest(self) -> Optional[Request]:
        """Pop the oldest NON-PROBE queued request for an idle sibling
        lane (work stealing). Probe requests are pinned to their
        quarantined lane — stealing one would let a healthy lane
        'recover' a lane it never ran on. Returns None when nothing is
        stealable; never blocks."""
        with self._cond:
            for r in self._q:
                if not r.probe:
                    self._q.remove(r)
                    return r
            return None

    def drain(self) -> List[Request]:
        """Remove and return everything queued (shutdown without drain:
        the service finalizes each with CANCELLED — still not silent)."""
        with self._cond:
            out = list(self._q)
            self._q.clear()
            return out
