"""Circuit breaker + brownout ladder — overload and failure degradation.

Two independent axes of degradation, both DECLARED (enumerated states a
test can assert and a manifest record can carry), never improvised:

**Circuit breaker** (failure axis): counts CONSECUTIVE solve failures —
exceptions and non-OK statuses of DISPATCHED solves, excluding
client-initiated CANCELLED and queue-expired deadlines (those never
reach a solve: they are overload symptoms, and feeding them to the
breaker would let overload trip it onto the slower ladder path and
amplify itself). A deadline that expires MID-solve does count: at the
solve level a wedged backend (`chaos.stuck_backend`) and a merely-slow
one are indistinguishable, and missing the wedged case means never
recovering. The cost of the occasional false trip is bounded by the
state machine below — one ladder success plus one probe and the breaker
is closed again, and queue-expired requests are finalized before
dispatch on either path, so an open breaker never serves already-dead
work. State machine, advanced at dispatch (`begin`) and outcome
(`record`):

    CLOSED ──(streak >= failure_threshold)──> OPEN
    OPEN:      dispatches route through the escalation ladder
               (`resilience.resilient_svd` — more conservative, self-
               healing) instead of the plain stepper path; a ladder
               success ──> HALF_OPEN (ladder failure: stays OPEN)
    HALF_OPEN: the next dispatch PROBES the base path;
               success ──> CLOSED, failure ──> OPEN

The breaker never rejects on its own — an OPEN breaker degrades the
solve path; shedding is the brownout ladder's last rung. Deterministic
by construction (no wall-clock cooldown): every transition is caused by
a recorded dispatch outcome, so the whole sequence reconstructs from the
per-request ``"serve"`` manifest records.

**Brownout** (overload axis, computed by the service from queue fill):

    FULL ──> SIGMA_ONLY ──> SHED

FULL serves what was asked; SIGMA_ONLY admits but drops the factor
computation (``compute_u = compute_v = False``: no rotation-product
accumulation, no factor postprocessing/recombination, no sigma
refinement — at kernel-path bucket sizes the sweeps themselves still
run, so this sheds the factor-side cost, not the whole solve; the result
says ``degraded=True``); SHED rejects at admission
(`AdmissionReason.BROWNOUT_SHED`). Levels are decided at ADMISSION time
so a request's service class is fixed (and recorded) the moment it is
accepted.
"""

from __future__ import annotations

import enum
import threading
from typing import List, Tuple


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class Brownout(enum.IntEnum):
    """Ordered degradation ladder (higher = more degraded)."""

    FULL = 0
    SIGMA_ONLY = 1
    SHED = 2


class CircuitBreaker:
    """Thread-safe consecutive-failure breaker (see module docstring)."""

    def __init__(self, failure_threshold: int = 3):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.failure_threshold = int(failure_threshold)
        self._state = BreakerState.CLOSED
        self._streak = 0
        self._lock = threading.Lock()
        # (from, to, cause) transition log for healthz / debugging; the
        # authoritative reconstruction source is the manifest records.
        self.transitions: List[Tuple[str, str, str]] = []

    def state(self) -> BreakerState:
        with self._lock:
            return self._state

    def begin(self) -> Tuple[str, BreakerState]:
        """(dispatch path, state at dispatch): "base" when CLOSED or
        probing HALF_OPEN, "ladder" when OPEN."""
        with self._lock:
            path = "ladder" if self._state is BreakerState.OPEN else "base"
            return path, self._state

    def _move(self, to: BreakerState, cause: str) -> None:
        if self._state is not to:
            self.transitions.append((self._state.value, to.value, cause))
            self._state = to

    def record(self, ok: bool) -> BreakerState:
        """Record a dispatch outcome; returns the state after."""
        with self._lock:
            if self._state is BreakerState.CLOSED:
                if ok:
                    self._streak = 0
                else:
                    self._streak += 1
                    if self._streak >= self.failure_threshold:
                        self._move(BreakerState.OPEN,
                                   f"{self._streak} consecutive failures")
            elif self._state is BreakerState.OPEN:
                if ok:  # the ladder healed a solve — try the base path next
                    self._move(BreakerState.HALF_OPEN, "ladder success")
            else:  # HALF_OPEN: this outcome IS the base-path probe
                if ok:
                    self._streak = 0
                    self._move(BreakerState.CLOSED, "probe success")
                else:
                    self._move(BreakerState.OPEN, "probe failure")
            return self._state
