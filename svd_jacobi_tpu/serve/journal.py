"""Durable request journal — no admitted request is lost to a SIGKILL.

A write-ahead JSONL log of the serving layer's request lifecycle,
fsync'd per record (the `utils.checkpoint` durability discipline,
applied per line via `obs.manifest.append_jsonl`):

  * ``admit``    — written BEFORE the request is enqueued (write-ahead:
    there is no window in which a client holds a ticket for a request
    the journal has never heard of). Carries everything needed to
    re-create the request in a fresh process: the oriented input matrix
    (base64 + SHA-256), the compute flags, the deadline BUDGET and the
    wall-clock admit time (monotonic clocks do not survive a restart —
    the remaining budget is re-derived from wall time on replay).
  * ``dispatch`` — the request was popped by a lane (diagnostic: a
    dispatched-but-unfinalized request at replay was in flight when the
    process died).
  * ``finalize`` — the request reached a terminal status (served,
    rejected at the queue, rescued, cancelled — every terminal path the
    service has). Written right after the ticket's exactly-once
    finalization wins.

**Replay** (`Journal.replay`, driven by `SVDService.recover`): admits
without a finalize are the journal's debt — each is re-admitted at the
FRONT of its bucket's queue with its remaining deadline budget intact
(an already-expired one finalizes DEADLINE loudly instead). Exactly-once
across the restart boundary is the composition of (a) replay skipping
finalized ids, (b) the journal REWRITE at recovery (the new journal
holds exactly the re-admitted requests, attempt-bumped — a second crash
replays only what is still owed), and (c) `Ticket._finalize_once` inside
the process. A torn trailing record — the SIGKILL landed mid-append — is
quarantined by the tolerant reader, never fatal.

The journal is opt-in (``ServeConfig.journal_path``): journaling copies
every input matrix to host and fsyncs per lifecycle event, a durability
tax measured in the request path (PROFILE.md item 26).

**Exclusivity** (the federated-serving guard): a journal path is one
replica's write-ahead log, and two LIVE writers interleaving fsync'd
records into one path would corrupt the exactly-once story silently.
An EXCLUSIVE journal (``Journal(path, exclusive=True)`` — what
`SVDService` opens) therefore takes an ``O_EXCL`` lockfile
(``<path>.lock``, carrying pid + host boot id + a random token): a
second live opener raises `JournalLockedError` loudly. A DEAD owner's
stale lock (its pid is gone, or the host rebooted — the boot id
differs) is broken automatically with a `RuntimeWarning`, so the PR 9
restart lane (SIGKILL, then a fresh process recovers the same journal)
keeps working unattended. A lock whose owner is still alive is only
ever broken EXPLICITLY via `Journal.break_lock` — the replica router
calls it after (and only after) its supervisor has declared the owning
replica dead (`serve.router`). Non-exclusive handles (the default) are
the read/scan/forensics surface; their appends are for tools and tests
that own the path by construction.
"""

from __future__ import annotations

import base64
import hashlib
import itertools
import json
import os
import secrets
import time
import warnings
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional

from ..obs.manifest import append_jsonl, read_jsonl_tolerant

JOURNAL_VERSION = 1


class JournalLockedError(RuntimeError):
    """A second LIVE writer tried to open an exclusive journal: the
    path's ``.lock`` file names an owner whose process is still alive on
    this boot. Two live replicas must never interleave fsync'd writes
    into one journal — give each replica its own ``journal_path``, or
    (rescue only) break the lock explicitly AFTER the owner has been
    declared dead (`Journal.break_lock`)."""


class StaleFenceError(RuntimeError):
    """A rescue hand-off carried a fencing token OLDER than one the
    receiver already accepted for the same fault domain: the sender is
    a partitioned/raced rescuer acting on a view of the world that a
    newer rescue has already superseded. Refused LOUDLY (plus a
    ``fence_refused`` journal audit record) — admitting it would
    double-serve debt the newer rescue owns."""


def host_boot_id() -> str:
    """This host's boot identity: a pid is only meaningful within one
    boot (pids restart from scratch after a reboot, so a stale lock's
    pid could name an unrelated live process)."""
    try:
        return Path("/proc/sys/kernel/random/boot_id").read_text().strip()
    except OSError:
        return "boot-unknown"


def host_identity() -> str:
    """This host's name, for the lockfile's cross-host ownership check:
    on a SHARED filesystem (the multi-host federation's deployment
    model) a lock minted on another machine carries a pid + boot id
    that mean nothing here — `os.kill(pid, 0)` would probe an unrelated
    local process and the boot id would always look "rebooted". The
    host name is what lets `_acquire_lock` refuse to auto-break remote
    locks instead of silently treating every remote owner as dead."""
    import platform
    return platform.node() or "host-unknown"


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True      # exists, owned by someone else
    except OSError:
        return False
    return True


def _lock_is_remote(owner: dict) -> bool:
    """True when a lockfile payload names ANOTHER machine as its minter.
    Pre-host-field lockfiles (older writers) have no host claim and keep
    the original same-host treatment — the cross-host refusal only
    applies where the lockfile can actually prove remoteness."""
    owner_host = owner.get("host")
    return (isinstance(owner_host, str)
            and owner_host != host_identity())


class JournalState(NamedTuple):
    """One scan of the journal stream (see `Journal.scan`)."""

    admits: Dict[str, dict]       # id -> latest admit record, admit order
    dispatched: Dict[str, dict]   # id -> latest dispatch record
    finalized: Dict[str, str]     # id -> terminal status
    torn: int                     # quarantined unparseable lines

    @property
    def unfinalized(self) -> List[dict]:
        """Admit records still owed a terminal status, in admit order."""
        return [rec for rid, rec in self.admits.items()
                if rid not in self.finalized]


def _encode_array(a, mode: str = "full",
                  digest: Optional[str] = None) -> dict:
    """Journal payload for one input matrix. ``mode="full"`` carries the
    bytes (base64 — ~21 MB per 2048² float32 request, PROFILE.md item
    26's documented durability tax) so a crashed request is re-solvable;
    ``mode="digest"`` journals only the SHA-256 + shape/dtype — the tax
    drops to O(100 B), but the bytes are NOT recoverable and a crashed
    request replays as a loud ERROR instead of a re-solve
    (`decode_array`). ``digest`` may carry the ALREADY-computed SHA-256
    of these bytes (`serve.cache.input_digest` — the admission path
    hashes the oriented input once for the cache/ring key; hashing the
    same megabytes again here would double the tax)."""
    import numpy as np
    if mode not in ("full", "digest"):
        raise ValueError(f"journal payload mode must be 'full' or "
                         f"'digest', got {mode!r}")
    a = np.ascontiguousarray(np.asarray(a))
    raw = a.tobytes()
    payload = {
        "shape": [int(d) for d in a.shape],
        "dtype": str(a.dtype),
        "data_sha256": (digest if digest is not None
                        else hashlib.sha256(raw).hexdigest()),
    }
    if mode == "full":
        payload["data_b64"] = base64.b64encode(raw).decode("ascii")
    return payload


def decode_array(payload: dict):
    """Rebuild (and integrity-check) a journaled input matrix. Raises
    `ValueError` on a checksum mismatch — a corrupted payload must not be
    silently solved as if it were the client's data — and on a
    digest-only payload (``journal_payload="digest"``), whose bytes are
    gone by design: recovery finalizes that request ERROR loudly
    (path="recovery"), never silently."""
    import numpy as np
    if "data_b64" not in payload:
        raise ValueError(
            f"digest-only journal payload (sha256="
            f"{str(payload.get('data_sha256'))[:12]}..., shape="
            f"{tuple(payload.get('shape', ()))}): the input bytes were "
            f"not journaled (ServeConfig.journal_payload='digest') and "
            f"cannot be recovered")
    raw = base64.b64decode(payload["data_b64"])
    digest = hashlib.sha256(raw).hexdigest()
    if digest != payload["data_sha256"]:
        raise ValueError(
            f"journaled input payload checksum mismatch "
            f"({digest[:12]}... != {payload['data_sha256'][:12]}...)")
    return np.frombuffer(raw, dtype=np.dtype(payload["dtype"])).reshape(
        tuple(payload["shape"])).copy()


class Journal:
    """The write-ahead request journal of one `SVDService` (see module
    docstring). Thread-SAFE: every append takes the journal's re-entrant
    lock (and the low-level writer additionally serializes per path and
    writes each record as one unbuffered line), so concurrent client and
    worker appends always land whole-line; `rewrite` takes the same
    lock, and `exclusive()` lets recovery make its scan-then-rewrite
    compaction atomic against appends."""

    def __init__(self, path, *, exclusive: bool = False):
        import threading
        self.path = Path(path)
        # Exclusivity (module docstring): an exclusive handle owns the
        # path's O_EXCL lockfile for its lifetime — `SVDService` opens
        # its journal this way, so two live replicas can never
        # interleave writes into one path. The default (non-exclusive)
        # handle is the scan/forensics surface.
        self._lock_path = Path(str(self.path) + ".lock")
        self._lock_token: Optional[str] = None
        if exclusive:
            self._acquire_lock()
        self._seq = itertools.count()
        # fsync-latency accounting (the durability tax, live): every
        # append is one fsync'd write; the flight recorder's
        # `svdj_journal_fsync_seconds` histogram reads `last_append_s`
        # right after each call and the scrape-time collector reads the
        # cumulative pair. Plain floats/ints under the journal lock.
        self.appends = 0
        self.append_total_s = 0.0
        self.last_append_s: Optional[float] = None
        # Re-entrant so `exclusive()` callers can still append inside
        # the critical section; appends and the recovery rewrite all
        # take it, making scan-then-rewrite atomic against concurrent
        # lifecycle appends from worker/client threads (a record
        # appended mid-compaction would otherwise be erased by the
        # rewrite — a silent durability hole).
        self._lock = threading.RLock()

    def exclusive(self):
        """The journal's own lock, for callers that must make a
        read-modify-rewrite atomic against concurrent appends
        (`SVDService.recover`'s scan + compaction)."""
        return self._lock

    # -- cross-process exclusivity (the O_EXCL lockfile) --------------------

    @property
    def locked(self) -> bool:
        """True while this handle owns the path's exclusivity lock."""
        return self._lock_token is not None

    def _read_lock_owner(self) -> dict:
        try:
            return json.loads(self._lock_path.read_text())
        except (OSError, json.JSONDecodeError):
            # Unreadable/torn lockfile: no liveness can be established —
            # treat as a dead owner (breaking it is the safe direction:
            # a LIVE owner rewrites nothing through the lockfile, it
            # only holds it).
            return {}

    def _acquire_lock(self) -> None:
        """Take the path's O_EXCL lockfile (pid + boot id + token).
        Raises `JournalLockedError` when a LIVE owner holds it; breaks a
        DEAD owner's stale lock (different boot, or its pid is gone)
        with a `RuntimeWarning` — the unattended restart-after-SIGKILL
        lane must not need an operator to rm a lockfile."""
        payload = json.dumps({
            "pid": os.getpid(), "boot_id": host_boot_id(),
            "host": host_identity(),
            "token": secrets.token_hex(8), "t_wall": time.time(),
            "path": str(self.path)}, sort_keys=True)
        self._lock_path.parent.mkdir(parents=True, exist_ok=True)
        for attempt in range(2):
            try:
                fd = os.open(str(self._lock_path),
                             os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
                try:
                    os.write(fd, payload.encode())
                    os.fsync(fd)
                finally:
                    os.close(fd)
                self._lock_token = json.loads(payload)["token"]
                return
            except FileExistsError:
                owner = self._read_lock_owner()
                pid = owner.get("pid")
                if _lock_is_remote(owner):
                    # Minted on ANOTHER machine (shared filesystem): the
                    # pid/boot-id liveness probe below is only valid on
                    # the lock-holder's host — a remote owner can never
                    # be proven dead from here, so auto-breaking would
                    # silently steal a LIVE remote replica's journal.
                    raise JournalLockedError(
                        f"journal {self.path} is exclusively locked by "
                        f"host {owner.get('host')!r} (pid {pid}, locked "
                        f"at {owner.get('t_wall')}) — liveness cannot be "
                        f"probed across machines. If that host is truly "
                        f"gone, fence the fault domain and break the "
                        f"lock explicitly: Journal.break_lock(path, "
                        f"force=True)")
                alive = (owner.get("boot_id") == host_boot_id()
                         and isinstance(pid, int) and _pid_alive(pid))
                if alive:
                    raise JournalLockedError(
                        f"journal {self.path} is exclusively owned by a "
                        f"LIVE process (pid {pid}, locked at "
                        f"{owner.get('t_wall')}): two live replicas must "
                        f"never share one journal path — give each its "
                        f"own, or break the lock only after the owner is "
                        f"declared dead (Journal.break_lock)")
                if attempt == 0:
                    warnings.warn(
                        f"journal {self.path}: breaking stale lock of "
                        f"dead owner (pid {pid}, boot "
                        f"{str(owner.get('boot_id'))[:8]}...)",
                        RuntimeWarning, stacklevel=3)
                    try:
                        self._lock_path.unlink()
                    except OSError:
                        pass
        raise JournalLockedError(
            f"journal {self.path}: could not acquire {self._lock_path} "
            f"(another opener keeps re-creating it)")

    def release(self) -> None:
        """Drop this handle's exclusivity lock (idempotent). Only
        removes the lockfile if it is still OURS — a router that broke
        this handle's lock and re-locked the path must not have its
        fresh lock deleted by the dead owner's eventual cleanup."""
        token, self._lock_token = self._lock_token, None
        if token is None:
            return
        if self._read_lock_owner().get("token") == token:
            try:
                self._lock_path.unlink()
            except OSError:
                pass

    @classmethod
    def break_lock(cls, path, *, force: bool = False) -> bool:
        """FORCE-remove a journal path's lockfile — the rescue path's
        explicit override, legitimate only once the lock's owner has
        been declared dead by a supervisor (the owner's pid may still be
        alive when the 'replica' was an in-process handle, which is why
        this cannot be the automatic dead-pid lane). A lock minted on
        ANOTHER machine (shared filesystem) additionally requires
        ``force=True``: no local supervisor can have probed a remote
        owner's liveness, so breaking it is only legitimate on the
        FENCED cross-machine rescue path (the fencing token was bumped
        first — `bump_fence_token` — so even a live remote owner can no
        longer finalize against this journal). Returns True when a
        lockfile existed."""
        lock = Path(str(Path(path)) + ".lock")
        if not force:
            try:
                owner = json.loads(lock.read_text())
            except (OSError, json.JSONDecodeError):
                owner = {}
            if _lock_is_remote(owner):
                raise JournalLockedError(
                    f"journal {path}: refusing to break a lock minted by "
                    f"remote host {owner.get('host')!r} (pid "
                    f"{owner.get('pid')}) — its liveness cannot be "
                    f"probed from {host_identity()!r}. Bump the fence "
                    f"token for this fault domain first, then break "
                    f"with force=True (the fenced cross-machine rescue "
                    f"path does exactly this)")
        try:
            lock.unlink()
            return True
        except OSError:
            return False

    def io_stats(self) -> dict:
        """Cumulative append/fsync accounting (scrape-time view)."""
        with self._lock:
            return {"appends": self.appends,
                    "append_total_s": self.append_total_s,
                    "last_append_s": self.last_append_s}

    def _timed_append(self, rec: dict) -> float:
        t0 = time.perf_counter()
        append_jsonl(self.path, rec)
        dt = time.perf_counter() - t0
        self.appends += 1
        self.append_total_s += dt
        self.last_append_s = dt
        # Returned (not just stored): the caller's histogram sample must
        # be THIS append's latency — re-reading last_append_s after the
        # lock is released could observe a concurrent append's value.
        return dt

    # -- writers ------------------------------------------------------------

    def append_admit(self, req, *, attempt: int = 1,
                     admitted_wall: Optional[float] = None,
                     payload_mode: str = "full") -> float:
        """Journal one admitted request — called BEFORE the queue admit
        (write-ahead). ``admitted_wall`` preserves the ORIGINAL admit
        time across recovery rewrites so deadline budgets keep decaying
        from the client's real submit, not from each restart.
        ``payload_mode`` selects the input encoding (`_encode_array`):
        "full" bytes or "digest" fingerprint-only. Returns this append's
        fsync latency in seconds (all three writers do)."""
        rec = {
            "journal_version": JOURNAL_VERSION,
            "kind": "admit",
            "seq": next(self._seq),
            "id": req.id,
            "t_wall": (time.time() if admitted_wall is None
                       else float(admitted_wall)),
            "attempt": int(attempt),
            "m": int(req.m), "n": int(req.n),
            "orig_shape": [int(d) for d in req.orig_shape],
            "transposed": bool(req.transposed),
            "bucket": req.bucket.name,
            "compute_u": bool(req.compute_u),
            "compute_v": bool(req.compute_v),
            "degraded": bool(req.degraded),
            "brownout": str(req.brownout),
            "deadline_s": (None if req.deadline_s is None
                           else float(req.deadline_s)),
            "top_k": None if req.top_k is None else int(req.top_k),
            "phase": str(getattr(req, "phase", "full")),
            # Tenant attribution survives replica death: recovery and
            # cross-replica debt rescue rebuild the Request (and its SLO
            # accounting) under the ORIGINAL tenant, not the rescuer's.
            "tenant": str(getattr(req, "tenant", "default")),
            "input": _encode_array(req.a, payload_mode,
                                   digest=getattr(req, "digest", None)),
        }
        with self._lock:
            return self._timed_append(rec)

    def append_dispatch(self, request_id: str, *, lane: int,
                        batch_id: Optional[str] = None) -> float:
        with self._lock:
            return self._timed_append({
                "journal_version": JOURNAL_VERSION, "kind": "dispatch",
                "seq": next(self._seq), "id": str(request_id),
                "t_wall": time.time(), "lane": int(lane),
                "batch_id": batch_id})

    def append_finalize(self, request_id: str, status: str) -> float:
        with self._lock:
            return self._timed_append({
                "journal_version": JOURNAL_VERSION, "kind": "finalize",
                "seq": next(self._seq), "id": str(request_id),
                "t_wall": time.time(), "status": str(status)})

    def append_audit(self, kind: str, **fields) -> float:
        """Append one AUDIT record (e.g. ``fence_refused`` — a stale
        fencing token loudly refused, the split-brain forensics trail).
        Audit kinds are deliberately outside the admit/dispatch/finalize
        lifecycle: `scan` ignores unknown kinds, so audit records ride
        the same fsync'd stream without perturbing replay — an old
        reader sees them as no-ops, a forensics pass reads them raw."""
        rec = {"journal_version": JOURNAL_VERSION, "kind": str(kind),
               "t_wall": time.time(), "host": host_identity()}
        rec.update(fields)
        with self._lock:
            rec["seq"] = next(self._seq)
            return self._timed_append(rec)

    # -- readers ------------------------------------------------------------

    def scan(self, *, quarantine: bool = True) -> JournalState:
        """Parse the stream (tolerant: torn lines are quarantined to
        ``<path>.torn`` with a warning, everything parseable counts).
        Pass ``quarantine=False`` when polling a LIVE journal (e.g. the
        restart drill watching a serving child): a half-flushed
        in-flight tail line is not a crash artifact and must not be
        siphoned into the sidecar on every poll."""
        admits: Dict[str, dict] = {}
        dispatched: Dict[str, dict] = {}
        finalized: Dict[str, str] = {}
        torn = 0
        if self.path.exists():
            records, torn = read_jsonl_tolerant(self.path,
                                                quarantine=quarantine)
            for rec in records:
                kind, rid = rec.get("kind"), rec.get("id")
                if rid is None:
                    continue
                if kind == "admit":
                    admits[rid] = rec
                elif kind == "dispatch":
                    dispatched[rid] = rec
                elif kind == "finalize":
                    finalized[rid] = str(rec.get("status"))
        return JournalState(admits=admits, dispatched=dispatched,
                            finalized=finalized, torn=torn)

    def replay(self) -> List[dict]:
        """The journal's debt: admit records with no finalize, in admit
        order — exactly the requests a restarted service must re-admit."""
        return self.scan().unfinalized

    # -- recovery rewrite ---------------------------------------------------

    def rewrite(self, admit_records: List[dict]) -> None:
        """Atomically replace the journal with exactly ``admit_records``
        (the re-admitted debt, attempt-bumped by the caller): temp file,
        fsync, rename, directory fsync — the `utils.checkpoint` rename
        discipline, so a crash mid-rewrite leaves either the old journal
        or the new one, never a half-written hybrid. Resets the history
        a second crash would otherwise replay twice."""
        tmp = self.path.with_name(self.path.name + ".tmp")
        self.path.parent.mkdir(parents=True, exist_ok=True)
        with self._lock:
            with tmp.open("w") as f:
                for rec in admit_records:
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            try:
                fd = os.open(str(self.path.parent), os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
            except OSError:
                pass  # some filesystems reject directory fsync; best-effort
            # Fresh sequence numbers follow the rewritten prefix.
            self._seq = itertools.count(len(admit_records))


# -- fencing tokens (cross-machine rescue, serve.transport) -------------------
#
# One monotonically increasing integer PER FAULT DOMAIN (per journal
# path), persisted in ``<journal>.fence`` next to the journal on the
# shared filesystem. A rescuer bumps it BEFORE stealing the domain's
# journal; every debt hand-off carries the bumped token and every
# replica remembers the token it booted under — a partitioned-but-alive
# replica that comes back sees a higher token on disk and must refuse
# to finalize anything (loudly, `append_audit("fence_refused")`), which
# is what makes cross-machine rescue exactly-once even when "dead" was
# really "partitioned". Plain read-modify-write + atomic rename: two
# RACING rescuers may mint the same token, and the receiving service's
# ledger (`SVDService.admit_journal_debt`) treats an equal token's
# duplicate request ids as idempotent replays — either interleaving
# admits each request exactly once.


def fence_token_path(journal_path) -> Path:
    return Path(str(Path(journal_path)) + ".fence")


def read_fence_token(journal_path) -> int:
    """The fault domain's current fencing token (0 = never fenced)."""
    try:
        payload = json.loads(fence_token_path(journal_path).read_text())
        return int(payload.get("token", 0))
    except (OSError, ValueError, TypeError, json.JSONDecodeError):
        return 0


def bump_fence_token(journal_path, *, minted_by: str = "rescue") -> int:
    """Advance the fault domain's fencing token (atomic rename + fsync,
    the `utils.checkpoint` discipline) and return the new value. Called
    by a rescuer BEFORE it breaks the domain's journal lock: from this
    instant, any replica still bound to the old token is fenced out of
    finalizing against this journal."""
    path = fence_token_path(journal_path)
    token = read_fence_token(journal_path) + 1
    payload = json.dumps({
        "token": token, "t_wall": time.time(),
        "minted_by": str(minted_by), "host": host_identity()},
        sort_keys=True)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + f".tmp-{os.getpid()}")
    fd = os.open(str(tmp), os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        os.write(fd, payload.encode())
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    return token
