"""Content-addressed result cache + byte-budgeted promotion store.

The don't-recompute-what-you-know half of the serving layer (ROADMAP
"Two-phase lazy-vector serving + streaming updates"), two stores:

**ResultCache** — completed full decompositions keyed by content: the
SHA-256 digest of the submitted input bytes plus everything that shapes
the answer (oriented shape, dtype, compute flags, top-k rank, routed
bucket, and the bucket's resolved solver-config hash — the PR 9
`config_hash` discipline, so a tuning-table or config change can never
serve a stale result). A hit finalizes the request in O(ms) with ZERO
solver dispatch, checked on a digest fast-path at admission so a hit
never occupies a queue slot (`SVDService.submit`). Explicit invalidation
(`invalidate(digest)` — the client's "this matrix changed" signal, or
`invalidate()` for everything) plus byte-budget LRU eviction keep it
bounded; every store/hit/evict/invalidate appends a schema-versioned
``"cache"`` manifest record (`obs.manifest.build_cache`).

**PromotionStore** — the retained solve state of sigma-phase requests
(`submit(phase="sigma")`): the preconditioned triangle L (+ Q1/order),
the converged column stacks, and the ACCUMULATED ROTATION PRODUCT of the
sweep loop — everything `Ticket.promote()` needs to resume the SAME
solve from its checkpointed stage to full U/V (one finish-stage
dispatch; never a fresh solve). Byte-budgeted LRU with explicit release;
a promote after eviction raises `PromotionError` loudly (the client can
always fall back to a full re-submit — which the ResultCache may then
serve). States are process-local device arrays: they do NOT survive a
restart (the journal re-solves a recovered sigma request instead).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Any, List, Optional


class PromotionError(RuntimeError):
    """Loud promotion failure: no retained state for the request (never
    a sigma-phase request, state evicted/released, non-OK sigma solve,
    or a restarted process). The caller's recourse is a fresh full
    submit — possibly a result-cache hit."""


def input_digest(a) -> str:
    """SHA-256 of the ORIENTED input bytes — THE content identity every
    don't-recompute surface keys by: the `ResultCache`, the journal
    payload checksum, `Ticket.digest`, and the replica router's
    consistent-hash ring (`serve.router`) all use this one definition,
    so a byte-identical resubmit computes the same key everywhere
    (device arrays pay one D2H copy; the cache trades that for whole
    skipped solves)."""
    import hashlib

    import numpy as _np
    return hashlib.sha256(
        _np.ascontiguousarray(_np.asarray(a)).tobytes()).hexdigest()


def _nbytes(x) -> int:
    return int(getattr(x, "nbytes", 0) or 0)


def tree_nbytes(*xs) -> int:
    """Total byte size of a loose collection of arrays/Nones (the stores'
    budget accounting; nested dicts of arrays count their values)."""
    total = 0
    for x in xs:
        if x is None:
            continue
        if isinstance(x, dict):
            total += tree_nbytes(*x.values())
        elif isinstance(x, (tuple, list)):
            total += tree_nbytes(*x)
        else:
            total += _nbytes(x)
    return total


@dataclasses.dataclass
class PromotionState:
    """Everything needed to resume one sigma-phase solve to full U/V.

    ``kind`` selects the resume path:

      * ``"state"`` — the checkpointed stepper stage: single-form
        (member-sliced, for coalesced dispatches) column/rotation stacks
        plus the preconditioning factors; promotion runs the SAME finish
        jits the full-phase dispatch would have (`solver._finish_pallas_jit`
        / `_finish_jit` — already bucket-compiled), then the bucket
        family's lift and the request's slice.
      * ``"result"`` — the factors already exist (a sigma request served
        on the escalation-ladder path, whose fused solve computes them
        anyway): promotion returns them with no device work at all.
    """

    kind: str                     # "state" | "result"
    bucket: Any                   # serve.buckets.Bucket
    # -- request identity (for the promote-time slice + manifest record)
    m: int
    n: int
    transposed: bool
    compute_u: bool               # the REQUEST's factor flags
    compute_v: bool
    top_k: Optional[int]
    digest: Optional[str]         # input digest when the cache computed one
    lane: int
    # The submitting tenant — promote-time result-cache stores key under
    # it (tenant isolation holds across the σ→promote flow too).
    tenant: str = "default"
    # -- kind="state": the checkpointed stage -----------------------------
    path: str = "kernel"          # "kernel" | "xla" (which finish jit)
    top: Any = None
    bot: Any = None
    vtop: Any = None
    vbot: Any = None
    work: Any = None              # preconditioned triangle L (kernel path)
    q1: Any = None
    order: Any = None
    core_n: int = 0               # the CORE problem's logical n
    precondition: bool = False
    refine: bool = False
    core_u: bool = False          # the CORE solve's compute flags
    core_v: bool = False
    lift: Any = None              # _pre_core context (tall/topk families)
    off_rel: float = 0.0
    sweeps: int = 0
    # -- kind="result": the finished factors ------------------------------
    u: Any = None
    s: Any = None
    v: Any = None
    # Terminal solve status (the retained sweep loop's own — promotion
    # re-reports it; a SolveStatus code array or int).
    status: Any = None
    created: float = dataclasses.field(default_factory=time.monotonic)
    nbytes: int = 0

    def measure(self) -> "PromotionState":
        self.nbytes = tree_nbytes(self.top, self.bot, self.vtop, self.vbot,
                                  self.work, self.q1, self.order, self.lift,
                                  self.u, self.s, self.v)
        return self


class PromotionStore:
    """Byte-budgeted LRU of `PromotionState`s, keyed by request id.

    ``put`` returns the ids it evicted to fit (the service records each
    as a "cache" manifest event, kind promotion/evict — an evicted
    client's promote fails LOUDLY, never silently serves stale factors).
    A state larger than the whole budget is refused (returned as its own
    eviction) rather than silently wedging the store."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._d: "OrderedDict[str, PromotionState]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.stats = {"put": 0, "promoted": 0, "released": 0, "evicted": 0,
                      "missing": 0}

    def put(self, request_id: str, ps: PromotionState) -> List[str]:
        ps.measure()
        evicted: List[str] = []
        with self._lock:
            if self.max_bytes <= 0 or ps.nbytes > self.max_bytes:
                # Retaining nothing is a loud contract (promote raises);
                # report the refused state as an eviction of its own id.
                self.stats["evicted"] += 1
                return [request_id]
            old = self._d.pop(request_id, None)
            if old is not None:
                self._bytes -= old.nbytes
            while self._d and self._bytes + ps.nbytes > self.max_bytes:
                rid, victim = self._d.popitem(last=False)
                self._bytes -= victim.nbytes
                evicted.append(rid)
                self.stats["evicted"] += 1
            self._d[request_id] = ps
            self._bytes += ps.nbytes
            self.stats["put"] += 1
        return evicted

    def take(self, request_id: str) -> PromotionState:
        """Pop the state for promotion; `PromotionError` when absent."""
        with self._lock:
            ps = self._d.pop(request_id, None)
            if ps is None:
                self.stats["missing"] += 1
                raise PromotionError(
                    f"no promotion state retained for request "
                    f"{request_id!r} (not a sigma-phase request, already "
                    f"promoted/released, evicted under the byte budget, "
                    f"or the serving process restarted)")
            self._bytes -= ps.nbytes
            self.stats["promoted"] += 1
            return ps

    def release(self, request_id: str) -> bool:
        """Explicitly drop a retained state (the client will never
        promote); True when something was held."""
        with self._lock:
            ps = self._d.pop(request_id, None)
            if ps is None:
                return False
            self._bytes -= ps.nbytes
            self.stats["released"] += 1
            return True

    def __contains__(self, request_id: str) -> bool:
        with self._lock:
            return request_id in self._d

    def retag_lane(self, lane_index: int, new_lane: int = -1) -> List[str]:
        """Promotion-state rescue on lane eviction (`fleet.Fleet.evict`):
        re-tag every state held for an evicted lane so the stream shows
        who was rescued. The retained arrays themselves stay valid — they
        are process-local (committed to a device whose runtime is still
        alive even when its LANE is quarantined), and the promote-time
        finish jits run wherever the caller dispatches them. Returns the
        re-tagged request ids."""
        with self._lock:
            out = []
            for rid, ps in self._d.items():
                if ps.lane == lane_index:
                    ps.lane = new_lane
                    out.append(rid)
            return out

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._d), "bytes": self._bytes,
                    "max_bytes": self.max_bytes, **self.stats}


class ResultCache:
    """Byte-budgeted LRU of finished host-side factor sets, keyed by
    ``(input digest, identity string)`` — see the module docstring for
    what the identity covers. Values are host numpy arrays (a hit must
    not depend on any device's health) plus the terminal metadata the
    finalize needs."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._d: "OrderedDict[tuple, dict]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.stats = {"hits": 0, "misses": 0, "stores": 0, "evicted": 0,
                      "invalidated": 0}

    @staticmethod
    def entry_nbytes(entry: dict) -> int:
        return tree_nbytes(entry.get("u"), entry.get("s"), entry.get("v"))

    def get(self, key: tuple) -> Optional[dict]:
        with self._lock:
            entry = self._d.get(key)
            if entry is None:
                self.stats["misses"] += 1
                return None
            self._d.move_to_end(key)
            self.stats["hits"] += 1
            return entry

    def put(self, key: tuple, entry: dict) -> "tuple[bool, List[tuple]]":
        """Store one entry; returns ``(stored, evicted_keys)``. An entry
        larger than the whole budget is REFUSED (``stored`` False, no
        stats bump) — the caller must not record a store that never
        happened."""
        nb = self.entry_nbytes(entry)
        evicted: List[tuple] = []
        with self._lock:
            if self.max_bytes <= 0 or nb > self.max_bytes:
                return False, evicted
            old = self._d.pop(key, None)
            if old is not None:
                self._bytes -= self.entry_nbytes(old)
            while self._d and self._bytes + nb > self.max_bytes:
                k, victim = self._d.popitem(last=False)
                self._bytes -= self.entry_nbytes(victim)
                evicted.append(k)
                self.stats["evicted"] += 1
            self._d[key] = entry
            self._bytes += nb
            self.stats["stores"] += 1
        return True, evicted

    def invalidate(self, digest: Optional[str] = None) -> int:
        """Drop every entry of one input digest (the client's "this
        matrix changed" signal), or everything when ``digest`` is None.
        Returns the number of entries dropped."""
        with self._lock:
            if digest is None:
                n = len(self._d)
                self._d.clear()
                self._bytes = 0
            else:
                victims = [k for k in self._d if k[0] == digest]
                for k in victims:
                    self._bytes -= self.entry_nbytes(self._d.pop(k))
                n = len(victims)
            self.stats["invalidated"] += n
            return n

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._d), "bytes": self._bytes,
                    "max_bytes": self.max_bytes, **self.stats}
