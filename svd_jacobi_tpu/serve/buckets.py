"""Shape buckets — the compile-cache contract of the serving layer.

A jit-compiled solver retraces per (shape, dtype); an open-ended request
stream would therefore pay a multi-second compile per novel shape, which
no deadline survives. The service instead declares a SMALL STATIC set of
tall (m >= n, dtype) buckets; every admitted request is zero-padded up to
the cheapest bucket that holds it, so after one warmup per bucket every
dispatch is a cache hit (`config.RETRACE_BUDGETS` entries
``solver._sweep_step_pallas_jit`` etc.; proven by
`analysis.recompile_guard.run_serve_sequence`). A request that fits no
bucket is REJECTED at admission (`AdmissionReason.NO_BUCKET`) — loudly,
never solved off-bucket.

Zero-padding is exact for the SVD, not an approximation: padded columns
are exactly zero, so they deflate (sigma 0, sorted to the back by the
descending sort) and never rotate against live columns; padded ROWS stay
exactly zero through every column rotation (a rotation forms linear
combinations of columns, and both combined entries in a padded row are
zero). The original factors are therefore recovered by slicing:
``u[:m, :k], s[:k], v[:n, :k]`` with ``k = min(m, n)``.

Rank-deficiency caveat: a request with EXACT-zero singular values ties
with the padding's zero sigmas in the descending sort, so its null-space
slots may come back as zero columns in the sliced factors. This matches
the unpadded solver's own rank-deficiency guard (`solver._normalize_cols`
returns zero columns for zero sigmas rather than arbitrary vectors;
`utils.validation.live_orthogonality_error` deflates them), so serving
changes nothing about the contract: null-space columns of U/V are zero,
not orthonormal completions.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple, Union


class Bucket(NamedTuple):
    """One declared padded shape: tall (m >= n) plus the dtype name."""

    m: int
    n: int
    dtype: str

    @property
    def name(self) -> str:
        return f"{self.m}x{self.n}:{self.dtype}"

    @property
    def cost(self) -> int:
        # One-sided Jacobi cost proxy (O(m n^2) per sweep) — routing picks
        # the cheapest bucket that holds the request, not the smallest
        # area, so a tall-skinny request never lands in a huge square
        # bucket when a cheaper tall one fits.
        return self.m * self.n * self.n


BucketSpec = Union[Bucket, Tuple[int, int, str], str]


def as_bucket(spec: BucketSpec) -> Bucket:
    """Coerce a (m, n, dtype) tuple / "MxN:dtype" string / Bucket."""
    if isinstance(spec, Bucket):
        b = spec
    elif isinstance(spec, str):
        try:
            dims, dtype = spec.split(":")
            m, n = dims.split("x")
            b = Bucket(int(m), int(n), dtype)
        except ValueError:
            raise ValueError(
                f"bucket spec {spec!r} is not of the form 'MxN:dtype'")
    else:
        m, n, dtype = spec
        b = Bucket(int(m), int(n), str(dtype))
    import jax.numpy as jnp
    b = Bucket(b.m, b.n, str(jnp.dtype(b.dtype).name))
    if b.n < 1 or b.m < b.n:
        raise ValueError(
            f"bucket {b.name}: buckets are tall, need m >= n >= 1 "
            f"(the service orients wide requests by transposition)")
    return b


class BucketSet:
    """The declared bucket set, sorted by routing cost."""

    def __init__(self, buckets: Sequence[BucketSpec]):
        bs = [as_bucket(b) for b in buckets]
        if not bs:
            raise ValueError("a serving bucket set cannot be empty")
        if len(set(bs)) != len(bs):
            raise ValueError(f"duplicate buckets in {bs}")
        self.buckets: Tuple[Bucket, ...] = tuple(
            sorted(bs, key=lambda b: (b.cost, b.m, b.n, b.dtype)))

    def __iter__(self):
        return iter(self.buckets)

    def __len__(self):
        return len(self.buckets)

    def route(self, m: int, n: int, dtype: str) -> Optional[Bucket]:
        """Cheapest bucket holding a TALL-oriented (m >= n) request of
        exact dtype, or None (-> admission rejects with NO_BUCKET)."""
        import jax.numpy as jnp
        dtype = str(jnp.dtype(dtype).name)
        for b in self.buckets:
            if b.dtype == dtype and b.m >= m and b.n >= n:
                return b
        return None

    def resolve_solver_configs(self, base) -> dict:
        """bucket -> concrete solver config, resolved through the active
        tuning table ONCE at declaration time (`tune.resolve_config`):
        every "auto"/None knob of ``base`` the table can pin shape-safely
        is pinned to the value the solver's own planner would resolve for
        the bucket's padded shape. The service stores this map and every
        dispatch path — lanes included — reads it instead of re-resolving
        per request; resolution being pure/deterministic, the pinned
        configs produce byte-identical jit keys to the auto path (the
        TUNE001 analysis pass proves no new retraces)."""
        from ..tune import tables
        return {b: tables.resolve_config(base, m=b.m, n=b.n, dtype=b.dtype)
                for b in self.buckets}

    def resolved_batch_tiers(self) -> dict:
        """bucket -> coalescing tier tuple from the active tuning table
        (`ServeConfig.batch_tiers="auto"`): tiers are a measured knob —
        which batch sizes amortize the latency-bound rotation chain is
        backend-dependent (PROFILE.md item 22) — so the table rows carry
        them per (n-class, aspect, dtype, backend, device_kind). Resolved
        once at declaration, like the solver configs."""
        from ..tune import tables
        return {b: tuple(sorted(set(
            int(t) for t in tables.resolve(b.n, m=b.m,
                                           dtype=b.dtype).batch_tiers)))
                for b in self.buckets}

    @staticmethod
    def pad(a, bucket: Bucket):
        """Zero-pad a tall (m, n) array up to the bucket shape (exact for
        the SVD — see the module docstring)."""
        import jax.numpy as jnp
        m, n = a.shape
        if (m, n) == (bucket.m, bucket.n):
            return a
        return jnp.pad(a, ((0, bucket.m - m), (0, bucket.n - n)))
