"""Shape buckets — the compile-cache contract of the serving layer.

A jit-compiled solver retraces per (shape, dtype); an open-ended request
stream would therefore pay a multi-second compile per novel shape, which
no deadline survives. The service instead declares a SMALL STATIC set of
tall (m >= n, dtype) buckets; every admitted request is zero-padded up to
the cheapest bucket that holds it, so after one warmup per bucket every
dispatch is a cache hit (`config.RETRACE_BUDGETS` entries
``solver._sweep_step_pallas_jit`` etc.; proven by
`analysis.recompile_guard.run_serve_sequence`). A request that fits no
bucket is REJECTED at admission (`AdmissionReason.NO_BUCKET`) — loudly,
never solved off-bucket.

Buckets come in three FAMILIES (``Bucket.kind``), one per workload the
service understands (README "Workloads"):

  * ``"full"`` — the classic padded full decomposition;
  * ``"tall"`` — genuinely rectangular m >= 8n shapes, dispatched
    through the blocked-TSQR lane (chunked QR, then the Jacobi core on
    the n x n triangle only) instead of a padded square solve. A tall
    bucket still serves FULL factors — it is a cheaper dispatch
    strategy, not a different contract — so ordinary requests route into
    it whenever it is the cheapest fit;
  * ``"topk"`` — truncated top-k requests (`submit(..., top_k=k)`),
    dispatched through the randomized range-finder lane. The bucket's
    ``k`` is the RANK CLASS: it bounds the admissible request k and
    fixes the static sketch width (k + oversample), so the compile
    contract holds across request k values (no per-k retrace — the
    request's k only slices the result). Full requests never route into
    a topk bucket (its result is truncated), and topk requests route
    ONLY into topk buckets.

Zero-padding is exact for the SVD, not an approximation: padded columns
are exactly zero, so they deflate (sigma 0, sorted to the back by the
descending sort) and never rotate against live columns; padded ROWS stay
exactly zero through every column rotation (a rotation forms linear
combinations of columns, and both combined entries in a padded row are
zero). The original factors are therefore recovered by slicing:
``u[:m, :k], s[:k], v[:n, :k]`` with ``k = min(m, n)`` (the request's
``top_k`` on the truncated family).

Rank-deficiency caveat: a request with EXACT-zero singular values ties
with the padding's zero sigmas in the descending sort, so its null-space
slots may come back as zero columns in the sliced factors. This matches
the unpadded solver's own rank-deficiency guard (`solver._normalize_cols`
returns zero columns for zero sigmas rather than arbitrary vectors;
`utils.validation.live_orthogonality_error` deflates them), so serving
changes nothing about the contract: null-space columns of U/V are zero,
not orthonormal completions.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple, Union

BUCKET_KINDS = ("full", "tall", "topk")


class Bucket(NamedTuple):
    """One declared padded shape: tall (m >= n) plus the dtype name,
    the workload family ``kind`` and — for the "topk" family — the rank
    class ``k`` (0 otherwise)."""

    m: int
    n: int
    dtype: str
    kind: str = "full"
    k: int = 0

    @property
    def name(self) -> str:
        base = f"{self.m}x{self.n}:{self.dtype}"
        if self.kind == "tall":
            return base + ":tall"
        if self.kind == "topk":
            return base + f":topk{self.k}"
        return base

    @property
    def cost(self) -> int:
        # Routing picks the cheapest bucket that holds the request, not
        # the smallest area. Cost proxies per family: one-sided Jacobi is
        # O(m n^2) per sweep; the tall lane pays the TSQR (2 m n^2-class)
        # plus a small n^3 solve — same leading term, discounted so a
        # tall bucket beats an equal-area square one; the top-k lane is
        # O(m n l) with l = k + oversample.
        if self.kind == "topk":
            return self.m * self.n * max(1, self.k)
        if self.kind == "tall":
            return (2 * self.m * self.n * self.n) // 3
        return self.m * self.n * self.n


BucketSpec = Union[Bucket, Tuple, str]

# Weighted-fair-queueing charge reference: the classic 64x64 full
# bucket's per-sweep cost. A dequeue charges its tenant the routed
# bucket's cost over this, so "fair share" is fair in WORK, not request
# count — a tenant submitting big buckets spends its share faster than
# one submitting small ones.
_WFQ_REF_COST = 64 * 64 * 64


def admission_cost(bucket: Optional[Bucket]) -> float:
    """The WFQ charge of dequeuing one request routed to ``bucket``
    (`serve.queue.TenantTable.charge`). Floored at 1.0 so a tiny (or
    bucket-less rescue) request still spends a full dequeue — fairness
    must not be gameable by slicing work arbitrarily fine."""
    if bucket is None:
        return 1.0
    return max(1.0, bucket.cost / _WFQ_REF_COST)


def as_bucket(spec: BucketSpec) -> Bucket:
    """Coerce a bucket spec: a Bucket, an (m, n, dtype[, kind[, k]])
    tuple, or a string ``"MxN:dtype"`` / ``"MxN:dtype:tall"`` /
    ``"MxN:dtype:topkK"``."""
    if isinstance(spec, Bucket):
        b = spec
    elif isinstance(spec, str):
        try:
            parts = spec.split(":")
            dims, dtype = parts[0], parts[1]
            m, n = dims.split("x")
            kind, k = "full", 0
            if len(parts) == 3:
                fam = parts[2]
                if fam == "tall":
                    kind = "tall"
                elif fam.startswith("topk"):
                    kind, k = "topk", int(fam[len("topk"):])
                else:
                    raise ValueError(fam)
            elif len(parts) != 2:
                raise ValueError(spec)
            b = Bucket(int(m), int(n), dtype, kind, int(k))
        except (ValueError, IndexError):
            raise ValueError(
                f"bucket spec {spec!r} is not of the form 'MxN:dtype', "
                f"'MxN:dtype:tall' or 'MxN:dtype:topkK'")
    else:
        parts = tuple(spec)
        if len(parts) == 3:
            m, n, dtype = parts
            kind, k = "full", 0
        elif len(parts) == 4:
            m, n, dtype, kind = parts
            k = 0
        elif len(parts) == 5:
            m, n, dtype, kind, k = parts
        else:
            raise ValueError(f"bucket spec {spec!r}: expected "
                             f"(m, n, dtype[, kind[, k]])")
        b = Bucket(int(m), int(n), str(dtype), str(kind), int(k))
    import jax.numpy as jnp
    b = Bucket(b.m, b.n, str(jnp.dtype(b.dtype).name), b.kind, b.k)
    if b.n < 1 or b.m < b.n:
        raise ValueError(
            f"bucket {b.name}: buckets are tall, need m >= n >= 1 "
            f"(the service orients wide requests by transposition)")
    if b.kind not in BUCKET_KINDS:
        raise ValueError(f"bucket {b.name}: unknown kind {b.kind!r} "
                         f"(known: {BUCKET_KINDS})")
    if b.kind == "tall" and b.m < 8 * b.n:
        raise ValueError(
            f"bucket {b.name}: the tall family requires m >= 8n (below "
            f"that the TSQR lane does not pay; declare a 'full' bucket)")
    if b.kind == "topk" and not 1 <= b.k <= b.n:
        raise ValueError(
            f"bucket {b.name}: the topk family needs 1 <= k <= n, "
            f"got k={b.k}")
    if b.kind != "topk" and b.k:
        raise ValueError(f"bucket {b.name}: k is only meaningful on the "
                         f"topk family")
    return b


class BucketSet:
    """The declared bucket set, sorted by routing cost."""

    def __init__(self, buckets: Sequence[BucketSpec]):
        bs = [as_bucket(b) for b in buckets]
        if not bs:
            raise ValueError("a serving bucket set cannot be empty")
        if len(set(bs)) != len(bs):
            raise ValueError(f"duplicate buckets in {bs}")
        self.buckets: Tuple[Bucket, ...] = tuple(
            sorted(bs, key=lambda b: (b.cost, b.m, b.n, b.dtype, b.kind,
                                      b.k)))

    def __iter__(self):
        return iter(self.buckets)

    def __len__(self):
        return len(self.buckets)

    def route(self, m: int, n: int, dtype: str,
              top_k: Optional[int] = None) -> Optional[Bucket]:
        """Cheapest bucket holding a TALL-oriented (m >= n) request of
        exact dtype, or None (-> admission rejects with NO_BUCKET).

        ``top_k`` selects the family: None routes over the full/tall
        buckets (a tall bucket serves full factors — see module
        docstring); an int routes ONLY over topk buckets whose rank
        class covers it (``bucket.k >= top_k``), so the request's k can
        never widen a bucket's static sketch."""
        import jax.numpy as jnp
        dtype = str(jnp.dtype(dtype).name)
        for b in self.buckets:
            if b.dtype != dtype or b.m < m or b.n < n:
                continue
            if top_k is None:
                if b.kind in ("full", "tall"):
                    return b
            elif b.kind == "topk" and b.k >= top_k:
                return b
        return None

    def resolve_solver_configs(self, base) -> dict:
        """bucket -> concrete solver config, resolved through the active
        tuning table ONCE at declaration time (`tune.resolve_config`):
        every "auto"/None knob of ``base`` the table can pin shape-safely
        is pinned to the value the solver's own planner would resolve for
        the bucket's padded shape (topk buckets pass their rank class so
        the sketch knobs resolve through the k-class rows). The service
        stores this map and every dispatch path — lanes included — reads
        it instead of re-resolving per request; resolution being
        pure/deterministic, the pinned configs produce byte-identical jit
        keys to the auto path (the TUNE001 analysis pass proves no new
        retraces)."""
        from ..tune import tables
        return {b: tables.resolve_config(
                    base, m=b.m, n=b.n, dtype=b.dtype,
                    k=(b.k if b.kind == "topk" else None))
                for b in self.buckets}

    def resolved_batch_tiers(self) -> dict:
        """bucket -> coalescing tier tuple from the active tuning table
        (`ServeConfig.batch_tiers="auto"`): tiers are a measured knob —
        which batch sizes amortize the latency-bound rotation chain is
        backend-dependent (PROFILE.md item 22) — so the table rows carry
        them per (n-class, aspect, dtype, backend, device_kind).
        Resolved once at declaration, like the solver configs."""
        from ..tune import tables
        return {b: tuple(sorted(set(
            int(t) for t in tables.resolve(
                b.n, m=b.m, dtype=b.dtype,
                k=(b.k if b.kind == "topk" else None)).batch_tiers)))
                for b in self.buckets}

    @staticmethod
    def pad(a, bucket: Bucket):
        """Zero-pad a tall (m, n) array up to the bucket shape (exact for
        the SVD — see the module docstring)."""
        import jax.numpy as jnp
        m, n = a.shape
        if (m, n) == (bucket.m, bucket.n):
            return a
        return jnp.pad(a, ((0, bucket.m - m), (0, bucket.n - n)))
