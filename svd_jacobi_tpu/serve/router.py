"""Federated replica router — cross-process fault domains with rescue.

The robustness ladder so far hardens the solve (PR 3), the request
(PR 4), the lane (PR 6), and the process (PR 9) — but one `SVDService`
is still ONE fault domain: the reference's root-rank MPI design (one
process dies, the job is gone) reproduced at serving scale. This module
is the next ring up: a front-end `ReplicaRouter` federating N service
REPLICAS, giving them the exact supervision contract lanes already have
— eviction on outcome-caused sickness, journal-based rescue of a dead
replica's debt, outcome-caused probe recovery — one level up.

**Routing** — a consistent-hash ring (`HashRing`, SHA-256 positioned, so
placement is deterministic across processes and PYTHONHASHSEED) keyed by
``(bucket, input digest)``: a byte-identical resubmit computes the same
digest (`serve.cache.input_digest`, the `ResultCache` key ingredient)
and therefore lands on the replica that owns the cached result — the
admission fast-path stays a sub-millisecond hit even behind the router.
Requests without a digestable identity fall back to bucket affinity
(the ring keyed by bucket alone), quarantined replicas are failed over
in deterministic ring order, and when no replica is healthy the router
rejects loudly with `AdmissionReason.NO_REPLICA` — never a queue nobody
will pop. Overload rejections (QUEUE_FULL / DEADLINE_BUDGET / SHED) on
the owner also fail over: capacity elsewhere in the federation is the
point of having one.

**Replica fault domains** — every replica owns its OWN write-ahead
journal path, guarded by the journal's O_EXCL lockfile
(`serve.journal.JournalLockedError`): two live replicas can never
interleave fsync'd records into one path, so a dead replica's journal
is a complete, uncorrupted statement of its unfinalized debt. Replicas
come in two shapes behind one handle interface: **in-process**
(`LocalReplica` — an `SVDService` per replica, the test/default shape)
and **spool subprocess** (`SpoolReplica` — a real OS process driven
through an atomic-rename file spool, `run_spool_replica`; the chaos
drill SIGKILLs one of these for real).

**Supervision** — `ReplicaRouter`'s supervisor thread mirrors
`fleet.Fleet._tick` one fault-domain up, with the SAME two-tier
staleness verdict (`fleet.heartbeat_stale`): ``replica_dead`` (the
process/workers are gone), ``heartbeat_stale`` (no heartbeat within the
idle bound — or the longer step bound while busy in a device/compile
call — while holding work), ``bad_outcomes`` (consecutive
NONFINITE/ERROR results observed by the router), and
``breaker_stuck_open`` (every lane breaker OPEN across consecutive
healthz reads). Eviction **rescues**: the router breaks the dead
journal's lock (legitimate exactly because the supervisor has declared
the owner dead — `Journal.break_lock`'s contract), scans it under a
fresh exclusive lock, and re-admits the unfinalized debt at queue FRONT
on healthy replicas (ring-routed per record, remaining wall-clock
deadline budget intact) via `SVDService.admit_journal_debt` — which
write-ahead journals each rescued request on the RECEIVER before
enqueueing it, so a second crash replays it again. Exactly-once is the
existing composition: replay-skips-finalized + the receiver's
write-ahead admit + `Ticket._finalize_once`; rescued serve records
carry ``path="replica_rescue"``. Recovery is outcome-caused: a zero
solve probed through the replica's NORMAL dispatch path (respawning a
dead replica first) returns it to ACTIVE on success — no wall-clock
amnesty.

**Shared cold start** — every replica points at ONE persistent
compile-cache namespace (`ServeConfig.compile_cache_dir`): PR 9's
content-hash discipline (config + tuning-table + backend identity in
the namespace hash) makes concurrent multi-process sharing safe by
construction, so replica 2 warm-boots with ZERO fresh backend compiles
after replica 1 warmed — proven by the chaos drill's warm-boot
acceptance.

**Observability** — every transition / rescue / route / probe appends a
schema-versioned ``"router"`` manifest record (`obs.manifest
.build_router`, registered through the KINDS registry) to the same
stream as the per-request "serve" records; `ReplicaRouter.healthz()` is
the federated view (per-replica states, heartbeat ages, ring ownership,
rescue totals, per-replica /metrics listener addresses); with
``RouterConfig.metrics`` the router keeps live `MetricsRegistry` gauges
(``svdj_replica_state``, ``svdj_ring_owned_buckets``,
``svdj_replica_rescued_total``, routes/probes counters) that
`obs.registry.registry_from_manifest` reconstructs offline.

The `ROUTE001` analysis pass (`analysis.route_checks`) pins the two
load-bearing properties: routing is a pure function of (ring, bucket,
digest, replica states); and a rescue keeps the once-per-bucket compile
contract on the receiving replica under `RecompileGuard`.
"""

from __future__ import annotations

import bisect
import dataclasses
import enum
import hashlib
import itertools
import json
import os
import sys
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from .buckets import BucketSet
from .journal import Journal, host_boot_id
from .queue import AdmissionError, AdmissionReason
from .service import ServeConfig, ServeResult, SVDService

# Admission reasons that mean "this replica cannot take it right now,
# but a sibling might" — the router fails these over along the ring.
# Client-fault reasons (NO_BUCKET, NONFINITE_INPUT, UNKNOWN_TENANT)
# re-raise untouched: no replica can fix the request. RATE_LIMITED is
# deliberately NOT here either — each replica enforces the tenant's
# admits/s independently, so failing a rate-limited submit over would
# multiply the tenant's effective rate by the replica count (an
# adversarial tenant could farm the ring for free capacity).
_FAILOVER_REASONS = frozenset({
    AdmissionReason.SHUTDOWN, AdmissionReason.QUEUE_FULL,
    AdmissionReason.DEADLINE_BUDGET, AdmissionReason.BROWNOUT_SHED,
    AdmissionReason.NO_LANE,
})


class ReplicaState(enum.Enum):
    ACTIVE = "active"
    QUARANTINED = "quarantined"


class ReplicaUnavailable(RuntimeError):
    """A replica handle refused a submit because its backing service /
    process is gone (dead flag, no live workers). Router-internal: the
    submit path treats it like a SHUTDOWN rejection and fails over."""


# -- consistent-hash ring -----------------------------------------------------


class HashRing:
    """SHA-256-positioned consistent-hash ring over replica indices.

    Every replica contributes ``vnodes`` virtual points (hash of
    ``replica-<id>:vnode-<v>``); a request key (bucket name + input
    digest — or bucket name alone for the affinity fallback) hashes to a
    ring position, and `preference` walks clockwise from there returning
    each replica ONCE in first-encounter order: index 0 is the owner,
    the tail is the deterministic failover order. Pure function of the
    replica set — no clocks, no process state, no `hash()` (SHA-256
    makes placement identical across processes and PYTHONHASHSEED,
    which is what lets a restarted router, the analysis pass, and an
    offline reader all agree on who owned what)."""

    def __init__(self, replica_ids, vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.replica_ids = tuple(int(r) for r in replica_ids)
        if len(set(self.replica_ids)) != len(self.replica_ids):
            raise ValueError(f"duplicate replica ids: {self.replica_ids}")
        self.vnodes = int(vnodes)
        pts = []
        for rid in self.replica_ids:
            for v in range(self.vnodes):
                pts.append((self._h(f"replica-{rid}:vnode-{v}"), rid))
        pts.sort()
        self._points = pts
        self._hashes = [h for h, _ in pts]

    @staticmethod
    def _h(s: str) -> int:
        return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8],
                              "big")

    @classmethod
    def key(cls, bucket_name: str, digest: Optional[str] = None) -> int:
        """Ring position of one request identity: ``(bucket, digest)``
        for content-addressed placement (a byte-identical resubmit maps
        here again), bucket alone for the affinity fallback."""
        base = (str(bucket_name) if digest is None
                else f"{bucket_name}:{digest}")
        return cls._h(base)

    def preference(self, bucket_name: str,
                   digest: Optional[str] = None) -> Tuple[int, ...]:
        """Replica ids in deterministic ring-walk order from the key
        point (owner first, failovers after), each exactly once."""
        if not self._points:
            return ()
        k = self.key(bucket_name, digest)
        i = bisect.bisect_right(self._hashes, k)
        seen: List[int] = []
        for j in range(len(self._points)):
            rid = self._points[(i + j) % len(self._points)][1]
            if rid not in seen:
                seen.append(rid)
                if len(seen) == len(self.replica_ids):
                    break
        return tuple(seen)

    def owner(self, bucket_name: str,
              digest: Optional[str] = None) -> int:
        return self.preference(bucket_name, digest)[0]

    def ownership(self, bucket_names) -> Dict[str, int]:
        """bucket name -> owning replica (the affinity fallback view;
        healthz / the ring-ownership gauge render this)."""
        return {str(b): self.owner(str(b)) for b in bucket_names}


# -- spool codec (subprocess replicas) ---------------------------------------


def _write_json_atomic(path: Path, obj: dict) -> None:
    """tmp + rename: a reader (poller) either sees the whole file or no
    file — never a torn JSON (the spool protocol's one invariant)."""
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(obj, sort_keys=True))
    os.replace(tmp, path)


def _read_json(path: Path) -> Optional[dict]:
    try:
        return json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None


def _unlink_quiet(path: Path) -> None:
    try:
        path.unlink()
    except OSError:
        pass


def _encode_result(res: ServeResult) -> dict:
    """Outbox encoding of one terminal result (factors base64'd with the
    journal's checksummed array codec)."""
    from .journal import _encode_array
    out = {
        "id": res.request_id,
        "status": None if res.status is None else res.status.name,
        "error": res.error,
        "sweeps": int(res.sweeps),
        "bucket": res.bucket,
        "queue_wait_s": float(res.queue_wait_s),
        "solve_time_s": (None if res.solve_time_s is None
                         else float(res.solve_time_s)),
        "path": res.path,
        "degraded": bool(res.degraded),
    }
    for name, val in (("u", res.u), ("s", res.s), ("v", res.v)):
        out[name] = None if val is None else _encode_array(val)
    return out


def _decode_result(rec: dict) -> ServeResult:
    from ..solver import SolveStatus
    from .journal import decode_array
    factors = {}
    for name in ("u", "s", "v"):
        enc = rec.get(name)
        factors[name] = None if enc is None else decode_array(enc)
    if rec.get("transposed"):
        # The worker solved the ORIENTED array (the router transposed a
        # wide input and swapped the flags at encode time); undo the
        # orientation on the factors, exactly like `SVDService._slice`.
        factors["u"], factors["v"] = factors["v"], factors["u"]
    status = rec.get("status")
    return ServeResult(
        u=factors["u"], s=factors["s"], v=factors["v"],
        status=(None if status in (None, "ERROR")
                or status.startswith("REJECTED_")
                else SolveStatus[status]),
        error=rec.get("error"), sweeps=int(rec.get("sweeps", 0)),
        bucket=rec.get("bucket"),
        queue_wait_s=float(rec.get("queue_wait_s", 0.0)),
        solve_time_s=rec.get("solve_time_s"),
        path=str(rec.get("path", "base")),
        degraded=bool(rec.get("degraded", False)),
        request_id=str(rec.get("id", "?")))


# -- sub-ticket adapters ------------------------------------------------------


class _LocalSub:
    """Uniform poll surface over an in-process `Ticket`."""

    def __init__(self, ticket):
        self.ticket = ticket
        self.request_id = ticket.request_id

    def done(self) -> bool:
        return self.ticket.done()

    def poll(self, slice_s: float) -> Optional[ServeResult]:
        try:
            return self.ticket.result(timeout=slice_s)
        except TimeoutError:
            return None

    def cancel(self) -> None:
        self.ticket.cancel()

    def cleanup(self) -> None:
        pass


class _SpoolSub:
    """Uniform poll surface over a spool replica's outbox file."""

    def __init__(self, outbox_path: Path, request_id: str):
        self.path = Path(outbox_path)
        self.request_id = str(request_id)

    def done(self) -> bool:
        return self.path.exists()

    def poll(self, slice_s: float) -> Optional[ServeResult]:
        if not self.path.exists():
            time.sleep(min(slice_s, 0.02))
            if not self.path.exists():
                return None
        rec = _read_json(self.path)
        if rec is None:
            return None
        return _decode_result(rec)

    def cancel(self) -> None:
        # Best-effort only: cross-process cancellation is not part of
        # the spool protocol (the request's own deadline bounds it).
        pass

    def cleanup(self) -> None:
        """Unlink the consumed outbox file: a result can carry megabytes
        of base64 factors, and a long-running federation must not leak
        one file per served request."""
        try:
            self.path.unlink()
        except OSError:
            pass


class RouterTicket:
    """Client handle on one federated request: blocks on `result`,
    survives a mid-flight rescue (the router re-binds it to the rescued
    request's new replica — the client never learns its replica died),
    resolves EXACTLY once (first writer wins, mirroring
    `Ticket._finalize_once` at the router level). ``digest`` is the
    oriented-input SHA-256 the ring routed by — the resubmit key.
    ``tenant`` is the EXPLICIT tenant name the client submitted under
    ("default" when none — an api_token resolves on the replica, not
    here, so the router never learns the token map)."""

    def __init__(self, request_id: str, digest: Optional[str],
                 bucket: Optional[str], router=None,
                 tenant: str = "default"):
        self.request_id = str(request_id)
        self.digest = digest
        self.bucket = bucket
        self.tenant = str(tenant)
        self._router = router
        self._done = threading.Event()
        self._result: Optional[ServeResult] = None
        self._lock = threading.Lock()
        self._binding: Optional[tuple] = None   # (replica, sub)
        # Hard wall-clock bound (requests WITH a deadline only): past
        # ``_deadline_wall + _grace_s`` the client self-serves DEADLINE
        # instead of polling a blackholed replica forever — under a
        # network partition no outbox file / RPC reply may EVER come,
        # and the client's liveness must not depend on one.
        self._deadline_wall: Optional[float] = None
        self._grace_s: float = 15.0

    def _bind(self, replica, sub) -> None:
        with self._lock:
            self._binding = (replica, sub)

    def _resolve_once(self, result: ServeResult, replica=None) -> bool:
        with self._lock:
            if self._done.is_set():
                return False
            self._result = result
            self._done.set()
            binding = self._binding
        if binding is not None:
            binding[1].cleanup()    # e.g. unlink a consumed outbox file
        if self._router is not None:
            self._router._on_resolve(self, replica, result)
        return True

    def done(self) -> bool:
        if self._done.is_set():
            return True
        with self._lock:
            binding = self._binding
        if binding is not None and binding[1].done():
            res = binding[1].poll(0.0)
            if res is not None:
                self._resolve_once(res, binding[0])
        return self._done.is_set()

    def cancel(self) -> None:
        with self._lock:
            binding = self._binding
        if binding is not None:
            binding[1].cancel()

    def _past_wall(self) -> bool:
        return (self._deadline_wall is not None
                and time.time() > self._deadline_wall + self._grace_s)

    def _serve_wall_deadline(self) -> None:
        """Self-serve the DEADLINE verdict: the request's wall-clock
        budget (plus the rescue grace) is spent and the bound replica
        may be blackholed — a partition must degrade to a LOUD deadline,
        never to a client hung on a reply that cannot come."""
        from ..solver import SolveStatus
        self._resolve_once(ServeResult(
            u=None, s=None, v=None, status=SolveStatus.DEADLINE,
            error=None, sweeps=0, bucket=self.bucket,
            queue_wait_s=0.0, solve_time_s=None,
            path="client_deadline", degraded=True,
            request_id=self.request_id), None)

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            if self._done.is_set():
                return self._result
            if self._past_wall():
                self._serve_wall_deadline()
                continue
            with self._lock:
                binding = self._binding
            slice_s = 0.05
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"request {self.request_id} not terminal after "
                        f"{timeout}s")
                slice_s = min(slice_s, remaining)
            if binding is None:
                self._done.wait(slice_s)
                continue
            res = binding[1].poll(slice_s)
            if res is not None:
                self._resolve_once(res, binding[0])


# -- replica handles ----------------------------------------------------------


class ReplicaHandle:
    """The router's view of one replica: identity, health bookkeeping,
    and the submit/debt surfaces. Concrete shapes: `LocalReplica`
    (in-process `SVDService`) and `SpoolReplica` (a real subprocess
    behind an atomic-rename file spool)."""

    kind = "?"
    # Whether a FINALIZED result outlives the replica's death: a spool
    # outbox file or an in-process Ticket does, an HTTP replica's
    # in-memory result window does not — the rescue resolves such
    # finalized-but-unfetched requests loudly instead of leaving their
    # router tickets polling a host that can never answer.
    results_survive_death = True

    def __init__(self, index: int, journal_path):
        self.index = int(index)
        self.journal_path = str(journal_path)
        self.state = ReplicaState.ACTIVE
        self.generation = 0
        self.bad_streak = 0          # consecutive NONFINITE/ERROR results
        self.open_streak = 0         # consecutive all-breakers-OPEN reads
        self.rescued_off = 0
        self.routes = 0
        self.outstanding: set = set()     # rids currently bound here
        # Staleness-clock floor (monotonic): bumped when the ROUTER
        # hands this replica work out-of-band (rescued debt). An idle
        # replica legitimately stops beating; the moment re-homed debt
        # makes it `holds_work()`, its heartbeat age must be measured
        # from the hand-off, not from the idle era — otherwise the
        # supervisor evicts the rescue target on the very next tick.
        self.hb_floor = time.monotonic()
        self.last_probe = 0.0
        self.last_respawn = 0.0
        self.probe_sub = None
        self.probe_rid: Optional[str] = None
        self.transitions: List[tuple] = []
        self._created = time.monotonic()

    # -- interface ----------------------------------------------------------
    def start(self) -> None: ...
    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None: ...
    def submit(self, a, **kw): ...
    def admit_debt(self, records, *, fence_token: Optional[int] = None,
                   fence_domain: Optional[str] = None) -> Dict[str, Any]:
        ...
    def alive(self) -> bool: ...
    def heartbeat_age(self, now: float) -> float: ...
    def busy(self) -> bool: ...
    def holds_work(self) -> bool: ...
    def healthz(self) -> Optional[dict]: ...
    def respawn(self) -> None: ...
    def fence(self, token: Optional[int] = None) -> Optional[int]: ...
    def quiesce(self, timeout: float = 2.0) -> None: ...

    def death_cause(self) -> str:
        """Why `alive()` is False, as an eviction-cause label. The
        network transport distinguishes ``lease_expired`` (partitioned
        OR dead — the fencing token makes acting on it safe) and
        ``replica_fenced`` from plain process death."""
        return "replica_dead"

    def lease_until(self, now: float) -> Optional[float]:
        """Monotonic expiry of an unexpired liveness lease (the network
        transport's promise — `fleet.heartbeat_stale` trusts it over
        the heartbeat age); None when this transport has no leases."""
        return None

    def unconsumed_debt(self, exclude) -> List[dict]:
        """Transport-level write-ahead records the replica accepted but
        never journaled (only the spool transport has such a seam — an
        in-process submit IS the journal append)."""
        return []

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        hz = None
        try:
            hz = self.healthz()
        except Exception:
            pass
        return {
            "replica": self.index,
            "kind": self.kind,
            "state": self.state.value,
            "alive": bool(self.alive()),
            "heartbeat_age_s": self.heartbeat_age(now),
            "busy": bool(self.busy()),
            "holds_work": bool(self.holds_work()),
            "bad_streak": self.bad_streak,
            "open_streak": self.open_streak,
            "routes": self.routes,
            "rescued_off": self.rescued_off,
            "outstanding": len(self.outstanding),
            "journal": self.journal_path,
            "http": None if not isinstance(hz, dict) else hz.get("http"),
        }


class LocalReplica(ReplicaHandle):
    """One in-process `SVDService` as a replica fault domain. Death is
    simulated (`chaos.kill_replica` -> `_chaos_kill`: workers exit
    without serving or finalizing, queued requests stay as journal
    debt, the journal lock stays held — everything a SIGKILL strands,
    minus the ability to interrupt a solve already inside the device);
    the REAL process-loss shape is `SpoolReplica` + the subprocess
    drill. `respawn` builds a fresh service on the same per-replica
    config (breaking the dead one's journal lock first, replaying
    whatever debt the rescue left behind)."""

    kind = "local"

    def __init__(self, index: int, config: ServeConfig, *,
                 respawn_warmup: bool = False):
        if config.journal_path is None:
            raise ValueError("a LocalReplica needs its own journal_path "
                             "(the rescue contract reads it)")
        super().__init__(index, config.journal_path)
        self.config = config
        self.respawn_warmup = bool(respawn_warmup)
        self.dead = False
        self._died_at = 0.0
        self._frozen_at: Optional[float] = None    # wedge: frozen heartbeat
        self._frozen_until = 0.0
        self.service = SVDService(config)

    def start(self) -> None:
        self.service.start()

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        if not self.dead:
            self.service.stop(drain=drain, timeout=timeout)

    def submit(self, a, **kw):
        if self.dead:
            raise ReplicaUnavailable(
                f"replica {self.index} is dead (simulated process loss)")
        return _LocalSub(self.service.submit(a, **kw))

    def admit_debt(self, records, *, fence_token: Optional[int] = None,
                   fence_domain: Optional[str] = None) -> Dict[str, Any]:
        if self.dead:
            raise ReplicaUnavailable(f"replica {self.index} is dead")
        tickets = self.service.admit_journal_debt(
            records, fence_token=fence_token, fence_domain=fence_domain)
        return {rid: _LocalSub(t) for rid, t in tickets.items()}

    def freeze_heartbeat(self, wedge_s: float) -> None:
        """`chaos.wedge_replica`: the router-visible heartbeat freezes
        for ``wedge_s`` (the service underneath keeps running — the
        woken-wedge first-writer-wins discipline applies)."""
        now = time.monotonic()
        self._frozen_at = now
        self._frozen_until = now + float(wedge_s)

    def _heartbeat(self) -> float:
        now = time.monotonic()
        if self._frozen_at is not None:
            if now < self._frozen_until:
                return self._frozen_at
            self._frozen_at = None
        if self.dead:
            return self._died_at
        return max(l.heartbeat for l in self.service.fleet.lanes)

    def heartbeat_age(self, now: float) -> float:
        return now - self._heartbeat()

    def alive(self) -> bool:
        if self.dead:
            return False
        return any(l.thread is not None and l.thread.is_alive()
                   for l in self.service.fleet.lanes)

    def busy(self) -> bool:
        return (not self.dead
                and any(l.in_step for l in self.service.fleet.lanes))

    def holds_work(self) -> bool:
        if self.outstanding:
            return True
        if self.dead:
            return False
        return any(l.in_flight or l.queue.depth() > 0
                   for l in self.service.fleet.lanes)

    def healthz(self) -> Optional[dict]:
        return None if self.dead else self.service.healthz()

    def simulate_kill(self) -> None:
        """The in-process SIGKILL twin (consumed from
        `chaos.kill_replica` by the router's submit path, or called
        directly by tests)."""
        if self.dead:
            return
        self.dead = True
        self._died_at = time.monotonic()
        self.service._chaos_kill()

    def fence(self, token: Optional[int] = None) -> Optional[int]:
        """STONITH before rescue: an alive-but-sick replica (stale
        heartbeat, bad outcomes, stuck breaker) is hard-stopped so it
        cannot keep serving requests whose debt the rescue is about to
        re-home — without the fence, everything it still held would be
        double-served and its journal rewritten under a live writer.
        ``token`` is the fencing token the rescuer minted (unused here:
        an in-process kill is synchronous and cannot race the rescue
        the way a partitioned remote process can)."""
        self.simulate_kill()
        return token

    def quiesce(self, timeout: float = 2.0) -> None:
        """Bounded wait for the dead service's workers to reach their
        exits, so the rescue's journal scan sees every finalize a
        mid-solve worker still managed to append."""
        deadline = time.monotonic() + timeout
        for lane in self.service.fleet.lanes:
            t = lane.thread
            if t is not None:
                t.join(max(0.0, deadline - time.monotonic()))

    def respawn(self) -> None:
        """Fresh service, same fault domain: break the dead service's
        journal lock (a SIGKILL'd owner released nothing), replay the
        journal's remaining debt, start. The shared compile-cache
        namespace makes this warm — the PR 9 property the federation
        inherits."""
        Journal.break_lock(self.journal_path)
        svc = SVDService(self.config)
        svc.recover()
        svc.start()
        if self.respawn_warmup:
            svc.warmup(timeout=600.0)
        self.service = svc
        self.dead = False
        self._frozen_at = None
        self.generation += 1


class SpoolReplica(ReplicaHandle):
    """A real-subprocess replica behind an atomic-rename file spool
    (`run_spool_replica` is the process's serve loop):

      * ``<spool>/inbox/<rid>.json``  — router -> replica: one submit
        (journal-codec input payload + flags + wall-clock deadline), a
        rescue debt batch, or a stop command;
      * ``<spool>/outbox/<rid>.json`` — replica -> router: one terminal
        result (status + factors, journal codec);
      * ``<spool>/heartbeat.json``    — replica -> router: liveness (pid
        + boot id + busy/holds_work + a trimmed healthz snapshot incl.
        the REAL metrics listener port), rewritten every loop turn.

    The router never shares memory with it — SIGKILL the process and
    everything the drill needs (journal, lockfile, spool) is on disk.
    ``respawn`` is delegated to the harness (a process supervisor in
    production, the test in the drill) via the ``respawn_cmd``
    callable."""

    kind = "spool"

    def __init__(self, index: int, spool_dir, journal_path, *,
                 respawn_cmd=None):
        super().__init__(index, journal_path)
        self.spool = Path(spool_dir)
        self.inbox = self.spool / "inbox"
        self.outbox = self.spool / "outbox"
        self.heartbeat_path = self.spool / "heartbeat.json"
        self.inbox.mkdir(parents=True, exist_ok=True)
        self.outbox.mkdir(parents=True, exist_ok=True)
        self._respawn_cmd = respawn_cmd
        self._hb_cache: dict = {}
        self._hb_read = 0.0

    def start(self) -> None:
        pass    # the process is started by the harness

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        _write_json_atomic(self.inbox / "zz-stop.json", {"kind": "stop"})

    def _hb(self) -> dict:
        now = time.monotonic()
        if now - self._hb_read > 0.02:
            self._hb_cache = _read_json(self.heartbeat_path) or {}
            self._hb_read = now
        return self._hb_cache

    def submit(self, a, *, compute_u=True, compute_v=True,
               deadline_s=None, request_id=None, top_k=None,
               phase="full", digest=None, tenant=None, api_token=None):
        """Write one ADMIT-SHAPED submit record into the inbox: the
        record carries the oriented payload plus the full journal-admit
        field set, so an inbox file the replica never got to consume is
        itself a complete write-ahead record the rescue can re-home
        (`unconsumed_debt`) — the spool seam closes the durability hole
        between 'the router handed it over' and 'the replica journaled
        it'. Orientation happens HERE (flags swapped for wide inputs);
        the worker submits the oriented array verbatim and the result
        decode swaps the factors back (`_decode_result`)."""
        import numpy as _np

        from .journal import _encode_array
        if not self.alive():
            raise ReplicaUnavailable(
                f"spool replica {self.index} has no live process")
        rid = str(request_id)
        a = _np.asarray(a)
        transposed = a.ndim == 2 and a.shape[0] < a.shape[1]
        oriented = a.T if transposed else a
        if transposed:
            compute_u, compute_v = compute_v, compute_u
        m, n = (int(d) for d in oriented.shape)
        rec = {
            "kind": "submit", "id": rid, "t_wall": time.time(),
            "attempt": 1,
            "deadline_s": (None if deadline_s is None
                           else float(deadline_s)),
            "m": m, "n": n,
            "orig_shape": [int(d) for d in a.shape],
            "transposed": bool(transposed),
            "bucket": None,
            "compute_u": bool(compute_u), "compute_v": bool(compute_v),
            "degraded": False, "brownout": "FULL",
            "top_k": None if top_k is None else int(top_k),
            "phase": str(phase),
            "input": _encode_array(oriented, digest=digest),
        }
        if tenant is not None:
            rec["tenant"] = str(tenant)
        if api_token is not None:
            rec["api_token"] = str(api_token)
        _write_json_atomic(self.inbox / f"{rid}.json", rec)
        return _SpoolSub(self.outbox / f"{rid}.json", rid)

    def unconsumed_debt(self, exclude) -> List[dict]:
        """The spool seam's durability tail, collected at rescue time:
        submit records (and rescue batches) still sitting UNCONSUMED in
        the dead replica's inbox. Each is admit-shaped by construction,
        so the rescue re-homes them exactly like journal debt; consumed
        files are removed (the replica is fenced — the rescuer owns its
        spool). ``exclude`` holds ids the journal already accounts for
        (admitted or finalized there — the journal wins: it is further
        along the pipeline)."""
        out: List[dict] = []
        seen = set(exclude)
        for f in sorted(self.inbox.glob("*.json")):
            rec = _read_json(f)
            if rec is None:
                continue
            kind = rec.get("kind")
            recs = []
            if kind == "submit":
                recs = [rec]
            elif kind == "debt":
                recs = list(rec.get("records") or ())
            else:
                continue      # fences/stops are not debt
            for r in recs:
                rid = str(r.get("id"))
                if rid in seen or rid.startswith("probe-"):
                    continue
                seen.add(rid)
                out.append(r)
            try:
                f.unlink()
            except OSError:
                pass
        return out

    def admit_debt(self, records, *, fence_token: Optional[int] = None,
                   fence_domain: Optional[str] = None) -> Dict[str, Any]:
        name = f"00-debt-{time.time_ns()}.json"
        _write_json_atomic(self.inbox / name,
                           {"kind": "debt", "records": list(records),
                            "fence_token": fence_token,
                            "fence_domain": fence_domain})
        return {rec["id"]: _SpoolSub(self.outbox / f"{rec['id']}.json",
                                     rec["id"])
                for rec in records}

    def alive(self) -> bool:
        hb = self._hb()
        pid = hb.get("pid")
        if not isinstance(pid, int):
            # Not yet booted: alive-by-grace (the supervisor's staleness
            # clock, seeded at handle creation, bounds the grace).
            return True
        if hb.get("boot_id") not in (None, host_boot_id()):
            return False
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except OSError:
            return True
        return True

    def heartbeat_age(self, now: float) -> float:
        hb = self._hb()
        t = hb.get("t_wall")
        if not isinstance(t, (int, float)):
            return now - self._created
        # Wall-clock heartbeat (monotonic clocks do not cross process
        # boundaries); ages compare against wall time.
        return max(0.0, time.time() - float(t))

    def busy(self) -> bool:
        return bool(self._hb().get("busy"))

    def holds_work(self) -> bool:
        return bool(self.outstanding) or bool(self._hb().get("holds_work"))

    def healthz(self) -> Optional[dict]:
        return self._hb().get("healthz")

    def respawn(self) -> None:
        if self._respawn_cmd is None:
            return    # the harness owns process lifecycle
        self._respawn_cmd()
        self._hb_cache, self._hb_read = {}, 0.0
        self._created = time.monotonic()
        self.generation += 1

    def fence(self, token: Optional[int] = None) -> Optional[int]:
        """STONITH before rescue: tell a possibly-still-alive replica
        process to exit IMMEDIATELY without serving anything else (the
        spool loop `os._exit`s on the fence command — SIGKILL semantics,
        queued work stays as journal debt). A no-op for a process that
        is already gone: the fence file just sits in the inbox, and a
        RESPAWNED replica consumes-and-ignores any fence older than its
        own boot. ``token`` is the rescuer's fencing token, carried for
        the audit trail (the spool transport shares a filesystem, so
        the token FILE next to the journal is what a comeback reads)."""
        _write_json_atomic(self.inbox / "000-fence.json",
                           {"kind": "fence", "t_wall": time.time(),
                            "token": token})
        return token

    def quiesce(self, timeout: float = 2.0) -> None:
        """Bounded wait for the fenced process to actually be gone
        (pid-liveness via the heartbeat), so the journal scan cannot
        race a final append."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and self.alive():
            self._hb_read = 0.0      # force a fresh heartbeat read
            time.sleep(0.05)


# -- the router ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Federation-layer configuration (each replica's own knobs ride in
    ``serve``; ``journal_path`` / ``metrics_port`` there are PER-REPLICA
    and derived — give the template None / 0)."""

    replicas: int = 2
    serve: ServeConfig = ServeConfig()
    # Root of the per-replica state: replica i's journal lives at
    # ``<state_dir>/replica-<i>/journal.jsonl`` (its own fault domain's
    # write-ahead log — the rescue contract reads exactly this path).
    state_dir: Optional[str] = None
    ring_vnodes: int = 64
    # Two-tier replica staleness (the lane supervisor's verdict, one
    # ring up — `fleet.heartbeat_stale`).
    heartbeat_timeout_s: float = 2.0
    step_timeout_s: float = 300.0
    # Evict after this many consecutive NONFINITE/ERROR results the
    # router observed from one replica.
    failure_threshold: int = 3
    # Evict after this many consecutive healthz reads with EVERY lane
    # breaker OPEN (the replica's own ladder is not healing it).
    open_threshold: int = 4
    supervise_interval_s: float = 0.05
    probe_interval_s: float = 0.25
    probe_timeout_s: float = 60.0
    # Minimum spacing between respawn attempts of one dead replica: a
    # respawned process needs boot time (runtime import + cache-warm)
    # before its heartbeat proves it alive, and re-respawning every
    # probe interval meanwhile would spawn a storm of workers fighting
    # over one journal lock.
    respawn_grace_s: float = 45.0
    # Warm a respawned local replica's registry before ACTIVE probing
    # (cheap when the shared compile cache is hot; the drill proves 0
    # fresh compiles).
    respawn_warmup: bool = False
    # Client-side hard wall: a request with a deadline resolves (DEADLINE,
    # loudly) at most this long AFTER its deadline expired, even when its
    # replica is blackholed and no result file / RPC will ever answer —
    # `RouterTicket.result` self-serves the verdict. The grace covers the
    # rescue path (re-homed debt still finishing near the deadline).
    client_grace_s: float = 15.0
    manifest_path: Optional[str] = None
    max_records: int = 2048
    metrics: bool = False


class ReplicaRouter:
    """Front-end federating N `SVDService` replicas (module docstring).

    Build with in-process replicas (the default: ``RouterConfig.serve``
    templated per replica under ``state_dir``) or hand in pre-built
    handles (the subprocess drill passes `SpoolReplica`s)::

        router = ReplicaRouter(RouterConfig(replicas=2,
                                            state_dir=tmp)).start()
        t = router.submit(a, deadline_s=5.0)
        res = t.result(timeout=60.0)
        router.stop()
    """

    def __init__(self, config: RouterConfig = RouterConfig(),
                 replicas: Optional[List[ReplicaHandle]] = None):
        if config.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got "
                             f"{config.replicas}")
        self.config = config
        self.buckets = BucketSet(config.serve.buckets)
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._probe_seq = itertools.count()
        self._records: list = []
        self._stats: dict = {}
        self._outstanding: Dict[str, RouterTicket] = {}
        self._accepting = False
        self.total_rescues = 0
        if replicas is not None:
            self.replicas = list(replicas)
        else:
            if config.state_dir is None:
                raise ValueError("RouterConfig.state_dir is required for "
                                 "router-built local replicas (their "
                                 "per-replica journals live there)")
            self.replicas = [
                LocalReplica(i, self._replica_config(i),
                             respawn_warmup=config.respawn_warmup)
                for i in range(config.replicas)]
        self.ring = HashRing([r.index for r in self.replicas],
                             vnodes=config.ring_vnodes)
        self._stop = threading.Event()
        self._sup_thread: Optional[threading.Thread] = None
        # Live federation gauges (None when off — free-when-off, the
        # OBS002 discipline).
        self.metrics = None
        if config.metrics:
            from ..obs.registry import MetricsRegistry
            self.metrics = MetricsRegistry()
            self.metrics.add_collector(self._collect_metrics)
        # The federated /metrics + /healthz listener (start_http).
        self._http = None
        self._http_addr: Optional[Tuple[str, int]] = None

    def _replica_config(self, index: int) -> ServeConfig:
        """Replica ``index``'s ServeConfig: the template with a
        PER-REPLICA journal path (its own fault domain), digesting on
        (the ring and resubmit keys need it), an ephemeral metrics port
        when a fixed one was asked (N replicas on one host must not
        collide — the real port is in healthz), and the SHARED
        compile-cache namespace left exactly as the template says (the
        whole point: one namespace, N replicas, PR 9's content hash
        makes it safe)."""
        cfg = self.config
        rdir = Path(cfg.state_dir) / f"replica-{index}"
        port = cfg.serve.metrics_port
        return dataclasses.replace(
            cfg.serve,
            journal_path=str(rdir / "journal.jsonl"),
            compute_digest=True,
            manifest_path=cfg.manifest_path,
            metrics_port=(0 if port is not None else None))

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ReplicaRouter":
        for r in self.replicas:
            r.start()
        self._accepting = True
        self._stop.clear()
        self._sup_thread = threading.Thread(
            target=self._supervise, name="svdj-router-supervisor",
            daemon=True)
        self._sup_thread.start()
        return self

    def warmup(self, timeout: float = 600.0) -> None:
        """Warm every LOCAL replica's registry (spool replicas warm
        themselves at boot). Sequential on purpose: replica 0 populates
        the shared persistent cache, replicas 1..N-1 then warm from
        cache hits — the shared-cold-start property, observable in each
        replica's coldstart record."""
        for r in self.replicas:
            if isinstance(r, LocalReplica):
                r.service.warmup(timeout=timeout)

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        self._accepting = False
        self.stop_http()
        self._stop.set()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout)
        for r in self.replicas:
            try:
                r.stop(drain=drain, timeout=timeout)
            except Exception as e:
                print(f"svdj-router: replica {r.index} stop failed: {e}",
                      file=sys.stderr)

    def __enter__(self) -> "ReplicaRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=False, timeout=10.0)

    # -- admission / routing ------------------------------------------------

    def submit(self, a, *, compute_u: bool = True, compute_v: bool = True,
               deadline_s: Optional[float] = None,
               request_id: Optional[str] = None,
               top_k: Optional[int] = None,
               phase: str = "full",
               tenant: Optional[str] = None,
               api_token: Optional[str] = None) -> RouterTicket:
        """Admit one request into the federation: route by the
        consistent-hash ring — ``(bucket, digest)`` so byte-identical
        resubmits hit the replica owning the cached result — failing
        over past quarantined/refusing replicas in deterministic ring
        order, or raise `AdmissionError` (``NO_REPLICA`` when the whole
        federation is down; client-fault reasons re-raised from the
        replica untouched). ``tenant``/``api_token`` pass through to
        the replica verbatim and resolve THERE — and per-tenant QoS
        rejections (RATE_LIMITED, UNKNOWN_TENANT) are NOT failover
        reasons: failing a rate-limited request over the ring would
        multiply the tenant's admitted rate by the replica count."""
        import numpy as _np

        from ..resilience import chaos
        from .cache import input_digest
        if not self._accepting:
            raise AdmissionError(AdmissionReason.SHUTDOWN,
                                 "router is not accepting requests")
        a = _np.asarray(a)
        if a.ndim != 2:
            raise ValueError(f"expected a 2-D matrix, got shape {a.shape}")
        transposed = a.shape[0] < a.shape[1]
        oriented = a.T if transposed else a
        m, n = (int(d) for d in oriented.shape)
        tk = None if top_k is None else min(int(top_k), min(m, n))
        bucket = self.buckets.route(m, n, str(oriented.dtype), top_k=tk)
        if bucket is None:
            raise AdmissionError(
                AdmissionReason.NO_BUCKET,
                f"shape {tuple(a.shape)} dtype {a.dtype} fits no declared "
                f"bucket {[b.name for b in self.buckets]}")
        digest = input_digest(oriented)
        rid = request_id or f"fed-{next(self._seq):05d}"
        pref = self.ring.preference(bucket.name, digest)
        last: Optional[AdmissionError] = None
        for idx in pref:
            replica = self._replica(idx)
            if replica is None or replica.state is not ReplicaState.ACTIVE:
                continue
            # Consume a fault shot only when THIS handle can act on it
            # (the in-process simulations; a SpoolReplica's process is
            # killed/wedged by the harness for real) — consuming first
            # would silently swallow a shot aimed at a spool replica.
            if isinstance(replica, LocalReplica):
                wedge = chaos.consume_replica_wedge(idx)
                if wedge is not None:
                    replica.freeze_heartbeat(wedge)
            try:
                sub = replica.submit(
                    a, compute_u=compute_u, compute_v=compute_v,
                    deadline_s=deadline_s, request_id=rid, top_k=top_k,
                    phase=phase, digest=digest, tenant=tenant,
                    api_token=api_token)
            except ReplicaUnavailable as e:
                last = AdmissionError(AdmissionReason.SHUTDOWN, str(e))
                continue
            except AdmissionError as e:
                if e.reason in _FAILOVER_REASONS:
                    last = e
                    continue
                raise    # client fault: no replica can fix the request
            tenant_label = "default" if tenant is None else str(tenant)
            ticket = RouterTicket(rid, digest, bucket.name, router=self,
                                  tenant=tenant_label)
            if deadline_s is not None and deadline_s != float("inf"):
                ticket._deadline_wall = time.time() + float(deadline_s)
                ticket._grace_s = self.config.client_grace_s
            ticket._bind(replica, sub)
            with self._lock:
                self._outstanding[rid] = ticket
                replica.outstanding.add(rid)
                replica.routes += 1
            self._bump("routed", f"replica:{idx}")
            if self.metrics is not None:
                self.metrics.inc("svdj_router_routes_total",
                                 replica=idx, bucket=bucket.name,
                                 tenant=tenant_label,
                                 help="requests routed to a replica")
            self._record(event="route", replica=idx, request_id=rid,
                         bucket=bucket.name, digest=digest,
                         owner=pref[0], failover=(idx != pref[0]),
                         tenant=tenant_label)
            # Armed replica death fires AFTER the submit landed (the
            # request is write-ahead journaled on the replica): the
            # durable state the rescue replays is exactly "this request
            # was admitted when the replica died". Only a LocalReplica
            # consumes the shot (see the wedge consumption above).
            if (isinstance(replica, LocalReplica)
                    and chaos.consume_replica_kill(idx)):
                replica.simulate_kill()
            return ticket
        if last is not None:
            raise last
        raise AdmissionError(
            AdmissionReason.NO_REPLICA,
            f"all {len(self.replicas)} replicas are quarantined/dead")

    def _replica(self, index: int) -> Optional[ReplicaHandle]:
        for r in self.replicas:
            if r.index == index:
                return r
        return None

    def _on_resolve(self, ticket: RouterTicket, replica,
                    result: ServeResult) -> None:
        """Outcome bookkeeping at router level (mirrors
        `Lane.note_outcome`): consecutive NONFINITE/ERROR results from
        one replica are its bad-outcome eviction ladder."""
        with self._lock:
            self._outstanding.pop(ticket.request_id, None)
            if replica is not None:
                replica.outstanding.discard(ticket.request_id)
                status = (result.status.name
                          if result.status is not None else "ERROR")
                if result.error is not None or status in ("NONFINITE",
                                                          "ERROR"):
                    replica.bad_streak += 1
                else:
                    replica.bad_streak = 0
        name = ("ERROR" if result.error is not None
                else result.status.name if result.status is not None
                else "?")
        self._bump(f"resolved:{name}")

    # -- supervision --------------------------------------------------------

    def _supervise(self) -> None:
        interval = self.config.supervise_interval_s
        while not self._stop.wait(interval):
            try:
                self._tick()
            except Exception as e:    # the supervisor must outlive surprises
                print(f"svdj-router: supervisor tick failed: {e}",
                      file=sys.stderr)

    def _tick(self, now: Optional[float] = None) -> None:
        from .fleet import heartbeat_stale
        cfg = self.config
        now = time.monotonic() if now is None else now
        for replica in self.replicas:
            if replica.state is ReplicaState.ACTIVE:
                cause = None
                if not replica.alive():
                    cause = replica.death_cause()
                elif heartbeat_stale(
                        now, now - min(replica.heartbeat_age(now),
                                       now - replica.hb_floor),
                        busy=replica.busy(),
                        holds_work=replica.holds_work(),
                        idle_timeout_s=cfg.heartbeat_timeout_s,
                        busy_timeout_s=cfg.step_timeout_s,
                        lease_until=replica.lease_until(now)):
                    cause = "heartbeat_stale"
                elif replica.bad_streak >= cfg.failure_threshold:
                    cause = "bad_outcomes"
                else:
                    cause = self._breaker_verdict(replica)
                if cause is not None:
                    self.evict(replica, cause)
            elif self._accepting:
                self._probe(replica, now)

    def _breaker_verdict(self, replica: ReplicaHandle) -> Optional[str]:
        """breaker_stuck_open, surfaced through healthz: every lane
        breaker OPEN across `open_threshold` consecutive reads means the
        replica's own escalation ladder is not healing it."""
        try:
            hz = replica.healthz()
        except Exception:
            return None
        if not isinstance(hz, dict):
            return None
        lanes = (hz.get("fleet") or {}).get("lanes") or []
        breakers = [l.get("breaker") for l in lanes]
        if breakers and all(b == "open" for b in breakers):
            replica.open_streak += 1
        else:
            replica.open_streak = 0
        if replica.open_streak >= self.config.open_threshold:
            return "breaker_stuck_open"
        return None

    def evict(self, replica: ReplicaHandle, cause: str) -> None:
        """Quarantine a sick replica and rescue its journal debt.
        Idempotent; mirrors `fleet.Fleet.evict` one fault-domain up."""
        with self._lock:
            if replica.state is not ReplicaState.ACTIVE:
                return
            replica.state = ReplicaState.QUARANTINED
            replica.generation += 1
            replica.bad_streak = 0
            replica.open_streak = 0
            # Probe clock starts AT eviction (never an instant probe in
            # the same tick as the rescue).
            replica.last_probe = time.monotonic()
            replica.probe_sub = None
        replica.transitions.append(("active", "quarantined", cause))
        self._bump("evictions", f"evict_cause:{cause}")
        if self.metrics is not None:
            self.metrics.inc("svdj_replica_transitions_total",
                             replica=replica.index,
                             to_state="quarantined",
                             help="replica state transitions")
        self._record(event="replica_transition", replica=replica.index,
                     from_state="active", to_state="quarantined",
                     cause=cause)
        try:
            self._rescue(replica, cause)
        except Exception as e:
            # A failed rescue must be LOUD but must not kill the
            # supervisor: the debt stays in the dead journal for the
            # next attempt (probe-restore or operator).
            self._bump("rescue_errors")
            self._record(event="rescue", replica=replica.index,
                         cause=cause, count=0, request_ids=[],
                         targets=[], error=f"{type(e).__name__}: {e}")
            print(f"svdj-router: rescue of replica {replica.index} "
                  f"failed: {e}", file=sys.stderr)
        self._record(event="healthz", replica=None,
                     healthz=self.healthz(probe_replicas=False))

    def _rescue(self, replica: ReplicaHandle, cause: str) -> None:
        """Replica-death rescue (module docstring): break the dead
        journal's lock — legitimate exactly HERE, after the supervisor
        declared the owner dead — scan it exclusively, re-admit the
        unfinalized debt ring-routed onto healthy replicas at queue
        FRONT (`SVDService.admit_journal_debt`, write-ahead on the
        receiver), re-bind the outstanding router tickets, and compact
        the dead journal to empty. A record with no healthy target
        resolves ERROR loudly, never silently."""
        # FENCE first (STONITH): a replica evicted while its process is
        # still alive — stale heartbeat, bad outcomes, stuck breaker —
        # must stop serving BEFORE its journal is stolen, or everything
        # it still holds is double-served under a rewritten journal.
        # Already-dead replicas ignore the fence by construction. The
        # fencing TOKEN is minted before anything else: a partitioned
        # replica that never hears the fence RPC still finds the bumped
        # token on disk and self-fences, and a racing second rescuer's
        # older token is refused by every debt receiver
        # (`SVDService.admit_journal_debt` -> `StaleFenceError`).
        from .journal import bump_fence_token
        fence_token = bump_fence_token(
            replica.journal_path,
            minted_by=f"router-rescue-{replica.index}")
        replica.fence(fence_token)
        replica.quiesce(timeout=3.0)
        # force=True: this IS the fenced cross-host path — the token
        # bump above is the authorization `break_lock` asks for before
        # it will touch a lock minted on another host.
        Journal.break_lock(replica.journal_path, force=True)
        j = Journal(replica.journal_path, exclusive=True)
        moved: List[str] = []
        targets_used: List[int] = []
        try:
            with j.exclusive():
                state = j.scan()
                debt = [rec for rec in state.unfinalized
                        if not str(rec["id"]).startswith("probe-")]
                # The transport seam's durability tail: admit-shaped
                # records the dead replica ACCEPTED (atomic inbox
                # rename) but never journaled are debt too — the
                # journal wins on any id it already accounts for.
                debt += replica.unconsumed_debt(
                    set(state.admits) | set(state.finalized))
                groups: Dict[int, List[dict]] = {}
                orphans: List[dict] = []
                for rec in debt:
                    digest = (rec.get("input") or {}).get("data_sha256")
                    target = None
                    for idx in self.ring.preference(
                            str(rec.get("bucket")), digest):
                        cand = self._replica(idx)
                        if (cand is not None and cand is not replica
                                and cand.state is ReplicaState.ACTIVE
                                and cand.alive()):
                            target = cand
                            break
                    if target is None:
                        orphans.append(rec)
                    else:
                        groups.setdefault(target.index, []).append(rec)
                for idx, recs in groups.items():
                    target = self._replica(idx)
                    subs = target.admit_debt(
                        recs, fence_token=fence_token,
                        fence_domain=replica.journal_path)
                    # The admit answered: the target is alive RIGHT NOW.
                    # Restart its staleness clock — see `hb_floor`.
                    target.hb_floor = time.monotonic()
                    targets_used.append(idx)
                    for rec in recs:
                        rid = rec["id"]
                        moved.append(rid)
                        # graftlock: ok(journal->router inversion is rescue-only — the journal here belongs to the fenced+quiesced dead replica, so no live path can hold the router lock while waiting on it; rebinding must stay inside the exclusive section so a crashed rescue replays cleanly)
                        with self._lock:
                            rt = self._outstanding.get(rid)
                            replica.outstanding.discard(rid)
                            if rt is not None and rid in subs:
                                target.outstanding.add(rid)
                        if rt is not None and rid in subs:
                            rt._bind(target, subs[rid])
                lost: List[str] = []
                if not replica.results_survive_death:
                    # Finalized on the dead replica, result never
                    # fetched: the result lived only in the dead
                    # process, and journal exactly-once forbids a
                    # silent re-solve — resolve the still-outstanding
                    # ticket LOUDLY (the transport's finalized-but-lost
                    # submit answer, at the router level).
                    for rid, status in sorted(state.finalized.items()):
                        # graftlock: ok(journal->router inversion is rescue-only — same justification as the rebind loop above: the journal belongs to the fenced+quiesced dead replica, no live path holds the router lock while waiting on it)
                        with self._lock:
                            rt = self._outstanding.get(rid)
                            bound_here = rid in replica.outstanding
                        if rt is None or not bound_here:
                            continue
                        if rt._resolve_once(ServeResult(
                                u=None, s=None, v=None, status=None,
                                error=(f"request finalized {status} on "
                                       f"replica {replica.index} before "
                                       f"it died ({cause}); the result "
                                       f"did not survive (journal "
                                       f"exactly-once forbids a silent "
                                       f"re-solve)"),
                                sweeps=0, bucket=rt.bucket,
                                queue_wait_s=0.0, solve_time_s=None,
                                path="replica_rescue", degraded=True,
                                request_id=rid), replica):
                            lost.append(rid)
                for rec in orphans:
                    # No healthy replica left: loud terminal, exactly
                    # like the fleet's no-healthy-lane rescue.
                    rt = self._outstanding.get(rec["id"])
                    if rt is not None:
                        rt._resolve_once(ServeResult(
                            u=None, s=None, v=None, status=None,
                            error=(f"replica {replica.index} evicted "
                                   f"({cause}) and no healthy replica "
                                   f"to rescue onto"),
                            sweeps=0, bucket=rec.get("bucket"),
                            queue_wait_s=0.0, solve_time_s=None,
                            path="replica_rescue", degraded=False,
                            request_id=rec["id"]), replica)
                # Every debt record is accounted (re-admitted write-ahead
                # on a receiver, or terminally resolved): compact the
                # dead journal so a restart of this replica replays
                # nothing twice. FINALIZE TOMBSTONES are kept for the
                # requests the dead replica already served — the
                # federation's exactly-once accounting stays auditable
                # across the rescue (a late-waking duplicate finalize is
                # detectable against them), and a respawn's recover()
                # reads them as zero debt. ORPHANS (no healthy target)
                # get ERROR tombstones: their loud terminal must leave a
                # durable trace too, not just an in-memory ticket
                # resolution — never a silent drop, even on disk.
                from .journal import JOURNAL_VERSION
                tombstones = [
                    (rid, status)
                    for rid, status in sorted(state.finalized.items())
                ] + [(rec["id"], "ERROR") for rec in orphans]
                j.rewrite([
                    {"journal_version": JOURNAL_VERSION,
                     "kind": "finalize", "seq": i, "id": rid,
                     "t_wall": time.time(), "status": status,
                     "rescue_compacted": True}
                    for i, (rid, status) in enumerate(tombstones)])
        finally:
            j.release()
        replica.rescued_off += len(moved)
        with self._lock:
            self.total_rescues += len(moved)
        self._bump(*(["rescued"] * len(moved)))
        if self.metrics is not None and moved:
            self.metrics.inc("svdj_replica_rescued_total", len(moved),
                             replica=replica.index,
                             help="requests rescued off a dead replica")
        self._record(event="rescue", replica=replica.index, cause=cause,
                     count=len(moved), request_ids=moved,
                     targets=sorted(set(targets_used)),
                     orphaned=len(debt) - len(moved), torn=state.torn,
                     lost_results=lost, fence_token=fence_token)

    # -- recovery -----------------------------------------------------------

    def _probe(self, replica: ReplicaHandle, now: float) -> None:
        """Outcome-caused replica recovery: a zeros solve of the
        smallest bucket through the replica's NORMAL dispatch path
        (respawning a dead replica first); OK -> ACTIVE."""
        import numpy as _np
        sub = replica.probe_sub
        if sub is not None:
            if not sub.done():
                if not replica.alive():
                    replica.probe_sub = None
                    self._record(event="probe", replica=replica.index,
                                 ok=False, request_id=replica.probe_rid,
                                 error="probe replica died")
                return
            res = sub.poll(0.0)
            sub.cleanup()          # a probe result file must not leak
            replica.probe_sub = None
            if res is None:
                return
            from ..solver import SolveStatus
            ok = res.error is None and res.status is SolveStatus.OK
            self._bump(f"probe:{'ok' if ok else 'fail'}")
            if self.metrics is not None:
                self.metrics.inc("svdj_replica_probes_total",
                                 ok=str(bool(ok)).lower(),
                                 replica=replica.index,
                                 help="quarantined-replica probes")
            self._record(event="probe", replica=replica.index,
                         ok=bool(ok), request_id=replica.probe_rid,
                         error=res.error)
            if ok:
                self.restore(replica, "probe success")
            return
        if now - replica.last_probe < self.config.probe_interval_s:
            return
        replica.last_probe = now
        if not replica.alive():
            if now - replica.last_respawn < self.config.respawn_grace_s:
                return    # a respawn is still booting; give it time
            replica.last_respawn = now
            try:
                replica.respawn()
            except Exception as e:
                self._record(event="probe", replica=replica.index,
                             ok=False, request_id=None,
                             error=f"respawn failed: "
                                   f"{type(e).__name__}: {e}")
                return
        b = min(self.buckets, key=lambda b: b.cost)
        rid = f"probe-fed{replica.index}-{next(self._probe_seq)}"
        try:
            sub = replica.submit(
                _np.zeros((b.m, b.n), _np.dtype(b.dtype)),
                compute_u=False, compute_v=False,
                deadline_s=self.config.probe_timeout_s,
                request_id=rid,
                top_k=(b.k if b.kind == "topk" else None))
        except (ReplicaUnavailable, AdmissionError) as e:
            self._record(event="probe", replica=replica.index, ok=False,
                         request_id=rid, error=str(e))
            return
        replica.probe_sub = sub
        replica.probe_rid = rid

    def restore(self, replica: ReplicaHandle, cause: str) -> None:
        with self._lock:
            if replica.state is not ReplicaState.QUARANTINED:
                return
            replica.state = ReplicaState.ACTIVE
            replica.bad_streak = 0
            replica.open_streak = 0
        replica.transitions.append(("quarantined", "active", cause))
        self._bump("restores")
        if self.metrics is not None:
            self.metrics.inc("svdj_replica_transitions_total",
                             replica=replica.index, to_state="active",
                             help="replica state transitions")
        self._record(event="replica_transition", replica=replica.index,
                     from_state="quarantined", to_state="active",
                     cause=cause)

    # -- views --------------------------------------------------------------

    def ready(self) -> bool:
        return bool(self._accepting
                    and any(r.state is ReplicaState.ACTIVE and r.alive()
                            for r in self.replicas))

    def healthz(self, probe_replicas: bool = True) -> dict:
        """The federated view: per-replica snapshots (states, heartbeat
        ages, streaks, outstanding counts, metrics listener addresses),
        ring ownership of every declared bucket, rescue totals."""
        now = time.monotonic()
        reps = [r.snapshot(now) for r in self.replicas]
        out = {
            "ok": any(r["alive"] for r in reps),
            "ready": self.ready(),
            "replicas": reps,
            "active": sum(1 for r in reps if r["state"] == "active"),
            "quarantined": sum(1 for r in reps
                               if r["state"] == "quarantined"),
            "rescues": self.total_rescues,
            "ring": self.ring.ownership(b.name for b in self.buckets),
            "stats": self.stats(),
            "http": (None if self._http_addr is None
                     else {"host": self._http_addr[0],
                           "port": self._http_addr[1]}),
        }
        if probe_replicas:
            out["replica_healthz"] = {
                r.index: self._safe_healthz(r) for r in self.replicas}
        return out

    @staticmethod
    def _safe_healthz(replica: ReplicaHandle) -> Optional[dict]:
        try:
            return replica.healthz()
        except Exception:
            return None

    def metrics_targets(self) -> List[Tuple[str, int]]:
        """The REAL (host, port) of every replica's live /metrics
        listener (ephemeral ports resolved through healthz) — what a
        Prometheus scraper should be pointed at."""
        out = []
        for r in self.replicas:
            hz = self._safe_healthz(r)
            http = (hz or {}).get("http")
            if isinstance(http, dict) and http.get("port"):
                out.append((str(http.get("host", "127.0.0.1")),
                            int(http["port"])))
        return out

    def metrics_text(self) -> str:
        """ONE scrape target for the whole federation: the router's own
        registry plus every replica's exposition re-emitted with a
        ``replica="<index>"`` label, # HELP/# TYPE dedup'd per family
        (first writer wins). Local replicas are read in-process; spool
        replicas are scraped over HTTP at the REAL listener their
        heartbeat-carried healthz advertises. A replica that cannot be
        read degrades to a comment line — the federated scrape stays
        serviceable under the same chaos the router routes around."""
        families: Dict[str, dict] = {}
        comments: List[str] = []
        if self.metrics is None:
            comments.append("# svdj router metrics disabled "
                            "(RouterConfig.metrics=False)")
        else:
            self._merge_exposition(self.metrics.render(), None,
                                   families, comments)
        for r in self.replicas:
            try:
                text = self._replica_exposition(r)
            except Exception as e:
                comments.append(f"# svdj-router: replica {r.index} "
                                f"metrics unavailable: {e}")
                continue
            self._merge_exposition(text, str(r.index), families, comments)
        lines: List[str] = []
        for fam in families.values():
            lines.extend(fam["meta"])
            lines.extend(fam["samples"])
        lines.extend(comments)
        return "\n".join(lines) + "\n"

    @staticmethod
    def _replica_exposition(replica: ReplicaHandle) -> str:
        """One replica's raw Prometheus exposition: in-process for a
        `LocalReplica`, HTTP for anything behind a transport (the spool
        heartbeat's healthz carries the ephemeral listener address)."""
        if isinstance(replica, LocalReplica):
            if replica.dead:
                raise ReplicaUnavailable("dead (simulated process loss)")
            return replica.service.metrics_text()
        hz = replica.healthz() or {}
        http = hz.get("http")
        if not (isinstance(http, dict) and http.get("port")):
            raise ReplicaUnavailable(
                "no live /metrics listener advertised in healthz")
        import urllib.request
        url = (f"http://{http.get('host', '127.0.0.1')}"
               f":{int(http['port'])}/metrics")
        with urllib.request.urlopen(url, timeout=2.0) as resp:
            return resp.read().decode("utf-8", "replace")

    @staticmethod
    def _merge_exposition(text: str, replica: Optional[str],
                          families: Dict[str, dict],
                          comments: List[str]) -> None:
        """Fold one exposition into the per-family merge accumulator,
        injecting ``replica=<label>`` into every sample that does not
        already carry one (the router's own per-replica gauges do).
        Histogram ``_bucket``/``_sum``/``_count`` samples group under
        their base family so the merged exposition keeps each family's
        lines contiguous, as the text format requires."""
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                name = line.split(" ", 3)[2]
                fam = families.setdefault(
                    name, {"meta": [], "samples": []})
                if line not in fam["meta"]:
                    fam["meta"].append(line)
                continue
            if line.startswith("#"):
                comments.append(line if replica is None
                                else f"{line}  (replica {replica})")
                continue
            head, _, value = line.rpartition(" ")
            if not head:
                continue
            if replica is not None and 'replica="' not in head:
                if head.endswith("}"):
                    head = head[:-1] + f',replica="{replica}"}}'
                else:
                    head = f'{head}{{replica="{replica}"}}'
            name = head.split("{", 1)[0]
            for suffix in ("_bucket", "_sum", "_count"):
                if (name.endswith(suffix)
                        and name[:-len(suffix)] in families):
                    name = name[:-len(suffix)]
                    break
            fam = families.setdefault(name, {"meta": [], "samples": []})
            fam["samples"].append(f"{head} {value}")

    # -- federated /metrics + /healthz listener (stdlib) --------------------

    @property
    def http_address(self) -> Optional[Tuple[str, int]]:
        """(host, port) of the live federated listener, or None."""
        return self._http_addr

    def start_http(self, host: str = "127.0.0.1", port: int = 0
                   ) -> Tuple[str, int]:
        """The federation's single scrape target: GET /metrics returns
        `metrics_text()` (every replica's exposition replica-labelled,
        plus the router's own gauges), GET /healthz the federated
        `healthz()` JSON (inf/nan sanitized). Same stdlib listener
        shape as `SVDService.start_http`; idempotent; `stop()` shuts it
        down."""
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        if self._http is not None:
            return self._http_addr
        rtr = self

        def _json_safe(obj):
            if isinstance(obj, dict):
                return {str(k): _json_safe(v) for k, v in obj.items()}
            if isinstance(obj, (list, tuple)):
                return [_json_safe(v) for v in obj]
            if isinstance(obj, float) and (obj != obj or obj in (
                    float("inf"), float("-inf"))):
                return str(obj)
            return obj

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?", 1)[0] == "/metrics":
                    body = rtr.metrics_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?", 1)[0] == "/healthz":
                    body = _json.dumps(
                        _json_safe(rtr.healthz())).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # scrapes must not spam stderr
                pass

        self._http = ThreadingHTTPServer((host, int(port)), Handler)
        self._http_addr = (self._http.server_address[0],
                           self._http.server_address[1])
        threading.Thread(target=self._http.serve_forever,
                         name="svdj-router-http", daemon=True).start()
        return self._http_addr

    def stop_http(self) -> None:
        http, self._http, self._http_addr = self._http, None, None
        if http is not None:
            http.shutdown()
            http.server_close()

    def _collect_metrics(self, reg) -> None:
        owned: Dict[int, int] = {}
        for b in self.buckets:
            owned[self.ring.owner(b.name)] = \
                owned.get(self.ring.owner(b.name), 0) + 1
        for r in self.replicas:
            ri = str(r.index)
            reg.set("svdj_replica_state",
                    1.0 if r.state is ReplicaState.ACTIVE else 0.0,
                    replica=ri, help="1 = ACTIVE, 0 = QUARANTINED")
            reg.set("svdj_ring_owned_buckets",
                    float(owned.get(r.index, 0)), replica=ri,
                    help="declared buckets whose ring owner this is")
            reg.set("svdj_replica_outstanding",
                    float(len(r.outstanding)), replica=ri,
                    help="router tickets currently bound to the replica")

    def records(self) -> list:
        with self._lock:
            return list(self._records)

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)

    # -- bookkeeping --------------------------------------------------------

    def _bump(self, *keys: str) -> None:
        with self._lock:
            for k in keys:
                self._stats[k] = self._stats.get(k, 0) + 1

    def _record(self, *, event: str, replica: Optional[int] = None,
                **extra) -> None:
        from .. import obs
        record = obs.manifest.build_router(event=event, replica=replica,
                                           **extra)
        with self._lock:
            if self.config.max_records > 0:
                self._records.append(record)
                del self._records[:-self.config.max_records]
        if self.config.manifest_path is not None:
            try:
                from .. import obs as _obs
                _obs.manifest.append(self.config.manifest_path, record)
            except Exception as e:
                self._bump("manifest_errors")
                print(f"svdj-router: manifest append failed: {e}",
                      file=sys.stderr)


# -- spool replica serve loop (the subprocess side) ---------------------------


def run_spool_replica(spool_dir, config: ServeConfig, *,
                      poll_s: float = 0.02, warmup: bool = False,
                      max_runtime_s: Optional[float] = None) -> int:
    """The serve loop of one spool-replica PROCESS (`SpoolReplica`'s
    counterpart; `tests/_router_worker.py` and `cli serve-demo
    --replicas` spawn this): build the service, replay the journal (a
    restarted replica recovers its own remaining debt), warm from the
    shared compile cache, then poll the inbox — submits, rescue debt
    batches, stop — writing one atomic outbox file per terminal result
    and rewriting the heartbeat every turn. Returns the process exit
    code (0 on a clean stop)."""
    spool = Path(spool_dir)
    inbox, outbox = spool / "inbox", spool / "outbox"
    inbox.mkdir(parents=True, exist_ok=True)
    outbox.mkdir(parents=True, exist_ok=True)
    hb_path = spool / "heartbeat.json"
    boot_wall = time.time()     # fences older than this target a past life

    from .journal import decode_array
    svc = SVDService(config)
    outstanding: Dict[str, Any] = {}
    # Per-request orientation of the PLAIN submit lane: the router
    # pre-oriented the payload, so the outbox record must tell the
    # decoder to swap the factors back (journal-debt results are
    # de-oriented by the service itself and never swap).
    transpose_out: Dict[str, bool] = {}
    # Ids the journal already accounts for (admitted or finalized in a
    # previous life): an inbox file that survived the crash window
    # between journal append and unlink must NOT be double-admitted.
    journal_seen: set = set()
    finalized_prev: Dict[str, str] = {}
    if (config.journal_path is not None
            and Path(config.journal_path).exists()):
        st0 = Journal(config.journal_path).scan(quarantine=False)
        journal_seen = set(st0.admits) | set(st0.finalized)
        finalized_prev = dict(st0.finalized)
        outstanding.update(svc.recover())
    svc.start()
    coldstart = None
    if warmup:
        svc.warmup(timeout=600.0)
        cold = [r for r in svc.records() if r.get("kind") == "coldstart"]
        if cold:
            coldstart = {
                "fresh_compiles": cold[-1]["fresh_compiles"],
                "cache_hits": cold[-1]["cache_hits"],
                "backend_compiles": cold[-1]["backend_compiles"],
                "total_s": cold[-1]["total_s"]}

    def write_heartbeat() -> None:
        lanes = svc.fleet.lanes
        _write_json_atomic(hb_path, {
            "t_wall": time.time(),
            "pid": os.getpid(),
            "boot_id": host_boot_id(),
            "busy": any(l.in_step for l in lanes),
            "holds_work": bool(outstanding) or any(
                l.in_flight or l.queue.depth() > 0 for l in lanes),
            "coldstart": coldstart,
            "healthz": _trim_healthz(svc),
        })

    # The heartbeat is the PROCESS's liveness signal, so it must not
    # depend on the inbox loop's scheduling: on a loaded host the GIL
    # can starve the loop past the router's idle staleness bound while
    # the solve threads are making perfectly good progress — a dedicated
    # writer thread keeps the signal honest (a SIGKILL stops it all the
    # same, which is the event it exists to expose).
    hb_stop = threading.Event()

    def _hb_loop() -> None:
        while not hb_stop.wait(0.2):
            try:
                write_heartbeat()
            except Exception:
                pass

    write_heartbeat()
    threading.Thread(target=_hb_loop, name="svdj-spool-heartbeat",
                     daemon=True).start()

    t_end = (None if max_runtime_s is None
             else time.monotonic() + max_runtime_s)
    stop_rc: Optional[int] = None
    try:
        while stop_rc is None:
            if t_end is not None and time.monotonic() > t_end:
                stop_rc = 4    # runtime fuse: a forgotten worker exits
                break
            for f in sorted(inbox.glob("*.json")):
                rec = _read_json(f)
                if rec is None:
                    continue    # mid-rename glimpse; next turn
                kind = rec.get("kind")
                if kind == "stop":
                    _unlink_quiet(f)
                    stop_rc = 0
                    break
                if kind == "fence":
                    # Router fencing (STONITH before journal rescue):
                    # exit IMMEDIATELY, serving nothing else — queued
                    # work must stay as journal debt for the rescuer.
                    # A fence older than this process's boot targeted a
                    # previous life (the respawn must not re-die on it).
                    if float(rec.get("t_wall", 0.0)) >= boot_wall:
                        os._exit(5)
                    _unlink_quiet(f)
                    continue
                if kind == "debt":
                    try:
                        ft = rec.get("fence_token")
                        outstanding.update(svc.admit_journal_debt(
                            rec["records"],
                            fence_token=(None if ft is None
                                         else int(ft)),
                            fence_domain=rec.get("fence_domain")))
                    except Exception as e:
                        # A malformed rescue batch must not kill the
                        # replica loop; the router's own debt accounting
                        # (the receiver journals write-ahead) bounds the
                        # damage to the bad batch.
                        print(f"svdj-spool: debt admit failed: "
                              f"{type(e).__name__}: {e}", file=sys.stderr)
                    _unlink_quiet(f)
                    continue
                rid = str(rec.get("id"))
                if rid in journal_seen:
                    # The crash window between a previous life's journal
                    # append and the inbox unlink: the journal already
                    # owns this id (its debt was replayed at boot, its
                    # finalize settled it) — double-admitting it here
                    # would break exactly-once. A finalized-but-lost
                    # result is reported LOUDLY, never silently.
                    if (rid in finalized_prev
                            and not (outbox / f"{rid}.json").exists()
                            and rid not in outstanding):
                        _write_json_atomic(outbox / f"{rid}.json", {
                            "id": rid, "status": None,
                            "error": (f"request finalized "
                                      f"{finalized_prev[rid]} before a "
                                      f"crash; the result did not "
                                      f"survive the restart (journal "
                                      f"exactly-once forbids a silent "
                                      f"re-solve)"),
                            "sweeps": 0, "bucket": None,
                            "queue_wait_s": 0.0, "solve_time_s": None,
                            "path": "recovery", "degraded": False,
                            "u": None, "s": None, "v": None})
                    _unlink_quiet(f)
                    continue
                try:
                    a = decode_array(rec["input"])     # ORIENTED payload
                    deadline_s = rec.get("deadline_s")
                    if deadline_s is not None:
                        # Wall-clock deadline budget across the process
                        # boundary: decay from the router's submit time.
                        deadline_s = (float(rec["t_wall"])
                                      + float(deadline_s) - time.time())
                    t = svc.submit(a, request_id=rid,
                                   compute_u=bool(rec.get("compute_u",
                                                          True)),
                                   compute_v=bool(rec.get("compute_v",
                                                          True)),
                                   deadline_s=deadline_s,
                                   top_k=rec.get("top_k"),
                                   phase=str(rec.get("phase", "full")),
                                   # The payload checksum IS the oriented
                                   # digest — no third hash of the same
                                   # bytes on the replica.
                                   digest=(rec.get("input") or {}).get(
                                       "data_sha256"),
                                   tenant=rec.get("tenant"),
                                   api_token=rec.get("api_token"))
                    outstanding[rid] = t
                    transpose_out[rid] = bool(rec.get("transposed",
                                                      False))
                except AdmissionError as e:
                    _write_json_atomic(outbox / f"{rid}.json", {
                        "id": rid,
                        "status": f"REJECTED_{e.reason.name}",
                        "error": e.detail, "sweeps": 0, "bucket": None,
                        "queue_wait_s": 0.0, "solve_time_s": None,
                        "path": "rejected", "degraded": False,
                        "u": None, "s": None, "v": None})
                except Exception as e:
                    _write_json_atomic(outbox / f"{rid}.json", {
                        "id": rid, "status": None,
                        "error": f"{type(e).__name__}: {e}", "sweeps": 0,
                        "bucket": None, "queue_wait_s": 0.0,
                        "solve_time_s": None, "path": "rejected",
                        "degraded": False, "u": None, "s": None,
                        "v": None})
                # Unlink AFTER the request is journaled (inside submit)
                # or terminally answered: a crash mid-processing leaves
                # the inbox file as the write-ahead record the rescue
                # replays (`SpoolReplica.unconsumed_debt`); the
                # journal_seen dedupe absorbs the double-accounting
                # window on restart.
                _unlink_quiet(f)
            for rid in [r for r, t in outstanding.items() if t.done()]:
                res = outstanding.pop(rid).result(0)
                enc = _encode_result(res)
                enc["transposed"] = transpose_out.pop(rid, False)
                _write_json_atomic(outbox / f"{rid}.json", enc)
            time.sleep(poll_s)
    finally:
        hb_stop.set()
        try:
            svc.stop(drain=True, timeout=60.0)
            for rid in list(outstanding):
                t = outstanding.pop(rid)
                if t.done():
                    enc = _encode_result(t.result(0))
                    enc["transposed"] = transpose_out.pop(rid, False)
                    _write_json_atomic(outbox / f"{rid}.json", enc)
        except Exception:
            pass
    return int(stop_rc or 0)


def _trim_healthz(svc: SVDService) -> dict:
    """The heartbeat's healthz excerpt: JSON-safe, small, and carrying
    exactly what the router supervisor reads (breaker states per lane,
    readiness, the REAL metrics listener address)."""
    hz = svc.healthz()
    fleet = hz.get("fleet") or {}
    return {
        "ok": bool(hz.get("ok")),
        "ready": bool(hz.get("ready")),
        "breaker": hz.get("breaker"),
        "queue_depth": int(hz.get("queue_depth", 0)),
        "in_flight": hz.get("in_flight"),
        "http": hz.get("http"),
        "fleet": {"lanes": [
            {"lane": l.get("lane"), "breaker": l.get("breaker"),
             "state": l.get("state")}
            for l in (fleet.get("lanes") or [])]},
    }
