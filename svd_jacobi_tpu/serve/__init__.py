"""svd_jacobi_tpu.serve — deadline-aware batched SVD serving.

The request-level robustness layer (PR 4) on top of the solve-level one
(PR 3, `resilience`): an in-process, thread-safe SVD service with

  * bounded admission + load shedding (`queue`): reject-with-reason,
    never silent drops;
  * shape-bucketed dispatch (`buckets`): requests pad to a small static
    (m, n, dtype) bucket set so the jit caches hit after one warmup per
    bucket (`config.RETRACE_BUDGETS`);
  * per-request deadlines and cooperative cancellation, enforced between
    sweeps by `solver.SweepStepper.set_control` and surfaced as
    `SolveStatus.DEADLINE` / `SolveStatus.CANCELLED`;
  * a circuit breaker over consecutive solve failures that routes
    dispatches through `resilience.resilient_svd`'s escalation ladder,
    plus queue-pressure brownout (full SVD -> sigma-only -> shed)
    (`breaker`);
  * health/readiness probes and per-request schema-versioned ``"serve"``
    manifest records (`obs.manifest.build_serve`) (`service`);
  * fleet mode (``ServeConfig.lanes > 1``, `fleet`): one solve lane per
    device, each its own fault domain, with bucket-affinity routing,
    work stealing, lane eviction into QUARANTINED on the declared
    sickness causes, dead-lane request rescue onto healthy lanes, and
    outcome-caused probe recovery — all reconstructable from ``"fleet"``
    manifest records;
  * restart survivability (`registry` + `journal`): ONE authoritative
    entry registry of every compilable (lane, bucket, tier, variant)
    jit entry, AOT ``lower().compile()`` warmup through a persistent
    executable cache namespaced by config + tuning-table content hash
    (a warm restart pays ZERO fresh compiles), a write-ahead fsync'd
    request journal with exactly-once replay after SIGKILL
    (`SVDService.recover`), and zero-downtime `SVDService.reload`
    (background AOT warm, atomic swap) — README "Restart & cold start";
  * federated serving (`router`): a `ReplicaRouter` fronting N service
    REPLICAS — consistent-hash routing keyed by (bucket, input digest)
    so byte-identical resubmits hit the replica owning the cached
    result, per-replica journals guarded by O_EXCL lockfiles
    (`JournalLockedError`), replica-death journal rescue at queue FRONT
    on healthy replicas (``path="replica_rescue"``), outcome-caused
    probe recovery, one shared persistent compile-cache namespace
    (replica 2 warm-boots with zero fresh compiles), and ``"router"``
    manifest records — README "Federated serving";
  * multi-host HTTP transport (`transport`): the federation over an
    UNRELIABLE network — a versioned JSON wire protocol mapping 1:1
    onto the Ticket lifecycle, per-RPC timeouts with deadline-budget
    decay across hops, bounded decorrelated-jitter retries, idempotency
    keys (retried submits after a lost ACK admit exactly once), replica
    leases with monotonic FENCING tokens (`bump_fence_token` /
    `StaleFenceError`) so a partitioned-but-alive replica can never
    double-serve rescued debt, half-open connection quarantine, and
    partition-healed reconciliation — chaos-tested against the
    fault-injecting proxy (`resilience.netfault`), README "Federated
    serving: multi-host HTTP transport";
  * two-phase σ-first serving + content-addressed result cache
    (`cache`): ``submit(phase="sigma")`` returns σ at interactive
    latency with the solve's checkpointed stage retained under a byte
    budget, ``Ticket.promote()`` resumes the SAME solve to full U/V
    (never a fresh solve), and byte-identical full-phase resubmits
    finalize at admission with zero dispatch — README "Two-phase &
    incremental serving".

Quickstart::

    from svd_jacobi_tpu.serve import ServeConfig, SVDService

    with SVDService(ServeConfig(buckets=((256, 256, "float32"),))) as svc:
        t = svc.submit(a, deadline_s=2.0)
        res = t.result(timeout=30.0)
        if res.status is not None and res.status.name == "OK":
            u, s, v = res.u, res.s, res.v

`python -m svd_jacobi_tpu.cli serve-demo` runs a seeded closed-loop
client against a live service.
"""

from __future__ import annotations

from .breaker import BreakerState, Brownout, CircuitBreaker
from .buckets import Bucket, BucketSet, as_bucket
from .cache import PromotionError, PromotionStore, ResultCache, input_digest
from .fleet import Fleet, Lane, LaneState
from .journal import (Journal, JournalLockedError, StaleFenceError,
                      bump_fence_token, fence_token_path, read_fence_token)
from .queue import AdmissionError, AdmissionQueue, AdmissionReason, Request
from .registry import (CompileCounter, EntryKey, EntryRegistry,
                       enable_persistent_cache, jit_entries)
from .router import (HashRing, LocalReplica, ReplicaRouter, ReplicaState,
                     RouterConfig, RouterTicket, SpoolReplica,
                     run_spool_replica)
from .service import ServeConfig, ServeResult, SVDService, Ticket
from .transport import (HttpReplica, HttpReplicaServer, TransportError,
                        run_http_replica)

__all__ = [
    "AdmissionError", "AdmissionQueue", "AdmissionReason", "Bucket",
    "BucketSet", "BreakerState", "Brownout", "CircuitBreaker",
    "CompileCounter", "EntryKey", "EntryRegistry", "Fleet", "HashRing",
    "HttpReplica", "HttpReplicaServer",
    "Journal", "JournalLockedError", "Lane", "LaneState", "LocalReplica",
    "PromotionError", "PromotionStore", "ReplicaRouter", "ReplicaState",
    "Request", "ResultCache", "RouterConfig", "RouterTicket",
    "ServeConfig", "ServeResult", "SpoolReplica", "StaleFenceError",
    "SVDService", "Ticket", "TransportError",
    "as_bucket", "bump_fence_token", "enable_persistent_cache",
    "fence_token_path", "input_digest", "jit_entries", "read_fence_token",
    "run_http_replica", "run_spool_replica",
]
