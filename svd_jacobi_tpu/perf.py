"""`python -m svd_jacobi_tpu.perf` — the roofline performance
observatory entry point (report / model / check). The implementation
lives in `obs.perf`, which is stdlib-only by contract; this shim exists
so the observatory rides the same `-m` bus as `.analysis` and `.serve`.
"""

from .obs.perf import (ConvergenceRecorder, build_report, check_files,
                       device_block, main, render_report)

__all__ = ["ConvergenceRecorder", "build_report", "check_files",
           "device_block", "main", "render_report"]

if __name__ == "__main__":
    import sys

    sys.exit(main())
