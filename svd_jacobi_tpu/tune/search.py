"""Empirical knob search — the harness that regenerates tuning tables.

ATLAS/OpenTuner-style measured search over the solver's tunable knobs
(:data:`tune.tables.KNOBS`), per benchmark shape, with the measurement
discipline PROFILE.md rounds 4-5 used by hand:

  * SAME-SESSION A/B: every candidate is timed in one process against the
    baseline (the knobs the active resolution would pick today), interleaved
    warm — environment drift between sessions was the reason item 18's
    crossovers needed same-session re-runs;
  * WARM-UP DISCARD: the first run of every candidate compiles and warms
    caches and is never timed;
  * PER-POINT TIME BUDGET: a candidate whose first timed repetition
    exceeds the budget records that one honest repetition and stops —
    a full CPU regeneration stays bounded (~10 min default grid);
  * COORDINATE DESCENT, not a full cross product: knob axes are swept one
    at a time from the baseline (the measured knobs interact weakly —
    items 17-18 tuned them independently), so the point count is the SUM
    of axis sizes, not the product;
  * CONSERVATIVE WINNERS: a candidate must beat the baseline by more than
    ``min_gain`` (default 3% — under the same-session run-to-run noise
    floor observed in PROFILE.md) to displace it, so a regenerated table
    never encodes noise as a verdict.

Each searched shape appends one schema-versioned ``"tune"`` manifest
record (grid point knobs + times + winner — `obs.manifest.build_tune`),
so a table's provenance reconstructs from the record stream alone.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import tables


@dataclasses.dataclass
class Point:
    """One measured grid point."""

    knobs: Dict[str, object]
    time_s: Optional[float] = None
    reps: int = 0
    ok: bool = False
    note: str = ""

    def as_record(self) -> dict:
        return {"knobs": dict(self.knobs),
                "time_s": self.time_s, "reps": self.reps,
                "ok": self.ok, "note": self.note}


@dataclasses.dataclass
class ShapeResult:
    """Search outcome for one benchmark shape. The ``sketch_*`` fields
    carry the top-k sketch-axis sweep (oversample/power_iters measured
    against a `solver.svd_topk` objective at rank ``sketch_k``) when the
    shape was eligible; None otherwise."""

    m: int
    n: int
    dtype: str
    key: Dict[str, str]
    baseline: Point
    points: List[Point]
    winner: Dict[str, object]
    tiers: Optional[List[dict]] = None
    sketch_k: Optional[int] = None
    sketch_baseline: Optional[Point] = None
    sketch_points: List[Point] = dataclasses.field(default_factory=list)
    sketch_winner: Optional[Dict[str, object]] = None


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _build_config(base, knobs: Dict[str, object]):
    """An `SVDConfig` with the candidate knob values applied (only the
    solver-side knobs; serve ``batch_tiers`` is measured separately)."""
    import dataclasses as _dc
    updates = {}
    for k in ("block_size", "mixed_store", "pair_solver", "precondition",
              "criterion", "rounds_resident"):
        if k in knobs:
            updates[k] = knobs[k]
    if updates.get("pair_solver", "auto") not in ("auto", "pallas",
                                                  "block_rotation"):
        # Preconditioning is a Pallas-path mode; pinning "on" onto an
        # explicit XLA solver is a validation error, not a grid point.
        if updates.get("precondition", "auto") in ("on", "double"):
            updates["precondition"] = "auto"
    return _dc.replace(base, **updates)


def time_solve(a, config, *, reps: int, budget_s: float,
               compute_uv: bool = True,
               top_k: Optional[int] = None) -> Point:
    """Best-of-``reps`` wall time of one config on one input, warm-up
    discarded, bounded by ``budget_s`` of TIMED work. ``top_k`` switches
    the objective to `solver.svd_topk` (the sketch-axis sweep's
    objective). Failures (a config invalid for the shape, OOM, ...)
    record as ok=False — one broken candidate must not void the shape's
    whole search."""
    from .. import solver
    from ..utils._exec import force
    point = Point(knobs={})
    try:
        if top_k is not None:
            solve = lambda: solver.svd_topk(a, top_k,
                                            compute_u=compute_uv,
                                            compute_v=compute_uv,
                                            config=config)
        else:
            solve = lambda: solver.svd(a, compute_u=compute_uv,
                                       compute_v=compute_uv, config=config)
        r = solve()
        force((r.s, r.status))          # warm-up: compile + caches, DISCARDED
        if r.status_enum().name not in ("OK", "STAGNATED"):
            point.note = f"warmup status {r.status_enum().name}"
            return point
        best = float("inf")
        spent = 0.0
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            force((solve().s,))
            dt = time.perf_counter() - t0
            best = min(best, dt)
            point.reps += 1
            spent += dt
            if spent + best > budget_s:
                break                    # budget: keep what was measured
        point.time_s = best
        point.ok = True
    except Exception as e:               # noqa: BLE001 — candidate quality
        point.note = f"{type(e).__name__}: {e}"
    return point


def _axes(n: int, dtype: str, baseline: Dict[str, object],
          smoke: bool) -> List[Tuple[str, List[object]]]:
    """The knob axes swept for one shape (values exclude the baseline's
    own — it is already measured). Axis values are capability-filtered
    up front so the grid never spends budget on a certainly-invalid
    point (f64 x pallas, b > n/2, ...)."""
    import jax.numpy as jnp
    f64 = jnp.dtype(dtype) == jnp.float64
    # Whether auto routing would take the Pallas kernel path — the
    # precondition knob only exists there, and sweeping it on an
    # XLA-routed shape would time the identical program twice (recording
    # noise as a verdict).
    pallas_routed = (not f64) and n >= 64
    if smoke:
        # The documented smoke grid: 2 knob axes, tiny value sets.
        axes = [("block_size", [b for b in (4, 8) if b <= max(1, n // 2)]),
                ("pair_solver", (["pallas", "block_rotation"]
                                 if pallas_routed else [])
                 + ["qr-svd"])]
        return [(k, [v for v in vs if v != baseline.get(k)])
                for k, vs in axes]
    block_axis = [b for b in (64, 128, 256) if b <= max(1, (n + 1) // 2)]
    if not block_axis:
        block_axis = [b for b in (4, 8, 16, 32) if b <= max(1, n // 2)]
    # gram-eigh is offered only where U orthogonality is not at stake —
    # it converges to the absolute class only (ops.blockwise), so a
    # measured table must never route compute_uv solves onto it.
    # block_rotation shares the kernel lane's capability window (f32-only
    # rotations, min(m, n) >= 64 to block usefully).
    solver_axis = (["qr-svd"] if f64
                   else (["pallas", "block_rotation", "resident", "hybrid",
                          "qr-svd"]
                         if n >= 64 else ["hybrid", "qr-svd"]))
    axes = [
        ("block_size", block_axis),
        ("pair_solver", solver_axis),
    ]
    if pallas_routed:
        axes.append(("precondition", ["on", "off"]))
        # Residency depth of the resident lane (rounds per VMEM panel
        # pass). Swept AFTER pair_solver so it prices against a resident
        # incumbent; the search loop skips it when the incumbent routed
        # elsewhere (the knob is dead there — identical programs).
        axes.append(("rounds_resident", [2, 4, 8]))
    return [(k, [v for v in vs if v != baseline.get(k)]) for k, vs in axes]


def measure_batch_tiers(n: int, m: int, dtype: str, *, candidates=(4, 16),
                        reps: int, budget_s: float,
                        base_config=None) -> Tuple[Tuple[int, ...],
                                                   List[dict]]:
    """Measure which coalescing tiers pay on this backend: per-candidate
    tier B, one `solver.svd_batched` dispatch of a B-stack vs B serial
    solves of the same members (same-session, warm-up discarded). A tier
    joins the set when the coalesced dispatch is cheaper per member
    (ratio > 1.05 — the coalescing exists to amortize the latency-bound
    rotation chain, PROFILE.md item 22)."""
    import jax.numpy as jnp

    from .. import solver
    from ..config import SVDConfig
    from ..utils import matgen
    from ..utils._exec import force

    base = base_config if base_config is not None else SVDConfig()
    dt = jnp.dtype(dtype)
    rows: List[dict] = []
    tiers = [1]
    for bsz in sorted(set(int(b) for b in candidates)):
        if bsz < 2:
            continue
        try:
            stack = jnp.stack([matgen.random_dense(m, n, seed=5000 + j,
                                                   dtype=dt)
                               for j in range(bsz)])
            batched = lambda: solver.svd_batched(stack, config=base)
            serial = lambda: [solver.svd(stack[j], config=base)
                              for j in range(bsz)]
            force((batched().s,))                      # warm-up, discarded
            force(tuple(r.s for r in serial()))
            t_b = t_s = float("inf")
            spent = 0.0
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                force((batched().s,))
                dt_b = time.perf_counter() - t0
                t_b = min(t_b, dt_b)
                t0 = time.perf_counter()
                force(tuple(r.s for r in serial()))
                dt_s = time.perf_counter() - t0
                t_s = min(t_s, dt_s)
                # Budget on the MEASURED durations (the minima would
                # undercount a slow point and run far past the budget).
                spent += dt_b + dt_s
                if spent > budget_s:
                    break
            ratio = t_s / t_b if t_b > 0 else 0.0
            keep = ratio > 1.05
            rows.append({"tier": bsz, "batched_s": t_b, "serial_s": t_s,
                         "speedup": ratio, "kept": keep})
            if keep:
                tiers.append(bsz)
        except Exception as e:              # noqa: BLE001
            rows.append({"tier": bsz, "batched_s": None, "serial_s": None,
                         "speedup": None, "kept": False,
                         "note": f"{type(e).__name__}: {e}"})
    return tuple(tiers), rows


def search_shape(m: int, n: int, dtype: str, *, reps: int, budget_s: float,
                 min_gain: float, smoke: bool,
                 base_config=None) -> ShapeResult:
    """Coordinate-descent search over one shape: measure the baseline
    (today's resolution), sweep each knob axis, and keep a challenger
    only when it beats the incumbent by more than ``min_gain``."""
    import jax.numpy as jnp

    from ..config import SVDConfig
    from ..utils import matgen

    from .. import solver

    dt = jnp.dtype(dtype)
    a = matgen.random_dense(m, n, seed=1_000_000, dtype=dt)
    base = base_config if base_config is not None else SVDConfig()
    resolved = tables.resolve(n, m=m, dtype=dtype)
    key = {
        "n_class": tables.n_class(n),
        "aspect": tables.aspect_class(m, n),
        "dtype": str(dt.name),
        "backend": tables._runtime_identity()[0],
        "device_kind": tables._runtime_identity()[1],
    }
    # The baseline records the ROUTED solver (what "auto" resolves to
    # today), so the sweep never wastes a point re-timing the identical
    # program under an explicit spelling — and a winner row pins the
    # measured method by name, not "auto".
    routed = (solver._resolve_options(a, base, compute_uv=True)[2]
              if base.pair_solver == "auto" else base.pair_solver)
    baseline_knobs = {
        "block_size": resolved.block_size,
        "mixed_store": resolved.mixed_store,
        "pair_solver": routed,
        "precondition": resolved.precondition,
        "criterion": base.criterion,
    }
    _log(f"tune: shape {m}x{n} {dt.name} baseline {baseline_knobs}")
    baseline = time_solve(a, base, reps=reps, budget_s=budget_s)
    baseline.knobs = dict(baseline_knobs)
    if not baseline.ok:
        _log(f"tune: baseline failed ({baseline.note}); shape skipped")
        return ShapeResult(m=m, n=n, dtype=dt.name, key=key,
                           baseline=baseline, points=[],
                           winner=dict(baseline_knobs))
    _log(f"tune: baseline {baseline.time_s:.4f} s ({baseline.reps} reps)")

    incumbent_knobs = dict(baseline_knobs)
    incumbent_time = baseline.time_s
    points: List[Point] = []
    for knob, values in _axes(n, dt.name, baseline_knobs, smoke):
        if (knob == "rounds_resident"
                and incumbent_knobs.get("pair_solver") != "resident"):
            _log("tune:   rounds_resident skipped (incumbent solver is "
                 f"{incumbent_knobs.get('pair_solver')!r}, not resident)")
            continue
        for value in values:
            cand = dict(incumbent_knobs)
            cand[knob] = value
            cfg = _build_config(base, cand)
            point = time_solve(a, cfg, reps=reps, budget_s=budget_s)
            point.knobs = dict(cand)
            points.append(point)
            shown = f"{point.time_s:.4f} s" if point.ok else point.note
            _log(f"tune:   {knob}={value!r}: {shown}")
            if (point.ok and point.time_s is not None
                    and point.time_s < incumbent_time * (1.0 - min_gain)):
                incumbent_knobs = cand
                incumbent_time = point.time_s
                _log(f"tune:   -> new incumbent ({knob}={value!r})")
    res = ShapeResult(m=m, n=n, dtype=dt.name, key=key, baseline=baseline,
                      points=points, winner=incumbent_knobs)
    if not smoke and min(m, n) >= 256:
        _search_sketch_axes(res, a, base, reps=reps, budget_s=budget_s,
                            min_gain=min_gain)
    return res


# The sketch knob axes of the top-k lane (solver.svd_topk), swept with
# the SAME coordinate-descent discipline and >= min_gain win threshold
# as the solver axes — but against a TRUNCATED objective at rank n/8
# (the workload class the lane exists for). Values bracket the Halko
# defaults; the baseline is today's table resolution for the rank class.
SKETCH_AXES = (("oversample", (4, 8, 16)), ("power_iters", (0, 1, 2)))


def _sketch_config(base, knobs: Dict[str, object]):
    import dataclasses as _dc
    ups = {k: knobs[k] for k in ("oversample", "power_iters", "tsqr_chunk")
           if k in knobs}
    return _dc.replace(base, **ups)


def _search_sketch_axes(res: ShapeResult, a, base, *, reps: int,
                        budget_s: float, min_gain: float) -> None:
    """Sweep the sketch axes for one eligible shape, writing the
    ``sketch_*`` fields of ``res``. Accuracy guard: a candidate only
    displaces the incumbent when its top-k sigmas stay within 2x of the
    baseline's error against the full-solve oracle — a sketch knob that
    buys speed by dropping accuracy is not a win, it is a different
    contract."""
    import numpy as np

    from .. import solver
    m, n = res.m, res.n
    k = max(8, n // 8)
    res.sketch_k = k
    r0 = tables.resolve(n, m=m, dtype=res.dtype, k=k)
    base_knobs = {"oversample": r0.oversample, "power_iters": r0.power_iters}
    _log(f"tune: sketch axes (top-k objective, k={k}) baseline "
         f"{base_knobs}")
    s_full = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)[:k]

    def sigma_err(cfg) -> float:
        r = solver.svd_topk(a, k, config=cfg)
        return float(np.max(np.abs(np.asarray(r.s, np.float64) - s_full)
                            / np.maximum(s_full, 1e-300)))

    baseline = time_solve(a, _sketch_config(base, base_knobs), reps=reps,
                          budget_s=budget_s, top_k=k)
    baseline.knobs = dict(base_knobs)
    res.sketch_baseline = baseline
    res.sketch_winner = dict(base_knobs)
    if not baseline.ok:
        _log(f"tune: sketch baseline failed ({baseline.note}); skipped")
        return
    base_err = sigma_err(_sketch_config(base, base_knobs))
    incumbent = dict(base_knobs)
    incumbent_time = baseline.time_s
    for knob, values in SKETCH_AXES:
        for value in values:
            if value == incumbent.get(knob):
                continue
            cand = dict(incumbent)
            cand[knob] = value
            cfg = _sketch_config(base, cand)
            point = time_solve(a, cfg, reps=reps, budget_s=budget_s,
                               top_k=k)
            point.knobs = dict(cand)
            res.sketch_points.append(point)
            shown = f"{point.time_s:.4f} s" if point.ok else point.note
            _log(f"tune:   sketch {knob}={value!r}: {shown}")
            if (point.ok and point.time_s is not None
                    and point.time_s < incumbent_time * (1.0 - min_gain)):
                err = sigma_err(cfg)
                if err > 2.0 * max(base_err, 1e-7):
                    point.note = (f"faster but sigma err {err:.2e} vs "
                                  f"baseline {base_err:.2e} — rejected")
                    _log(f"tune:   -> rejected on accuracy ({point.note})")
                    continue
                incumbent = cand
                incumbent_time = point.time_s
                _log(f"tune:   -> new sketch incumbent ({knob}={value!r})")
    res.sketch_winner = incumbent


def _winner_row(res: ShapeResult) -> dict:
    """A table row from one shape's winner. The row matches the shape's
    full key (backend + device_kind pinned — a measured verdict holds
    only for the hardware it was measured on); knob values that are
    still the AUTO defaults pin anyway, recording the measurement."""
    knobs: Dict[str, object] = {}
    for k, v in res.winner.items():
        if k in ("pair_solver", "criterion") and v == "auto":
            continue                      # never pin an unmeasured "auto"
        if k in tables.KNOBS:
            knobs[k] = v
    if knobs.get("block_size") == tables.heuristic_block_size(res.n):
        # The winner IS the exact-n ladder value: record the ladder
        # POLICY (null), not the number — a class spans many n and two
        # same-class shapes with different ladder optima would otherwise
        # write conflicting rows.
        knobs["block_size"] = None
    if res.tiers is not None:
        kept = tuple(sorted({1} | {r["tier"] for r in res.tiers
                                   if r.get("kept")}))
        knobs["batch_tiers"] = list(kept)
    delta = None
    if res.baseline.time_s and res.winner != res.baseline.knobs:
        best = min((p.time_s for p in res.points
                    if p.ok and p.knobs == res.winner),
                   default=res.baseline.time_s)
        delta = f"{res.baseline.time_s:.4f} -> {best:.4f} s"
    return {
        "match": dict(res.key),
        "knobs": knobs,
        "evidence": (f"measured {res.m}x{res.n} {res.dtype} "
                     f"(baseline {res.baseline.time_s:.4f} s"
                     + (f"; winner {delta}" if delta else "; baseline kept")
                     + ")"),
    }


DEFAULT_SHAPES = ((256, 256, "float32"), (512, 512, "float32"),
                  (2048, 256, "float32"))
SMOKE_SHAPES = ((64, 48, "float32"), (96, 64, "float32"))


def run(*, shapes: Sequence[Tuple[int, int, str]], out_path,
        reps: int = 3, budget_s: float = 60.0, min_gain: float = 0.03,
        smoke: bool = False, tiers_shape: Optional[Tuple[int, int, str]]
        = None, manifest_path: Optional[str] = "reports/manifest.jsonl",
        table_id: Optional[str] = None, base_config=None) -> dict:
    """The full regeneration: search every shape, write the table, append
    the "tune" manifest records. Returns a summary dict (one parseable
    JSON object — the __main__ prints it)."""
    from ..obs import manifest

    t_start = time.perf_counter()
    results: List[ShapeResult] = []
    for m, n, dtype in shapes:
        res = search_shape(int(m), int(n), str(dtype), reps=reps,
                           budget_s=budget_s, min_gain=min_gain,
                           smoke=smoke, base_config=base_config)
        results.append(res)
    if tiers_shape is not None:
        tm, tn, tdtype = tiers_shape
        target = next((r for r in results
                       if (r.m, r.n, r.dtype) == (int(tm), int(tn),
                                                  str(tdtype))), None)
        tiers, tier_rows = measure_batch_tiers(
            int(tn), int(tm), str(tdtype),
            candidates=(4,) if smoke else (4, 16),
            reps=reps, budget_s=budget_s, base_config=base_config)
        _log(f"tune: batch tiers {tiers} ({tier_rows})")
        if target is not None:
            target.tiers = tier_rows
        else:
            # A tiers_shape outside the searched set has no class row to
            # attach the verdict to — dropping it loudly beats grafting
            # it onto an unrelated shape's row.
            _log(f"tune: tiers shape {tiers_shape} not among the searched "
                 f"shapes; tier verdict dropped")

    backend, device_kind = tables._runtime_identity()
    tid = table_id or (f"{backend}-{device_kind}-"
                       f"{'smoke' if smoke else 'r01'}")
    rows = []
    by_match: Dict[str, dict] = {}
    for res in results:
        if not res.baseline.ok:
            continue
        row = _winner_row(res)
        mkey = json.dumps(row["match"], sort_keys=True)
        prior = by_match.get(mkey)
        if prior is None:
            by_match[mkey] = row
            rows.append(row)
            continue
        # Two searched shapes landed in the same class key: merge —
        # first writer wins a conflicting knob (declaration order is
        # the documented tie-break), agreement just accumulates
        # evidence. Disagreement on a non-null knob is surfaced in the
        # evidence string so a reader of the table sees it.
        for k, v in row["knobs"].items():
            if k not in prior["knobs"]:
                prior["knobs"][k] = v
            elif prior["knobs"][k] != v:
                prior["evidence"] += (f"; CONFLICT from {res.m}x{res.n}: "
                                      f"{k}={v!r} lost to "
                                      f"{prior['knobs'][k]!r}")
        prior["evidence"] += f" | {row['evidence']}"
    # Sketch-axis winners: one EXTRA row per shape whose top-k sweep
    # displaced the baseline, matched on the measured rank class (the
    # k_class axis) so it applies only to truncated solves of that
    # class. A baseline-kept sweep writes no row — the shipped k-class
    # verdicts stand.
    for res in results:
        if (res.sketch_winner is None or res.sketch_baseline is None
                or not res.sketch_baseline.ok
                or res.sketch_winner == res.sketch_baseline.knobs):
            continue
        rows.append({
            "match": {**res.key, "k_class": tables.k_class(res.sketch_k)},
            "knobs": {kn: v for kn, v in res.sketch_winner.items()
                      if kn in tables.SKETCH_KNOBS},
            "evidence": (f"sketch axes measured on {res.m}x{res.n} "
                         f"{res.dtype} top-k k={res.sketch_k} (baseline "
                         f"{res.sketch_baseline.knobs} "
                         f"{res.sketch_baseline.time_s:.4f} s)"),
        })
    # The generic fallback row closes every table (tables without one
    # would leave unmatched problems knob-less).
    rows.append({"match": {}, "knobs": dict(tables.GENERIC_KNOBS),
                 "evidence": "generic fallback: the hand-picked defaults "
                             "(tune.tables.GENERIC_KNOBS)"})
    table = tables.save_table(
        out_path, table_id=tid, rows=rows,
        provenance=(f"regenerated by `python -m svd_jacobi_tpu.tune` on "
                    f"{backend}/{device_kind}; shapes "
                    f"{[(r.m, r.n, r.dtype) for r in results]}; see the "
                    f"'tune' manifest records for the full grid"))

    records = []
    for res in results:
        sketch = None
        if res.sketch_baseline is not None:
            sketch = {
                "k": res.sketch_k,
                "baseline": res.sketch_baseline.as_record(),
                "grid": [p.as_record() for p in res.sketch_points],
                "winner": dict(res.sketch_winner or {}),
            }
        rec = manifest.build_tune(
            m=res.m, n=res.n, dtype=res.dtype, key=res.key,
            baseline=res.baseline.as_record(),
            grid=[p.as_record() for p in res.points],
            winner=dict(res.winner),
            table_id=table.table_id, table_sha256=table.sha256,
            tiers=res.tiers, smoke=bool(smoke), sketch=sketch)
        records.append(rec)
        if manifest_path and manifest_path != "off":
            manifest.append(manifest_path, rec)
    summary = {
        "table": str(out_path),
        "table_id": table.table_id,
        "table_sha256": table.sha256,
        "shapes": len(results),
        "points": sum(len(r.points) for r in results),
        "changed": sum(1 for r in results
                       if r.baseline.ok and r.winner != r.baseline.knobs),
        "wall_s": round(time.perf_counter() - t_start, 2),
        "manifest": (manifest_path if manifest_path
                     and manifest_path != "off" else None),
    }
    return summary
