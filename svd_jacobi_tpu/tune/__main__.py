"""`python -m svd_jacobi_tpu.tune` — regenerate a tuning table by
measurement (also reachable as `python -m svd_jacobi_tpu.cli tune ...`).

Benchmarks the knob grid on the ATTACHED backend (this is a measurement
tool — unlike `svd_jacobi_tpu.analysis` it deliberately dials the real
device) and writes a schema-versioned, content-hashed table; pin it with
``--tuning-table=PATH`` on bench.py / the CLI, or SVDJ_TUNING_TABLE.

    python -m svd_jacobi_tpu.tune --smoke            # bounded CPU smoke grid
    python -m svd_jacobi_tpu.tune --out reports/tuning-cpu.json
    python -m svd_jacobi_tpu.tune --shapes 2048x2048:float32,65536x4096:float32

Exit 0 on a written table; one "tune" manifest record per searched shape.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path


def _parse_shapes(spec: str):
    shapes = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            dims, dtype = part.split(":")
            m, n = dims.split("x")
            shapes.append((int(m), int(n), dtype))
        except ValueError:
            raise SystemExit(f"--shapes entry {part!r} is not of the form "
                             f"'MxN:dtype'")
    if not shapes:
        raise SystemExit("--shapes parsed to an empty list")
    return tuple(shapes)


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="svd-tune",
        description="Measured autotuner: benchmark the knob grid and write "
                    "a versioned tuning table.")
    p.add_argument("--smoke", action="store_true",
                   help="bounded smoke grid (2 shapes x 2 knob axes, tiny "
                        "budgets) — the `-m tune` CI lane's configuration")
    p.add_argument("--shapes", default=None, metavar="MxN:dtype,...",
                   help="benchmark shapes (default: a CPU-regenerable "
                        "small/medium set; --smoke overrides)")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="table output path (default: "
                        "reports/tuning-<backend>.json)")
    p.add_argument("--reps", type=int, default=3,
                   help="timed repetitions per grid point (best-of; the "
                        "warm-up run is always discarded)")
    p.add_argument("--budget-s", type=float, default=60.0,
                   help="per-point TIMED budget in seconds; a point whose "
                        "first repetition exceeds it records that one "
                        "honest rep and stops")
    p.add_argument("--min-gain", type=float, default=0.03,
                   help="fraction a challenger must beat the incumbent by "
                        "to win (conservative: below this is noise)")
    p.add_argument("--tiers", default="auto",
                   choices=["auto", "off"],
                   help="also measure serve batch tiers (svd_batched vs "
                        "serial same-session A/B) on the smallest shape")
    p.add_argument("--table-id", default=None,
                   help="table id (default: <backend>-<device_kind>-r01)")
    p.add_argument("--manifest", default="reports/manifest.jsonl",
                   help="manifest JSONL ('tune' records; 'off' disables)")
    p.add_argument("--platform", default=None,
                   help="pin the JAX backend (e.g. cpu) before any device "
                        "dial — the same escape hatch as bench.py")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = _parse_args(sys.argv[1:] if argv is None else list(argv))

    import jax
    platform = args.platform or os.environ.get("JAX_PLATFORMS")
    if platform:
        jax.config.update("jax_platforms", platform)

    from . import search, tables
    if args.smoke:
        shapes = search.SMOKE_SHAPES
        reps = min(args.reps, 2)
        budget_s = min(args.budget_s, 10.0)
    else:
        shapes = (_parse_shapes(args.shapes) if args.shapes
                  else search.DEFAULT_SHAPES)
        reps, budget_s = args.reps, args.budget_s
    if any(d == "float64" for _, _, d in shapes):
        jax.config.update("jax_enable_x64", True)

    backend = jax.default_backend()
    out = Path(args.out) if args.out else Path("reports") / (
        f"tuning-{backend}{'-smoke' if args.smoke else ''}.json")
    tiers_shape = None
    if args.tiers == "auto":
        # Tier measurement on the smallest shape: coalescing pays most at
        # small buckets (PROFILE.md item 22), and the smallest shape keeps
        # the B-stack solves inside the budget.
        tiers_shape = min(shapes, key=lambda s: s[0] * s[1] * s[1])
    summary = search.run(
        shapes=shapes, out_path=out, reps=reps, budget_s=budget_s,
        min_gain=args.min_gain, smoke=args.smoke, tiers_shape=tiers_shape,
        manifest_path=args.manifest,
        table_id=args.table_id)
    # Prove the written table loads + resolves before calling it done.
    table = tables.load_table(out)
    summary["rows"] = len(table.rows)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
