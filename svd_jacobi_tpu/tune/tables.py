"""Versioned tuning tables — the ONE lookup layer behind every "auto" knob.

PROFILE.md items 17-18 established that the performance-critical knobs
(block width ``b``, ``mixed_store``, ``pair_solver``, ``precondition``,
serve ``batch_tiers``) have data-size- and chip-dependent crossover points
that were found by hand, one chip and one round at a time. This module
replaces the growing if-ladders (``SVDConfig.pick_block_size``, the
``"auto"`` branches in ``solver._resolve_options``/``solver._plan_entry``)
with a declarative, schema-versioned, content-hashed table:

  * a table is a JSON document of ROWS; each row has a ``match`` block
    (``n_class`` / ``aspect`` / ``dtype`` / ``backend`` / ``device_kind``,
    absent keys are wildcards) and a ``knobs`` block (concrete values for
    any subset of :data:`KNOBS`);
  * :func:`resolve` classifies a problem ``(n, m, dtype, backend,
    device_kind)`` and walks the matching rows most-specific-first; the
    first row providing a knob wins, and the builtin ``generic`` defaults
    (exactly the pre-table hand-picked heuristics) backstop everything —
    so a MISSING or corrupt table degrades loudly to the historical
    behavior, never to a crash;
  * resolution is a PURE DETERMINISTIC function of its arguments — no
    clocks, no benchmarking, no device calls beyond the (cached) backend
    identity — so it is jit/retrace-safe and the analysis passes
    (``TUNE001``) can machine-check it.

Tables are produced two ways: the SHIPPED default
(``tune/tables/default.json``) encodes the measured conclusions of
PROFILE.md items 17-18 (b=256 for fused square n >= 8192, b=128 below and
for tall-skinny, ``mixed_store="f32"``), and `python -m svd_jacobi_tpu.tune`
regenerates a local table by measuring the knob grid on the attached
backend (``tune.search``). Pin one with ``--tuning-table=PATH`` (bench/cli),
``SVDJ_TUNING_TABLE=PATH`` (environment), or :func:`set_active_table`;
``off`` bypasses tables entirely (builtin generic defaults).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import os
import warnings
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple, Union

SCHEMA_VERSION = 1

# The tunable knobs a table row may pin. Everything else in SVDConfig is
# either semantic (tolerances, job options) or validated elsewhere.
# oversample / power_iters / tsqr_chunk are the SKETCH knobs of the
# top-k / tall lanes (solver.svd_topk / svd_tall / ops.sketch);
# grad_degenerate_rtol is the differentiable-solver safeguard band
# (svd_jacobi_tpu.grad — per-dtype rows: f32 needs a wider cluster band
# than f64, null = 8*eps of the accumulation dtype).
KNOBS = ("block_size", "mixed_store", "pair_solver", "precondition",
         "criterion", "batch_tiers", "oversample", "power_iters",
         "tsqr_chunk", "grad_degenerate_rtol", "rounds_resident")

# The sketch-knob subset, used by the TUNE001 coverage rule: a declared
# top-k serve bucket must get these from a MEASURED (non-generic) row.
SKETCH_KNOBS = ("oversample", "power_iters", "tsqr_chunk")

# Problem-size classes (columns n of the tall-oriented problem). The
# boundaries are the measured crossover neighborhoods of PROFILE.md item
# 18 (b=128 -> 256 at n = 8192) and the kernel-path threshold
# (solver._resolve_options: the Pallas lane needs min(m, n) >= 64 to
# block usefully).
N_CLASSES = ("tiny", "small", "medium", "large")
# Aspect classes on m/n of the tall-oriented (m >= n) problem. "tall"
# starts at m >= 8n: item 18's 65536x4096 (m/n = 16) keeps b=128 while
# 32768x8192 (m/n = 4) takes the square verdict — the boundary sits
# between them.
ASPECT_CLASSES = ("square", "tall")
TALL_ASPECT_RATIO = 8
# Rank classes of a top-k request (the k-class match axis): "none" is a
# full/tall solve (no truncation — rows matching a real k-class never
# apply to it), the rest bound the requested rank. Boundaries follow the
# serve bucket granularity (a bucket's k is the class representative).
K_CLASSES = ("none", "small", "medium", "large")

_MATCH_KEYS = ("n_class", "aspect", "dtype", "backend", "device_kind",
               "k_class")
_VALID_MIXED_STORE = ("f32", "bf16", "bf16g")
_VALID_PAIR_SOLVER = ("pallas", "block_rotation", "resident", "qr-svd",
                      "gram-eigh", "hybrid")
# "double" (dgejsv's second QR) is deliberately NOT a table value: it is
# a fused-single-solve-only mode the stepper/batched/mesh lanes cannot
# run, so a row pinning it would make the fused and served solves of the
# same problem diverge. Explicit config.precondition="double" remains
# available; tables choose between on/off.
_VALID_PRECONDITION = ("on", "off")
_VALID_CRITERION = ("follow", "rel", "abs")


def n_class(n: int) -> str:
    """Size class of the column count ``n`` (tall-oriented problem)."""
    if n < 64:
        return "tiny"
    if n < 2048:
        return "small"
    if n < 8192:
        return "medium"
    return "large"


def aspect_class(m: Optional[int], n: int) -> str:
    """Aspect class: "tall" from m >= 8n up, else "square". ``m`` None
    (callers that only know n, e.g. direct ``pick_block_size`` use)
    defaults to square — the historical n-only behavior."""
    if m is None:
        return "square"
    return "tall" if m >= TALL_ASPECT_RATIO * n else "square"


def k_class(k: Optional[int]) -> str:
    """Rank class of a top-k request; None/0 = "none" (full-rank solve)."""
    if not k:
        return "none"
    if k <= 64:
        return "small"
    if k <= 256:
        return "medium"
    return "large"


def normalize_device_kind(kind: str) -> str:
    """Canonical device-kind token: lowercase, spaces/underscores to
    dashes ("TPU v5 lite" -> "tpu-v5-lite") so table rows match across
    jax's spelling variations."""
    return str(kind).strip().lower().replace(" ", "-").replace("_", "-")


@functools.lru_cache(maxsize=None)
def _runtime_identity() -> Tuple[str, str]:
    """(backend, device_kind) of the attached runtime, cached. Resolution
    never calls this when the caller pins both — keeping offline use
    (table tooling, tests) free of any device dial."""
    import jax
    backend = jax.default_backend()
    devices = jax.devices()
    kind = devices[0].device_kind if devices else "unknown"
    return backend, normalize_device_kind(kind)


def heuristic_block_size(n: int) -> int:
    """The legacy hand-picked block-width ladder — the pre-table
    ``SVDConfig.pick_block_size`` body, kept verbatim as the ``generic``
    fallback so a missing/bypassed table reproduces the historical
    defaults bit-for-bit. Measured basis (PROFILE.md item 18): b=256
    crosses the f32 ridge and wins end-to-end from n = 8192 up (16384^2:
    34.8 vs 39.0 s) and loses below (4096^2: 0.98 vs 0.88 s); b=512
    exceeds the rotation kernel's scoped-VMEM budget (2.1x slower via
    the XLA fallback)."""
    if n >= 8192:
        return 256
    if n >= 2048:
        return 128
    b = 1
    while b * 16 <= n and b < 128:
        b *= 2
    return b


def default_gram_dtype(dtype) -> str:
    """The one declared mixed-precision accumulation boundary
    (``config.MIXED_PRECISION_BOUNDARIES``): Gram panels / rotations
    accumulate in ``promote_types(input, float32)`` — f32 for f32/bf16
    inputs, f64 for f64. Shared by ``solver._resolve_options`` and
    ``ops.blockwise.orthogonalize_pairs`` so the None-default cannot
    drift between the fused and block-solver lanes."""
    import jax.numpy as jnp
    return jnp.promote_types(jnp.dtype(dtype), jnp.float32).name


# The builtin ``generic`` knob set: exactly the historical hand-picked
# defaults. ``block_size`` None = the exact-n heuristic ladder;
# ``pair_solver`` "pallas" is a CANDIDATE subject to the solver's
# capability guards (f64 -> qr-svd, min(m, n) < 64 -> hybrid/gram-eigh,
# explicit criterion="abs" -> XLA solvers), which reproduce the old
# if-ladder; ``criterion`` "follow" = derive from the resolved method
# (abs for gram-eigh, rel otherwise).
GENERIC_KNOBS: Dict[str, object] = {
    "block_size": None,
    "mixed_store": "f32",      # PROFILE.md item 17 (v5e measured)
    "pair_solver": "pallas",
    "precondition": "on",
    "criterion": "follow",
    "batch_tiers": (1, 4, 16),  # config.DEFAULT_BATCH_TIERS
    # Sketch knobs of the top-k/tall lanes (Halko defaults): +8 columns
    # of oversampling, one stabilized power iteration, heuristic TSQR
    # chunk rows (None = ops.sketch.default_chunk).
    "oversample": 8,
    "power_iters": 1,
    "tsqr_chunk": None,
    # Differentiable-solver degenerate band (None = 8*eps of the
    # accumulation dtype at solve time — the dtype-derived floor; the
    # shipped table pins per-dtype rows on top).
    "grad_degenerate_rtol": None,
    # Residency depth R of the "resident" lane (None = the lane's
    # builtin ops.pallas_resident.DEFAULT_ROUNDS; solve-time clamped to
    # the sweep's 2k-1 rounds).
    "rounds_resident": None,
}


class TableError(ValueError):
    """A tuning table failed schema/content-hash validation."""


class Resolved(NamedTuple):
    """One resolution: every tunable knob concrete, plus provenance.

    ``block_size`` is always a concrete int (row value, or the heuristic
    ladder when the winning row declined to pin it). ``generic_only`` is
    True when NO non-generic row contributed any knob — the signal the
    TUNE001 analysis pass uses to prove the declared serve buckets are
    covered by measured rows; ``sketch_generic_only`` is the same signal
    restricted to the sketch knobs (:data:`SKETCH_KNOBS`) — TUNE001's
    extension for the top-k bucket family. ``source`` is
    "<table_id>:<row indices>" for provenance."""

    block_size: int
    mixed_store: str
    pair_solver: str
    precondition: str
    criterion: str
    batch_tiers: Tuple[int, ...]
    oversample: int
    power_iters: int
    tsqr_chunk: Optional[int]
    grad_degenerate_rtol: Optional[float]
    rounds_resident: Optional[int]
    generic_only: bool
    sketch_generic_only: bool
    source: str


def _validate_row(row: dict, where: str, errors: List[str]) -> None:
    if not isinstance(row, dict):
        errors.append(f"{where}: expected object, got {type(row).__name__}")
        return
    match = row.get("match")
    knobs = row.get("knobs")
    if not isinstance(match, dict):
        errors.append(f"{where}.match: missing or not an object")
        match = {}
    if not isinstance(knobs, dict):
        errors.append(f"{where}.knobs: missing or not an object")
        knobs = {}
    for k in match:
        if k not in _MATCH_KEYS:
            errors.append(f"{where}.match.{k}: unknown match key "
                          f"(known: {_MATCH_KEYS})")
    if "n_class" in match and match["n_class"] not in N_CLASSES:
        errors.append(f"{where}.match.n_class: {match['n_class']!r} not in "
                      f"{N_CLASSES}")
    if "aspect" in match and match["aspect"] not in ASPECT_CLASSES:
        errors.append(f"{where}.match.aspect: {match['aspect']!r} not in "
                      f"{ASPECT_CLASSES}")
    if "k_class" in match and match["k_class"] not in K_CLASSES:
        errors.append(f"{where}.match.k_class: {match['k_class']!r} not in "
                      f"{K_CLASSES}")
    for k in knobs:
        if k not in KNOBS:
            errors.append(f"{where}.knobs.{k}: unknown knob "
                          f"(known: {KNOBS})")
    bs = knobs.get("block_size", None)
    if bs is not None and (not isinstance(bs, int) or bs < 1):
        errors.append(f"{where}.knobs.block_size: expected null or int >= 1, "
                      f"got {bs!r}")
    for name, valid in (("mixed_store", _VALID_MIXED_STORE),
                        ("pair_solver", _VALID_PAIR_SOLVER),
                        ("precondition", _VALID_PRECONDITION),
                        ("criterion", _VALID_CRITERION)):
        if name in knobs and knobs[name] not in valid:
            errors.append(f"{where}.knobs.{name}: {knobs[name]!r} not in "
                          f"{valid}")
    if "oversample" in knobs and (
            not isinstance(knobs["oversample"], int)
            or knobs["oversample"] < 1):
        errors.append(f"{where}.knobs.oversample: expected int >= 1, got "
                      f"{knobs['oversample']!r}")
    if "power_iters" in knobs and (
            not isinstance(knobs["power_iters"], int)
            or knobs["power_iters"] < 0):
        errors.append(f"{where}.knobs.power_iters: expected int >= 0, got "
                      f"{knobs['power_iters']!r}")
    tc = knobs.get("tsqr_chunk", None)
    if tc is not None and (not isinstance(tc, int) or tc < 1):
        errors.append(f"{where}.knobs.tsqr_chunk: expected null or int >= 1, "
                      f"got {tc!r}")
    gr = knobs.get("grad_degenerate_rtol", None)
    if gr is not None and (not isinstance(gr, (int, float))
                           or isinstance(gr, bool) or not gr > 0):
        errors.append(f"{where}.knobs.grad_degenerate_rtol: expected null "
                      f"or a number > 0, got {gr!r}")
    rr = knobs.get("rounds_resident", None)
    if rr is not None and (not isinstance(rr, int) or isinstance(rr, bool)
                           or rr < 1):
        errors.append(f"{where}.knobs.rounds_resident: expected null or "
                      f"int >= 1, got {rr!r}")
    tiers = knobs.get("batch_tiers")
    if tiers is not None and (
            not isinstance(tiers, (list, tuple)) or not tiers
            or any(not isinstance(t, int) or t < 1 for t in tiers)):
        errors.append(f"{where}.knobs.batch_tiers: expected a non-empty "
                      f"list of ints >= 1, got {tiers!r}")
    elif tiers is not None and 1 not in tiers:
        # Without tier 1 a lone request would zero-pad into the smallest
        # larger tier — paying a multi-member batched solve per solo
        # request, silently. The search harness always includes 1.
        errors.append(f"{where}.knobs.batch_tiers: must include tier 1 "
                      f"(the non-coalesced dispatch), got {tiers!r}")


def content_hash(payload: dict) -> str:
    """SHA-256 of the canonical-JSON table body (everything except
    ``content_sha256`` itself) — the same content-hash discipline as
    ``obs.manifest.config_hash``: two tables with equal hashes resolve
    identically whatever the file's formatting."""
    body = {k: v for k, v in payload.items() if k != "content_sha256"}
    canon = json.dumps(body, sort_keys=True, default=list)
    return hashlib.sha256(canon.encode()).hexdigest()


@dataclasses.dataclass(frozen=True)
class TuningTable:
    """An immutable, validated tuning table (see module docstring)."""

    table_id: str
    rows: Tuple[dict, ...]
    sha256: str
    provenance: str = ""

    @staticmethod
    def from_payload(payload: dict, *, verify_hash: bool = True
                     ) -> "TuningTable":
        """Validate a parsed JSON document into a table. Raises
        :class:`TableError` listing every violation; hash mismatches are
        a violation too (a hand-edited table must be re-hashed via
        :func:`save_table` — silent edits are exactly what the hash
        exists to catch)."""
        errors: List[str] = []
        if not isinstance(payload, dict):
            raise TableError("table: not a JSON object")
        # Canonicalize to pure JSON values (tuples -> lists) so a table
        # built in memory and its file round-trip compare equal; the
        # content hash already serializes through the same mapping.
        payload = json.loads(json.dumps(payload, default=list))
        version = payload.get("schema_version")
        if version != SCHEMA_VERSION:
            errors.append(f"schema_version: {version!r} != supported "
                          f"{SCHEMA_VERSION}")
        table_id = payload.get("table_id")
        if not isinstance(table_id, str) or not table_id:
            errors.append("table_id: missing or empty")
            table_id = "?"
        rows = payload.get("rows")
        if not isinstance(rows, list) or not rows:
            errors.append("rows: missing or empty")
            rows = []
        for i, row in enumerate(rows):
            _validate_row(row, f"rows[{i}]", errors)
        declared = payload.get("content_sha256")
        actual = content_hash(payload)
        if verify_hash and declared != actual:
            errors.append(f"content_sha256: declared {str(declared)[:12]}... "
                          f"!= actual {actual[:12]}... (table edited "
                          f"without re-hashing?)")
        if errors:
            raise TableError("invalid tuning table: " + "; ".join(errors))
        return TuningTable(table_id=table_id,
                           rows=tuple(dict(r) for r in rows),
                           sha256=actual,
                           provenance=str(payload.get("provenance", "")))

    def to_payload(self) -> dict:
        payload = {
            "schema_version": SCHEMA_VERSION,
            "table_id": self.table_id,
            "provenance": self.provenance,
            "rows": [dict(r) for r in self.rows],
        }
        payload["content_sha256"] = content_hash(payload)
        return payload

    def _matching_rows(self, key: Dict[str, str]) -> List[Tuple[int, dict]]:
        """(index, row) of every row matching ``key``, most specific
        first (ties keep declaration order — tables list their sharper
        rows first by convention)."""
        scored = []
        for i, row in enumerate(self.rows):
            match = row.get("match", {})
            ok = all(match[k] == key[k] for k in match)
            if ok:
                scored.append((-len(match), i, row))
        scored.sort(key=lambda t: (t[0], t[1]))
        return [(i, row) for _, i, row in scored]

    def resolve(self, n: int, m: Optional[int] = None,
                dtype: str = "float32", backend: Optional[str] = None,
                device_kind: Optional[str] = None,
                k: Optional[int] = None) -> Resolved:
        """Resolve every tunable knob for one problem (see module
        docstring for the layered row semantics). ``k`` is the top-k
        request rank (None = full/tall solve): it selects the k-class
        match axis, so rows can pin sketch knobs per rank class."""
        import jax.numpy as jnp
        if backend is None or device_kind is None:
            rb, rk = _runtime_identity()
            backend = backend or rb
            device_kind = device_kind or rk
        key = {
            "n_class": n_class(int(n)),
            "aspect": aspect_class(None if m is None else int(m), int(n)),
            "dtype": str(jnp.dtype(dtype).name),
            "backend": str(backend),
            "device_kind": normalize_device_kind(device_kind),
            "k_class": k_class(None if k is None else int(k)),
        }
        knobs = dict(GENERIC_KNOBS)
        contributors: List[str] = []
        generic_only = True
        sketch_generic_only = True
        unresolved = set(KNOBS)
        for i, row in self._matching_rows(key):
            row_knobs = row.get("knobs", {})
            took = [k_ for k_ in list(unresolved) if k_ in row_knobs]
            for k_ in took:
                knobs[k_] = row_knobs[k_]
                unresolved.discard(k_)
            if took:
                contributors.append(str(i))
                if row.get("match"):
                    generic_only = False
                    if any(k_ in SKETCH_KNOBS for k_ in took):
                        sketch_generic_only = False
            if not unresolved:
                break
        bs = knobs["block_size"]
        tc = knobs["tsqr_chunk"]
        gr = knobs["grad_degenerate_rtol"]
        rr = knobs["rounds_resident"]
        return Resolved(
            block_size=int(bs) if bs is not None
            else heuristic_block_size(int(n)),
            mixed_store=str(knobs["mixed_store"]),
            pair_solver=str(knobs["pair_solver"]),
            precondition=str(knobs["precondition"]),
            criterion=str(knobs["criterion"]),
            batch_tiers=tuple(int(t) for t in knobs["batch_tiers"]),
            oversample=int(knobs["oversample"]),
            power_iters=int(knobs["power_iters"]),
            tsqr_chunk=None if tc is None else int(tc),
            grad_degenerate_rtol=None if gr is None else float(gr),
            rounds_resident=None if rr is None else int(rr),
            generic_only=generic_only,
            sketch_generic_only=sketch_generic_only,
            source=f"{self.table_id}:{','.join(contributors) or 'builtin'}",
        )


def builtin_table() -> TuningTable:
    """The in-memory fallback table: ONE generic row carrying the
    hand-picked defaults (:data:`GENERIC_KNOBS`). Used when no table is
    shipped/pinned, when the active table fails validation, and under
    ``--tuning-table=off`` — in all three cases resolution equals the
    pre-table heuristics exactly."""
    rows = ({"match": {}, "knobs": dict(GENERIC_KNOBS)},)
    payload = {"schema_version": SCHEMA_VERSION, "table_id": "builtin",
               "provenance": "hand-picked defaults (pre-table heuristics)",
               "rows": [dict(r) for r in rows]}
    return TuningTable(table_id="builtin", rows=rows,
                       sha256=content_hash(payload),
                       provenance=payload["provenance"])


def load_table(path) -> TuningTable:
    """Load + validate one table file. Raises :class:`TableError` /
    ``OSError`` — callers that must never crash (the active-table
    machinery) catch and fall back to :func:`builtin_table`."""
    with Path(path).open() as f:
        payload = json.load(f)
    return TuningTable.from_payload(payload)


def save_table(path, *, table_id: str, rows: Sequence[dict],
               provenance: str = "") -> TuningTable:
    """Validate, content-hash and write a table; returns the loaded
    result (so a written table is by construction loadable)."""
    payload = {
        "schema_version": SCHEMA_VERSION,
        "table_id": str(table_id),
        "provenance": str(provenance),
        "rows": [dict(r) for r in rows],
    }
    payload["content_sha256"] = content_hash(payload)
    table = TuningTable.from_payload(payload)   # validate before writing
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("w") as f:
        json.dump(payload, f, indent=2, sort_keys=True, default=list)
        f.write("\n")
    return table


def shipped_table_dir() -> Path:
    return Path(__file__).parent / "tables"


def shipped_table_path() -> Path:
    return shipped_table_dir() / "default.json"


# --------------------------------------------------------------------------
# Active-table selection. Priority: explicit set_active_table() > the
# SVDJ_TUNING_TABLE environment variable > the shipped default. A table
# that fails to load is a LOUD warning + builtin fallback, never a crash
# (a corrupt table file must not take the solver down with it).

_ENV_VAR = "SVDJ_TUNING_TABLE"
_active: Dict[str, object] = {"table": None, "pinned": False,
                              "env_seen": None}


def _load_or_fallback(source: str, loader) -> TuningTable:
    try:
        return loader()
    except (TableError, OSError, json.JSONDecodeError) as e:
        warnings.warn(
            f"tuning table {source} failed to load ({e}); falling back to "
            f"the builtin generic defaults (hand-picked heuristics)",
            RuntimeWarning, stacklevel=3)
        return builtin_table()


def set_active_table(
        table: Union[None, str, Path, TuningTable]) -> TuningTable:
    """Pin the process-wide active table. ``"off"`` = builtin generic
    defaults (bypass tables); a path = load it (loud fallback to builtin
    on failure); a :class:`TuningTable` = use as-is; ``None`` = unpin
    (back to env/shipped discovery). Returns the now-active table."""
    if table is None:
        _active.update(table=None, pinned=False, env_seen=None)
        return active_table()
    if isinstance(table, TuningTable):
        resolved = table
    elif str(table) == "off":
        resolved = builtin_table()
    else:
        resolved = _load_or_fallback(str(table),
                                     lambda: load_table(table))
    _active.update(table=resolved, pinned=True)
    return resolved


def active_table() -> TuningTable:
    """The table :func:`resolve` consults (see selection priority above).
    The environment variable is re-read on change so test harnesses can
    swap tables between cases without touching module state."""
    env = os.environ.get(_ENV_VAR)
    if not _active["pinned"]:
        if env != _active["env_seen"] or _active["table"] is None:
            _active["env_seen"] = env
            if env == "off":
                _active["table"] = builtin_table()
            elif env:
                _active["table"] = _load_or_fallback(
                    f"{_ENV_VAR}={env}", lambda: load_table(env))
            else:
                path = shipped_table_path()
                if path.exists():
                    _active["table"] = _load_or_fallback(
                        str(path), lambda: load_table(path))
                else:
                    _active["table"] = builtin_table()
    return _active["table"]


def resolve(n: int, m: Optional[int] = None, dtype: str = "float32",
            backend: Optional[str] = None,
            device_kind: Optional[str] = None,
            k: Optional[int] = None,
            table: Optional[TuningTable] = None) -> Resolved:
    """Module-level resolution through the active (or given) table —
    the single lookup every "auto" knob goes through. Deterministic:
    same arguments + same table content => same result, in any process
    (proven by tests/test_tune.py's cross-process case). ``k`` selects
    the top-k rank class (None = full/tall solve)."""
    t = table if table is not None else active_table()
    return t.resolve(n, m=m, dtype=dtype, backend=backend,
                     device_kind=device_kind, k=k)


def resolve_config(config, m: int, n: int, dtype,
                   backend: Optional[str] = None,
                   device_kind: Optional[str] = None,
                   k: Optional[int] = None):
    """A concrete ``SVDConfig`` for one declared problem shape: every
    knob the caller left on "auto"/None is pinned to the table's choice
    (explicit user values always win). Used by the serving layer to
    resolve ONCE per bucket at declaration — lanes inherit the resolved
    config and never re-resolve per dispatch.

    Only shape-safe knobs are pinned: ``block_size`` (the value the
    solver's own planner would resolve to — identical jit keys),
    ``mixed_store`` (read only on the mixed Pallas path, valid
    everywhere), and the sketch knobs ``oversample``/``power_iters``/
    ``tsqr_chunk`` (read only by the top-k/tall lanes; ``k`` selects
    their rank class for top-k buckets).
    ``pair_solver``/``precondition``/``criterion`` stay
    "auto": their resolution is capability-guarded per entry point
    (f64/tiny-n/compute_uv) and pinning them here would turn the
    solver's auto-routing into hard validation errors on the guarded
    paths. They still resolve through the SAME table at solve time, so
    the choice is one table either way."""
    import dataclasses as _dc
    if m < n:
        m, n = n, m   # tall orientation, as every solve entry enforces
    r = resolve(n, m=m, dtype=dtype, backend=backend,
                device_kind=device_kind, k=k)
    updates = {}
    if config.block_size is None:
        updates["block_size"] = int(r.block_size)
    if config.mixed_store == "auto":
        updates["mixed_store"] = r.mixed_store
    # Sketch knobs (read only by the top-k/tall lanes, valid everywhere):
    # pinned to what solve-time auto resolution would pick for this
    # (shape, k-class) — identical static jit arguments either way.
    if config.oversample is None:
        updates["oversample"] = int(r.oversample)
    if config.power_iters is None:
        updates["power_iters"] = int(r.power_iters)
    if config.tsqr_chunk is None and r.tsqr_chunk is not None:
        updates["tsqr_chunk"] = int(r.tsqr_chunk)
    # The differentiable-solver safeguard band (read only by the grad
    # rules; valid everywhere): pinned like the sketch knobs so a
    # bucket-resolved config differentiates identically to solve-time
    # auto resolution.
    if (getattr(config, "grad_degenerate_rtol", None) is None
            and r.grad_degenerate_rtol is not None):
        updates["grad_degenerate_rtol"] = float(r.grad_degenerate_rtol)
    return _dc.replace(config, **updates) if updates else config
