"""Measured autotuner: benchmark-driven tuning tables behind every "auto".

Two halves (ROADMAP "Measured autotuner replacing hand-picked constants"):

  * :mod:`tune.tables` — schema-versioned, content-hashed tuning tables
    plus the ONE deterministic lookup (:func:`resolve`) every "auto"/None
    knob in `SVDConfig` goes through (block width, ``mixed_store``,
    ``pair_solver``, ``precondition``, ``criterion``, serve batch tiers).
    Shipped defaults (``tune/tables/default.json``) encode the measured
    conclusions of PROFILE.md items 17-18; a missing or corrupt table
    falls back — loudly — to the builtin generic row, which reproduces
    the historical hand-picked heuristics exactly.
  * :mod:`tune.search` — the ATLAS/OpenTuner-style empirical search
    harness (`python -m svd_jacobi_tpu.tune`, `cli.py tune`): benchmarks
    the knob grid per (n-class, aspect-class, dtype, backend,
    device_kind) with a same-session A/B protocol, warm-up discard and a
    per-point time budget, writes a regenerated table, and appends one
    schema-versioned "tune" manifest record per searched shape so a
    table's provenance reconstructs from the record stream.
"""

from __future__ import annotations

from .tables import (GENERIC_KNOBS, KNOBS, Resolved, TableError, TuningTable,
                     active_table, aspect_class, builtin_table,
                     default_gram_dtype, heuristic_block_size, load_table,
                     n_class, resolve, resolve_config, save_table,
                     set_active_table, shipped_table_dir, shipped_table_path)

__all__ = [
    "GENERIC_KNOBS", "KNOBS", "Resolved", "TableError", "TuningTable",
    "active_table", "aspect_class", "builtin_table", "default_gram_dtype",
    "heuristic_block_size", "load_table", "n_class", "resolve",
    "resolve_config", "save_table", "set_active_table", "shipped_table_dir",
    "shipped_table_path",
]
