"""Benchmark driver: one-sided block-Jacobi SVD on the attached accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

The reference publishes no numbers (SURVEY.md section 6), so the baseline is
self-generated on the same device: `jnp.linalg.svd` (XLA's built-in SVD) on
the identical input — `vs_baseline` is our speedup over it (>1 means faster).
`value` is nominal GFLOP/s using the classic full-SVD flop count
4*m*n^2 + 8*n^3 (= 12 n^3 at m = n), so runs at different shapes stay
comparable; `mfu` relates that to the chip's f32-effective peak.

Usage:
  python bench.py [N] [dtype] [M]      (defaults: 2048, float32, M=N)
  flags: --baseline=xla|numpy    (numpy: for CPU-backend parity runs)
         --oracle=auto|on|off    (off skips the host f64 sigma oracle;
                                  auto skips it above 2048)
         --reps=K                (best-of-K interleaved timing, default 6)
         --novec                 (sigma-only solve, jobu = jobv = NoVec)
         --no-baseline           (skip the XLA baseline entirely — for
                                  sizes where its attempt is KNOWN to OOM
                                  the device and poison the heap for the
                                  timed run that follows)
         --backend-timeout=SECS  (deadline for device discovery, default
                                  300 — a downed device pool HANGS
                                  jax.devices(); past the deadline a
                                  parseable error row is emitted and the
                                  process exits with code 3)
         --sweep                 (run the whole BASELINE.md accelerator
                                  table — one JSON line per config — in a
                                  fresh subprocess each so compile caches
                                  and HBM don't leak across sizes; a
                                  baseline that cannot compile, e.g. XLA
                                  svd at 16384^2, reports vs_baseline
                                  null instead of failing the row)
         --manifest=PATH         (run manifest: append one obs.manifest
                                  JSONL record per run; default
                                  reports/manifest.jsonl, =off disables)
         --telemetry             (also capture the in-graph per-sweep
                                  event stream into the manifest, from ONE
                                  extra UNTIMED telemetered solve after
                                  the timing loop — the timed repetitions
                                  stay on the zero-telemetry jit, so the
                                  reported numbers are unperturbed)
         --serve-throughput      (closed-loop serve benchmark of the
                                  request-coalescing lane: one JSON row
                                  of requests/s + p50/p99 latency per
                                  --tiers batch tier over the same
                                  seeded mix, plus the coalesced-over-
                                  serial speedup row; see
                                  bench._serve_throughput for its flags)
         --serve-coldstart       (cold vs warm restart cost of the
                                  persistent executable cache: two
                                  serve-demo --warmup subprocesses
                                  against one cache dir; the warm row
                                  must report ZERO fresh compiles —
                                  PROFILE.md item 26)
         --serve-tenants         (multi-tenant fairness A/B: the seeded
                                  adversarial flood schedule paced in
                                  real time through the pre-tenancy
                                  anonymous surface vs the QoS front
                                  door — victim p50/p99 + goodput and
                                  abuser served/shed per arm, plus the
                                  victim-p99 isolation ratio row —
                                  PROFILE.md item 35)
         --serve-metrics-overhead (same-session A/B of the closed-loop
                                  throughput fleet with the flight
                                  recorder ON vs OFF: interleaved laps
                                  of the same seeded mix, one JSON row
                                  per mode plus an overhead row —
                                  acceptance: < 2% req/s delta on the
                                  2-core container; PROFILE.md item 28)
         --serve-twophase        (the don't-recompute ledger, all
                                  same-session A/B: sigma-phase and
                                  promote-to-full latency vs a cold
                                  full solve, svd_update vs cold on a
                                  rank-1-perturbed input, and the
                                  result-cache hit row with its
                                  zero-dispatch proof — PROFILE.md
                                  item 27)
         --tuning-table=PATH     (pin a measured tuning table for every
                                  "auto" knob; =off bypasses tables —
                                  the builtin hand-picked heuristics.
                                  The A/B lever of PROFILE.md item 24)
         --retry-backoff-s=SECS  (backoff before the ONE bounded retry a
                                  transient backend failure earns —
                                  UNAVAILABLE/device-pool outages, the
                                  BENCH_r05 class; the retry is noted in
                                  the emitted row as "retried".
                                  Default 15)
         --top-k=K               (truncated top-K row via the randomized
                                  range-finder lane, timed against OUR
                                  OWN full solve at the same shape; emits
                                  the svd_topk GFLOP/s row under the
                                  honest 2mnl-class flop model PLUS a
                                  topk_speedup row — the >= 4x
                                  acceptance number)
         --tall-vs-pad           (tall-lane row, m >= 8n required: timed
                                  against the full solve on the input
                                  padded to square; emits a
                                  tall_vs_pad_speedup row)
         --pair-solver=NAME      (pin the solver lane; a non-auto pin
                                  makes the baseline OUR OWN auto-routed
                                  solve and emits a pair_solver_speedup
                                  A/B row — e.g.
                                  --pair-solver=block_rotation for the
                                  MXU-native blocked-rotation lane vs
                                  the current kernel)

Every solve row carries ``mfu``: measured GFLOP/s over the device's
f32-effective peak (obs.costmodel.PEAK_FLOPS, keyed by device kind; CPU
rows use a documented rough estimate and say so via "peak_est"), plus
``peak_flops_source``/``hbm_bw_source`` provenance ("table" vs the
estimate fallback) for every derived metric.

``--check-against=BENCH_rNN.json`` gates the fresh headline row against
the BENCH_*.json history beside that file: `obs.perf.check_rows` fits a
per-metric noise band from repeated rows and the bench exits rc 4 on a
regression beyond it — append and gate in one run.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# f32-effective peak FLOP/s by device kind: the authoritative table now
# lives in `svd_jacobi_tpu.obs.costmodel.PEAK_FLOPS`, right beside its
# HBM-bandwidth sibling (`costmodel.HBM_BW`) so the MFU denominator and
# the roofline denominators can never disagree. TPU entries are the
# chip's bf16 MXU peak / 6 (the solver's f32-HIGHEST matmuls run as
# bf16x6 passes); the "cpu" entry is a DOCUMENTED ROUGH ESTIMATE for
# the 2-core bench container (2 cores x ~8 f32 FLOP/cycle FMA+AVX x
# ~3 GHz ~= 48 GFLOP/s). Unknown device kinds fall back to the CPU
# estimate; the estimated bit lands in the row as
# `peak_flops_source="peak_est"` / `hbm_bw_source="bw_est"` so an
# uncalibrated MFU or roofline number can never pass silently as a
# measured one.

def _peak_flops(device_kind: str):
    """(peak_flops, estimated?) for one device kind."""
    from svd_jacobi_tpu.obs.costmodel import peak_flops
    return peak_flops(device_kind)


def _hbm_bw(device_kind: str):
    """(bytes/s, estimated?) for one device kind."""
    from svd_jacobi_tpu.obs.costmodel import hbm_bandwidth
    return hbm_bandwidth(device_kind)


def _mfu(gflops: float, device_kind: str):
    """(mfu, estimated?) of a measured GFLOP/s rate on this device."""
    peak, est = _peak_flops(device_kind)
    return round(gflops * 1e9 / peak, 4), est


def _model_hbm_gbps(cfg, m, n, dtype_name, pair_solver, sweeps, t_s,
                    novec, top_k):
    """(modeled GB/s, resolved lane): the cost model's solve HBM bytes
    (obs.costmodel.solve_costs — the SAME model the roofline observatory
    joins traces against) over the measured wall time. The bandwidth-side
    twin of `mfu`: a lane that cuts traffic at equal FLOPs (resident:
    ~R x fewer apply bytes per sweep) moves THIS number even when
    GFLOP/s barely does. Modeled bytes, not counters — comparable across
    rows, honest about its provenance via `hbm_bw_source`."""
    import numpy as _np
    from svd_jacobi_tpu import solver as _solver
    from svd_jacobi_tpu.obs import costmodel
    mm, nn = (m, n) if m >= n else (n, m)
    ps = pair_solver
    if ps == "auto":
        from svd_jacobi_tpu.tune import tables as _tables
        ps = _tables.resolve(nn, mm, dtype_name).pair_solver or "pallas"
    b = cfg.pick_block_size(nn, m=mm, dtype=dtype_name)
    rr = None
    if ps == "resident":
        if b % 2:
            b += 1
        k = max(1, -(-nn // (2 * b)))
        rr = _solver._resolve_rounds_resident(
            cfg, nn, mm, _np.dtype(dtype_name), 2 * k - 1)
    # Staged kernel lanes spend all but the ~2 polish sweeps in bulk
    # (the solver's measured bulk->polish crossover on the bench
    # spectra); single-stage lanes are all-polish.
    bulk = (max(0.0, float(sweeps) - 2.0)
            if ps in ("hybrid", "block_rotation", "resident") else 0.0)
    phases = costmodel.solve_costs(
        mm, nn, block_size=b, dtype=dtype_name, pair_solver=ps,
        sweeps=max(float(sweeps), 1.0), bulk_sweeps=bulk,
        compute_u=not novec, compute_v=not novec,
        top_k=top_k, rounds_resident=rr)
    return round(costmodel.total_cost(phases).hbm_bytes / t_s / 1e9,
                 3), ps


def _force(tree):
    from svd_jacobi_tpu.utils._exec import force
    return force(tree)


# Error-text markers of TRANSIENT backend failures (device-pool outage,
# tunnel reset — the BENCH_r05 class) as opposed to deterministic ones
# (OOM, shape/validation errors). Deliberately narrow: retrying a
# deterministic failure would just double the time to the same error row.
_TRANSIENT_MARKERS = ("UNAVAILABLE", "ABORTED", "device pool",
                      "socket closed", "connection reset",
                      "backend unreachable", "heartbeat")


def _transient_reason(err: "str | None") -> "str | None":
    """The matched marker when ``err`` reads as a transient backend
    failure, else None."""
    if not err:
        return None
    low = err.lower()
    for marker in _TRANSIENT_MARKERS:
        if marker.lower() in low:
            return marker
    return None


def _time_interleaved(fns, *args, reps: int = 2):
    """(best_times, warm_results, errors): best-of-reps device wall time
    for each callable, forced by scalar readback, with the timed
    repetitions of all callables INTERLEAVED — the tunnel's latency
    drifts on the seconds scale, so back-to-back blocks would hand
    whichever runs second a different environment. The warm-up results
    are returned so callers do not pay an extra full solve to get the
    factors.

    A callable that FAILS to compile/run (e.g. `jnp.linalg.svd` at 16384^2
    OOM-kills the remote TPU compile helper) gets time None and warm None
    instead of sinking the whole bench run; its stringified error rides in
    ``errors`` so the caller can tell a transient outage (worth one
    bounded retry) from a deterministic failure."""
    warms, dead = [], set()
    errors = [None] * len(fns)
    for i, f in enumerate(fns):
        try:
            w = f(*args)
            _force(w)  # compile + warm
        except Exception as e:
            print(f"note: candidate {i} failed ({type(e).__name__}); "
                  f"timing the others", file=sys.stderr)
            w = None
            dead.add(i)
            errors[i] = f"{type(e).__name__}: {e}"
            import gc
            gc.collect()   # release the failed attempt's device buffers
        warms.append(w)
    if dead:
        # A failed candidate (OOM-killed remote compile, device OOM) can
        # leave the backend in a degraded state; one untimed re-run of each
        # live candidate restores caches before anything is measured
        # (observed: 16384^2 measured 99 s right after the baseline's
        # compiler was OOM-killed vs 39 s clean).
        for i, f in enumerate(fns):
            if i not in dead:
                try:
                    _force(f(*args))
                except Exception as e:
                    print(f"note: candidate {i} failed on the re-warm "
                          f"({type(e).__name__})", file=sys.stderr)
                    dead.add(i)
                    warms[i] = None
                    errors[i] = f"{type(e).__name__}: {e}"
    best = [float("inf")] * len(fns)
    for _ in range(max(1, reps)):
        for i, f in enumerate(fns):
            if i in dead:
                continue
            t0 = time.perf_counter()
            try:
                _force(f(*args))
            except Exception as e:
                # A failure DURING the timed repetitions (the mid-round
                # outage class) kills this candidate the same way a warm
                # failure does — partial timings are discarded so the
                # caller's transient-retry path sees time None + the
                # error, not a number measured against a dying backend.
                print(f"note: candidate {i} failed mid-timing "
                      f"({type(e).__name__}); dropping its timings",
                      file=sys.stderr)
                dead.add(i)
                warms[i] = None
                errors[i] = f"{type(e).__name__}: {e}"
                continue
            best[i] = min(best[i], time.perf_counter() - t0)
    best = [None if i in dead else b for i, b in enumerate(best)]
    return best, warms, errors


# The measured-table configs of BASELINE.md (square + tall-skinny, f32,
# up to the largest shapes that fit the 16 GB HBM; 16384^2 has no XLA
# baseline — jnp.linalg.svd cannot compile there). The f64 row runs the
# fp64 accuracy class (the reference's end-to-end precision,
# lib/Matrix.cuh:13) on the CPU backend every round — f64 routes to the
# qr-svd XLA block solver (solver._resolve_options: the Pallas kernel
# computes rotations in f32 and the TPU has no native f64 MXU).
SWEEP_CONFIGS = [
    ("2048", "float32", None, []),
    ("4096", "float32", None, []),
    ("5000", "float32", None, []),
    ("8192", "float32", None, []),
    ("2048", "float32", "16384", []),
    ("4096", "float32", "65536", []),
    ("512", "float64", None, ["--platform=cpu", "--baseline=numpy"]),
    ("16384", "float32", None, ["--reps=2"]),
    ("8192", "float32", "32768", ["--no-baseline", "--reps=2"]),
    ("16384", "float32", None, ["--novec", "--reps=2"]),
    # The reference's staged scale targets (runSVDMPICUDAWithoutCMake.slurm
    # :34-36). 20000^2 sigma-only fits the attachment's ~90 s
    # single-execution deadline fused (PROFILE.md item 19); the 30000-class
    # row (30208^2 = next exact block multiple) must run host-stepped
    # (one jitted sweep per execution) with the input buffer released
    # after init (--donate) to fit HBM.
    ("20000", "float32", None, ["--novec", "--no-baseline", "--reps=2"]),
    ("20000", "float32", None, ["--no-baseline", "--reps=1", "--stepped",
                                "--donate", "--precondition=off",
                                "--sigma-refine=off"]),
    ("30208", "float32", None, ["--novec", "--no-baseline", "--reps=1",
                                "--precondition=off", "--stepped",
                                "--donate"]),
]


# Exit code for "backend unreachable" (the watchdog row): lets --sweep
# stop after the first dead-backend row instead of paying the discovery
# deadline once per remaining config.
_BACKEND_DOWN_RC = 3


def _serve_throughput(flags) -> None:
    """--serve-throughput: closed-loop serve benchmark of the coalescing
    win. A fleet of client threads drives one bucket's request mix
    through a live `serve.SVDService`, once per configured batch tier
    (same mix, same fleet), and each tier emits one parseable JSON row:
    requests/s + p50/p99 end-to-end latency. A final row reports the
    coalesced-over-serial speedup — the number the micro-batched solve
    lane exists for (PROFILE.md item 22).

    Flags: --bucket=MxN:dtype (default 64x64:float32)
           --tiers=1,16       (max_batch values to measure, in order)
           --lanes=1,2        (fleet lane counts to measure per tier; a
                               lanes>1 row also emits a lane-scaling
                               ratio vs the lanes=1 row of its tier —
                               PROFILE.md item 23)
           --requests=N --clients=C --batch-window-ms=W --deadline-s=D
    """
    import os
    import threading

    import jax
    platform = flags.get("platform") or os.environ.get("JAX_PLATFORMS")
    if platform:
        jax.config.update("jax_platforms", platform)

    from svd_jacobi_tpu.serve import as_bucket
    bucket = as_bucket(flags.get("bucket", "64x64:float32"))
    if bucket.dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    if "tuning-table" in flags:
        from svd_jacobi_tpu import tune
        tune.set_active_table(flags["tuning-table"])

    import jax.numpy as jnp

    from svd_jacobi_tpu import SVDConfig
    from svd_jacobi_tpu.serve import ServeConfig, SVDService
    from svd_jacobi_tpu.utils import matgen

    requests = int(flags.get("requests", "64"))
    clients = int(flags.get("clients", "32"))
    window_ms = float(flags.get("batch-window-ms", "25"))
    deadline_s = float(flags.get("deadline-s", "600"))
    tiers = [int(t) for t in flags.get("tiers", "1,16").split(",")]
    lanes_list = [int(x) for x in flags.get("lanes", "1").split(",")]
    # --pair-solver=pallas pins the stacked kernel lane for buckets below
    # the auto threshold (n < 64) — tiny buckets are exactly where
    # coalescing pays most, and the stacked lane amortizes where the
    # vmapped XLA lane cannot.
    solver_cfg = SVDConfig(pair_solver=flags.get("pair-solver", "auto"))

    # One shared request mix (seeded) so every tier serves the same work.
    # Held as HOST arrays: client threads then submit numpy, whose
    # admission screen is a free host check instead of a per-submit
    # device op contending with the worker's solve.
    mats = [np.asarray(matgen.random_dense(bucket.m, bucket.n,
                                           seed=1000 + i,
                                           dtype=jnp.dtype(bucket.dtype)))
            for i in range(min(requests, 16))]

    rows = []
    for max_batch, n_lanes in [(t, l) for t in tiers for l in lanes_list]:
        cfg = ServeConfig(
            buckets=(bucket,), solver=solver_cfg,
            max_queue_depth=max(64, 4 * max_batch),
            max_batch=max_batch,
            batch_window_s=(window_ms / 1e3 if max_batch > 1 else 0.0),
            batch_tiers=((1, max_batch) if max_batch > 1 else (1,)),
            lanes=n_lanes, steal=True,
            # Brownout off: a degraded response would change the work mix
            # between tiers and poison the comparison.
            brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
        svc = SVDService(cfg).start()
        svc.warmup(timeout=1800.0)

        outcomes = []
        lock = threading.Lock()
        counter = [0]

        def client(_cid):
            while True:
                with lock:
                    i = counter[0]
                    if i >= requests:
                        return
                    counter[0] += 1
                a = mats[i % len(mats)]
                t0 = time.perf_counter()
                try:
                    res = svc.submit(a, deadline_s=deadline_s).result(
                        timeout=1800.0)
                    ok = (res.error is None and res.status is not None
                          and res.status.name == "OK")
                except Exception:
                    ok = False
                dt = time.perf_counter() - t0
                with lock:
                    outcomes.append((dt, ok))

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(c,), daemon=True)
                   for c in range(max(1, clients))]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=1800.0)
        wall = time.perf_counter() - t0
        stats = svc.stats()
        svc.stop(drain=True, timeout=60.0)

        lat = sorted(d for d, _ in outcomes)
        q = (lambda p: round(lat[min(len(lat) - 1,
                                     int(p * len(lat)))] * 1e3, 2)
             if lat else None)
        row = {
            "metric": (f"serve_throughput_{bucket.name}_b{max_batch}"
                       f"_l{n_lanes}"),
            "value": round(len(outcomes) / wall, 2),
            "unit": "requests/s",
            "max_batch": max_batch,
            "lanes": n_lanes,
            "batch_window_ms": window_ms,
            "clients": clients,
            "requests": len(outcomes),
            "ok": sum(1 for _, ok in outcomes if ok),
            "p50_ms": q(0.50), "p99_ms": q(0.99),
            "wall_s": round(wall, 3),
            "batched_dispatches": stats.get("batched_dispatches", 0),
            "device": str(jax.devices()[0]),
        }
        print(json.dumps(row))
        rows.append(row)
    base_rows = {(r["max_batch"], r["lanes"]): r for r in rows}
    base = base_rows.get((1, 1))
    if base is not None and base["value"]:
        for r in rows:
            if r is base or r["lanes"] != 1:
                continue
            print(json.dumps({
                "metric": (f"serve_coalescing_speedup_{bucket.name}"
                           f"_b{r['max_batch']}"),
                "value": round(r["value"] / base["value"], 3),
                "unit": "x vs batch-1",
                "ok": (r["ok"] == r["requests"]
                       and base["ok"] == base["requests"]),
            }))
    # Fleet lane scaling (PROFILE.md item 23): each lanes>1 row vs the
    # lanes=1 row of the SAME batch tier.
    for r in rows:
        b1 = base_rows.get((r["max_batch"], 1))
        if r["lanes"] == 1 or b1 is None or not b1["value"]:
            continue
        print(json.dumps({
            "metric": (f"serve_lane_scaling_{bucket.name}"
                       f"_b{r['max_batch']}_l{r['lanes']}"),
            "value": round(r["value"] / b1["value"], 3),
            "unit": "x vs 1 lane",
            "ok": (r["ok"] == r["requests"] and b1["ok"] == b1["requests"]),
        }))


def _serve_tenants(flags) -> None:
    """--serve-tenants: multi-tenant fairness A/B (PROFILE.md item 35).
    The seeded adversarial flood schedule (`resilience.chaos.
    adversarial_tenant` — the SAME schedule the chaos drills and
    `serve-demo --adversary` replay) is paced through a live service
    twice, in real time: once through the PRE-TENANCY surface (every
    submit anonymous, one FIFO lane — the victim queues behind the
    whole flood) and once through the QoS front door (victim "alice"
    weight 4, abuser "mallory" token-bucket rate-limited, weighted-fair
    dequeue sheds the flood at the door). One JSON row per arm with the
    victim's p50/p99 end-to-end latency + goodput and the abuser's
    served/shed counts, then the headline isolation row: victim p99
    no-QoS over QoS — the number the front door exists for.

    Flags: --bucket=MxN:dtype     (default 64x48:float32)
           --victims=N            (victim submits; default 12)
           --abuse-factor=K       (abuser floods K x victims; default 4)
           --victim-interval-ms   (victim pacing; default 60)
           --abuser-rate=R        (QoS arm: abuser admits/s; default 2)
    """
    import os
    import threading

    import jax
    platform = flags.get("platform") or os.environ.get("JAX_PLATFORMS")
    if platform:
        jax.config.update("jax_platforms", platform)

    from svd_jacobi_tpu.serve import as_bucket
    bucket = as_bucket(flags.get("bucket", "64x48:float32"))
    if bucket.dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    if "tuning-table" in flags:
        from svd_jacobi_tpu import tune
        tune.set_active_table(flags["tuning-table"])

    import jax.numpy as jnp

    from svd_jacobi_tpu import SVDConfig
    from svd_jacobi_tpu.resilience import chaos
    from svd_jacobi_tpu.serve import (AdmissionError, ServeConfig,
                                      SVDService)
    from svd_jacobi_tpu.utils import matgen

    victims = int(flags.get("victims", "12"))
    abuse_factor = int(flags.get("abuse-factor", "4"))
    interval_s = float(flags.get("victim-interval-ms", "60")) / 1e3
    abuser_rate = float(flags.get("abuser-rate", "2"))
    events = chaos.adversarial_tenant(
        "flood", n_victim=victims, abuse_factor=abuse_factor,
        victim_interval_s=interval_s)
    # Host-side numpy inputs, premade: the paced dispatcher must spend
    # its tick submitting, not generating.
    mats = {s: np.asarray(matgen.random_dense(
                bucket.m, bucket.n, seed=s,
                dtype=jnp.dtype(bucket.dtype)))
            for s in sorted({ev["mat_seed"] for ev in events})}

    def one_arm(qos_on: bool) -> dict:
        tenancy = (dict(tenants={"alice": {"weight": 4.0},
                                 "mallory": {"rate": abuser_rate,
                                             "burst": 2.0}})
                   if qos_on else {})
        cfg = ServeConfig(
            buckets=(bucket,), solver=SVDConfig(),
            max_queue_depth=max(64, 2 * len(events)),
            # Brownout off: a degraded response would change the work
            # between arms and poison the comparison.
            brownout_sigma_only_at=2.0, brownout_shed_at=2.0,
            **tenancy)
        svc = SVDService(cfg).start()
        svc.warmup(timeout=1800.0)
        lock = threading.Lock()
        lat = {"alice": [], "mallory": []}
        shed = {"alice": 0, "mallory": 0}
        waiters = []

        def waiter(ticket, who, t_sub):
            ok = False
            try:
                res = ticket.result(timeout=1800.0)
                ok = (res.error is None and res.status is not None
                      and res.status.name == "OK")
            except Exception:
                pass
            with lock:
                lat[who].append((time.perf_counter() - t_sub, ok))

        t0 = time.perf_counter()
        for ev in events:
            lag = t0 + ev["at_s"] - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            t_sub = time.perf_counter()
            try:
                # The pre-tenancy arm submits ANONYMOUSLY (the exact
                # single-caller surface); the QoS arm carries identity.
                ticket = svc.submit(
                    mats[ev["mat_seed"]],
                    tenant=(ev["tenant"] if qos_on else None))
            except AdmissionError:
                with lock:
                    shed[ev["tenant"]] += 1
                continue
            th = threading.Thread(target=waiter,
                                  args=(ticket, ev["tenant"], t_sub),
                                  daemon=True)
            th.start()
            waiters.append(th)
        for th in waiters:
            th.join(timeout=1800.0)
        wall = time.perf_counter() - t0
        svc.stop(drain=True, timeout=60.0)
        out = {"wall_s": round(wall, 3)}
        for who in ("alice", "mallory"):
            xs = sorted(d for d, _ in lat[who])
            q = (lambda p: round(xs[min(len(xs) - 1,
                                        int(p * len(xs)))] * 1e3, 2)
                 if xs else None)
            out[who] = {"submits": len(lat[who]) + shed[who],
                        "served": len(lat[who]),
                        "ok": sum(1 for _, ok in lat[who] if ok),
                        "shed": shed[who],
                        "p50_ms": q(0.50), "p99_ms": q(0.99)}
        return out

    rows = {}
    for qos_on in (False, True):
        arm = "qos" if qos_on else "noqos"
        r = one_arm(qos_on)
        rows[arm] = r
        print(json.dumps({
            "metric": f"serve_tenants_{arm}_{bucket.name}",
            "value": r["alice"]["p99_ms"],
            "unit": "ms victim p99",
            "victims": victims, "abuse_factor": abuse_factor,
            "victim_interval_ms": interval_s * 1e3,
            "alice": r["alice"], "mallory": r["mallory"],
            "wall_s": r["wall_s"],
            "device": str(jax.devices()[0]),
        }))
    a, b = rows["noqos"]["alice"], rows["qos"]["alice"]
    print(json.dumps({
        "metric": f"serve_tenant_isolation_{bucket.name}",
        "value": (round(a["p99_ms"] / b["p99_ms"], 2)
                  if a["p99_ms"] and b["p99_ms"] else None),
        "unit": "x victim p99, no-QoS / QoS",
        "victim_goodput": {"noqos": a["ok"], "qos": b["ok"]},
        "abuser_shed_qos": rows["qos"]["mallory"]["shed"],
        "ok": (a["ok"] == a["submits"] and b["ok"] == b["submits"]
               and rows["qos"]["mallory"]["shed"] > 0),
    }))


def _serve_metrics_overhead(flags) -> None:
    """--serve-metrics-overhead: what does the flight recorder COST when
    it is on? Same-session A/B: the closed-loop throughput fleet serves
    the identical seeded request mix in interleaved laps — recorder OFF,
    recorder ON (registry + spans + SLO), repeated ``--laps`` times —
    and each mode's best lap becomes one JSON row; the final row is the
    relative req/s delta (acceptance: < 2% on the 2-core CPU container;
    PROFILE.md item 28). Interleaved laps, best-of: host-load drift on a
    shared container would otherwise hand whichever mode runs second a
    different machine.

    Flags: --bucket=MxN:dtype (default 48x48:float32)
           --requests=N --clients=C (default 48 / 8)
           --laps=K (interleaved off/on lap pairs, default 3)
    """
    import os
    import threading

    import jax
    platform = flags.get("platform") or os.environ.get("JAX_PLATFORMS")
    if platform:
        jax.config.update("jax_platforms", platform)

    from svd_jacobi_tpu.serve import as_bucket
    bucket = as_bucket(flags.get("bucket", "48x48:float32"))
    if bucket.dtype == "float64":
        jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp

    from svd_jacobi_tpu import SVDConfig
    from svd_jacobi_tpu.serve import ServeConfig, SVDService
    from svd_jacobi_tpu.utils import matgen

    requests = int(flags.get("requests", "48"))
    clients = int(flags.get("clients", "8"))
    laps = max(1, int(flags.get("laps", "3")))
    mats = [np.asarray(matgen.random_dense(bucket.m, bucket.n,
                                           seed=2000 + i,
                                           dtype=jnp.dtype(bucket.dtype)))
            for i in range(min(requests, 16))]

    def one_lap(metrics_on: bool) -> tuple:
        cfg = ServeConfig(
            buckets=(bucket,), solver=SVDConfig(),
            max_queue_depth=max(64, requests + 2),
            metrics=metrics_on,
            brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
        svc = SVDService(cfg).start()
        svc.warmup(timeout=1800.0)
        lock = threading.Lock()
        counter = [0]
        ok_count = [0]

        def client(_cid):
            while True:
                with lock:
                    i = counter[0]
                    if i >= requests:
                        return
                    counter[0] += 1
                try:
                    res = svc.submit(mats[i % len(mats)],
                                     deadline_s=600.0).result(timeout=1800.0)
                    good = (res.error is None and res.status is not None
                            and res.status.name == "OK")
                except Exception:
                    good = False
                if good:
                    with lock:
                        ok_count[0] += 1

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(c,), daemon=True)
                   for c in range(max(1, clients))]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=1800.0)
        wall = time.perf_counter() - t0
        scrape_series = 0
        if metrics_on:
            # One scrape per lap proves the exposition stays serviceable
            # under the full mix (and its cost is OUTSIDE the timed lap).
            scrape_series = sum(
                1 for ln in svc.metrics_text().splitlines()
                if ln and not ln.startswith("#"))
        svc.stop(drain=True, timeout=60.0)
        return requests / wall, ok_count[0], scrape_series

    # Only CLEAN laps (every request OK) may contribute a best-of rps:
    # a lap shortened by a failed-fast request would otherwise post the
    # highest number and the acceptance flag would read a DIFFERENT
    # lap's ok-ness.
    best = {False: 0.0, True: 0.0}
    clean_laps = {False: 0, True: 0}
    series = 0
    for _ in range(laps):
        for mode in (False, True):
            rps, ok, ns = one_lap(mode)
            if ok == requests:
                clean_laps[mode] += 1
                best[mode] = max(best[mode], rps)
            if mode:
                series = max(series, ns)
    for mode in (False, True):
        print(json.dumps({
            "metric": (f"serve_metrics_overhead_{bucket.name}_"
                       f"{'on' if mode else 'off'}"),
            "value": round(best[mode], 2) if clean_laps[mode] else None,
            "unit": "requests/s",
            "metrics": mode,
            "requests": requests, "clients": clients, "laps": laps,
            "clean_laps": clean_laps[mode],
            "ok": clean_laps[mode] > 0,
            **({"scrape_series": series} if mode else {}),
            "device": str(jax.devices()[0]),
        }))
    measurable = clean_laps[False] > 0 and clean_laps[True] > 0 \
        and best[False] > 0
    delta_pct = ((best[False] - best[True]) / best[False] * 100.0
                 if measurable else None)
    print(json.dumps({
        "metric": f"serve_metrics_overhead_{bucket.name}",
        "value": None if delta_pct is None else round(delta_pct, 2),
        "unit": "% req/s lost with recorder on",
        "accept_under_pct": 2.0,
        "ok": delta_pct is not None and delta_pct < 2.0,
        "rps_off": round(best[False], 2), "rps_on": round(best[True], 2),
        "clean_laps_off": clean_laps[False],
        "clean_laps_on": clean_laps[True],
    }))


def _serve_twophase(flags) -> None:
    """--serve-twophase: the don't-recompute ledger (PROFILE.md item
    27), one JSON row per lane, all same-session A/B on one live
    service + solver:

      * sigma-phase latency vs full-phase latency (what σ-first defers);
      * promote-to-full latency vs a COLD full solve of the same
        request — the >= 2x acceptance (promotion resumes the retained
        stage; a cold solve pays every sweep again);
      * `solver.svd_update` on a rank-r-perturbed input vs a cold
        `solver.svd` — the >= 3x acceptance (warm start enters
        near-diagonal; PROFILE.md item 4's convergence class);
      * result-cache hit latency, with the zero-dispatch proof
        (lane dispatch count unchanged across the hit).

    Flags: --bucket=MxN:dtype (default 256x256:float32)
           --reps=K            (median-of-K per row, default 5)
           --update-n=N        (solver-level update A/B size, 512)
           --pair-solver=NAME  (solver lane, default auto)
    """
    import os
    import statistics

    import jax
    platform = flags.get("platform") or os.environ.get("JAX_PLATFORMS")
    if platform:
        jax.config.update("jax_platforms", platform)

    from svd_jacobi_tpu.serve import as_bucket
    bucket = as_bucket(flags.get("bucket", "256x256:float32"))
    if bucket.dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    if "tuning-table" in flags:
        from svd_jacobi_tpu import tune
        tune.set_active_table(flags["tuning-table"])

    import jax.numpy as jnp

    from svd_jacobi_tpu import SVDConfig, solver
    from svd_jacobi_tpu.serve import ServeConfig, SVDService
    from svd_jacobi_tpu.utils import matgen

    reps = int(flags.get("reps", "5"))
    solver_cfg = SVDConfig(pair_solver=flags.get("pair-solver", "auto"))
    dev = str(jax.devices()[0])
    dt = jnp.dtype(bucket.dtype)
    mats = [np.asarray(matgen.random_dense(bucket.m, bucket.n,
                                           seed=4000 + i, dtype=dt))
            for i in range(2 * reps + 1)]

    cfg = ServeConfig(
        buckets=(bucket,), solver=solver_cfg, max_queue_depth=64,
        result_cache_bytes=256 << 20,
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    svc = SVDService(cfg).start()
    try:
        svc.warmup(timeout=1800.0)

        def _serve_lap(i, phase):
            t0 = time.perf_counter()
            t = svc.submit(mats[i], phase=phase)
            res = t.result(timeout=1800.0)
            dt_submit = time.perf_counter() - t0
            assert res.error is None and res.status.name == "OK", res
            return dt_submit, t

        # Distinct inputs per rep: a repeated byte-identical full submit
        # would be served by the result cache and time the WRONG thing.
        full_s, sigma_s, promote_s = [], [], []
        for i in range(reps):
            d_full, _ = _serve_lap(1 + i, "full")
            full_s.append(d_full)
            d_sig, ticket = _serve_lap(1 + reps + i, "sigma")
            sigma_s.append(d_sig)
            t0 = time.perf_counter()
            rp = ticket.promote(timeout=1800.0)
            _force((rp.u, rp.s, rp.v))
            promote_s.append(time.perf_counter() - t0)
            assert rp.status.name == "OK"
        full_t = statistics.median(full_s)
        sigma_t = statistics.median(sigma_s)
        promote_t = statistics.median(promote_s)
        print(json.dumps({
            "metric": f"serve_sigma_latency_{bucket.name}",
            "value": round(sigma_t * 1e3, 2), "unit": "ms",
            "full_ms": round(full_t * 1e3, 2),
            "sigma_over_full": round(sigma_t / full_t, 3),
            "reps": reps, "device": dev}))
        print(json.dumps({
            "metric": f"serve_promote_speedup_{bucket.name}",
            "value": round(full_t / promote_t, 2),
            "unit": "x vs cold full solve",
            "promote_ms": round(promote_t * 1e3, 2),
            "cold_full_ms": round(full_t * 1e3, 2),
            "sigma_plus_promote_over_full":
                round((sigma_t + promote_t) / full_t, 3),
            "ok": full_t / promote_t >= 2.0,
            "reps": reps, "device": dev}))

        # Result-cache hit: mats[1] completed a clean full solve above —
        # resubmit the SAME bytes; the hit must finalize at admission
        # with the lane dispatch count unchanged.
        dispatches = svc.fleet.lanes[0].dispatches
        hit_s = []
        for _ in range(reps):
            t0 = time.perf_counter()
            t = svc.submit(mats[1])
            res = t.result(timeout=60.0)
            hit_s.append(time.perf_counter() - t0)
            assert res.path == "cache", res.path
        zero_dispatch = svc.fleet.lanes[0].dispatches == dispatches
        print(json.dumps({
            "metric": f"serve_cache_hit_latency_{bucket.name}",
            "value": round(statistics.median(hit_s) * 1e3, 3),
            "unit": "ms",
            "vs_cold_full_x": round(full_t / statistics.median(hit_s), 1),
            "zero_dispatch": zero_dispatch,
            "ok": zero_dispatch,
            "reps": reps, "device": dev}))
    finally:
        svc.stop(drain=False, timeout=60.0)

    # Solver-level evolving-matrix A/B: cold svd vs warm-started
    # svd_update on a rank-1-perturbed input (same session, same jits —
    # both lanes warmed before timing).
    n_upd = int(flags.get("update-n", "512"))
    rng = np.random.default_rng(42)
    a0 = jnp.asarray(rng.standard_normal((n_upd, n_upd)).astype(dt))
    pert = (rng.standard_normal((n_upd, 1))
            @ rng.standard_normal((1, n_upd))).astype(dt)
    a_new = a0 + jnp.asarray(0.01 * pert / n_upd)
    prior = solver.svd(a0, config=solver_cfg)
    _force((prior.u, prior.s, prior.v))
    cold_fn = lambda: solver.svd(a_new, config=solver_cfg)
    warm_fn = lambda: solver.svd_update(prior, a_new, config=solver_cfg)
    _force(cold_fn().s), _force(warm_fn().s)      # compile both lanes
    cold_s, warm_s, sweeps = [], [], {}
    for _ in range(reps):
        t0 = time.perf_counter()
        rc = cold_fn()
        _force((rc.u, rc.s, rc.v))
        cold_s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        rw = warm_fn()
        _force((rw.u, rw.s, rw.v))
        warm_s.append(time.perf_counter() - t0)
        sweeps = {"cold": int(rc.sweeps), "warm": int(rw.sweeps)}
    cold_t, warm_t = (statistics.median(cold_s), statistics.median(warm_s))
    print(json.dumps({
        "metric": f"svd_update_speedup_{n_upd}",
        "value": round(cold_t / warm_t, 2),
        "unit": "x vs cold solve",
        "cold_ms": round(cold_t * 1e3, 2),
        "warm_ms": round(warm_t * 1e3, 2),
        "sweeps": sweeps,
        "ok": cold_t / warm_t >= 3.0,
        "reps": reps, "device": dev}))


def _sweep(passthrough) -> None:
    """Run every SWEEP_CONFIGS row in a fresh subprocess, forwarding all
    other flags verbatim (--reps, --oracle, --baseline keep their
    single-config semantics and defaults; a row's own flags win)."""
    import subprocess
    for n, dtype, m, row_flags in SWEEP_CONFIGS:
        row_keys = {f.lstrip("-").split("=", 1)[0] for f in row_flags}
        keep = [f for f in passthrough
                if f.lstrip("-").split("=", 1)[0] not in row_keys]
        cmd = [sys.executable, __file__, n, dtype] + ([m] if m else [])
        full_cmd = cmd + keep + row_flags
        rc = subprocess.run(full_cmd).returncode
        if rc == _BACKEND_DOWN_RC:
            print("sweep aborted: accelerator backend unreachable",
                  file=sys.stderr)
            sys.exit(_BACKEND_DOWN_RC)
        if rc != 0:
            raise subprocess.CalledProcessError(rc, full_cmd)


def _serve_coldstart(flags) -> None:
    """--serve-coldstart: measure the restart cost the persistent
    executable cache removes (PROFILE.md item 26). Two `serve-demo
    --warmup --requests 0` SUBPROCESSES against the same fresh cache
    directory — restarts must cross a process boundary, or the
    in-process jit caches would fake the warm number:

      row 1 (cold): empty cache — warmup pays every fresh compile;
      row 2 (warm): same cache — warmup must be ~all cache hits, and
        its fresh-compile count is asserted in the row (nonzero =
        the restart story is broken, loudly).

    Flags: --cache-dir=DIR (default: a fresh temp dir),
    --buckets=spec,spec (default: 64x48:float32)."""
    import json as _json
    import subprocess
    import tempfile
    cache = flags.get("cache-dir") or tempfile.mkdtemp(
        prefix="svdj-coldstart-")
    buckets = (flags.get("buckets") or "64x48:float32").split(",")
    cmd = [sys.executable, "-m", "svd_jacobi_tpu.cli", "serve-demo",
           "--requests", "0", "--clients", "1", "--warmup",
           "--compile-cache", cache, "--report-dir", "off"]
    # The table changes BOTH the measured config and the cache namespace
    # (its content hash is part of the key) — an unforwarded pin would
    # silently measure the untuned deployment.
    if flags.get("tuning-table"):
        cmd += ["--tuning-table", flags["tuning-table"]]
    for b in buckets:
        cmd += ["--bucket", b]
    rows = []
    for phase in ("cold", "warm"):
        t0 = time.perf_counter()
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=900.0)
        wall = time.perf_counter() - t0
        if out.returncode != 0:
            raise SystemExit(f"serve-coldstart {phase} phase failed "
                             f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
        summary = _json.loads(out.stdout.strip().splitlines()[-1])
        row = {
            "metric": f"serve_coldstart_{phase}",
            "buckets": buckets,
            "warmup_s": summary.get("warmup_s"),
            "process_wall_s": wall,
            "fresh_compiles": (summary.get("coldstart") or {}).get(
                "fresh_compiles"),
            "cache_hits": (summary.get("coldstart") or {}).get(
                "cache_hits"),
            "cache_dir": cache,
        }
        print(_json.dumps(row))
        rows.append(row)
    if rows[0]["warmup_s"] and rows[1]["warmup_s"]:
        print(_json.dumps({
            "metric": "serve_coldstart_speedup",
            "cold_warmup_s": rows[0]["warmup_s"],
            "warm_warmup_s": rows[1]["warmup_s"],
            "speedup": rows[0]["warmup_s"] / rows[1]["warmup_s"],
            "warm_fresh_compiles": rows[1]["fresh_compiles"],
            "warm_cache_ok": rows[1]["fresh_compiles"] == 0,
        }))
    if rows[1]["fresh_compiles"] is None:
        # An unmeasured run must not pass as a verified one: the warm
        # phase produced no coldstart record, so the zero-fresh-compiles
        # acceptance was never checked.
        raise SystemExit("serve-coldstart: the warm phase reported no "
                         "coldstart record (fresh_compiles is None) — "
                         "the zero-fresh-compiles acceptance was NOT "
                         "verified")
    if rows[1]["fresh_compiles"] != 0:
        raise SystemExit("serve-coldstart: the WARM restart still paid "
                         f"{rows[1]['fresh_compiles']} fresh compile(s) — "
                         "the persistent executable cache is not doing "
                         "its job")


def _serve_federation(flags) -> None:
    """--serve-federation: what does the replica router buy (PROFILE.md
    item 30)? Three measurements over one seeded closed-loop mix:

      rows 1-2: replicas=1 vs replicas=2 closed-loop throughput through
        the SAME `serve.router.ReplicaRouter` front-end (+ a scaling
        ratio row — on the 2-core CPU container the replicas share the
        device, so this is an overhead statement, not a speed claim);
      row 3 (availability): replicas=2 with the owner replica KILLED
        mid-load — every request must still reach a terminal status,
        the rescue count and the killed-window latency penalty are the
        availability price of a replica death;
      row 4: byte-identical resubmit end-to-end latency (the
        consistent-hash ring must land it on the owner's result cache —
        the admission fast-path behind the router).

    Flags: --bucket=MxN:dtype (default 48x32:float32) --requests=N
           --clients=C --deadline-s=D
           --transport=local|http (http: every replica is a live
             in-process `serve.transport.HttpReplicaServer` and the
             router reaches it only over `HttpReplica` RPCs — the rows
             are suffixed `_http` and their delta vs the local rows is
             the wire-protocol overhead; kill-one goes through lease
             expiry + fenced journal rescue instead of the in-process
             death signal)
    """
    import dataclasses
    import os
    import tempfile
    import threading
    from pathlib import Path

    import jax
    platform = flags.get("platform") or os.environ.get("JAX_PLATFORMS")
    if platform:
        jax.config.update("jax_platforms", platform)

    from svd_jacobi_tpu.serve import as_bucket
    bucket = as_bucket(flags.get("bucket", "48x32:float32"))
    if bucket.dtype == "float64":
        jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp

    from svd_jacobi_tpu import SVDConfig
    from svd_jacobi_tpu.resilience import chaos
    from svd_jacobi_tpu.serve import (ReplicaRouter, RouterConfig,
                                      ServeConfig)
    from svd_jacobi_tpu.serve.cache import input_digest
    from svd_jacobi_tpu.utils import matgen

    requests = int(flags.get("requests", "32"))
    clients = int(flags.get("clients", "8"))
    deadline_s = float(flags.get("deadline-s", "600"))
    transport = flags.get("transport", "local")
    if transport not in ("local", "http"):
        raise SystemExit(f"--transport={transport!r}: local|http")
    suffix = "_http" if transport == "http" else ""
    mats = [np.asarray(matgen.random_dense(bucket.m - 4, bucket.n - 2,
                                           seed=1000 + i,
                                           dtype=jnp.dtype(bucket.dtype)))
            for i in range(min(requests, 16))]

    def build(n_replicas):
        serve_cfg = ServeConfig(
            buckets=(bucket,), solver=SVDConfig(),
            max_queue_depth=max(64, 2 * requests),
            result_cache_bytes=64 << 20,
            brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
        state_dir = tempfile.mkdtemp(prefix="svdj-fed-")
        cfg = RouterConfig(
            replicas=n_replicas, serve=serve_cfg, state_dir=state_dir,
            supervise_interval_s=0.02, heartbeat_timeout_s=2.0,
            probe_interval_s=0.25)
        if transport != "http":
            return ReplicaRouter(cfg).start(), []
        # HTTP federation: each replica is an in-process server with its
        # own journal + fence token; the router only speaks RPC to it.
        from svd_jacobi_tpu.serve.transport import (HttpReplica,
                                                    HttpReplicaServer)
        servers, handles = [], []
        for i in range(n_replicas):
            rdir = Path(state_dir) / f"replica-{i}"
            rc = dataclasses.replace(
                serve_cfg, journal_path=str(rdir / "journal.jsonl"),
                compute_digest=True)
            # warmup=True: router.warmup() only reaches LOCAL replicas,
            # so HTTP servers AOT-warm at boot (replica 0 fills the
            # shared persistent cache, later replicas warm from hits).
            server = HttpReplicaServer(rc, warmup=True).start()
            servers.append(server)
            handles.append(HttpReplica(i, server.address, rc.journal_path))
        return ReplicaRouter(cfg, replicas=handles).start(), servers

    def shutdown(router, servers):
        router.stop(drain=True, timeout=60.0)
        for server in servers:
            server.stop(drain=True, timeout=30.0)

    def closed_loop(router, kill_at=None, servers=None):
        outcomes, lock, counter = [], threading.Lock(), [0]
        killed = threading.Event()

        def client(_cid):
            while True:
                with lock:
                    i = counter[0]
                    if i >= requests:
                        return
                    counter[0] += 1
                if (kill_at is not None and i == kill_at
                        and not killed.is_set()):
                    killed.set()
                    victim = router.ring.owner(bucket.name,
                                               input_digest(mats[0]))
                    if servers:
                        # HTTP: kill the SERVER (the handle only learns
                        # through lease expiry + fenced rescue).
                        servers[victim].simulate_kill()
                    else:
                        router.replicas[victim].simulate_kill()
                a = mats[i % len(mats)]
                t0 = time.perf_counter()
                try:
                    res = router.submit(a, deadline_s=deadline_s).result(
                        timeout=1800.0)
                    ok = (res.error is None and res.status is not None
                          and res.status.name == "OK")
                    path = res.path
                except Exception:
                    ok, path = False, "raised"
                dt = time.perf_counter() - t0
                with lock:
                    outcomes.append((dt, ok, path))

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(c,), daemon=True)
                   for c in range(max(1, clients))]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=1800.0)
        return outcomes, time.perf_counter() - t0

    rows = {}
    for n_replicas in (1, 2):
        router, servers = build(n_replicas)
        try:
            router.warmup(timeout=1800.0)
            outcomes, wall = closed_loop(router)
        finally:
            shutdown(router, servers)
        lat = sorted(d for d, _, _ in outcomes)
        q = (lambda p: round(lat[min(len(lat) - 1,
                                     int(p * len(lat)))] * 1e3, 2)
             if lat else None)
        row = {
            "metric": f"serve_federation_{bucket.name}_r{n_replicas}{suffix}",
            "value": round(len(outcomes) / wall, 2),
            "unit": "requests/s",
            "replicas": n_replicas, "clients": clients,
            "requests": len(outcomes),
            "ok": sum(1 for _, ok, _ in outcomes if ok),
            "p50_ms": q(0.50), "p99_ms": q(0.99),
            "wall_s": round(wall, 3),
            "device": str(jax.devices()[0]),
        }
        print(json.dumps(row))
        rows[n_replicas] = row
    if rows[1]["value"]:
        print(json.dumps({
            "metric": f"serve_federation_scaling_{bucket.name}{suffix}",
            "value": round(rows[2]["value"] / rows[1]["value"], 3),
            "unit": "x vs 1 replica",
            "ok": all(r["ok"] == r["requests"] for r in rows.values()),
        }))

    # Availability under replica death: kill the owner mid-load.
    router, servers = build(2)
    try:
        router.warmup(timeout=1800.0)
        with chaos.slow_solve(0.05, shots=requests):
            outcomes, wall = closed_loop(router, kill_at=requests // 3,
                                         servers=servers)
        rescued = router.total_rescues
        hz = router.healthz(probe_replicas=False)
        net = ([dict(r.net_stats) for r in router.replicas]
               if transport == "http" else None)
    finally:
        shutdown(router, servers)
    lat_ok = sorted(d for d, ok, _ in outcomes if ok)
    q = (lambda p: round(lat_ok[min(len(lat_ok) - 1,
                                    int(p * len(lat_ok)))] * 1e3, 2)
         if lat_ok else None)
    print(json.dumps({
        "metric": f"serve_federation_kill_one_{bucket.name}{suffix}",
        "value": round(sum(1 for _, ok, _ in outcomes if ok)
                       / max(1, len(outcomes)), 4),
        "unit": "terminal-OK fraction under 1-of-2 replica death",
        "requests": len(outcomes),
        "ok": sum(1 for _, ok, _ in outcomes if ok),
        "raised": sum(1 for _, _, p in outcomes if p == "raised"),
        "rescued": rescued,
        "p50_ms": q(0.50), "p99_ms": q(0.99),
        "wall_s": round(wall, 3),
        "replicas_active_after": hz["active"],
        **({"net": net} if net else {}),
    }))

    # Resubmit-hits-owner latency: the cached fast path behind the ring.
    router, servers = build(2)
    try:
        router.warmup(timeout=1800.0)
        a = mats[0]
        router.submit(a, deadline_s=deadline_s).result(timeout=1800.0)
        laps = []
        for _ in range(16):
            t0 = time.perf_counter()
            res = router.submit(a, deadline_s=deadline_s).result(
                timeout=60.0)
            laps.append(time.perf_counter() - t0)
            assert res.path == "cache", res.path
    finally:
        shutdown(router, servers)
    laps.sort()
    print(json.dumps({
        "metric": f"serve_federation_resubmit_hit_{bucket.name}{suffix}",
        "value": round(laps[len(laps) // 2] * 1e3, 3),
        "unit": "ms p50 end-to-end (byte-identical resubmit, cache hit "
                "on the ring owner)",
        "p99_ms": round(laps[-1] * 1e3, 3),
        "laps": len(laps),
    }))


def _grad_bench(flags, args) -> None:
    """--grad: the grad-of-nuclear-norm row (ROADMAP "Differentiable
    solver" acceptance). Times ``jax.jit(jax.grad(nuclear_norm))``
    through OUR solve (the custom VJP/JVP rules of svd_jacobi_tpu.grad;
    sigma-only job, so the backward pass is the no-F-matrix fast path)
    against the same loss through `jnp.linalg.svd`'s AD rule, and
    records the two acceptance checks inline: the gradient against f64
    central finite differences (directional, the loss recomputed in
    numpy f64), and finiteness on a clustered-sigma input (the
    degenerate-band mask's job). ``--grad-rule=vjp`` times the explicit
    custom_vjp mode instead of the default transposed-JVP rule."""
    import os

    import jax

    platform = flags.get("platform") or os.environ.get("JAX_PLATFORMS")
    if platform:
        jax.config.update("jax_platforms", platform)
    jax.config.update("jax_enable_x64", True)   # the f64 FD check needs it

    import jax.numpy as jnp
    import numpy as np

    import svd_jacobi_tpu as sj
    from svd_jacobi_tpu.utils import matgen

    if "tuning-table" in flags:
        from svd_jacobi_tpu import tune
        tune.set_active_table(flags["tuning-table"])
    n = int(args[0]) if args else 1024
    dtype_name = args[1] if len(args) > 1 else "float32"
    m = int(args[2]) if len(args) > 2 else n
    dtype = jnp.dtype(dtype_name)
    reps = int(flags.get("reps", "3"))
    rule = flags.get("grad-rule", "auto")
    cfg = sj.SVDConfig(grad_rule=rule)
    a = matgen.random_dense(m, n, dtype=dtype)

    def our_loss(x):
        return jnp.sum(sj.svd(x, compute_u=False, compute_v=False,
                              config=cfg).s)

    def xla_loss(x):
        return jnp.sum(jnp.linalg.svd(x, compute_uv=False))

    ours = jax.jit(jax.grad(our_loss))
    base = jax.jit(jax.grad(xla_loss))
    (t_ours, t_base), (g_ours, _), errs = _time_interleaved(
        [ours, base], a, reps=reps)

    # Acceptance check 1: directional f64 central finite differences of
    # the (solver-independent) nuclear norm.
    fd_rel_err = None
    if g_ours is not None:
        a64 = np.asarray(a, np.float64)
        g64 = np.asarray(g_ours, np.float64)
        rng = np.random.default_rng(0)
        h = 1e-3
        errs_fd = []
        for _ in range(3):
            d = rng.standard_normal(a64.shape)
            d /= np.linalg.norm(d)
            fd = (np.linalg.svd(a64 + h * d, compute_uv=False).sum()
                  - np.linalg.svd(a64 - h * d, compute_uv=False).sum()
                  ) / (2 * h)
            got = float((g64 * d).sum())
            errs_fd.append(abs(got - fd) / max(abs(fd), 1e-12))
        fd_rel_err = max(errs_fd)

    # Acceptance check 2: finite gradient on a clustered-sigma input
    # (tied leading sigmas + a geometric tail — every intra-cluster
    # F-matrix denominator is degenerate). Guarded like check 1: a
    # candidate `_time_interleaved` already tolerated failing must not
    # sink the row (the JSON below carries its error either way).
    clustered_finite = None
    if g_ours is not None:
        rng = np.random.default_rng(1)
        k = min(m, n)
        ties = min(8, k)
        qu, _ = np.linalg.qr(rng.standard_normal((m, k)))
        qv, _ = np.linalg.qr(rng.standard_normal((n, k)))
        sig = np.concatenate([np.full(ties, 1.0),
                              2.0 ** (-np.arange(k - ties) / 64.0 - 1)])
        a_cl = jnp.asarray(qu @ np.diag(sig) @ qv.T, dtype)
        try:
            g_cl = ours(a_cl)
            clustered_finite = bool(np.isfinite(np.asarray(g_cl)).all())
        except Exception as e:
            if errs[0] is None:
                errs[0] = f"clustered check: {type(e).__name__}: {e}"

    device_kind = jax.devices()[0].device_kind
    print(json.dumps({
        "metric": f"svd_grad_nuclear_{m}x{n}_{dtype_name}_s",
        "value": None if t_ours is None else round(t_ours, 4),
        "unit": "s",
        "vs_baseline": (None if t_ours is None or t_base is None
                        else round(t_base / t_ours, 3)),
        "baseline": "jax.grad of the same loss through jnp.linalg.svd",
        "baseline_s": None if t_base is None else round(t_base, 4),
        "grad_rule": rule,
        "fd_rel_err": None if fd_rel_err is None else float(fd_rel_err),
        "clustered_finite": clustered_finite,
        "reps": reps,
        "device_kind": device_kind,
        "error": errs[0],
    }))


def _check_against_gate(row: dict, against: str) -> bool:
    """Append-and-gate: check one bench row against the BENCH_*.json
    history beside the named round, under the fitted per-metric noise
    band (obs.perf.check_rows). Returns ok; report lines go to stderr.
    Callers exit rc 4 on a regression (distinct from solve/backend
    failures)."""
    import glob as _glob
    import os as _os
    from svd_jacobi_tpu.obs.perf import check_rows
    hist = []
    for p in sorted(_glob.glob(_os.path.join(
            _os.path.dirname(_os.path.abspath(against)) or ".",
            "BENCH_*.json"))):
        with open(p) as fh:
            data = json.load(fh)
        hist += data if isinstance(data, list) else [data]
    ok, lines = check_rows({"parsed": row}, hist)
    print("\n".join(lines), file=sys.stderr)
    return ok


def main() -> None:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    flags = dict(f.lstrip("-").split("=", 1) if "=" in f else (f.lstrip("-"), "1")
                 for f in sys.argv[1:] if f.startswith("--"))
    if "check-row" in flags:
        # --check-row=FILE.json --check-against=BENCH_rXX.json: run the
        # perf gate on an ALREADY-MEASURED (or synthetic) row without
        # solving anything — the tier-1 hook that keeps the gate's code
        # path exercised on hosts where a real solve row is too slow.
        if "check-against" not in flags:
            raise SystemExit("--check-row requires --check-against=FILE")
        with open(flags["check-row"]) as fh:
            synth_row = json.load(fh)
        sys.exit(0 if _check_against_gate(synth_row,
                                          flags["check-against"]) else 4)
    if "grad" in flags:
        _grad_bench(flags, args)
        return
    if "serve-federation" in flags:
        _serve_federation(flags)
        return
    if "serve-coldstart" in flags:
        _serve_coldstart(flags)
        return
    if "serve-throughput" in flags:
        _serve_throughput(flags)
        return
    if "serve-tenants" in flags:
        _serve_tenants(flags)
        return
    if "serve-metrics-overhead" in flags:
        _serve_metrics_overhead(flags)
        return
    if "serve-twophase" in flags:
        _serve_twophase(flags)
        return
    if "sweep" in flags:
        _sweep([f for f in sys.argv[1:]
                if f.startswith("--")
                and f.lstrip("-").split("=", 1)[0] != "sweep"])
        return
    n = int(args[0]) if len(args) > 0 else 2048
    dtype_name = args[1] if len(args) > 1 else "float32"
    m = int(args[2]) if len(args) > 2 else n
    baseline = flags.get("baseline", "xla")
    oracle = flags.get("oracle", "auto")
    reps = int(flags.get("reps", "6"))

    import os

    import jax

    # The axon TPU plugin ignores JAX_PLATFORMS from the environment; honor
    # it (and the --platform flag, which lets --sweep rows pin their own
    # backend) through the config API so CPU-parity rows of the baseline
    # table really run on CPU.
    platform = flags.get("platform") or os.environ.get("JAX_PLATFORMS")
    if platform:
        jax.config.update("jax_platforms", platform)
    if dtype_name == "float64":
        jax.config.update("jax_enable_x64", True)
    if "tuning-table" in flags:
        # --tuning-table=PATH pins a measured table for every "auto"
        # knob this run resolves; =off bypasses tables entirely (builtin
        # hand-picked heuristics) — the A/B lever PROFILE.md item 24 uses.
        from svd_jacobi_tpu import tune
        tune.set_active_table(flags["tuning-table"])

    # Backend watchdog: if the attachment's device pool is down,
    # jax.devices() HANGS indefinitely (observed: relay accepts TCP,
    # backend never answers). Probe it behind a deadline so the bench
    # emits a parseable error row instead of hanging until an external
    # kill.
    from svd_jacobi_tpu.utils._exec import probe_devices
    try:
        backend_timeout = float(flags.get("backend-timeout", "300"))
        if backend_timeout < 10.0:
            raise ValueError
    except ValueError:
        raise SystemExit("--backend-timeout=SECONDS (>= 10) required, got "
                         f"{flags.get('backend-timeout')!r}")
    devices, err = probe_devices(backend_timeout)
    if devices is None:
        why = err or ("device discovery hung past the deadline — "
                      "device pool down?")
        print(json.dumps({
            "metric": f"svd_{m}x{n}_{dtype_name}"
                      f"{'_novec' if 'novec' in flags else ''}_gflops",
            "value": None, "unit": "GFLOP/s", "vs_baseline": None,
            "error": f"accelerator backend unreachable ({why})"}))
        # Distinct exit code so --sweep's parent stops instead of burning
        # the deadline once per remaining row.
        sys.exit(_BACKEND_DOWN_RC)

    import jax.numpy as jnp
    import svd_jacobi_tpu as sj
    from svd_jacobi_tpu.utils import matgen, validation

    dtype = jnp.dtype(dtype_name)
    a = matgen.random_dense(m, n, dtype=dtype)

    novec = "novec" in flags   # sigma-only solve (jobu = jobv = NoVec)
    stepped = "stepped" in flags
    attempted_baseline = "no-baseline" not in flags
    # --top-k=K: truncated solve via the randomized range-finder lane;
    # the baseline becomes OUR OWN full solve at the same shape — the
    # topk_speedup row is the number the lane exists for. --tall-vs-pad:
    # the blocked-TSQR tall lane vs the full solve on the input padded
    # to square (what a square-bucket-only service would do).
    top_k = int(flags["top-k"]) if "top-k" in flags else None
    tall_vs_pad = "tall-vs-pad" in flags
    if top_k is not None and top_k < 1:
        raise SystemExit(f"--top-k must be >= 1, got {top_k}")
    if (top_k is not None or tall_vs_pad) and (
            stepped or "donate" in flags or "fused-gen" in flags):
        raise SystemExit("--top-k/--tall-vs-pad are fused-lane "
                         "comparisons; not combinable with "
                         "--stepped/--donate/--fused-gen")
    if top_k is not None and tall_vs_pad:
        raise SystemExit("--top-k and --tall-vs-pad are separate rows; "
                         "run them one at a time")
    if tall_vs_pad and m < 8 * n:
        raise SystemExit(f"--tall-vs-pad needs a tall shape (m >= 8n), "
                         f"got {m}x{n}")
    # --precondition=off: skip the Drmac QR (its Q1/R factors are 2 extra
    # n^2 buffers — the difference between fitting and OOM at 30000^2).
    # --block-size=K / --mixed-bulk: the block-width and mixed-regime
    # sweeps of PROFILE.md run through the same bench harness.
    # --pair-solver=NAME pins the solver lane; a non-auto pin (and no
    # other comparison row in flight) turns the baseline into OUR OWN
    # auto-routed solve — the lane A/B row (the block_rotation
    # acceptance comparison "vs the current lane").
    pair_solver = flags.get("pair-solver", "auto")
    pair_ab = (pair_solver != "auto" and top_k is None and not tall_vs_pad
               and attempted_baseline)
    if pair_ab and "stepped" in flags:
        # The A/B row is a LANE comparison; folding the host-stepped
        # loop's per-sweep dispatch overhead into "ours" against a fused
        # baseline would misattribute stepping cost to the lane (same
        # policy as --top-k/--tall-vs-pad).
        raise SystemExit("--pair-solver A/B rows are fused-lane "
                         "comparisons; not combinable with --stepped "
                         "(use --no-baseline to time a stepped pinned "
                         "lane without the A/B row)")
    cfg = sj.SVDConfig(
        pair_solver=pair_solver,
        precondition=flags.get("precondition", "auto"),
        block_size=(int(flags["block-size"]) if "block-size" in flags
                    else None),
        # --rounds-resident=R: residency depth for --pair-solver=resident
        # (clamped to the sweep's round count; table/default when unset).
        rounds_resident=(int(flags["rounds-resident"])
                         if "rounds-resident" in flags else None),
        mixed_bulk=({"on": True, "off": False, "auto": None}
                    [flags.get("mixed-bulk", "auto")]),
        mixed_store=flags.get("mixed-store", "auto"),
        sigma_refine={"on": True, "off": False}.get(
            flags.get("sigma-refine")),
        donate_input="donate" in flags)
    ours = lambda x: sj.svd(x, compute_u=not novec, compute_v=not novec,
                            config=cfg)
    if top_k is not None:
        from svd_jacobi_tpu.solver import svd_topk
        ours = lambda x: svd_topk(x, top_k, compute_u=not novec,
                                  compute_v=not novec, config=cfg)
    if tall_vs_pad:
        from svd_jacobi_tpu.solver import svd_tall
        ours = lambda x: svd_tall(x, compute_u=not novec,
                                  compute_v=not novec, config=cfg)
    if stepped:
        # Host-stepped solve (solver.SweepStepper, the checkpoint-grade
        # API): ONE jitted sweep per device execution. Required at the
        # largest sizes on this attachment — the tunnel enforces a ~90 s
        # single-execution deadline (measured, PROFILE.md item 19), which
        # a fused 30208^2 solve (~12 s/sweep x 16 sweeps) cannot fit; the
        # stepper's per-sweep executions ride well under it. Timing
        # includes the per-step host dispatch (~0.1 s/sweep here).
        from svd_jacobi_tpu import solver as _solver

        def ours(x):
            st = _solver.SweepStepper(x, compute_u=not novec,
                                      compute_v=not novec, config=cfg)
            state = st.init()
            while st.should_continue(state):
                state = st.step(state)
            return st.finish(state)
    if ("donate" in flags or "fused-gen" in flags) and attempted_baseline:
        # Both modes drop the caller-held input (a = None); the baseline
        # lambda would receive None and its failure would be mis-reported
        # as the "ours alone" encoding. Make the flag requirement loud.
        raise SystemExit("--donate/--fused-gen require --no-baseline "
                         "(the input buffer is consumed/never held; the "
                         "XLA baseline cannot run on the same input)")
    if "fused-gen" in flags and stepped:
        raise SystemExit("--fused-gen is incompatible with --stepped (the "
                         "host-stepped loop cannot run under one jit); "
                         "use --stepped --donate for the large stepped "
                         "rows")
    if "donate" in flags and "fused-gen" not in flags:
        # SVDConfig.donate_input consumes the input buffer (XLA aliases it
        # to a same-shaped factor output — usable for full-vector solves),
        # so each timed repetition regenerates the deterministic matrix;
        # residual/oracle need a surviving copy and are skipped.
        base = ours
        ours = lambda _x: base(matgen.random_dense(m, n, dtype=dtype))
        a = None
    if "fused-gen" in flags:
        # Largest-size rows: generate the (deterministic) input INSIDE the
        # solve's jit program, so the matrix is an internal temp XLA frees
        # after blockification instead of a caller-held buffer pinned
        # across the whole solve (plain donation is "not usable" for
        # sigma-only solves — there is no same-shaped output to alias).
        # Gen cost (one threefry pass) rides inside the timing; residual /
        # sigma-oracle need a host-visible copy and are skipped — the
        # accuracy class is pinned at the smaller sizes. Use exact
        # block-multiple N (e.g. 30208 = 2*59*256) to avoid the padding
        # copy as well.
        base = ours

        @jax.jit
        def _run():
            return base(matgen.random_dense(m, n, dtype=dtype))

        ours = lambda _x: _run()
        a = None
    # Test hook for the transient-retry path: the first K solve attempts
    # raise a synthetic UNAVAILABLE (the BENCH_r05 outage class) so the
    # retry is exercisable end-to-end without a real device-pool outage.
    chaos_left = int(os.environ.get("SVDJ_BENCH_CHAOS_TRANSIENT", "0") or 0)
    if chaos_left > 0:
        real_ours = ours
        _chaos_state = {"left": chaos_left}

        def ours(x):
            if _chaos_state["left"] > 0:
                _chaos_state["left"] -= 1
                raise RuntimeError(
                    "UNAVAILABLE: injected transient backend outage "
                    "(SVDJ_BENCH_CHAOS_TRANSIENT)")
            return real_ours(x)

    def _measure():
        if not attempted_baseline:
            (t_ours,), (r,), errs = _time_interleaved([ours], a, reps=reps)
            return (t_ours, None, r, errs[0],
                    "skipped (--no-baseline: known to OOM at this size)")
        if top_k is not None or tall_vs_pad:
            # The comparison row of the truncated/tall lanes: the
            # baseline is OUR OWN full solve — of the same input
            # (top-k), or of the input padded to square (tall: the
            # dispatch a square-bucket-only service would pay).
            if top_k is not None:
                base_fn = lambda x: sj.svd(x, compute_u=not novec,
                                           compute_v=not novec, config=cfg)
                name = "full svd() same shape"
            else:
                pad_cols = m - n
                base_fn = lambda x: sj.svd(
                    jnp.pad(x, ((0, 0), (0, pad_cols))),
                    compute_u=not novec, compute_v=not novec, config=cfg)
                name = "full svd() on pad-to-square"
            (t_ours, t_base), (r, _), errs = _time_interleaved(
                [ours, base_fn], a, reps=reps)
            return t_ours, t_base, r, errs[0], name
        if pair_ab:
            # Lane A/B: baseline = what "auto" routes this shape to
            # today (same session, same input, interleaved timing) —
            # UNLESS auto already routes to the pinned lane (a tuning
            # table can ship that verdict, e.g. default-r03's CPU
            # medium block_rotation row), in which case the comparison
            # falls back to the next kernel lane so the row never
            # measures a lane against itself.
            import dataclasses as _dc
            from svd_jacobi_tpu import solver as _solver
            auto_cfg = _dc.replace(cfg, pair_solver="auto")
            routed = _solver._resolve_options(
                a if m >= n else a.T, auto_cfg, not novec)[2]
            if routed == pair_solver:
                # Next kernel lane valid for this dtype (pallas computes
                # f32 rotations — an f64 run pinning qr-svd must not
                # crash the baseline; precondition is a kernel-lane mode,
                # so the XLA fallbacks drop it back to auto).
                if pair_solver != "pallas" and dtype != jnp.float64:
                    base_lane = "pallas"
                elif pair_solver != "hybrid":
                    base_lane = "hybrid"
                else:
                    base_lane = "qr-svd"
                base_cfg = _dc.replace(cfg, pair_solver=base_lane)
                if base_lane in ("hybrid", "qr-svd"):
                    # Kernel-lane-only modes must not crash the XLA
                    # fallback baseline (precondition='on', mixed_bulk,
                    # bulk_bf16 all raise off the kernel path).
                    base_cfg = _dc.replace(
                        base_cfg,
                        precondition=("auto" if base_cfg.precondition in
                                      ("on", "double")
                                      else base_cfg.precondition),
                        mixed_bulk=None, bulk_bf16=None)
                name = f"svd() {base_lane} lane same shape (auto already " \
                       f"routes {pair_solver})"
            else:
                base_cfg = auto_cfg
                name = f"svd() auto lane ({routed}) same shape"
            base_fn = lambda x: sj.svd(x, compute_u=not novec,
                                       compute_v=not novec,
                                       config=base_cfg)
            (t_ours, t_base), (r, _), errs = _time_interleaved(
                [ours, base_fn], a, reps=reps)
            return t_ours, t_base, r, errs[0], name
        if baseline == "numpy":
            an = np.asarray(a)
            (t_ours, t_base), (r, _), errs = _time_interleaved(
                [ours, lambda x: np.linalg.svd(an, full_matrices=False,
                                               compute_uv=not novec)], a,
                reps=reps)
            return t_ours, t_base, r, errs[0], "numpy.linalg.svd same host"
        (t_ours, t_base), (r, _), errs = _time_interleaved(
            [ours, lambda x: jnp.linalg.svd(x, full_matrices=False,
                                            compute_uv=not novec)], a,
            reps=reps)
        return t_ours, t_base, r, errs[0], "jnp.linalg.svd same device"

    # One BOUNDED retry, with backoff, when OUR solve failed with a
    # transient backend error (device-pool outage, tunnel reset — the
    # BENCH_r05 class): a momentary outage must not void a whole bench
    # round. The retry is noted in the emitted row ("retried") so the
    # number's provenance is honest; deterministic failures (OOM,
    # validation) never retry.
    try:
        retry_backoff = float(flags.get("retry-backoff-s", "15"))
    except ValueError:
        raise SystemExit("--retry-backoff-s=SECONDS required, got "
                         f"{flags.get('retry-backoff-s')!r}")
    retried = None
    t_ours, t_base, r, err, base_name = _measure()
    if t_ours is None:
        reason = _transient_reason(err)
        if reason is not None:
            print(f"note: transient backend failure ({reason}); retrying "
                  f"once after {retry_backoff:.0f}s backoff",
                  file=sys.stderr)
            time.sleep(max(0.0, retry_backoff))
            retried = {"reason": reason, "backoff_s": retry_backoff,
                       "error": err[:300]}
            t_ours, t_base, r, err, base_name = _measure()

    if t_ours is None:
        # Our own solver failed at this config (e.g. OOM): emit a row that
        # says so instead of killing the rest of a --sweep run.
        print(json.dumps({
            "metric": f"svd_{m}x{n}_{dtype_name}"
                      f"{'_novec' if novec else ''}_gflops",
            "value": None, "unit": "GFLOP/s", "vs_baseline": None,
            "error": "solver failed to compile/run at this config",
            "detail": err, "retried": retried,
            "device": str(jax.devices()[0])}))
        return

    # Residual computed ON DEVICE at pinned precision (a host transfer of
    # the factors through the tunnel would dominate at large N). A top-k
    # row skips it: the full-reconstruction residual of a TRUNCATED
    # factorization equals the discarded tail energy, not an error.
    extras = {}
    if (a is not None and r.u is not None and r.v is not None
            and top_k is None):
        extras["residual_rel"] = float(
            np.asarray(validation.relative_residual(a, r.u, r.s, r.v)))
    if oracle == "auto":
        oracle = "on" if max(m, n) <= 2048 else "off"
    if oracle == "on" and a is not None:
        s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
        if top_k is not None:
            s_ref = s_ref[:top_k]
        extras["sigma_err"] = float(validation.sigma_error(r.s, s_ref))

    # Honest FLOP model per lane. Full/tall: the classic full-SVD count
    # 4mn^2 + 8n^3 (the tall lane computes the same factorization — its
    # win is a smaller CONSTANT, so the model stays comparable across
    # rows). Top-k: the 2mnk-class randomized pipeline — sketch 2mnl,
    # power iterations 4mnl each, projection 2mnl, (q+1) TSQR passes
    # 2ml^2, the small (n, l) core ~4nl^2 + 8l^3, lift 2mlk — so a top-k
    # row's GFLOP/s is NOT comparable to a full row's (the whole point:
    # ~n/l times less work); the topk_speedup row carries the
    # end-to-end verdict.
    if top_k is not None:
        from svd_jacobi_tpu import solver as _solver
        p_over, q_iters, _ = _solver._resolve_sketch(cfg, n, m, dtype,
                                                     k=top_k)
        l = min(top_k + p_over, n)
        flops = (2.0 * m * n * l * (1 + 2 * q_iters)
                 + 2.0 * m * n * l                  # projection B = Q^T A
                 + (q_iters + 1) * 2.0 * m * l * l  # TSQR passes
                 + 4.0 * n * l * l + 8.0 * l**3     # small core
                 + 2.0 * m * l * top_k)             # lift U = Q Z
        extras["flop_model"] = "randomized-topk(2mnl-class)"
        extras["sketch_l"] = l
        extras["power_iters"] = q_iters
    else:
        flops = 4.0 * m * n**2 + 8.0 * n**3
    gflops = flops / t_ours / 1e9
    device_kind = jax.devices()[0].device_kind
    mfu, mfu_est = _mfu(gflops, device_kind)
    sweeps_meas = (int(r.sweeps) if np.ndim(r.sweeps) == 0
                   else int(np.max(np.asarray(r.sweeps))))
    hbm_gbps, model_lane = _model_hbm_gbps(
        cfg, m, n, dtype_name, pair_solver, sweeps_meas, t_ours,
        novec, top_k)
    tag = "_novec" if novec else ""
    lane = ("_topk_k%d" % top_k if top_k is not None
            else "_tall" if tall_vs_pad else "")
    if pair_solver != "auto":
        lane += f"_{pair_solver}"
    row = {
        "metric": f"svd{lane}_{m}x{n}_{dtype_name}{tag}_gflops",
        "value": round(gflops, 2),
        "unit": "GFLOP/s",
        "vs_baseline": (round(t_base / t_ours, 3) if t_base is not None
                        else None),
        "time_s": round(t_ours, 4),
        "baseline_time_s": (round(t_base, 4) if t_base is not None else None),
        "baseline": (base_name if t_base is not None or not attempted_baseline
                     else f"{base_name}: FAILED TO COMPILE/RUN"),
        "sweeps": sweeps_meas,
        "mfu": mfu,
        # Modeled solve HBM bytes over measured time (see
        # _model_hbm_gbps) and the lane the model priced (auto rows
        # name what auto routed to).
        "hbm_gbps": hbm_gbps,
        "hbm_model_lane": model_lane,
        # Provenance of every derived (per-peak / per-bandwidth) metric
        # in this row: "table" = tabulated device constant,
        # "peak_est"/"bw_est" = the documented fallback estimate.
        "peak_flops_source": "peak_est" if mfu_est else "table",
        "hbm_bw_source": "bw_est" if _hbm_bw(device_kind)[1] else "table",
        "device": str(jax.devices()[0]),
        **extras,
    }
    if mfu_est:
        row["peak_est"] = ("documented CPU-class estimate "
                           "(obs.costmodel.PEAK_FLOPS) — MFU comparable "
                           "across rounds, not absolute")
    if retried is not None:
        row["retried"] = retried
    print(json.dumps(row))
    if pair_ab and row["vs_baseline"] is not None:
        # The lane A/B as its own parseable row: end-to-end speedup of
        # the pinned pair-solver lane over what "auto" routes to today
        # (the block_rotation acceptance row at 512^2-2048^2).
        base_gflops = flops / t_base / 1e9
        print(json.dumps({
            "metric": f"pair_solver_speedup_{m}x{n}_{dtype_name}"
                      f"_{pair_solver}",
            "value": row["vs_baseline"],
            "unit": f"x vs {base_name}",
            "time_s": row["time_s"],
            "auto_time_s": row["baseline_time_s"],
            "mfu": mfu,
            "auto_mfu": _mfu(base_gflops, device_kind)[0],
            "sigma_err_vs_oracle": extras.get("sigma_err"),
        }))
    if top_k is not None and row["vs_baseline"] is not None:
        # The lane's raison d'etre, as its own parseable row: end-to-end
        # speedup of the truncated solve over the full one at the same
        # shape (acceptance target: >= 4x at 1024^2 f32, k <= n/8).
        print(json.dumps({
            "metric": f"topk_speedup_{m}x{n}_{dtype_name}_k{top_k}",
            "value": row["vs_baseline"],
            "unit": "x vs full solve",
            "time_s": row["time_s"],
            "full_time_s": row["baseline_time_s"],
            "sigma_err_vs_oracle": extras.get("sigma_err"),
        }))
    if tall_vs_pad and row["vs_baseline"] is not None:
        print(json.dumps({
            "metric": f"tall_vs_pad_speedup_{m}x{n}_{dtype_name}",
            "value": row["vs_baseline"],
            "unit": "x vs pad-to-square full solve",
            "time_s": row["time_s"],
            "padded_time_s": row["baseline_time_s"],
        }))

    manifest_path = flags.get("manifest", "reports/manifest.jsonl")
    if manifest_path == "1":
        # Bare `--manifest` (the flag parser's valueless sentinel): treat
        # as a boolean enable, not a file literally named "1".
        manifest_path = "reports/manifest.jsonl"
    if manifest_path != "off":
        from svd_jacobi_tpu import obs
        events = None
        if "telemetry" in flags:
            # One extra untimed solve with the event stream baked in — the
            # telemetered program is a different jit entry, so the timed
            # numbers above are untouched. Guarded: a failed replay (e.g.
            # OOM at the largest sizes) must not lose the manifest record
            # the timed row already earned.
            try:
                if stepped:
                    # The host-stepped path has no in-graph emission
                    # sites; record the per-sweep stream (incl. real wall
                    # times) from one instrumented host-stepped solve.
                    from svd_jacobi_tpu.utils import profiling
                    src = (a if a is not None
                           else matgen.random_dense(m, n, dtype=dtype))
                    _, log = profiling.instrumented_svd(
                        src, compute_u=not novec, compute_v=not novec,
                        config=cfg)
                    events = log.to_events()
                else:
                    fn = ours
                    if "fused-gen" in flags:
                        # `ours` replays a jit closure traced while
                        # telemetry was off (a cache hit emits nothing).
                        # A FRESH jit of the same closure traces inside
                        # the capture, keeping the generated matrix an
                        # internal temp like the timed fused-gen run.
                        run_tel = jax.jit(lambda: base(
                            matgen.random_dense(m, n, dtype=dtype)))
                        fn = lambda _x: run_tel()
                    with obs.metrics.capture() as events:
                        _force(fn(a))
            except Exception as e:
                print(f"note: telemetry replay failed "
                      f"({type(e).__name__}); manifest written without "
                      f"events", file=sys.stderr)
                events = None
        record = obs.manifest.build(
            "bench", m=m, n=n, dtype=dtype_name, config=cfg,
            solve={"time_s": float(t_ours), "sweeps": int(r.sweeps),
                   "off_norm": float(r.off_rel),
                   "gflops": round(gflops, 2),
                   "vs_baseline": row["vs_baseline"],
                   "mfu": row["mfu"],
                   "hbm_gbps": row["hbm_gbps"],
                   "hbm_model_lane": row["hbm_model_lane"],
                   **extras},
            stages=[{"name": "best_of_reps", "time_s": float(t_ours)}],
            telemetry=events,
            metric=row["metric"], baseline=row["baseline"],
            baseline_time_s=row["baseline_time_s"],
            novec=novec, stepped=stepped, reps=reps,
            retried=retried, top_k=top_k, tall_vs_pad=tall_vs_pad,
            argv=sys.argv[1:])
        obs.manifest.append(manifest_path, record)
        print(f"manifest: {manifest_path}", file=sys.stderr)

    if "check-against" in flags:
        if not _check_against_gate(row, flags["check-against"]):
            sys.exit(4)


if __name__ == "__main__":
    main()
