"""Benchmark driver: one-sided block-Jacobi SVD on the attached accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

The reference publishes no numbers (SURVEY.md section 6), so the baseline is
self-generated on the same chip: `jnp.linalg.svd` (XLA's built-in SVD) on the
identical input — `vs_baseline` is our speedup over it (>1 means faster).
`value` is nominal GFLOP/s using the classic 12*n^3 full-SVD flop count
(4mn^2 + 8n^3 at m = n), so runs at different sizes stay comparable.

Usage: python bench.py [N] [dtype]   (defaults: 2048, float32)
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _force(tree):
    from svd_jacobi_tpu.utils._exec import force
    return force(tree)


def _time(f, *args, reps: int = 2) -> float:
    """Best-of-reps device wall time."""
    _force(f(*args))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _force(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    dtype_name = sys.argv[2] if len(sys.argv) > 2 else "float32"

    import jax
    import jax.numpy as jnp
    import svd_jacobi_tpu as sj
    from svd_jacobi_tpu.utils import matgen, validation

    dtype = jnp.dtype(dtype_name)
    a = matgen.random_dense(n, n, dtype=dtype)

    t_ours = _time(lambda x: tuple(sj.svd(x)[:3]), a)
    t_xla = _time(lambda x: jnp.linalg.svd(x, compute_uv=True), a)

    r = sj.svd(a)
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    sigma_err = float(validation.sigma_error(r.s, s_ref))

    flops = 12.0 * n**3  # nominal full-SVD flop count (4mn^2 + 8n^3, m = n)
    print(json.dumps({
        "metric": f"svd_{n}x{n}_{dtype_name}_gflops",
        "value": round(flops / t_ours / 1e9, 2),
        "unit": "GFLOP/s",
        "vs_baseline": round(t_xla / t_ours, 3),
        "time_s": round(t_ours, 4),
        "baseline_time_s": round(t_xla, 4),
        "baseline": "jnp.linalg.svd same chip",
        "sweeps": int(r.sweeps),
        "sigma_err": sigma_err,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
