"""Fleet serving (`svd_jacobi_tpu.serve.fleet`): per-lane fault domains,
bucket-affinity routing + work stealing, lane eviction on every declared
sickness cause, dead-lane request rescue, probe recovery, and the fleet
manifest schema — plus the `-m chaos` kill-a-lane-mid-solve soak.

All CPU, all threads (the conftest backend has 8 virtual CPU devices, so
two lanes really do pin to two distinct devices). Small f64 buckets keep
every solve on the fast XLA block path.
"""

import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

from svd_jacobi_tpu import SVDConfig
from svd_jacobi_tpu.obs import manifest
from svd_jacobi_tpu.resilience import chaos
from svd_jacobi_tpu.serve import (AdmissionError, AdmissionQueue,
                                  AdmissionReason, Bucket, BreakerState,
                                  LaneState, ServeConfig, SVDService)
from svd_jacobi_tpu.solver import SolveStatus
from svd_jacobi_tpu.utils import matgen

pytestmark = pytest.mark.fleet

BUCKETS = ((32, 32, "float64"), (48, 32, "float64"))
SOLVER = SVDConfig(block_size=4)


def _cfg(**over):
    base = dict(buckets=BUCKETS, solver=SOLVER, max_queue_depth=16,
                lanes=2, supervise_interval_s=0.02,
                lane_heartbeat_timeout_s=2.0, lane_probe_interval_s=0.05,
                lane_probe_timeout_s=120.0, steal=False)
    base.update(over)
    return ServeConfig(**base)


def _mat(m, n, seed):
    return matgen.random_dense(m, n, seed=seed, dtype=jnp.float64)


def _sref(a):
    return np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)


def _wait_state(svc, lane, state, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if svc.fleet.lanes[lane].state is state:
            return True
        time.sleep(0.02)
    return False


def _fleet_events(svc):
    return [r for r in svc.records() if r.get("kind") == "fleet"]


def _serve_records(svc):
    return [r for r in svc.records() if r.get("kind") == "serve"]


class TestRoutingAndConfig:
    def test_bucket_affinity_is_stable(self):
        svc = SVDService(_cfg())
        b0, b1 = list(svc.buckets)
        assert svc.fleet.route(b0).index == 0
        assert svc.fleet.route(b1).index == 1
        assert svc.fleet.route(b0).index == 0   # stable, not round-robin

    def test_route_fails_over_and_no_lane_rejects(self):
        svc = SVDService(_cfg())
        b0 = list(svc.buckets)[0]
        svc.fleet.evict(svc.fleet.lanes[0], "test_forced")
        assert svc.fleet.route(b0).index == 1   # failover to next ACTIVE
        svc.fleet.evict(svc.fleet.lanes[1], "test_forced")
        with pytest.raises(AdmissionError) as ei:
            svc.fleet.route(b0)
        assert ei.value.reason is AdmissionReason.NO_LANE

    def test_lane_config_validation(self):
        with pytest.raises(ValueError, match="lanes"):
            SVDService(ServeConfig(buckets=BUCKETS, lanes=0))
        with pytest.raises(ValueError, match="lane_heartbeat"):
            SVDService(ServeConfig(buckets=BUCKETS, lanes=2,
                                   lane_heartbeat_timeout_s=0.0))
        with pytest.raises(ValueError, match="lane_failure_threshold"):
            SVDService(ServeConfig(buckets=BUCKETS, lanes=2,
                                   lane_failure_threshold=0))

    def test_lanes_pin_distinct_devices(self):
        svc = SVDService(_cfg())
        devs = [l.device for l in svc.fleet.lanes]
        assert all(d is not None for d in devs)
        assert len(set(devs)) == 2      # conftest: 8 virtual CPU devices
        # Single-lane mode keeps default placement — pre-fleet behavior.
        assert SVDService(
            ServeConfig(buckets=BUCKETS)).fleet.lanes[0].device is None


class TestMultiLaneServing:
    def test_both_lanes_serve_with_affinity(self):
        with SVDService(_cfg()) as svc:
            tickets = [(32, 32, svc.submit(_mat(32, 32, seed=i)))
                       for i in range(2)]
            tickets += [(48, 32, svc.submit(_mat(48, 32, seed=10 + i)))
                        for i in range(2)]
            for m, n, t in tickets:
                res = t.result(timeout=300.0)
                assert res.status is SolveStatus.OK
            recs = _serve_records(svc)
            h = svc.healthz()
        by_lane = {}
        for r in recs:
            by_lane.setdefault(r["lane"], []).append(r["bucket"])
        assert set(by_lane) == {0, 1}
        # Affinity: each bucket's requests all landed on its home lane.
        assert set(by_lane[0]) == {"32x32:float64"}
        assert set(by_lane[1]) == {"48x32:float64"}
        assert h["fleet"]["active"] == 2 and h["fleet"]["quarantined"] == 0

    def test_results_match_oracle_on_both_lanes(self):
        with SVDService(_cfg()) as svc:
            cases = [(32, 32, 40), (48, 32, 41)]
            for m, n, seed in cases:
                a = _mat(m, n, seed=seed)
                res = svc.submit(a).result(timeout=300.0)
                assert res.status is SolveStatus.OK
                np.testing.assert_allclose(np.asarray(res.s), _sref(a),
                                           rtol=1e-10, atol=1e-12)

    def test_work_stealing_drains_hot_lane(self):
        """A burst on ONE bucket backs up its home lane; the idle
        sibling must steal and serve — recorded as fleet steal events."""
        with SVDService(_cfg(steal=True)) as svc:
            # Warm both lanes so stealing is not masked by compile time.
            assert svc.submit(_mat(32, 32, seed=1)).result(
                300.0).status is SolveStatus.OK
            with chaos.slow_solve(0.2, shots=1):   # slow lane 0's next pop
                tickets = [svc.submit(_mat(30, 30, seed=100 + i))
                           for i in range(6)]
                res = [t.result(timeout=300.0) for t in tickets]
        assert all(r.status is SolveStatus.OK for r in res)
        assert svc.fleet.total_steals >= 1
        steals = [r for r in _fleet_events(svc) if r["event"] == "steal"]
        assert steals and steals[0]["lane"] == 1 and steals[0]["victim"] == 0
        lanes_used = {r["lane"] for r in _serve_records(svc)}
        assert lanes_used == {0, 1}

    def test_exactly_once_terminal_records(self):
        with SVDService(_cfg(steal=True)) as svc:
            tickets = [svc.submit(_mat(24, 24, seed=200 + i))
                       for i in range(8)]
            for t in tickets:
                assert t.result(timeout=300.0).status is SolveStatus.OK
            ids = [r["request"]["id"] for r in _serve_records(svc)]
        assert len(ids) == len(set(ids)) == 8


class TestAntiStarvation:
    """Satellite: `pop_same_bucket` may not starve a rarely-requested
    bucket behind a hot one forever — the oldest other-bucket request
    bounds the bypass."""

    def _req(self, rid, bucket, age_s):
        from svd_jacobi_tpu.serve.queue import Request
        now = time.monotonic()
        return Request(id=rid, a=None, m=bucket.m, n=bucket.n,
                       orig_shape=(bucket.m, bucket.n), transposed=False,
                       bucket=bucket, compute_u=True, compute_v=True,
                       degraded=False, deadline=None, deadline_s=None,
                       submitted=now - age_s)

    def test_aged_other_bucket_closes_the_window(self):
        hot = Bucket(8, 8, "float64")
        cold = Bucket(16, 16, "float64")
        q = AdmissionQueue(max_depth=8)
        q.admit(self._req("hot1", hot, age_s=0.0))
        q.admit(self._req("cold-old", cold, age_s=1.0))   # starving
        q.admit(self._req("hot2", hot, age_s=0.0))
        out = q.pop_same_bucket(hot, limit=4,
                                deadline=time.monotonic() + 5.0,
                                max_bypass_age=0.5)
        # hot1 sits AHEAD of the starved request (no bypass) and is
        # taken; hot2 is BEHIND it and must not jump the queue — and the
        # window closes immediately instead of blocking out the 5 s.
        assert [r.id for r in out] == ["hot1"]
        assert q.pop(0.01).id == "cold-old"               # next plain pop
        assert q.pop(0.01).id == "hot2"

    def test_no_bound_keeps_old_behavior(self):
        hot = Bucket(8, 8, "float64")
        cold = Bucket(16, 16, "float64")
        q = AdmissionQueue(max_depth=8)
        q.admit(self._req("hot1", hot, age_s=0.0))
        q.admit(self._req("cold-old", cold, age_s=1.0))
        q.admit(self._req("hot2", hot, age_s=0.0))
        out = q.pop_same_bucket(hot, limit=4, deadline=None)
        assert [r.id for r in out] == ["hot1", "hot2"]    # full bypass

    def test_served_coalescing_respects_the_bound(self):
        """End-to-end: under coalescing, the starved cold-bucket request
        is served no later than one hot batch after its age bound."""
        cfg = _cfg(lanes=1, max_batch=4, batch_window_s=0.05,
                   batch_tiers=(1, 4), batch_bypass_age_s=0.2)
        with SVDService(cfg) as svc:
            with chaos.slow_solve(0.15, shots=1):
                hot0 = svc.submit(_mat(8, 8, seed=300))      # occupies
                cold = svc.submit(_mat(40, 30, seed=301))    # other bucket
                hots = [svc.submit(_mat(8, 8, seed=302 + i))
                        for i in range(3)]
                rc = cold.result(timeout=300.0)
                rest = [t.result(timeout=300.0)
                        for t in [hot0] + hots]
        assert rc.status is SolveStatus.OK
        assert all(r.status is SolveStatus.OK for r in rest)


@pytest.mark.chaos
class TestLaneChaos:
    def test_kill_lane_evicts_rescues_and_recovers(self):
        """The acceptance ladder: kill one lane's worker mid-solve —
        its in-flight AND queued requests are rescued onto the healthy
        lane (every ticket terminal exactly once), the lane is
        quarantined with cause lane_dead, a probe returns it to ACTIVE,
        and the whole cycle reconstructs from validated fleet records."""
        with SVDService(_cfg()) as svc:
            a_vic = _mat(32, 32, seed=400)
            with chaos.kill_lane(0):
                victim = svc.submit(a_vic)                # dies in flight
                queued = [svc.submit(_mat(30, 30, seed=401 + i))
                          for i in range(2)]
                rv = victim.result(timeout=120.0)
                rq = [t.result(timeout=120.0) for t in queued]
            assert _wait_state(svc, 0, LaneState.ACTIVE), \
                svc.fleet.lanes[0].snapshot()
            # The recovered lane serves again — on its own thread.
            r_after = svc.submit(_mat(32, 32, seed=405)).result(120.0)
            recs = _serve_records(svc)
            events = _fleet_events(svc)
        # Rescued results are REAL solves (on lane 1), not error stubs.
        assert rv.status is SolveStatus.OK
        np.testing.assert_allclose(np.asarray(rv.s), _sref(a_vic),
                                   rtol=1e-10, atol=1e-12)
        assert all(r.status is SolveStatus.OK for r in rq)
        assert r_after.status is SolveStatus.OK
        # Exactly once: one terminal record per request id.
        ids = [r["request"]["id"] for r in recs]
        assert len(ids) == len(set(ids))
        # The eviction -> rescue -> probe -> recovery ladder, from records.
        for r in events:
            manifest.validate(r)
        trans = [(r["from_state"], r["to_state"], r["cause"])
                 for r in events if r["event"] == "lane_transition"
                 and r["lane"] == 0]
        assert ("active", "quarantined", "lane_dead") in trans
        assert ("quarantined", "active", "probe success") in trans
        rescues = [r for r in events if r["event"] == "rescue"
                   and r["lane"] == 0]
        assert rescues and sum(r["count"] for r in rescues) >= 1
        probes = [r for r in events if r["event"] == "probe"
                  and r["lane"] == 0]
        assert any(r["ok"] for r in probes)

    def test_wedge_lane_heartbeat_eviction(self):
        """A non-cooperatively wedged lane (no heartbeat, control
        ignored) is evicted on heartbeat staleness; its in-flight
        request is rescued and served by the healthy lane; the wedged
        worker wakes to a stale generation and cannot double-serve."""
        with SVDService(_cfg()) as svc:
            # Warm lane 0 so the wedge hits a hot cache (no compile in
            # the timing window).
            assert svc.submit(_mat(32, 32, seed=410)).result(
                300.0).status is SolveStatus.OK
            with chaos.wedge_lane(0, wedge_s=10.0):
                wedged = svc.submit(_mat(32, 32, seed=411))
                rw = wedged.result(timeout=60.0)
            assert rw.status is SolveStatus.OK
            recs = _serve_records(svc)
            events = _fleet_events(svc)
            assert _wait_state(svc, 0, LaneState.ACTIVE)
        # Served by the HEALTHY lane (the wedged one never dispatched it).
        rec = [r for r in recs if r["request"]["id"] == rw.request_id]
        assert len(rec) == 1 and rec[0]["lane"] == 1
        trans = [(r["to_state"], r["cause"]) for r in events
                 if r["event"] == "lane_transition" and r["lane"] == 0]
        assert ("quarantined", "heartbeat_stale") in trans

    def test_poison_lane_bad_outcome_eviction(self):
        """Repeated NONFINITE outcomes on one lane evict it (cause
        bad_outcomes) while results stay loud; once the poison shots are
        exhausted the probe solves clean and the lane returns."""
        cfg = _cfg(lane_failure_threshold=2, breaker_threshold=10)
        with SVDService(cfg) as svc:
            with chaos.poison_lane(0, shots=2):
                r1 = svc.submit(_mat(32, 32, seed=420)).result(120.0)
                r2 = svc.submit(_mat(32, 32, seed=421)).result(120.0)
            assert r1.status is SolveStatus.NONFINITE
            assert r2.status is SolveStatus.NONFINITE
            assert _wait_state(svc, 0, LaneState.QUARANTINED, 10.0)
            assert _wait_state(svc, 0, LaneState.ACTIVE)
            # Recovered: the same bucket solves clean on lane 0 again.
            a = _mat(32, 32, seed=422)
            r3 = svc.submit(a).result(120.0)
            events = _fleet_events(svc)
        assert r3.status is SolveStatus.OK
        trans = [(r["to_state"], r["cause"]) for r in events
                 if r["event"] == "lane_transition" and r["lane"] == 0]
        assert ("quarantined", "bad_outcomes") in trans
        assert ("active", "probe success") in trans

    def test_flag_unhealthy_evicts_with_cause(self):
        """The escalation-ladder watchdog's hook: a lane flagged
        unhealthy (ladder_overrun) is evicted on the next tick and its
        queued requests rescued."""
        with SVDService(_cfg()) as svc:
            svc.fleet.flag_unhealthy(svc.fleet.lanes[0], "ladder_overrun")
            assert _wait_state(svc, 0, LaneState.QUARANTINED, 10.0)
            events = _fleet_events(svc)
        trans = [(r["to_state"], r["cause"]) for r in events
                 if r["event"] == "lane_transition" and r["lane"] == 0]
        assert ("quarantined", "ladder_overrun") in trans

    def test_no_healthy_lane_rescue_is_loud(self):
        """With every other lane down, rescue cannot requeue — the
        request finalizes ERROR (path=rescue), never silently lost."""
        with SVDService(_cfg(lane_probe_interval_s=600.0)) as svc:
            svc.fleet.evict(svc.fleet.lanes[1], "test_forced")
            with chaos.kill_lane(0):
                t = svc.submit(_mat(32, 32, seed=430))
                res = t.result(timeout=60.0)
            recs = _serve_records(svc)
        assert res.error is not None and "no healthy lane" in res.error
        rec = [r for r in recs if r["request"]["id"] == t.request_id]
        assert len(rec) == 1
        assert rec[0]["status"] == "ERROR" and rec[0]["path"] == "rescue"

    def test_admit_racing_eviction_is_rescued(self, monkeypatch):
        """The submit-vs-evict race: a request admitted onto a lane that
        was evicted between routing and admission must be re-rescued by
        the submitter, not stranded until a probe revives the lane."""
        with SVDService(_cfg(lane_probe_interval_s=600.0)) as svc:
            fleet = svc.fleet
            orig_route = fleet.route
            fired = []

            def racy_route(bucket):
                lane = orig_route(bucket)
                if not fired:
                    fired.append(lane.index)
                    fleet.evict(lane, "test_race")   # evict AFTER routing
                return lane
            monkeypatch.setattr(fleet, "route", racy_route)
            res = svc.submit(_mat(32, 32, seed=450)).result(timeout=120.0)
            events = _fleet_events(svc)
        # Served despite landing on the just-evicted lane's queue...
        assert res.status is SolveStatus.OK
        # ...because the admit-race rescue moved it to the healthy lane.
        rescues = [r for r in events if r["event"] == "rescue"
                   and r.get("cause") == "admit_race"]
        assert rescues and rescues[0]["count"] == 1

    def test_rescue_respects_remaining_deadline(self):
        """A rescued request whose deadline already expired finalizes
        DEADLINE at rescue time — never re-served past its promise."""
        with SVDService(_cfg()) as svc:
            with chaos.kill_lane(0):
                # The deadline expires while the dead lane strands it.
                t = svc.submit(_mat(32, 32, seed=440), deadline_s=0.01)
                time.sleep(0.05)
                res = t.result(timeout=60.0)
        assert res.status is SolveStatus.DEADLINE
        assert res.sweeps == 0                    # no solve spent on it


@pytest.mark.chaos
@pytest.mark.soak
class TestFleetSoak:
    def test_kill_lane_under_closed_loop_fleet(self):
        """Satellite soak: a closed-loop client fleet runs while one
        lane is killed mid-solve. Every ticket reaches a terminal
        status exactly once, no client deadlocks, surviving lanes keep
        serving (OK sigmas match the oracle), the fleet stays ready
        throughout, and the killed lane returns to ACTIVE."""
        cfg = _cfg(max_queue_depth=64, steal=True)
        svc = SVDService(cfg).start()
        # Warm both buckets (compiles out of the timed window).
        for m, n, s in ((32, 32, 500), (48, 32, 501)):
            assert svc.submit(_mat(m, n, seed=s)).result(
                300.0).status is SolveStatus.OK

        results = {}
        res_lock = threading.Lock()
        ready_seen = []

        def client(cid):
            rng = np.random.default_rng(600 + cid)
            for j in range(4):
                wide = bool(rng.integers(2))
                m, n = (48, 32) if wide else (32, 32)
                m = int(rng.integers(m // 2, m + 1))
                n = int(rng.integers(2, min(m, n) + 1))
                try:
                    t = svc.submit(_mat(m, n, seed=1000 * cid + j),
                                   deadline_s=120.0)
                except AdmissionError as e:
                    with res_lock:
                        results[(cid, j)] = e.reason
                    continue
                ready_seen.append(svc.ready())
                try:
                    res = t.result(timeout=240.0)
                except TimeoutError:
                    res = None
                with res_lock:
                    results[(cid, j)] = res

        with chaos.kill_lane(0):
            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(4)]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=300.0)
        assert not any(th.is_alive() for th in threads), "client hung"
        assert _wait_state(svc, 0, LaneState.ACTIVE), \
            svc.fleet.lanes[0].snapshot()
        svc.stop(drain=True, timeout=120.0)

        assert len(results) == 16
        assert all(v is not None for v in results.values()), results
        statuses = [v.status for v in results.values()
                    if not isinstance(v, AdmissionReason)]
        # Surviving lanes kept serving: the overwhelming outcome is OK
        # (a killed-lane request may legitimately finalize DEADLINE if
        # its budget died with the lane — loud either way).
        assert statuses.count(SolveStatus.OK) >= len(statuses) - 2
        # Residuals unchanged: spot-check OK results against the oracle.
        ok_items = [((cid, j), v) for (cid, j), v in results.items()
                    if not isinstance(v, AdmissionReason)
                    and v.status is SolveStatus.OK][:3]
        for (cid, j), v in ok_items:
            rec = [r for r in _serve_records(svc)
                   if r["request"]["id"] == v.request_id]
            assert len(rec) == 1          # exactly once, in the records too
            m, n = rec[0]["request"]["m"], rec[0]["request"]["n"]
            a = _mat(m, n, seed=1000 * cid + j)
            np.testing.assert_allclose(np.asarray(v.s), _sref(a),
                                       rtol=1e-9, atol=1e-11)
        # The fleet stayed ready while clients were submitting.
        assert all(ready_seen)
        # Terminal exactly once across the whole soak.
        ids = [r["request"]["id"] for r in _serve_records(svc)]
        assert len(ids) == len(set(ids))
        for r in _fleet_events(svc):
            manifest.validate(r)
        trans = [(r["lane"], r["to_state"], r["cause"])
                 for r in _fleet_events(svc)
                 if r["event"] == "lane_transition"]
        assert (0, "quarantined", "lane_dead") in trans
        assert (0, "active", "probe success") in trans


class TestFleetManifest:
    def test_build_validate_summarize(self):
        rec = manifest.build_fleet(event="lane_transition", lane=1,
                                   from_state="active",
                                   to_state="quarantined",
                                   cause="heartbeat_stale")
        manifest.validate(rec)
        assert rec["kind"] == "fleet"
        text = manifest.summarize(rec)
        assert "lane=1" in text and "heartbeat_stale" in text
        rescue = manifest.build_fleet(event="rescue", lane=0, count=2,
                                      request_ids=["a", "b"],
                                      cause="lane_dead")
        assert "2 request(s)" in manifest.summarize(rescue)
        over = manifest.build_fleet(event="ladder_overrun", elapsed_s=3.5,
                                    budget_s=1.0)
        assert "elapsed=3.50s" in manifest.summarize(over)

    def test_invalid_fleet_record_rejected(self):
        rec = manifest.build_fleet(event="steal", lane=1, victim=0,
                                   request_id="r1")
        rec.pop("event")
        with pytest.raises(ValueError, match="event"):
            manifest.validate(rec)
        bad = manifest.build_fleet(event="probe")
        bad["lane"] = "not-an-int"
        with pytest.raises(ValueError, match="lane"):
            manifest.validate(bad)


class TestFleetRetraceContract:
    """CI satellite: each lane compiles once per (bucket, variant) and an
    affinity move costs at most one compile on the receiving lane — and
    the guard demonstrably fires when the budget is under-declared."""

    def test_fleet_case_within_budget(self):
        from svd_jacobi_tpu.analysis.recompile_guard import \
            run_serve_fleet_case
        findings, report = run_serve_fleet_case()
        assert findings == [], [f.message for f in findings]
        assert all(s == "OK" for s in report["serve_statuses"])

    def test_underdeclared_budget_fires(self):
        """Seeded failing fixture: FRESH buckets (cold caches) with the
        budget under-declared at 1 — the per-lane compiles must surface
        as RETRACE001 (this is what a per-dispatch leak looks like)."""
        from svd_jacobi_tpu.analysis.recompile_guard import \
            run_serve_fleet_case
        findings, _ = run_serve_fleet_case(
            expected_problems=1,
            buckets=((56, 40, "float32"), (88, 56, "float32")))
        assert findings, "under-declared fleet budget must fire RETRACE001"
        assert all(f.code == "RETRACE001" for f in findings)


class TestPerLaneAOTWarm:
    """Satellite: the AOT phase carries each lane's device into the
    lowering (`EntryRegistry.aot_plan` pins the specs), so a warm
    restart's zero-solve phase — whose dispatches run on device-pinned
    per-lane inputs — performs ZERO fresh compiles at lanes=2."""

    def test_warm_restart_zero_fresh_compiles_at_two_lanes(self, tmp_path):
        import jax
        # One bucket keeps the test inside the tier-1 budget; the
        # per-lane pinning claim is about LANES (warmup compiles every
        # bucket on every lane's device), not bucket count.
        cfg = _cfg(buckets=((32, 32, "float32"),),
                   solver=SVDConfig(pair_solver="pallas"),
                   compile_cache_dir=str(tmp_path / "cache"),
                   lane_probe_interval_s=600.0)
        svc = SVDService(cfg)
        # Construction enabled the persistent cache; drop every live jit
        # cache NOW so the helper programs other tests (or the conftest
        # graftcheck) already compiled — pre-cache-enable, hence never
        # persisted — are recompiled inside the cache window instead of
        # polluting the warm restart's fresh count.
        jax.clear_caches()
        svc.start()
        # The registry's plans must be pinned per lane (8-device test
        # backend: lanes 0/1 round-robin onto distinct devices).
        devs = {svc.registry.lane_device(i) for i in range(2)}
        assert len(devs) == 2 and None not in devs
        try:
            svc.warmup(timeout=600.0)
        finally:
            svc.stop(drain=False, timeout=10.0)
        # A fresh process is simulated by dropping every live jit cache:
        # the second service's warmup (AOT + zero-solve phases alike)
        # must be served entirely by the persistent executable cache.
        jax.clear_caches()
        svc2 = SVDService(cfg).start()
        try:
            svc2.warmup(timeout=600.0)
        finally:
            svc2.stop(drain=False, timeout=10.0)
        rec = [r for r in svc2.records()
               if r.get("kind") == "coldstart"][-1]
        assert rec["lanes"] == 2
        assert rec["fresh_compiles"] == 0, rec
        assert rec["cache_hits"] == rec["backend_compiles"] > 0


class TestPromotionRescue:
    """Promotion-state rescue on eviction: retained sigma-phase states
    of an evicted lane stay promotable, and the stream shows each one
    carried across the eviction as a "cache" rescue event."""

    def test_evicted_lane_states_stay_promotable(self):
        cfg = _cfg(lane_probe_interval_s=600.0)
        a = _mat(32, 32, seed=901)
        with SVDService(cfg) as svc:
            t = svc.submit(a, phase="sigma")
            assert t.result(timeout=300.0).status is SolveStatus.OK
            lane = svc.fleet.lanes[
                svc.fleet._bucket_home[svc.buckets.route(32, 32,
                                                         "float64")]]
            svc.fleet.evict(lane, "analysis_forced")
            rescued = [r for r in svc.records()
                       if r.get("kind") == "cache"
                       and r["event"] == "rescue"]
            assert [r["request_id"] for r in rescued] == [t.request_id]
            rp = t.promote(timeout=120.0)
            assert rp.status is SolveStatus.OK
            rec = (np.asarray(rp.u) * np.asarray(rp.s)) @ np.asarray(rp.v).T
            np.testing.assert_allclose(rec, np.asarray(a), atol=5e-12)
