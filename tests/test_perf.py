"""The roofline performance observatory (`obs.costmodel` /
`obs.attribution` / `obs.perf` + the PERF001 analysis pass):

  * offline-equals-live — the checked-in "perf" manifest record under
    `tests/fixtures/perf/` was emitted by a real `cli --profile` run on
    this CPU backend; rebuilding it offline from the gzipped trace
    through the same `obs.perf.build_report` path must reproduce it
    exactly (the ONE-code-path contract), and the stdlib read side must
    do so with jax import-BLOCKED (no accelerator stack on the machine
    that renders the table).
  * the noise-band bench regression gate — fit from repeated
    measurements only (a real 7x speedup never inflates the band), the
    seeded regressed row fails, the real r01 -> r04 trajectory passes,
    and an errored round (no measurement) can never demonstrate the
    absence of a regression.
  * per-sweep convergence telemetry — `ConvergenceRecorder` edges plus
    the serve wiring: one solve populates healthz["perf"] and the
    `svdj_sweeps_to_tol` gauge with ZERO extra device readback.
  * PERF001 — the model-agreement detector on a live probe (clean at
    1x, firing at the seeded 9x drift), the SCOPE_PHASES join, and the
    perf-off HLO byte-identity discipline.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from svd_jacobi_tpu.obs import costmodel, manifest
from svd_jacobi_tpu.obs import perf as obsperf

pytestmark = pytest.mark.perf

REPO = Path(__file__).resolve().parent.parent
FIXDIR = Path(__file__).resolve().parent / "fixtures" / "perf"
TRACE = FIXDIR / "solve_64x64_cpu.xplane.pb.gz"
FIXTURE_MANIFEST = FIXDIR / "manifest.jsonl"


def _fixture_record() -> dict:
    return json.loads(FIXTURE_MANIFEST.read_text())


# ---------------------------------------------------------------------------
# Offline equals live.


class TestOfflineEqualsLive:
    def test_rebuild_reproduces_live_emission_exactly(self):
        """The checked-in record IS a live `cli --profile` emission;
        `build_report` from the checked-in trace must reproduce every
        attribution field bit-for-bit (same parse, same join, same
        model — one code path)."""
        rec = _fixture_record()
        rebuilt = obsperf.build_report(
            str(TRACE), rec["workload"], rec["device"], source="cli")
        assert rebuilt["scopes"] == rec["scopes"]
        assert rebuilt["unscoped_s"] == rec["unscoped_s"]
        assert rebuilt["unattributed_s"] == rec["unattributed_s"]
        assert rebuilt["workload"] == rec["workload"]
        assert rebuilt["device"] == rec["device"]
        assert rebuilt["trace"] == rec["trace"]

    def test_fixture_record_validates_and_summarizes(self):
        rec = _fixture_record()
        manifest.validate(rec)
        text = manifest.summarize(rec)
        assert "perf" in text and "64x64" in text

    def test_report_cli_offline_with_jax_blocked(self, tmp_path):
        """`perf report` renders from the fixture with jax imports
        POISONED — the read side is stdlib-only, as promised to the
        machine without an accelerator stack."""
        driver = tmp_path / "driver.py"
        driver.write_text(textwrap.dedent(f"""
            import importlib.abc, json, sys

            class _NoJax(importlib.abc.MetaPathFinder):
                def find_spec(self, name, path=None, target=None):
                    if name == "jax" or name.startswith("jax."):
                        raise ImportError("jax is blocked in this test")
            sys.meta_path.insert(0, _NoJax())

            sys.path.insert(0, {str(REPO / 'svd_jacobi_tpu' / 'obs')!r})
            import perf
            rc = perf.main(["report", "--trace", {str(TRACE)!r},
                            "--manifest", {str(FIXTURE_MANIFEST)!r},
                            "--json"])
            sys.exit(rc)
        """))
        out = subprocess.run([sys.executable, str(driver)],
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        rec = json.loads(out.stdout)
        assert rec["scopes"] == _fixture_record()["scopes"]
        # The blocked-jax environment block proves no device was dialed.
        assert rec["environment"]["backend"] == "offline"

    def test_report_uses_manifest_workload(self, capsys):
        rc = obsperf.main(["report", "--trace", str(TRACE),
                           "--manifest", str(FIXTURE_MANIFEST)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "64x64" in out and "sweep.rotations" in out

    def test_model_cli_needs_no_trace(self, capsys):
        rc = obsperf.main(["model", "--n", "256", "--dtype", "float32"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "sweep.rotations" in out and "total" in out


# ---------------------------------------------------------------------------
# The noise-band regression gate.


class TestPerfCheck:
    def test_real_trajectory_passes(self):
        """r04 against r01..r03: the genuine 7x r02 -> r03 jump is an
        improvement step, not noise — the repeats-only band never
        inflates from it, and r04 (a further small gain) passes."""
        ok, lines = obsperf.check_files(str(REPO / "BENCH_r04.json"))
        assert ok, "\n".join(lines)
        assert any("pass" in ln for ln in lines)

    def test_seeded_regressed_row_fails(self):
        hist = []
        for i in (1, 2, 3, 4):
            hist.extend(obsperf._bench_rows(
                str(REPO / f"BENCH_r0{i}.json")))
        metric = (hist[-1].get("parsed") or {})["metric"]
        seeded = {"n": 6, "parsed": {"metric": metric, "value": 430.0,
                                     "unit": "GFLOP/s"}}
        ok, lines = obsperf.check_rows(seeded, hist)
        assert not ok
        assert any("beyond the" in ln for ln in lines)

    def test_errored_round_fails_by_policy(self):
        """r05 (rc=3, parsed.value null) cannot demonstrate the absence
        of a regression — the gate fails it instead of skipping it."""
        ok, lines = obsperf.check_files(str(REPO / "BENCH_r05.json"))
        assert not ok
        assert any("no measurement" in ln for ln in lines)

    def test_band_fit_from_repeats_only(self):
        values = [77.27, 76.31, 528.95, 562.45]   # the real trajectory
        band = obsperf.fit_noise_band(values)
        # The 85% improvement step is NOT a repeat; only the 1.2% and
        # 6% gaps feed the fit.
        assert 0.02 <= band <= 0.15

    def test_no_history_passes(self):
        row = {"parsed": {"metric": "svd_64x64_float32_gflops",
                          "value": 10.0}}
        ok, lines = obsperf.check_rows(row, [])
        assert ok and "no history" in lines[0]

    def test_lower_is_better_metrics_flip_direction(self):
        hist = [{"parsed": {"metric": "svd_64_time_s", "value": 1.0}}]
        worse = {"parsed": {"metric": "svd_64_time_s", "value": 2.0}}
        better = {"parsed": {"metric": "svd_64_time_s", "value": 0.9}}
        assert not obsperf.check_rows(worse, hist)[0]
        assert obsperf.check_rows(better, hist)[0]


# ---------------------------------------------------------------------------
# Convergence telemetry.


class TestConvergenceRecorder:
    def test_empty_recorder_has_no_block(self):
        assert obsperf.ConvergenceRecorder().block(tol=1e-6) is None

    def test_block_fields(self):
        rec = obsperf.ConvergenceRecorder(spectrum="32x32:float64")
        for off, stage in ((0.5, "bulk"), (1e-3, "bulk"),
                           (1e-8, "polish")):
            rec.record(off, stage)
        rec.record_rounds(rotated=6, total=8)
        blk = rec.block(tol=1e-6)
        assert blk["sweeps"] == 3
        assert blk["off_rel"][0] == 0.5 and blk["stages"][2] == "polish"
        assert blk["sweeps_to_tol"] == 3       # 1-based first <= tol
        assert blk["rotations_skipped_frac"] == pytest.approx(0.25)

    def test_sweeps_to_tol_none_when_never_reached(self):
        rec = obsperf.ConvergenceRecorder()
        rec.record(0.5)
        assert rec.sweeps_to_tol(1e-9) is None
        assert rec.block(tol=1e-9)["sweeps_to_tol"] is None


class TestServeConvergence:
    def test_one_solve_populates_healthz_and_gauge(self):
        """The serve hook: a host-stepped solve feeds the convergence
        block (off_rel decay, recorded from the scalar the stopping
        decision ALREADY pulls) into healthz["perf"] and the
        `svdj_sweeps_to_tol` gauge."""
        from svd_jacobi_tpu import SVDConfig
        from svd_jacobi_tpu.serve import ServeConfig, SVDService
        from svd_jacobi_tpu.utils import matgen

        cfg = ServeConfig(buckets=((32, 32, "float64"),),
                          solver=SVDConfig(block_size=4), metrics=True)
        svc = SVDService(cfg)
        svc.start()
        try:
            a = matgen.random_dense(30, 24, seed=7, dtype="float64")
            res = svc.submit(a, deadline_s=600.0).result(timeout=600.0)
            assert res.error is None and res.status.name == "OK"
            perf = svc.healthz()["perf"]
            assert perf["device"] is None or \
                perf["device"]["peak_flops_source"] in ("table",
                                                        "peak_est")
            conv = perf["convergence"]
            assert conv, "no convergence block after a solved request"
            blk = next(iter(conv.values()))
            assert blk["sweeps"] >= 1 and len(blk["off_rel"]) == \
                blk["sweeps"]
            assert blk["off_rel"][-1] <= blk["off_rel"][0]
            assert "svdj_sweeps_to_tol" in svc.metrics_text()
        finally:
            svc.stop()


# ---------------------------------------------------------------------------
# Device-constant provenance.


class TestDeviceBlock:
    def test_tabulated_kind_says_table(self):
        dev = obsperf.device_block("TPU v5e")
        assert dev["peak_flops_source"] == "table"
        assert dev["hbm_bw_source"] == "table"
        assert dev["peak_flops"] > 1e12

    def test_unknown_kind_says_estimated(self):
        dev = obsperf.device_block("cpu")
        assert dev["peak_flops_source"] == "peak_est"
        assert dev["hbm_bw_source"] == "bw_est"


# ---------------------------------------------------------------------------
# PERF001.


class TestPERF001:
    def test_scope_phase_join_clean(self):
        from svd_jacobi_tpu.analysis import perf_checks
        assert perf_checks.check_scope_phase_join() == []

    def test_perf_off_hlo_byte_identical(self):
        from svd_jacobi_tpu.analysis import perf_checks
        assert perf_checks.check_perf_off_hlo() == []

    def test_model_agrees_then_drift_fixture_fires(self):
        """One live probe: the model agrees at 1x and the seeded 9x
        drift (a lost n^3 term's magnitude) trips the detector — the
        detector can FAIL, not just pass."""
        from svd_jacobi_tpu.analysis import entries, perf_checks
        probe = next(p for p in entries.single_device_probes()
                     if p.name == "pallas")
        model = perf_checks._probe_model_flops(probe)
        xla = perf_checks._xla_flops(probe)
        assert xla > 0
        ratio = model / xla
        tol = perf_checks.MODEL_TOL_FACTOR
        assert 1.0 / tol <= ratio <= tol, ratio
        assert not (1.0 / tol <= ratio * 9.0 <= tol)
