"""The analyzer analyzed: every graftcheck pass must (a) report ZERO
findings on the real package and (b) demonstrably catch its seeded
violation — a fixture corpus for the AST rules
(tests/fixtures/graft_violations/), constructed bad programs for the
jaxpr/HLO/retrace passes. A checker that cannot fail its fixture is
decoration, not CI.
"""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import svd_jacobi_tpu as sj
from svd_jacobi_tpu import SVDConfig
from svd_jacobi_tpu import config as sj_config
from svd_jacobi_tpu.analysis import (Finding, ast_lint, entries, hlo_checks,
                                     jaxpr_checks, recompile_guard)
from svd_jacobi_tpu.obs import manifest, metrics

FIXTURES = Path(__file__).parent / "fixtures" / "graft_violations"


def _codes(findings):
    return sorted({f.code for f in findings})


# ---------------------------------------------------------------------------
# AST lint: corpus caught, package clean.


class TestAstLintCorpus:
    @pytest.mark.parametrize("fixture,code,min_hits", [
        ("graft001_host_cast.py", "GRAFT001", 4),
        ("graft002_traced_if.py", "GRAFT002", 2),
        ("graft003_import_time.py", "GRAFT003", 2),
        ("graft004_jit_key.py", "GRAFT004", 3),
    ])
    def test_seeded_violation_caught(self, fixture, code, min_hits):
        findings = ast_lint.lint_file(FIXTURES / fixture, rel=fixture,
                                      traced=True)
        hits = [f for f in findings if f.code == code]
        assert len(hits) >= min_hits, findings
        # ... and ONLY the seeded rule fires (no false positives from the
        # other rules on the same file).
        assert _codes(findings) == [code]

    def test_graft005_missing_scope_caught(self):
        findings = ast_lint.check_scope_coverage(
            {"gram": ("graft005_missing_scope.py", "hot_gram_panel"),
             "rotations": ("graft005_missing_scope.py", "covered_fn")},
            root=FIXTURES)
        assert _codes(findings) == ["GRAFT005"]
        assert "hot_gram_panel" in findings[0].message

    def test_graft001_suggests_host_scalar(self):
        findings = ast_lint.lint_file(FIXTURES / "graft001_host_cast.py",
                                      rel="x.py", traced=True)
        shard = [f for f in findings if "addressable_shards" in f.message]
        assert shard and "host_scalar" in shard[0].suggestion

    def test_pragma_suppresses(self):
        findings = ast_lint.lint_file(FIXTURES / "graft001_host_cast.py",
                                      rel="x.py", traced=True)
        lines = {f.where for f in findings}
        # suppressed_cast's float() is pragma'd away: its line absent.
        src = (FIXTURES / "graft001_host_cast.py").read_text().splitlines()
        pragma_line = next(i + 1 for i, l in enumerate(src)
                           if "graftcheck: ok" in l)
        assert f"x.py:{pragma_line}" not in lines

    def test_clean_control_fixture(self):
        findings = ast_lint.lint_file(FIXTURES / "clean_module.py",
                                      rel="clean.py", traced=True)
        assert findings == []

    def test_real_package_lints_clean(self):
        assert ast_lint.lint_package() == []

    def test_hot_scope_contract_is_current(self):
        # Every declared hot region resolves and is covered (a refactor
        # that renames a hot function must update config.HOT_SCOPES).
        assert ast_lint.check_scope_coverage() == []


# ---------------------------------------------------------------------------
# jaxpr checks: entries clean, seeded violations caught.


class TestJaxprChecks:
    def test_default_entries_clean(self):
        assert jaxpr_checks.check_default_entries(include_mesh=False) == []

    def test_mesh_entries_clean(self, eight_devices):
        probes = entries.mesh_probes()
        assert probes, "mesh probes missing on the 8-device backend"
        findings = []
        for p in probes:
            findings += jaxpr_checks.check_probe(p)
        assert findings == []

    def test_ungated_emit_is_flagged_when_statically_off(self):
        """Satellite guard: an emit call site NOT behind the static
        telemetry flag becomes a callback in the telemetry-off trace the
        moment the module flag is on — JAXPR001 catches exactly that."""
        def leaky(x):  # an "entry" whose emit forgot its static gate
            metrics.emit("sweep", off_rel=jnp.max(x))
            return x * 2

        prev = metrics.enabled()
        try:
            metrics.enable()
            closed = jax.make_jaxpr(leaky)(jnp.ones(4))
        finally:
            if not prev:
                metrics.disable()
        findings = jaxpr_checks.check_host_callbacks(closed, "leaky")
        assert _codes(findings) == ["JAXPR001"]
        assert "debug_callback" in findings[0].message

    def test_ungated_emit_module_flag_off_is_noop(self):
        """With the module flag off an ungated emit is a no-op: nothing
        in the trace, nothing delivered to sinks."""
        assert not metrics.enabled()
        hits = []
        remove = metrics.add_sink(hits.append)
        try:
            def leaky(x):
                metrics.emit("sweep", off_rel=jnp.max(x))
                return x * 2
            closed = jax.make_jaxpr(leaky)(jnp.ones(4))
            assert jaxpr_checks.check_host_callbacks(closed, "leaky") == []
            jax.jit(leaky)(jnp.ones(4))
            metrics.flush()
        finally:
            remove()
        assert hits == []

    def test_undeclared_upcast_caught(self):
        def sneaky(x):
            # f32 solve silently widening to f64: the classic violation.
            return jnp.sum(x.astype(jnp.float64))

        closed = jax.make_jaxpr(sneaky)(jnp.ones(4, jnp.float32))
        findings = jaxpr_checks.check_dtype_boundaries(
            closed, "sneaky", jnp.float32)
        assert _codes(findings) == ["JAXPR002"]
        assert "float64" in findings[0].message

    def test_declared_boundary_allowed(self):
        def mixed(x):
            return jnp.sum(x.astype(jnp.float32))  # bf16 -> f32: declared

        closed = jax.make_jaxpr(mixed)(jnp.ones(4, jnp.bfloat16))
        assert jaxpr_checks.check_dtype_boundaries(
            closed, "mixed", jnp.bfloat16) == []

    def test_callback_inside_loop_caught(self):
        def loopy(x):
            def body(_, c):
                jax.debug.callback(lambda v: None, jnp.max(c))
                return c * 0.5
            return jax.lax.fori_loop(0, 4, body, x)

        closed = jax.make_jaxpr(loopy)(jnp.ones(4))
        findings = jaxpr_checks.check_host_callbacks(closed, "loopy")
        assert "JAXPR001" in _codes(findings)


# ---------------------------------------------------------------------------
# HLO checks: budgets, donation, telemetry invariance.


class TestHloChecks:
    def test_collective_budget_matches_declaration(self, eight_devices):
        for probe in entries.mesh_probes():
            assert hlo_checks.check_collective_budget(probe) == [], probe.name

    def test_collective_budget_violation_detected(self, eight_devices):
        probe = entries.mesh_probes()[0]
        tampered = dict(sj_config.COLLECTIVE_BUDGET[probe.name])
        tampered["all_gather"] = 3       # declare gathers that don't exist
        findings = hlo_checks.check_collective_budget(probe, tampered)
        assert _codes(findings) == ["HLO001"]

    def test_undeclared_entry_flagged(self, eight_devices):
        probe = entries.mesh_probes()[0]
        import dataclasses
        unknown = dataclasses.replace(probe, name="never_declared")
        findings = hlo_checks.check_collective_budget(unknown)
        assert _codes(findings) == ["HLO001"]
        assert "declare" in findings[0].message

    def test_donation_survives(self):
        singles = {p.name: p for p in entries.single_device_probes()}
        assert hlo_checks.check_donation(
            singles["pallas_donated"], singles["pallas"]) == []

    def test_missing_donation_detected(self):
        singles = {p.name: p for p in entries.single_device_probes()}
        # Swap: the undonated entry presented as the donated one.
        findings = hlo_checks.check_donation(
            singles["pallas"], singles["pallas_donated"])
        codes = _codes(findings)
        assert codes == ["HLO002"] and len(findings) == 2

    def test_telemetry_invariance_all_entries(self):
        for probe in entries.single_device_probes():
            assert hlo_checks.check_telemetry_invariance(probe) == [], \
                probe.name

    def test_telemetry_invariance_mesh(self, eight_devices):
        probe = entries.mesh_probes()[0]
        assert hlo_checks.check_telemetry_invariance(probe) == []

    def test_dead_telemetry_flag_detected(self):
        """An entry that ignores its telemetry flag must be flagged."""
        import dataclasses
        from functools import partial

        @partial(jax.jit, static_argnames=("telemetry",))
        def dead_flag(x, *, telemetry=False):
            return x * 2  # flag unused: on == off

        probe = entries.EntryProbe(
            name="dead", fn=dead_flag, args=(jnp.ones(4),),
            kwargs={"telemetry": False})
        findings = hlo_checks.check_telemetry_invariance(probe)
        assert _codes(findings) == ["HLO003"]
        assert "dead" in findings[0].message

    def test_chaos_gate_clean_on_entries(self):
        for probe in entries.single_device_probes():
            assert hlo_checks.check_chaos_gate(probe) == [], probe.name

    def test_chaos_gate_armed_plan_flagged(self):
        """A production plan that resolved with fault injection armed must
        be flagged — chaos can never ride a real solve."""
        probe = entries.single_device_probes()[0]
        findings = hlo_checks.check_chaos_gate(
            probe.with_kwargs(chaos_nan_sweep=3))
        assert _codes(findings) == ["HLO004"]
        assert "ARMED" in findings[0].message

    def test_chaos_dead_gate_flagged(self):
        """An entry that ignores its chaos_nan_sweep static must be
        flagged (the chaos lane would be testing a no-op)."""
        from functools import partial

        @partial(jax.jit, static_argnames=("chaos_nan_sweep",))
        def dead_gate(x, *, chaos_nan_sweep=None):
            return x * 2  # hook unused: armed == unarmed

        probe = entries.EntryProbe(
            name="dead_chaos", fn=dead_gate, args=(jnp.ones(4),),
            kwargs={"chaos_nan_sweep": None})
        findings = hlo_checks.check_chaos_gate(probe)
        assert _codes(findings) == ["HLO004"]
        assert "no-op" in findings[0].message


# ---------------------------------------------------------------------------
# Recompile guard.


class TestRecompileGuard:
    def test_repeat_solves_do_not_retrace(self):
        from svd_jacobi_tpu.utils import matgen
        cfg = SVDConfig(pair_solver="pallas", max_sweeps=8)
        a = matgen.random_dense(32, 32, seed=0, dtype=jnp.float32)
        sj.svd(a, config=cfg)                    # warm outside the guard
        with recompile_guard.RecompileGuard() as guard:
            guard.expect("solver._svd_pallas", problems=0)
            for _ in range(3):
                sj.svd(a, config=cfg)            # identical problem key
            findings = guard.check()
        assert findings == []
        assert guard.new_traces()["solver._svd_pallas"] == 0

    def test_seeded_retrace_caught(self):
        from functools import partial

        @partial(jax.jit, static_argnames=("k",))
        def leaky_key(x, *, k):
            return x * k

        with recompile_guard.RecompileGuard(
                budgets={"leaky": 1}, entries={"leaky": leaky_key}) as guard:
            guard.expect("leaky", problems=1)    # ONE problem declared...
            for k in range(4):                   # ...but the key churns
                leaky_key(jnp.ones(4), k=k)
            findings = guard.check()
        assert _codes(findings) == ["RETRACE001"]
        assert guard.new_traces()["leaky"] == 4

    def test_monitoring_hook_counts_compiles(self):
        @jax.jit
        def fresh(x):
            return x + 1

        with recompile_guard.RecompileGuard(entries={}) as guard:
            fresh(jnp.ones(7))
        assert guard.backend_compiles >= 1

    def test_expect_unknown_entry_raises(self):
        with pytest.raises(KeyError):
            recompile_guard.RecompileGuard().expect("no_such_entry")


# ---------------------------------------------------------------------------
# Report plumbing: manifest records, CLI smoke.


class TestAnalysisReport:
    def test_manifest_round_trip(self, tmp_path):
        f = Finding(code="GRAFT001", where="x.py:3", message="m",
                    suggestion="s")
        rec = manifest.build_analysis(passes=[
            {"name": "ast", "ok": False, "findings": [f.as_dict()],
             "time_s": 0.1},
            {"name": "jaxpr", "ok": True, "findings": [], "time_s": 0.2},
        ])
        assert rec["ok"] is False and rec["findings_total"] == 1
        path = tmp_path / "m.jsonl"
        manifest.append(path, rec)
        loaded = manifest.load(path)[0]
        manifest.validate(loaded)
        assert loaded["passes"][0]["findings"][0]["code"] == "GRAFT001"
        assert "analysis" in manifest.summarize(loaded)

    def test_validate_rejects_malformed_pass(self):
        rec = manifest.build_analysis(passes=[
            {"name": "ast", "ok": True, "findings": [], "time_s": 0.0}])
        rec["passes"][0].pop("ok")
        with pytest.raises(ValueError, match="passes"):
            manifest.validate(rec)

    @pytest.mark.slow
    def test_cli_fast_passes_exit_zero(self, tmp_path):
        """Subprocess boot of `python -m svd_jacobi_tpu.analysis` — slow
        lane (the pass logic itself is covered in-process above)."""
        import os
        import subprocess
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.run(
            [sys.executable, "-m", "svd_jacobi_tpu.analysis",
             "--passes", "ast,jaxpr", "--report-dir", str(tmp_path)],
            capture_output=True, text=True, env=env,
            cwd=Path(__file__).parent.parent, timeout=600)
        assert p.returncode == 0, p.stderr[-800:]
        rec = manifest.load(tmp_path / "manifest.jsonl")[0]
        manifest.validate(rec)
        assert rec["kind"] == "analysis" and rec["ok"] is True


@pytest.mark.slow
def test_cli_all_passes_exit_zero(tmp_path):
    """The acceptance criterion end-to-end: the full graftcheck CLI is
    clean on the repo (slow lane: compiles the mesh entries)."""
    import os
    import subprocess
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, "-m", "svd_jacobi_tpu.analysis",
         "--report-dir", str(tmp_path)],
        capture_output=True, text=True, env=env,
        cwd=Path(__file__).parent.parent, timeout=600)
    assert p.returncode == 0, p.stderr[-1500:]


class TestServePromoteRetraceContract:
    """The two-phase half of the serve retrace contract: σ-then-promote
    request streams keep the once-per-bucket compile budget (the sigma
    extraction and the finish jits compile once per bucket, promotes are
    pure cache hits) — and the guard demonstrably fires when the budget
    is under-declared."""

    def test_promote_case_within_budget(self):
        from svd_jacobi_tpu.analysis.recompile_guard import \
            run_serve_promote_case
        findings, report = run_serve_promote_case()
        assert findings == [], [f.message for f in findings]
        assert all(s == "OK" for s in report["serve_statuses"])
        # The sigma extraction genuinely ran (and compiled once per
        # bucket, not zero times — a silent full-phase fallback would
        # also 'pass' the budget).
        assert report["new_traces"]["solver._sigma_from_state_jit"] == 2
        assert report["new_traces"]["solver._finish_pallas_jit"] == 2

    def test_underdeclared_promote_budget_fires(self):
        """Seeded failing fixture: FRESH buckets with every budget
        under-declared at 1 — the per-bucket compiles must surface as
        RETRACE001 (what a per-request or per-promote leak looks
        like)."""
        from svd_jacobi_tpu.analysis.recompile_guard import \
            run_serve_promote_case
        findings, _ = run_serve_promote_case(
            expected_problems=1,
            buckets=((52, 36, "float32"), (84, 52, "float32")))
        assert findings, "under-declared promote budget must fire"
        assert all(f.code == "RETRACE001" for f in findings)
