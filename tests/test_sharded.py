"""Distributed solver on the 8-virtual-device CPU mesh (SURVEY.md section 4:
the reference could only test multi-node on a live SLURM cluster; the mesh /
ppermute logic here runs entirely in CI)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from svd_jacobi_tpu import SVDConfig, _compat
from svd_jacobi_tpu.parallel import schedule as sched, sharded
from svd_jacobi_tpu.utils import matgen, validation


def _mesh(ndev):
    return sharded.make_mesh(jax.devices()[:ndev])


@pytest.mark.parametrize("ndev", [1, 2, 4, 8])
def test_ring_exchange_matches_schedule(ndev, eight_devices):
    """The sharded ring rotation is bit-identical to the single-device
    tournament rotation for a full cycle of rounds (the proof obligation from
    SURVEY.md section 7: ring schedule covers the same pairs)."""
    k = max(2 * ndev, 4)
    m, b = 3, 2
    rng = np.random.default_rng(0)
    top0 = jnp.asarray(rng.normal(size=(k, m, b)), jnp.float32)
    bot0 = jnp.asarray(rng.normal(size=(k, m, b)), jnp.float32)

    mesh = _mesh(ndev)
    spec = jax.sharding.PartitionSpec("blocks", None, None)

    def step(top, bot):
        return sharded._ring_exchange(top, bot, axis_name="blocks",
                                      n_devices=ndev)

    ring = jax.jit(_compat.shard_map(step, mesh=mesh, in_specs=(spec, spec),
                                 out_specs=(spec, spec)))
    t_ring, b_ring = top0, bot0
    t_ref, b_ref = top0, bot0
    for _ in range(sched.num_rounds(2 * k)):
        t_ring, b_ring = ring(t_ring, b_ring)
        t_ref, b_ref = sched.rotate_blocks(t_ref, b_ref)
        np.testing.assert_array_equal(np.asarray(t_ring), np.asarray(t_ref))
        np.testing.assert_array_equal(np.asarray(b_ring), np.asarray(b_ref))


@pytest.mark.parametrize("ndev", [2, 8])
def test_sharded_svd_f64(ndev, eight_devices):
    n = 96
    a = matgen.random_dense(n, n, dtype=jnp.float64, seed=21)
    r = sharded.svd(a, mesh=_mesh(ndev), config=SVDConfig(block_size=4))
    s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
    rep = validation.validate(a, r, s_ref=s_ref)
    assert float(rep.sigma_err) < 1e-12, rep.as_dict()
    assert float(rep.residual_rel) < 1e-13, rep.as_dict()
    assert float(rep.u_orth) < 1e-10, rep.as_dict()
    assert float(rep.v_orth) < 1e-10, rep.as_dict()


def test_sharded_matches_single_device(eight_devices):
    """Same input -> same singular values as the single-device solver, and
    the distributed traversal converges in a comparable number of sweeps."""
    n = 64
    a = matgen.random_dense(n, n, dtype=jnp.float64, seed=5)
    cfg = SVDConfig(block_size=4)
    from svd_jacobi_tpu import svd as svd_single
    r1 = svd_single(a, config=cfg)
    r8 = sharded.svd(a, mesh=_mesh(8), config=cfg)
    np.testing.assert_allclose(np.asarray(r8.s), np.asarray(r1.s),
                               rtol=1e-10, atol=1e-12)
    assert int(r8.sweeps) <= int(r1.sweeps) + 3


def test_sharded_tall_skinny(eight_devices):
    a = matgen.random_dense(200, 48, dtype=jnp.float64, seed=13)
    r = sharded.svd(a, mesh=_mesh(8), config=SVDConfig(block_size=2))
    s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
    rep = validation.validate(a, r, s_ref=s_ref)
    assert float(rep.sigma_err) < 1e-12
    assert float(rep.residual_rel) < 1e-13


def test_sharded_wide_via_transpose(eight_devices):
    a = matgen.random_dense(32, 80, dtype=jnp.float64, seed=17)
    r = sharded.svd(a, mesh=_mesh(4), config=SVDConfig(block_size=2))
    s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
    np.testing.assert_allclose(np.asarray(r.s), s_ref, rtol=1e-10, atol=1e-12)
    assert r.u.shape == (32, 32) and r.v.shape == (80, 32)


def test_sharded_novec(eight_devices):
    a = matgen.random_dense(40, 40, dtype=jnp.float64, seed=29)
    r = sharded.svd(a, mesh=_mesh(4), compute_u=False, compute_v=False,
                    config=SVDConfig(block_size=2))
    assert r.u is None and r.v is None
    s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
    np.testing.assert_allclose(np.asarray(r.s), s_ref, rtol=1e-10, atol=1e-12)


def test_sharded_input_already_sharded(eight_devices):
    """Accepts an input generated directly into a sharding
    (utils.matgen.sharded_random) — no host materialization."""
    mesh = _mesh(8)
    shard = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(None, "blocks"))
    a = matgen.sharded_random(64, 64, shard, dtype=jnp.float64)
    r = sharded.svd(a, mesh=mesh, config=SVDConfig(block_size=2))
    s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
    np.testing.assert_allclose(np.asarray(r.s), s_ref, rtol=1e-10, atol=1e-12)


def test_single_pair_single_device(eight_devices):
    """Regression: k == 1 ring exchange is a fixed point (2x2 matrix on a
    1-device mesh used to crash at trace time with mismatched carry types)."""
    a = matgen.random_dense(2, 2, dtype=jnp.float64, seed=1)
    r = sharded.svd(a, mesh=_mesh(1), config=SVDConfig(block_size=1))
    s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
    np.testing.assert_allclose(np.asarray(r.s), s_ref, rtol=1e-12, atol=1e-14)


def test_plan_caps_padding():
    """Regression: user-specified block sizes are shrunk on a mesh so the
    padded width stays within ~2x of n instead of scaling with P."""
    from svd_jacobi_tpu import solver
    for n, p, bs in [(64, 8, 16), (100, 8, 128), (256, 4, 128)]:
        b, k = solver._plan(n, p, SVDConfig(block_size=bs))
        assert 2 * k * b <= 2 * max(n, 4 * p), (n, p, bs, b, k)
        assert k % p == 0 and k >= 2 * p


def test_sharded_random_decomposition_invariant():
    """sharded_random is a pure function of (seed, m, n): bit-identical
    values on any mesh shape / axis (VERDICT r2 weak #8 — distributed and
    single-chip benches must solve the same matrix)."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from svd_jacobi_tpu.utils import matgen

    devs = jax.devices()
    ref = None
    for nd, spec in [(1, P(None, "x")), (4, P(None, "x")), (8, P(None, "x")),
                     (4, P("x", None))]:
        mesh = Mesh(np.array(devs[:nd]), ("x",))
        a = np.asarray(matgen.sharded_random(
            200, 264, NamedSharding(mesh, spec), seed=7))
        if ref is None:
            ref = a
        else:
            assert np.array_equal(ref, a)


def test_sharded_checkpoint_resume(tmp_path):
    """A killed sharded solve resumes from its snapshot and converges to the
    oracle (VERDICT r2 missing #5: checkpointing for the mesh solves that
    actually need it)."""
    import numpy as np
    from svd_jacobi_tpu.parallel import sharded
    from svd_jacobi_tpu.utils import checkpoint, matgen

    mesh = sharded.make_mesh()
    a = matgen.random_dense(96, 96, seed=3)
    path = tmp_path / "ck.npz"

    # "Crash" after two sweeps: snapshot exists, solve abandoned.
    st = sharded.SweepStepper(a, mesh=mesh)
    state = st.init()
    state = st.step(st.step(state))
    checkpoint.save_state(path, st, state)

    # Fresh process-equivalent: resume and finish through the one-call API.
    r = checkpoint.svd_checkpointed(a, path=path, mesh=mesh)
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6
    assert not path.exists()  # removed on success

    # A snapshot from a DIFFERENT mesh shape must be rejected.
    st_small = sharded.SweepStepper(a, mesh=sharded.make_mesh(jax.devices()[:4]))
    state_s = st_small.step(st_small.init())
    checkpoint.save_state(path, st_small, state_s)
    with pytest.raises(ValueError, match="does not match"):
        checkpoint.load_state(path, sharded.SweepStepper(a, mesh=mesh))


def test_instrumented_sharded():
    import numpy as np
    from svd_jacobi_tpu.parallel import sharded
    from svd_jacobi_tpu.utils import matgen, profiling

    mesh = sharded.make_mesh()
    a = matgen.random_dense(64, 64, seed=4)
    r, log = profiling.instrumented_svd(a, mesh=mesh)
    assert len(log.records) == int(r.sweeps)
    assert log.records[-1].off_norm <= log.records[0].off_norm


def test_mesh_sweepstepper_kernel_path(eight_devices):
    """The host-stepped MESH stepper must run the same sharded Pallas-path
    sweeps as the fused mesh solver (VERDICT r4 weak #3: checkpointed and
    instrumented mesh solves downgraded to the XLA hybrid stepping), with
    the fused path's preconditioned bookkeeping and sweep-count parity."""
    import svd_jacobi_tpu.solver as solver
    rng = np.random.default_rng(41)
    a = jnp.asarray(rng.standard_normal((128, 128)), jnp.float32)
    mesh = _mesh(8)
    st = sharded.SweepStepper(a, mesh=mesh)
    assert st._kernel_path and st.method == "pallas"
    state = st.init()
    # Kernel-path geometry matches the fused mesh solve's plan.
    b, k = solver._plan(128, 8, SVDConfig())
    assert state.top.shape[0] == k
    while st.should_continue(state):
        state = st.step(state)
    r = st.finish(state)
    a64 = np.asarray(a, np.float64)
    s_ref = np.linalg.svd(a64, compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6
    res = np.linalg.norm(np.asarray(r.u, np.float64)
                         * np.asarray(r.s, np.float64)
                         @ np.asarray(r.v, np.float64).T - a64)
    assert res / np.linalg.norm(a64) < 5e-6
    # Sweep parity with the fused mesh solve (same kernels, same loop).
    fused = sharded.svd(a, mesh=mesh)
    assert abs(int(r.sweeps) - int(fused.sweeps)) <= 1


def test_mesh_sweepstepper_kernel_path_novec(eight_devices):
    """Sigma-only mesh stepping on the kernel path (no accumulation)."""
    rng = np.random.default_rng(42)
    a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
    st = sharded.SweepStepper(a, mesh=_mesh(4), compute_u=False,
                              compute_v=False)
    assert st._kernel_path
    state = st.init()
    while st.should_continue(state):
        state = st.step(state)
    r = st.finish(state)
    assert r.u is None and r.v is None
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6


def test_mesh_rejects_single_device_only_modes():
    """Single-device-only config modes must be rejected loudly by the mesh
    solver instead of silently ignored (and recorded in reports as if
    applied)."""
    a = jnp.ones((16, 16), jnp.float32)
    mesh = sharded.make_mesh(jax.devices()[:1])
    with pytest.raises(ValueError, match="precondition"):
        sharded.svd(a, mesh=mesh, config=SVDConfig(precondition="double"))


def test_mesh_preconditioned_solve_matches_oracle():
    """The mesh solver preconditions by default now (QR outside shard_map,
    inverted bookkeeping: rotations -> U, normalized columns -> V) — full
    accuracy contract against the host oracle, including tall m > n and
    every factor-option combination."""
    rng = np.random.default_rng(31)
    for (m, n), cu, cv, full in [((96, 96), True, True, False),
                                 ((160, 96), True, True, True),
                                 ((96, 96), True, False, False),
                                 ((96, 96), False, True, False)]:
        a = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
        mesh = sharded.make_mesh()
        r = sharded.svd(a, mesh=mesh, compute_u=cu, compute_v=cv,
                        full_matrices=full)
        a64 = np.asarray(a, np.float64)
        s_ref = np.linalg.svd(a64, compute_uv=False)
        assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6
        if cu:
            u = np.asarray(r.u, np.float64)
            assert u.shape == ((m, m) if full else (m, n))
            assert np.max(np.abs(u.T @ u - np.eye(u.shape[1]))) < 1e-4
        if cv:
            v = np.asarray(r.v, np.float64)
            assert np.max(np.abs(v.T @ v - np.eye(n))) < 1e-4
        if cu and cv:
            res = np.linalg.norm(
                np.asarray(r.u, np.float64)[:, :n] * np.asarray(r.s, np.float64)
                @ np.asarray(r.v, np.float64).T - a64)
            assert res / np.linalg.norm(a64) < 5e-6


def test_mesh_precondition_sweep_parity():
    """Preconditioning must cut mesh sweeps the way it does single-chip
    (unpreconditioned mesh solves ran ~15 vs 11 sweeps at 2048^2 in r3)."""
    rng = np.random.default_rng(32)
    a = jnp.asarray(rng.standard_normal((128, 128)), jnp.float32)
    mesh = sharded.make_mesh()
    import svd_jacobi_tpu as sj
    r_on = sharded.svd(a, mesh=mesh, config=SVDConfig(precondition="on"))
    r_off = sharded.svd(a, mesh=mesh, config=SVDConfig(precondition="off"))
    assert int(r_on.sweeps) <= int(r_off.sweeps)
    # Like-for-like: the mesh runs pure-f32 sweeps, so compare against the
    # single-chip solver with the mixed bulk off (its sweep counter counts
    # bulk + polish otherwise).
    single = sj.svd(a, config=SVDConfig(mixed_bulk=False))
    assert abs(int(r_on.sweeps) - int(single.sweeps)) <= 2


@pytest.mark.rank
def test_mesh_tall_input_chunked_precondition():
    """Tall (m >= 8n) mesh solve: the preconditioner routes through the
    chunked TSQR (ops.sketch) under GSPMD, and the factors still match
    the host oracle — the 'mesh solves of tall inputs work' half of the
    rectangular-workloads lane. The collective budget of this entry is
    pinned by analysis (config.COLLECTIVE_BUDGET['sharded_pallas_tall'])."""
    rng = np.random.default_rng(41)
    a = jnp.asarray(rng.standard_normal((768, 96)), jnp.float32)
    mesh = sharded.make_mesh()
    r = sharded.svd(a, mesh=mesh)
    assert r.status_enum().name == "OK"
    a64 = np.asarray(a, np.float64)
    s_ref = np.linalg.svd(a64, compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6
    recon = (np.asarray(r.u, np.float64) * np.asarray(r.s, np.float64)
             @ np.asarray(r.v, np.float64).T)
    assert np.linalg.norm(recon - a64) / np.linalg.norm(a64) < 5e-6
