"""The serving flight recorder (`obs.registry` / `obs.spans` + the serve
layer's instrumentation): live metrics registry with Prometheus
exposition, per-request span timelines (live AND reconstructed offline
from manifest records), SLO accounting, the /metrics+/healthz HTTP
listener, the exporter under fleet chaos, and the OBS002 free-when-off
contract (zero registry mutations on the metrics-off hot path, seeded
failing fixture included).

Small f64 buckets keep every solve on the fast XLA block path (the
test_fleet.py discipline); the conftest backend has 8 virtual CPU
devices so two lanes really pin to two devices.
"""

import json
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from svd_jacobi_tpu import SVDConfig
from svd_jacobi_tpu.obs import manifest, registry as obsreg, spans as obsspans
from svd_jacobi_tpu.obs.registry import (MetricsRegistry, SLOTracker,
                                         parse_prometheus)
from svd_jacobi_tpu.obs.spans import SpanRecorder, timeline_from_manifest
from svd_jacobi_tpu.resilience import chaos
from svd_jacobi_tpu.serve import LaneState, ServeConfig, SVDService
from svd_jacobi_tpu.utils import matgen

pytestmark = pytest.mark.obs

BUCKETS = ((32, 32, "float64"), (48, 32, "float64"))
SOLVER = SVDConfig(block_size=4)


def _cfg(**over):
    base = dict(buckets=BUCKETS, solver=SOLVER, max_queue_depth=16,
                metrics=True, brownout_sigma_only_at=2.0,
                brownout_shed_at=2.0)
    base.update(over)
    return ServeConfig(**base)


def _mat(m, n, seed):
    return matgen.random_dense(m, n, seed=seed, dtype=jnp.float64)


class TestMetricsRegistry:
    def test_counter_gauge_histogram_render_valid_prometheus(self):
        reg = MetricsRegistry()
        reg.inc("svdj_test_total", help="a counter", bucket="b32", lane=0)
        reg.inc("svdj_test_total", 2.0, bucket="b32", lane=0)
        reg.set("svdj_test_depth", 7, lane=1)
        for v in (0.003, 0.2, 11.0):
            reg.observe("svdj_test_seconds", v, bucket="b32")
        text = reg.render()
        series = parse_prometheus(text)     # raises on malformed lines
        assert series['svdj_test_total{bucket="b32",lane="0"}'] == 3.0
        assert series['svdj_test_depth{lane="1"}'] == 7.0
        assert series['svdj_test_seconds_count{bucket="b32"}'] == 3.0
        assert "# TYPE svdj_test_seconds histogram" in text
        # Cumulative buckets are monotonic and end at +Inf == count.
        bucket_vals = [v for k, v in sorted(series.items())
                       if k.startswith("svdj_test_seconds_bucket")]
        assert series['svdj_test_seconds_bucket{bucket="b32",le="+Inf"}'] \
            == 3.0

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.inc("svdj_esc_total", reason='he said "no"\nplus\\slash')
        parse_prometheus(reg.render())

    def test_kind_conflict_is_loud(self):
        reg = MetricsRegistry()
        reg.inc("svdj_conflict")
        with pytest.raises(ValueError, match="already registered"):
            reg.set("svdj_conflict", 1.0)

    def test_mutation_counter_global_and_instance(self):
        before = obsreg.mutation_total()
        reg = MetricsRegistry()
        reg.inc("svdj_m_total")
        reg.set("svdj_m_gauge", 1.0)
        reg.observe("svdj_m_seconds", 0.1)
        assert reg.mutations == 3
        assert obsreg.mutation_total() - before == 3

    def test_collectors_refresh_at_render_and_survive_errors(self):
        reg = MetricsRegistry()
        state = {"v": 1.0}
        reg.add_collector(lambda r: r.set("svdj_live_gauge", state["v"]))

        def boom(_r):
            raise RuntimeError("sick collector")
        reg.add_collector(boom)
        assert parse_prometheus(reg.render())["svdj_live_gauge"] == 1.0
        state["v"] = 5.0
        text = reg.render()
        assert parse_prometheus(text)["svdj_live_gauge"] == 5.0
        assert "collector error" in text       # loud, not fatal

    def test_histogram_quantile_ordering(self):
        reg = MetricsRegistry()
        for v in [0.001] * 50 + [0.2] * 45 + [3.0] * 5:
            reg.observe("svdj_q_seconds", v)
        snap = reg.snapshot()["svdj_q_seconds"]["series"][""]
        assert snap["count"] == 100
        assert snap["p50"] <= snap["p99"]


class TestSLOTracker:
    def test_quantiles_misses_and_burn(self):
        slo = SLOTracker(objective=0.9, window=10)
        for _ in range(8):
            slo.observe("b32", 0.01, ok=True)
        slo.observe("b32", 5.0, ok=False, deadline_miss=True)
        slo.shed("b32")
        snap = slo.snapshot()
        b = snap["buckets"]["b32"]
        assert b["served"] == 9 and b["deadline_miss"] == 1
        assert b["shed"] == 1
        # 9 samples: enough for p50 (min 2), NOT for p99 (min 100) —
        # a small-sample p99 would just be the max of the reservoir, so
        # it reports null and healthz documents the minimum.
        assert b["latency_p50_s"] is not None
        assert b["latency_p99_s"] is None
        assert snap["quantile_min_samples"] == {"p50": 2, "p99": 100}
        # 2 bad of 10 in the window, objective 0.9 -> burn = 0.2/0.1 = 2
        assert snap["error_budget_burn"] == pytest.approx(2.0)
        assert "error-budget burn" in obsreg.render_slo(snap)

    def test_quantiles_populate_past_minimum(self):
        slo = SLOTracker(objective=0.9)
        for i in range(100):
            slo.observe("b32", 0.001 * (i + 1), ok=True)
        b = slo.snapshot()["buckets"]["b32"]
        assert b["latency_p50_s"] is not None
        assert b["latency_p99_s"] is not None
        assert b["latency_p50_s"] <= b["latency_p99_s"]

    def test_slo_from_records_matches_live_counting(self):
        recs = []
        for status, wait, solve in (("OK", 0.01, 0.1), ("OK", 0.0, 0.2),
                                    ("DEADLINE", 0.5, None)):
            recs.append(manifest.build_serve(
                request_id=f"r{len(recs)}", m=32, n=32, dtype="float64",
                bucket="b32", queue_wait_s=wait, solve_time_s=solve,
                status=status, path="base", breaker="closed",
                brownout="FULL"))
        recs.append(manifest.build_serve(
            request_id="r9", m=32, n=32, dtype="float64", bucket=None,
            queue_wait_s=0.0, solve_time_s=None,
            status="REJECTED_BROWNOUT_SHED", path="rejected",
            breaker="closed", brownout="SHED"))
        # A client-error rejection (NO_BUCKET) must NOT burn the budget
        # offline — mirroring the live SLOTracker feed exactly.
        recs.append(manifest.build_serve(
            request_id="r10", m=7, n=7, dtype="float64", bucket=None,
            queue_wait_s=0.0, solve_time_s=None,
            status="REJECTED_NO_BUCKET", path="rejected",
            breaker="closed", brownout="FULL"))
        snap = obsreg.slo_from_records(recs)
        b = snap["buckets"]["b32"]
        assert b["served"] == 3 and b["ok"] == 2
        assert b["deadline_miss"] == 1
        assert snap["buckets"]["_rejected"]["shed"] == 1


class TestSpanRecorder:
    def test_order_phases_render_and_bound(self):
        rec = SpanRecorder(max_requests=2)
        for name in ("admit", "queued", "dispatch", "sweep", "sweep",
                     "finish", "finalize"):
            rec.event("r1", name)
        tl = rec.timeline("r1")
        assert [e["name"] for e in tl] == ["admit", "queued", "dispatch",
                                          "sweep", "sweep", "finish",
                                          "finalize"]
        phases = {p["phase"]: p for p in rec.phases("r1")}
        assert set(phases) == {"queued", "solve", "finalize"}
        assert phases["solve"]["duration_s"] >= 0
        text = rec.render("r1")
        assert "dispatch" in text and "x2" in text
        # LRU bound: the oldest request ages out.
        rec.event("r2", "admit")
        rec.event("r3", "admit")
        assert rec.timeline("r1") == []

    def test_offline_reconstruction_from_synthetic_records(self):
        recs = [manifest.build_serve(
            request_id="rx", m=32, n=32, dtype="float64", bucket="b32",
            queue_wait_s=0.25, solve_time_s=0.5, status="OK", path="base",
            breaker="closed", brownout="FULL", sweeps=6, lane=0)]
        tl = timeline_from_manifest(recs, "rx")
        names = [e["name"] for e in tl]
        assert names == ["admit", "queued", "dispatch", "sweep", "finish",
                         "finalize"]
        # Durations reconstruct from the record's own fields.
        by = {e["name"]: e for e in tl}
        assert by["dispatch"]["t_wall"] - by["admit"]["t_wall"] == \
            pytest.approx(0.25)
        assert by["finish"]["t_wall"] - by["dispatch"]["t_wall"] == \
            pytest.approx(0.5)
        assert by["sweep"]["count"] == 6


class TestLifecycleTimelines:
    """The PR's acceptance: one request's full lifecycle reconstructs as
    an ordered span timeline BOTH live and offline from manifest
    records — for the plain full solve and for the σ→promote flow."""

    CORE = ["admit", "queued", "dispatch", "finish", "finalize"]

    def _core_order(self, names):
        return [n for n in names if n in self.CORE]

    def test_full_solve_live_and_offline_agree(self):
        with SVDService(_cfg()) as svc:
            t = svc.submit(_mat(30, 30, seed=1))
            assert t.result(timeout=300.0).status.name == "OK"
            live = [e["name"] for e in svc.timeline(t.request_id)]
            records = svc.records()
        assert self._core_order(live) == self.CORE
        assert live.count("sweep") >= 1
        # Sweeps sit strictly between dispatch and finish.
        assert live.index("dispatch") < live.index("sweep") \
            < live.index("finish")
        offline = [e["name"]
                   for e in timeline_from_manifest(records, t.request_id)]
        assert self._core_order(offline) == self.CORE
        assert "sweep" in offline

    def test_sigma_promote_flow_live_and_offline(self):
        with SVDService(_cfg()) as svc:
            t = svc.submit(_mat(32, 32, seed=2), phase="sigma")
            sig = t.result(timeout=300.0)
            assert sig.status.name == "OK" and sig.u is None
            pro = t.promote(timeout=60.0)
            assert pro.status.name == "OK" and pro.u is not None
            live = [e["name"] for e in svc.timeline(t.request_id)]
            records = svc.records()
        # Live: the retained state and the promotion both on the SAME
        # request's timeline, after the solve finished.
        assert self._core_order(live) == self.CORE
        assert "retain" in live and "promote" in live
        assert live.index("retain") < live.index("promote")
        offline = timeline_from_manifest(records, t.request_id)
        names = [e["name"] for e in offline]
        assert self._core_order(names) == self.CORE
        assert "retain" in names and "promote" in names
        assert names.index("finalize") < names.index("promote")
        # The promote event carries its provenance.
        promo = [e for e in offline if e["name"] == "promote"
                 and e.get("promoted_from")][0]
        assert promo["promoted_from"] == t.request_id

    def test_cache_hit_timeline(self):
        with SVDService(_cfg(result_cache_bytes=1 << 20)) as svc:
            a = _mat(30, 30, seed=3)
            svc.submit(a).result(timeout=300.0)
            t2 = svc.submit(a)
            assert t2.result(1.0).path == "cache"
            live = [e["name"] for e in svc.timeline(t2.request_id)]
            records = svc.records()
        assert live == ["admit", "cache_hit", "finalize"]
        offline = [e["name"]
                   for e in timeline_from_manifest(records, t2.request_id)]
        # Live and offline must agree on the ORDER, not just membership
        # (the cache-path events reconstruct to one shared timestamp, so
        # the causal tie-break rank carries the whole claim).
        assert offline == live


class TestServiceScrape:
    def test_scrape_has_every_required_family_and_matches_stats(self):
        with SVDService(_cfg(result_cache_bytes=1 << 20)) as svc:
            a = _mat(30, 30, seed=4)
            assert svc.submit(a).result(timeout=300.0).status.name == "OK"
            svc.submit(a).result(1.0)                     # cache hit
            svc.submit(_mat(24, 24, seed=5),
                       phase="sigma").result(timeout=300.0)
            text = svc.metrics_text()
            stats = svc.stats()
            health = svc.healthz()
        series = parse_prometheus(text)
        for family in ("svdj_requests_admitted_total",
                       "svdj_requests_finalized_total",
                       "svdj_dispatches_total", "svdj_sweeps_total",
                       "svdj_queue_depth", "svdj_lane_state",
                       "svdj_breaker_state", "svdj_brownout_level",
                       "svdj_result_cache_bytes",
                       "svdj_promotion_store_bytes",
                       "svdj_cache_events_total",
                       "svdj_queue_wait_seconds",
                       "svdj_solve_seconds",
                       "svdj_request_latency_seconds",
                       "svdj_slo_error_budget_burn",
                       "svdj_slo_latency_seconds"):
            assert any(k.startswith(family) for k in series), family
        finalized = sum(v for k, v in series.items()
                        if k.startswith("svdj_requests_finalized_total"))
        assert finalized == stats["served"]
        # SLO accounting surfaced through healthz too.
        assert health["slo"]["buckets"]["32x32:float64"]["ok"] >= 2
        assert health["slo"]["error_budget_burn"] == 0.0

    def test_rejection_counts_and_burns(self):
        with SVDService(_cfg(max_queue_depth=1,
                             brownout_sigma_only_at=0.01,
                             brownout_shed_at=0.01)) as svc:
            with chaos.slow_solve(0.15, shots=2):
                t1 = svc.submit(_mat(30, 30, seed=6))
                # Wait for the worker to pop t1 (in-flight, slowed)...
                deadline = time.monotonic() + 30.0
                while (svc.queue.depth() > 0
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
                # ...then fill the 1-deep queue; the NEXT submit sheds.
                t2 = svc.submit(_mat(30, 30, seed=7))
                from svd_jacobi_tpu.serve import AdmissionError
                with pytest.raises(AdmissionError):
                    svc.submit(_mat(30, 30, seed=8))
                t1.result(timeout=300.0)
                t2.result(timeout=300.0)
            series = parse_prometheus(svc.metrics_text())
        rej = [k for k in series
               if k.startswith("svdj_requests_rejected_total")]
        assert rej and sum(series[k] for k in rej) >= 1

    def test_metrics_off_text_and_zero_mutations(self):
        before = obsreg.mutation_total()
        with SVDService(_cfg(metrics=False)) as svc:
            assert svc.submit(
                _mat(30, 30, seed=8)).result(timeout=300.0).status.name \
                == "OK"
            text = svc.metrics_text()
            assert svc.timeline("anything") == []
            assert "slo" not in svc.healthz()
        assert text.startswith("# svdj metrics disabled")
        assert obsreg.mutation_total() - before == 0

    def test_journal_fsync_histogram(self, tmp_path):
        with SVDService(_cfg(journal_path=str(tmp_path / "j.jsonl"))) \
                as svc:
            assert svc.submit(
                _mat(30, 30, seed=9)).result(timeout=300.0).status.name \
                == "OK"
            # The client unblocks the instant the ticket flips, BEFORE
            # the worker's finalize append (best-effort journaling is
            # deliberately off the client's critical path) — give the
            # append a moment rather than racing it.
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                series = parse_prometheus(svc.metrics_text())
                if series.get("svdj_journal_appends_total") == 3.0:
                    break
                time.sleep(0.02)
        # admit + dispatch + finalize = 3 fsync'd appends observed.
        assert series.get("svdj_journal_fsync_seconds_count") == 3.0
        assert series.get("svdj_journal_appends_total") == 3.0


class TestHttpListener:
    def test_metrics_and_healthz_endpoints(self):
        import http.client
        with SVDService(_cfg(metrics_port=0)) as svc:
            host, port = svc.http_address
            assert svc.submit(
                _mat(30, 30, seed=10)).result(timeout=300.0).status.name \
                == "OK"
            conn = http.client.HTTPConnection(host, port, timeout=10)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            assert resp.status == 200
            assert "version=0.0.4" in resp.getheader("Content-Type")
            parse_prometheus(resp.read().decode())
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            health = json.loads(resp.read())
            assert health["ok"] is True and "slo" in health
            conn.request("GET", "/nope")
            assert conn.getresponse().status == 404
            conn.close()
        # stop() shut the listener down.
        assert svc.http_address is None


@pytest.mark.chaos
class TestExporterUnderFleetChaos:
    """Satellite: kill a lane mid-load; the scrape must stay
    serviceable, lane-state gauges must transition
    ACTIVE->QUARANTINED->ACTIVE, and the steal/rescue counters must
    match the validated fleet manifest records."""

    def test_scrape_serviceable_through_kill_and_recovery(self):
        cfg = _cfg(lanes=2, supervise_interval_s=0.02,
                   lane_probe_interval_s=0.05, lane_probe_timeout_s=120.0,
                   steal=True, max_queue_depth=32)
        with SVDService(cfg) as svc:
            def scrape():
                text = svc.metrics_text()
                return parse_prometheus(text)

            states = {0: set()}
            series = scrape()
            assert series['svdj_lane_state{lane="0"}'] == 1.0
            states[0].add(1.0)
            with chaos.kill_lane(0):
                tickets = [svc.submit(_mat(32, 32, seed=100 + i))
                           for i in range(6)]
                deadline = time.monotonic() + 60.0
                quarantined = False
                while time.monotonic() < deadline:
                    series = scrape()        # serviceable THROUGHOUT
                    states[0].add(series['svdj_lane_state{lane="0"}'])
                    if svc.fleet.lanes[0].state is LaneState.QUARANTINED:
                        quarantined = True
                    if quarantined and svc.fleet.lanes[0].state is \
                            LaneState.ACTIVE:
                        break
                    time.sleep(0.02)
                results = [t.result(timeout=300.0) for t in tickets]
            # Every ticket terminal; the gauge saw both states.
            assert all(r.status is not None or r.error for r in results)
            assert states[0] == {0.0, 1.0}
            deadline = time.monotonic() + 60.0
            while (svc.fleet.lanes[0].state is not LaneState.ACTIVE
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            series = scrape()
            assert series['svdj_lane_state{lane="0"}'] == 1.0
            # The acceptance's required families, present mid-soak.
            for family in ("svdj_queue_depth", "svdj_lane_state",
                           "svdj_breaker_state", "svdj_brownout_level",
                           "svdj_result_cache_bytes",
                           "svdj_promotion_store_bytes",
                           "svdj_slo_error_budget_burn"):
                assert any(k.startswith(family) for k in series), family
            # Live counters == the validated fleet manifest records.
            records = svc.records()
            for rec in records:
                manifest.validate(rec)
            fleet_recs = [r for r in records if r.get("kind") == "fleet"]
            rescued_recs = sum(r.get("count", 0) for r in fleet_recs
                               if r.get("event") == "rescue")
            steals_recs = sum(1 for r in fleet_recs
                              if r.get("event") == "steal")
            transitions_recs = sum(1 for r in fleet_recs
                                   if r.get("event") == "lane_transition")
            live_rescued = sum(v for k, v in series.items()
                               if k.startswith("svdj_rescued_total"))
            live_steals = sum(v for k, v in series.items()
                              if k.startswith("svdj_steals_total"))
            live_transitions = sum(
                v for k, v in series.items()
                if k.startswith("svdj_lane_transitions_total"))
            assert live_rescued == rescued_recs
            assert live_steals == steals_recs
            assert live_transitions == transitions_recs
            # ...and the offline reconstruction derives the same series.
            offline = obsreg.registry_from_manifest(records)
            off_series = parse_prometheus(offline.render())
            assert sum(v for k, v in off_series.items()
                       if k.startswith("svdj_rescued_total")) \
                == rescued_recs
            assert sum(v for k, v in off_series.items()
                       if k.startswith("svdj_steals_total")) == steals_recs


class TestOBS002:
    def test_pass_is_green(self):
        from svd_jacobi_tpu.analysis import obs_checks
        findings, report = obs_checks.run_metrics_off_case()
        assert findings == [], [f.message for f in findings]
        assert report["mutation_delta"] == 0

    def test_seeded_leak_fixture_fires(self):
        from svd_jacobi_tpu.analysis import obs_checks
        findings, report = obs_checks.run_metrics_off_case(seed_leak=True)
        assert report["mutation_delta"] > 0
        assert any("not free when off" in f.message for f in findings)

    def test_metrics_off_hlo_byte_identity(self):
        from svd_jacobi_tpu.analysis import obs_checks
        assert obs_checks.check_metrics_off_hlo() == []

    def test_idle_overhead_within_budget(self):
        from svd_jacobi_tpu.analysis import obs_checks
        findings, report = obs_checks.check_idle_overhead(
            mutations=2000, scrapes=5)
        assert findings == [], [f.message for f in findings]
        assert report["per_mutation_s"] < obs_checks.MUTATION_BUDGET_S


class TestKindsRegistry:
    def test_partial_registration_is_loud(self):
        with pytest.raises(KeyError, match="without"):
            manifest.register_kind("half-baked", builder=lambda: {},
                                   validator=None,
                                   summarizer=lambda r: "")

    def test_duplicate_registration_is_loud(self):
        with pytest.raises(KeyError, match="already registered"):
            manifest.register_kind("serve", builder=lambda: {},
                                   validator=lambda r, e: None,
                                   summarizer=lambda r: "")

    def test_every_kind_has_all_three(self):
        assert set(manifest.KINDS) >= {"cli", "bench", "analysis", "retry",
                                       "serve", "tune", "fleet", "cache",
                                       "coldstart"}
        for name, kind in manifest.KINDS.items():
            assert callable(kind.builder), name
            assert callable(kind.validator), name
            assert callable(kind.summarizer), name

    def test_non_string_kind_falls_back_not_typeerror(self):
        # A list-valued "kind" is well-formed JSON; the registry lookup
        # must fall back to the solve shape (the pre-registry if/elif
        # behavior), never raise TypeError: unhashable.
        with pytest.raises(ValueError, match="invalid manifest record"):
            manifest.validate({"kind": ["serve"]})
        assert "run @" in manifest.summarize({"kind": ["serve"]})

    def test_unknown_kind_still_falls_back(self):
        # Forward compatibility: a record from a NEWER writer validates
        # and renders through the solve branch, exactly as before.
        rec = manifest.build("cli", m=8, n=8, dtype="float32",
                             config=SVDConfig(),
                             solve={"time_s": 1.0, "sweeps": 1,
                                    "off_norm": 0.0})
        rec["kind"] = "from-the-future"
        manifest.validate(rec)
        assert "from-the-future run @" in manifest.summarize(rec)


class TestMetricsDumpCLI:
    def _manifest(self, tmp_path):
        with SVDService(_cfg(result_cache_bytes=1 << 20,
                             manifest_path=str(tmp_path / "m.jsonl"))) \
                as svc:
            a = _mat(30, 30, seed=11)
            t = svc.submit(a)
            assert t.result(timeout=300.0).status.name == "OK"
            svc.submit(a).result(1.0)
        return tmp_path / "m.jsonl", t.request_id

    def test_prometheus_slo_and_timeline_dumps(self, tmp_path, capsys):
        from svd_jacobi_tpu import cli
        path, rid = self._manifest(tmp_path)
        assert cli.main(["metrics", str(path)]) == 0
        out = capsys.readouterr().out
        series = parse_prometheus(out)
        assert any(k.startswith("svdj_requests_finalized_total")
                   for k in series)
        assert any(k.startswith("svdj_cache_events_total")
                   for k in series)
        assert cli.main(["metrics", str(path), "--slo"]) == 0
        out = capsys.readouterr().out
        assert "error-budget burn" in out and "32x32:float64" in out
        assert cli.main(["metrics", str(path), "--timeline", rid]) == 0
        out = capsys.readouterr().out
        assert "admit" in out and "finalize" in out

    def test_empty_manifest_exits_nonzero(self, tmp_path):
        from svd_jacobi_tpu import cli
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        assert cli.main(["metrics", str(p)]) == 1


class TestTelemetrySummaryScript:
    def _run(self, *argv):
        import subprocess
        import sys
        from pathlib import Path
        script = Path(__file__).resolve().parent.parent / "scripts" / \
            "telemetry_summary.py"
        return subprocess.run([sys.executable, str(script), *argv],
                              capture_output=True, text=True, timeout=120)

    def _write_mixed(self, tmp_path):
        path = tmp_path / "m.jsonl"
        manifest.append(path, manifest.build_serve(
            request_id="r0", m=32, n=32, dtype="float64", bucket="b32",
            queue_wait_s=0.0, solve_time_s=0.1, status="OK", path="base",
            breaker="closed", brownout="FULL"))
        manifest.append(path, manifest.build_cache(
            store="result", event="hit", request_id="r0", digest="ab" * 32))
        manifest.append(path, manifest.build_coldstart(
            entries=[{"entry": "e", "time_s": 0.1, "cache_hit": True}],
            total_s=0.2, backend_compiles=1, cache_hits=1,
            fresh_compiles=0, cache_dir=None, config_sha256=None))
        return path

    def test_kind_filter(self, tmp_path):
        path = self._write_mixed(tmp_path)
        out = self._run(str(path), "--kind", "cache")
        assert out.returncode == 0
        assert out.stdout.startswith("cache result/hit")
        assert "serve r0" not in out.stdout
        out = self._run(str(path), "--kind", "coldstart")
        assert out.returncode == 0 and "cache-hit" in out.stdout
        out = self._run(str(path), "--kind", "nonsense")
        assert out.returncode == 2 and "registered kinds" in out.stderr

    def test_slo_rendering(self, tmp_path):
        path = self._write_mixed(tmp_path)
        out = self._run(str(path), "--slo")
        assert out.returncode == 0
        assert "error-budget burn" in out.stdout and "b32" in out.stdout
