"""LAPACK surface, CLI driver, checkpoint/resume, stepper, profiling
(SURVEY.md C9 public API, C13/C14 harness, section 5 aux subsystems)."""

import json
import os

import numpy as np
import jax.numpy as jnp
import pytest

from svd_jacobi_tpu import SVDConfig, svd
from svd_jacobi_tpu.lapack import SVD_OPTIONS, gesvd
from svd_jacobi_tpu.solver import SweepStepper
from svd_jacobi_tpu.utils import checkpoint, matgen, profiling, validation


CFG = SVDConfig(block_size=4)


def _ref(a):
    return np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)


class TestGesvd:
    def test_somevec(self):
        a = matgen.random_dense(24, 16, dtype=jnp.float64, seed=1)
        u, s, vt = gesvd(SVD_OPTIONS.SomeVec, SVD_OPTIONS.SomeVec, a, config=CFG)
        assert u.shape == (24, 16) and vt.shape == (16, 16)
        np.testing.assert_allclose(np.asarray(u * s[None, :] @ vt),
                                   np.asarray(a), atol=1e-12)
        np.testing.assert_allclose(np.asarray(s), _ref(a), rtol=1e-10, atol=1e-12)

    def test_novec(self):
        a = matgen.random_dense(16, 16, dtype=jnp.float64, seed=2)
        u, s, vt = gesvd(SVD_OPTIONS.NoVec, SVD_OPTIONS.NoVec, a, config=CFG)
        assert u is None and vt is None
        np.testing.assert_allclose(np.asarray(s), _ref(a), rtol=1e-10, atol=1e-12)

    def test_allvec_tall(self):
        a = matgen.random_dense(20, 8, dtype=jnp.float64, seed=3)
        u, s, vt = gesvd(SVD_OPTIONS.AllVec, SVD_OPTIONS.AllVec, a, config=CFG)
        assert u.shape == (20, 20) and vt.shape == (8, 8)
        assert float(validation.orthogonality_error(u)) < 1e-12
        np.testing.assert_allclose(np.asarray(u[:, :8] * s[None, :] @ vt),
                                   np.asarray(a), atol=1e-12)

    def test_allvec_wide(self):
        a = matgen.random_dense(8, 20, dtype=jnp.float64, seed=4)
        u, s, vt = gesvd(SVD_OPTIONS.AllVec, SVD_OPTIONS.AllVec, a, config=CFG)
        assert u.shape == (8, 8) and vt.shape == (20, 20)
        assert float(validation.orthogonality_error(vt.T)) < 1e-11
        np.testing.assert_allclose(np.asarray(u * s[None, :] @ vt[:8]),
                                   np.asarray(a), atol=1e-12)

    def test_mixed_jobs(self):
        a = matgen.random_dense(12, 12, dtype=jnp.float64, seed=5)
        u, s, vt = gesvd(SVD_OPTIONS.SomeVec, SVD_OPTIONS.NoVec, a, config=CFG)
        assert u is not None and vt is None

    def test_type_errors(self):
        a = jnp.zeros((4, 4))
        with pytest.raises(TypeError):
            gesvd("AllVec", SVD_OPTIONS.NoVec, a)


class TestGesvdColLayout:
    """layout="col" makes the dgesvd drop-in literal (the reference's
    MATRIX_LAYOUT enum, lib/Utils.cuh:18-21): the input is the col-major
    image (transpose) of the logical matrix and the returned u/vt are
    col-major images too — mirroring TestGesvd case by case."""

    def _col(self, a):
        return jnp.asarray(np.asarray(a).T)

    def test_somevec_matches_row(self):
        a = matgen.random_dense(24, 16, dtype=jnp.float64, seed=1)
        u, s, vt = gesvd(SVD_OPTIONS.SomeVec, SVD_OPTIONS.SomeVec, a,
                         config=CFG)
        uc, sc, vtc = gesvd(SVD_OPTIONS.SomeVec, SVD_OPTIONS.SomeVec,
                            self._col(a), layout="col", config=CFG)
        assert uc.shape == (16, 24) and vtc.shape == (16, 16)
        np.testing.assert_allclose(np.asarray(sc), np.asarray(s))
        np.testing.assert_allclose(np.asarray(uc), np.asarray(u).T,
                                   atol=1e-12)
        np.testing.assert_allclose(np.asarray(vtc), np.asarray(vt).T,
                                   atol=1e-12)
        # The drop-in reconstruction, entirely in col-major images:
        # image(A) = image(V^T)^T? no — A = (uc^T) S (vtc^T).
        np.testing.assert_allclose(
            np.asarray(uc).T * np.asarray(sc)[None, :] @ np.asarray(vtc).T,
            np.asarray(a), atol=1e-12)

    def test_novec(self):
        a = matgen.random_dense(16, 16, dtype=jnp.float64, seed=2)
        u, s, vt = gesvd(SVD_OPTIONS.NoVec, SVD_OPTIONS.NoVec,
                         self._col(a), layout="col", config=CFG)
        assert u is None and vt is None
        np.testing.assert_allclose(np.asarray(s), _ref(a), rtol=1e-10,
                                   atol=1e-12)

    def test_mixed_jobs_swap(self):
        """jobu governs the LOGICAL U even under col layout (the job swap
        is internal)."""
        a = matgen.random_dense(12, 12, dtype=jnp.float64, seed=5)
        u, s, vt = gesvd(SVD_OPTIONS.SomeVec, SVD_OPTIONS.NoVec,
                         self._col(a), layout="col", config=CFG)
        assert u is not None and vt is None

    def test_allvec_tall(self):
        a = matgen.random_dense(20, 8, dtype=jnp.float64, seed=3)
        u, s, vt = gesvd(SVD_OPTIONS.AllVec, SVD_OPTIONS.AllVec, a,
                         config=CFG)
        uc, sc, vtc = gesvd(SVD_OPTIONS.AllVec, SVD_OPTIONS.AllVec,
                            self._col(a), layout="col", config=CFG)
        assert uc.shape == (20, 20) and vtc.shape == (8, 8)
        np.testing.assert_allclose(np.asarray(uc), np.asarray(u).T,
                                   atol=1e-12)
        np.testing.assert_allclose(np.asarray(vtc), np.asarray(vt).T,
                                   atol=1e-12)

    def test_unknown_layout_rejected(self):
        with pytest.raises(ValueError, match="layout"):
            gesvd(SVD_OPTIONS.NoVec, SVD_OPTIONS.NoVec, jnp.zeros((4, 4)),
                  layout="fortran")


class TestStepperAndCheckpoint:
    def test_stepper_matches_svd(self):
        a = matgen.random_dense(32, 32, dtype=jnp.float64, seed=6)
        r_fused = svd(a, config=CFG)
        st = SweepStepper(a, config=CFG)
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        r = st.finish(state)
        np.testing.assert_allclose(np.asarray(r.s), np.asarray(r_fused.s),
                                   rtol=1e-10, atol=1e-13)
        rep = validation.validate(a, r)
        assert float(rep.residual_rel) < 1e-13

    def test_stepper_hybrid_stages(self):
        a = matgen.random_dense(32, 32, dtype=jnp.float32, seed=7)
        cfg = SVDConfig(block_size=4, pair_solver="hybrid")
        r, log = profiling.instrumented_svd(a, config=cfg)
        stages = [rec.stage for rec in log.records]
        assert "bulk" in stages and "polish" in stages
        assert stages == sorted(stages, key=["bulk", "polish"].index)
        rep = validation.validate(a, r, s_ref=_ref(a))
        assert float(rep.sigma_err) < 1e-5
        assert float(rep.u_orth) < 5e-3

    def test_checkpoint_roundtrip(self, tmp_path):
        a = matgen.random_dense(32, 32, dtype=jnp.float64, seed=8)
        path = tmp_path / "ck.npz"
        r = checkpoint.svd_checkpointed(a, path=path, config=CFG)
        assert not path.exists()  # removed on success
        np.testing.assert_allclose(np.asarray(r.s), _ref(a),
                                   rtol=1e-10, atol=1e-12)

    def test_checkpoint_resume(self, tmp_path):
        a = matgen.random_dense(32, 32, dtype=jnp.float64, seed=9)
        path = tmp_path / "ck.npz"
        # Interrupt after 2 sweeps, snapshotting each sweep.
        st = SweepStepper(a, config=CFG)
        state = st.init()
        for _ in range(2):
            state = st.step(state)
        checkpoint.save_state(path, st, state)
        # Resume to completion.
        r = checkpoint.svd_checkpointed(a, path=path, config=CFG, keep=True)
        assert int(r.sweeps) > 2
        np.testing.assert_allclose(np.asarray(r.s), _ref(a),
                                   rtol=1e-10, atol=1e-12)
        rep = validation.validate(a, r)
        assert float(rep.residual_rel) < 1e-13

    def test_checkpoint_mismatch_rejected(self, tmp_path):
        a = matgen.random_dense(32, 32, dtype=jnp.float64, seed=10)
        path = tmp_path / "ck.npz"
        st = SweepStepper(a, config=CFG)
        checkpoint.save_state(path, st, st.init())
        b = matgen.random_dense(40, 40, dtype=jnp.float64, seed=10)
        with pytest.raises(ValueError, match="does not match"):
            checkpoint.svd_checkpointed(b, path=path, config=CFG)

    def test_checkpoint_wide_input(self, tmp_path):
        a = matgen.random_dense(16, 40, dtype=jnp.float64, seed=11)
        r = checkpoint.svd_checkpointed(a, path=tmp_path / "w.npz", config=CFG)
        np.testing.assert_allclose(np.asarray(r.s), _ref(a),
                                   rtol=1e-10, atol=1e-12)
        assert r.u.shape == (16, 16) and r.v.shape == (40, 16)


class TestCli:
    def test_cli_runs_and_reports(self, tmp_path, capsys):
        from svd_jacobi_tpu import cli
        rc = cli.main(["64", "--dtype", "float64", "--selftest-n", "32",
                       "--oracle", "--report-dir", str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()[-1]
        solve = json.loads(out)
        assert solve["residual_rel"] < 1e-12
        assert solve["sigma_err"] < 1e-12
        from svd_jacobi_tpu.obs import manifest
        records = manifest.load(tmp_path / "manifest.jsonl")
        assert len(records) == 1
        manifest.validate(records[0])
        rep = records[0]
        assert rep["kind"] == "cli"
        assert rep["self_test"]["ok"]
        assert rep["solve"]["sweeps"] >= 1
        assert {s["name"] for s in rep["stages"]} == {
            "self_test", "warmup_compile", "solve"}
        assert rep["telemetry"] is None      # no --telemetry flag

    def test_cli_distributed(self, tmp_path, eight_devices):
        from svd_jacobi_tpu import cli
        rc = cli.main(["48", "--dtype", "float64", "--distributed",
                       "--no-selftest", "--matrix", "dense",
                       "--report-dir", str(tmp_path)])
        assert rc == 0

    def test_cli_rejects_rect_triangular(self, tmp_path):
        from svd_jacobi_tpu import cli
        rc = cli.main(["32", "16", "--no-selftest",
                       "--report-dir", str(tmp_path)])
        assert rc == 2

    def test_cli_jobu_jobv(self, tmp_path, capsys):
        """Driver-level SVD_OPTIONS parity (reference main.cu:1587): a
        sigma-only run from the CLI alone reports null factor metrics, and
        the job options land in the JSON report."""
        from svd_jacobi_tpu import cli
        rc = cli.main(["64", "--dtype", "float64", "--no-selftest",
                       "--matrix", "dense", "--jobu", "none", "--jobv",
                       "none", "--oracle", "--report-dir", str(tmp_path)])
        assert rc == 0
        solve = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert solve["residual_rel"] is None
        assert solve["u_orth"] is None and solve["v_orth"] is None
        assert solve["sigma_err"] < 1e-12      # sigma still computed + checked
        from svd_jacobi_tpu.obs import manifest
        rep = manifest.load(tmp_path / "manifest.jsonl")[-1]
        assert rep["jobu"] == "none" and rep["jobv"] == "none"


def test_profiling_log_json():
    a = matgen.random_dense(24, 24, dtype=jnp.float64, seed=12)
    r, log = profiling.instrumented_svd(a, config=CFG)
    d = json.loads(log.to_json())
    assert d["total_time_s"] > 0
    assert len(d["sweeps"]) == int(r.sweeps)
    assert all(rec["off_norm"] >= 0 for rec in d["sweeps"])


def test_live_orth_bf16_deflates():
    """Regression: bfloat16 eps (numpy kind 'V') must not fall back to an
    f64-scale threshold — null columns of a rank-deficient bf16 input must
    be deflated from the live-orthogonality metric."""
    s_true = np.r_[np.ones(8), np.zeros(8)]
    a = matgen.with_known_spectrum(24, 16, s_true,
                                   dtype=jnp.float32).astype(jnp.bfloat16)
    r = svd(a, config=SVDConfig(block_size=4))
    err = float(validation.live_orthogonality_error(r.u, r.s))
    assert err < 0.1, err


def test_stepper_polish_actually_polishes():
    """Regression: the first polish sweep must not be stall-compared against
    the bulk phase's abs-scale off-norm (which spuriously terminated the
    polish phase with U unorthogonalized)."""
    a = matgen.with_known_spectrum(
        64, 64, np.geomspace(1, 1e-5, 64), dtype=jnp.float32)
    cfg = SVDConfig(block_size=8, pair_solver="hybrid")
    r, log = profiling.instrumented_svd(a, config=cfg)
    n_polish = sum(1 for rec in log.records if rec.stage == "polish")
    assert n_polish >= 1
    assert float(validation.live_orthogonality_error(r.u, r.s)) < 5e-3


def test_gesvd_mesh_routing(eight_devices):
    """gesvd(mesh=...) routes to the distributed solver and matches the
    host oracle (the reference's omp_mpi_cuda_dgesvd_local_matrices-shaped
    entry point)."""
    from svd_jacobi_tpu.lapack import SVD_OPTIONS, gesvd
    from svd_jacobi_tpu.parallel import sharded

    rng = np.random.default_rng(12)
    a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
    mesh = sharded.make_mesh()
    u, s, vt = gesvd(SVD_OPTIONS.SomeVec, SVD_OPTIONS.SomeVec, a, mesh=mesh)
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(s, np.float64) - s_ref)) / s_ref[0] < 5e-6
    rec = np.asarray(u, np.float64) @ np.diag(np.asarray(s, np.float64)) \
        @ np.asarray(vt, np.float64)
    res = np.linalg.norm(rec - np.asarray(a, np.float64)) / np.linalg.norm(np.asarray(a))
    assert res < 1e-5


def test_cli_parse_time_mode_rejections(tmp_path, monkeypatch):
    """Unsatisfiable flag combinations die at parse time (exit 2), before
    the warm-up self-test spends a solve."""
    # cli.main re-applies JAX_PLATFORMS from the environment, which would
    # flip the suite's forced-CPU backend onto a real attached TPU.
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    from svd_jacobi_tpu import cli
    base = ["64", "--no-selftest", "--report-dir", str(tmp_path)]
    assert cli.main(base + ["--distributed", "--precondition", "double"]) == 2
    assert cli.main(base + ["--distributed", "--mixed-bulk", "on"]) == 2
    assert cli.main(base + ["--mixed-bulk", "on",
                            "--pair-solver", "hybrid"]) == 2
    assert cli.main(base + ["--precondition", "on",
                            "--dtype", "float64"]) == 2
    assert cli.main(base + ["--mixed-bulk", "on",
                            "--dtype", "bfloat16"]) == 2


def test_cli_mixed_and_refine_flags(tmp_path, capsys, monkeypatch):
    """The mixed-bulk and sigma-refine knobs reach the solver through the
    CLI and are recorded in the report."""
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)  # see above
    import json as _json
    from svd_jacobi_tpu import cli
    rc = cli.main(["96", "--matrix", "dense", "--no-selftest",
                   "--mixed-bulk", "on", "--sigma-refine", "on",
                   "--oracle", "--report-dir", str(tmp_path)])
    assert rc == 0
    solve = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert solve["residual_rel"] < 1e-5
    assert solve["sigma_err"] < 1e-6
    from svd_jacobi_tpu.obs import manifest
    rep = manifest.load(tmp_path / "manifest.jsonl")[-1]
    # The manifest records the RESOLVED SVDConfig (tri-state flags land as
    # booleans), not the CLI spelling.
    assert rep["config"]["mixed_bulk"] is True
    assert rep["config"]["sigma_refine"] is True
