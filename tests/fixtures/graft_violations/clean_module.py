"""Control fixture: idiomatic traced library code — zero findings even
with the traced-module rules forced on."""

from functools import partial

import jax
import jax.numpy as jnp

from svd_jacobi_tpu.utils._exec import host_scalar


@partial(jax.jit, static_argnames=("with_v",))
def sweep_like(x, *, with_v=True):
    y = jnp.dot(x, x.T)
    if with_v:                            # static: fine
        y = y + jnp.eye(y.shape[0], dtype=y.dtype)
    m, n = y.shape                        # metadata: fine
    if m > n:                             # host ints: fine
        y = y.T
    return jax.lax.cond(jnp.max(y) > 0, lambda v: v, lambda v: -v, y)


def host_side_read(state):
    # The sanctioned scalar readback.
    return host_scalar(state)


def eps_of(dtype):
    return float(jnp.finfo(dtype).eps)    # metadata fn: fine
