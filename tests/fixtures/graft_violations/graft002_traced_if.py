"""Seeded GRAFT002 violations: Python control flow on traced booleans."""

import jax.numpy as jnp


def bad_if(x):
    coupling = jnp.max(jnp.abs(x))
    if coupling > 1e-6:                  # GRAFT002
        return x * 2
    return x


def bad_while(x):
    off = jnp.sum(x)
    while off > 0:                       # GRAFT002
        off = off - 1
    return off


def ok_structure_dispatch(v):
    # `is None` on a maybe-tracer is static structure, not a traced bool.
    z = jnp.zeros(()) if v is None else v
    if v is None:
        return z
    return v
