"""Seeded GRAFT003 violation: jax.numpy computation at module import."""

import jax.numpy as jnp

EYE = jnp.eye(8)                         # GRAFT003


class Holder:
    TABLE = jnp.arange(16)               # GRAFT003 (class body runs at import)


def fine():
    return jnp.ones(4)                   # inside a function: not flagged


if __name__ == "__main__":
    print(jnp.zeros(2))                  # __main__ guard: not flagged
