"""Seeded GRAFT001 violations: host materialization of traced values.

Never imported by the package — parsed by tests/test_analysis.py to prove
the rule fires. Expected findings: float() on a traced value, np.asarray()
on a traced value, .item(), and the ad-hoc .addressable_shards poke
(the solver.py:184 pattern that utils._exec.host_scalar replaced).
"""

import jax.numpy as jnp
import numpy as np


def bad_float_cast(x):
    y = jnp.sum(x * x)
    return float(y)                      # GRAFT001


def bad_np_materialize(x):
    g = jnp.dot(x, x)
    return np.asarray(g)                 # GRAFT001


def bad_item(x):
    return x.item()                      # GRAFT001


def bad_shard_poke(arr):
    return float(np.asarray(arr.addressable_shards[0].data))  # GRAFT001


def suppressed_cast(x):
    y = jnp.max(x)
    return float(y)  # graftcheck: ok GRAFT001
